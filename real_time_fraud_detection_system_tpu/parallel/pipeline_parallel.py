"""Pipeline parallelism: GPipe-style microbatch pipeline over ppermute.

Stages of a deep residual scorer live on different devices; microbatches
flow stage→stage over ICI with ``lax.ppermute`` while every stage
computes in parallel — the classic bubble-amortized schedule
(fill S-1 ticks, steady state, drain S-1 ticks).

The reference has nothing this deep (its dormant MLP is 2 layers), but a
framework claiming the reference's scale on TPU must place models deeper
than one chip; this is the canonical TPU idiom for it. The demo model is
a stack of S uniform residual blocks (``init_stack``) whose parameters
are stacked on a leading stage axis and sharded over the mesh, plus a
replicated input/output head applied outside the pipeline.

SPMD mechanics (all devices run the same program under ``shard_map``):

- tick t: stage 0 *injects* microbatch t (if any left), every stage
  applies its block to the activation it holds, stage S-1 *emits* its
  result into the output buffer at slot t-(S-1);
- between ticks, activations rotate one hop with ``ppermute`` (the ICI
  neighbor exchange);
- after S-1+M ticks the output buffer on the last stage holds all M
  microbatches; one ``psum`` broadcasts it (every other stage holds
  zeros).

Exactness: each microbatch passes through stages 0..S-1 in order, so the
pipelined result equals the sequential stack application bit-for-bit —
pinned by ``tests/test_tensor_pipeline.py``.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class StackParams(NamedTuple):
    """S uniform residual blocks, stacked on the leading (stage) axis."""

    w1: jnp.ndarray  # [S, H, H]
    b1: jnp.ndarray  # [S, H]
    w2: jnp.ndarray  # [S, H, H]
    b2: jnp.ndarray  # [S, H]


def init_stack(width: int, n_stages: int, seed: int = 0) -> StackParams:
    key = jax.random.PRNGKey(seed)
    ks = jax.random.split(key, 2)
    scale = np.sqrt(2.0 / width)
    w1 = scale * jax.random.normal(
        ks[0], (n_stages, width, width), dtype=jnp.float32)
    w2 = scale * jax.random.normal(
        ks[1], (n_stages, width, width), dtype=jnp.float32)
    z = jnp.zeros((n_stages, width), dtype=jnp.float32)
    return StackParams(w1=w1, b1=z, w2=w2, b2=z)


def block_apply(p: StackParams, s, h: jnp.ndarray) -> jnp.ndarray:
    """One residual block (params of stage ``s``): h + W2·relu(W1·h)."""
    inner = jax.nn.relu(h @ p.w1[s] + p.b1[s])
    return h + inner @ p.w2[s] + p.b2[s]


def stack_apply(p: StackParams, h: jnp.ndarray) -> jnp.ndarray:
    """Sequential reference: apply all S blocks in order (single device)."""
    for s in range(p.w1.shape[0]):
        h = block_apply(p, s, h)
    return h


def make_pipeline(
    mesh: Mesh,
    params: StackParams,
    n_micro: int,
    axis: Optional[str] = None,
):
    """→ (sharded_params, run(params, x) → y) with stages sharded over
    ``axis`` and ``x [B, H]`` split into ``n_micro`` microbatches.

    ``B`` must divide evenly by ``n_micro``; stage count must equal the
    axis size (one stage per device — the deployment shape; several
    blocks per device just means a deeper ``block_apply``).
    """
    from real_time_fraud_detection_system_tpu.parallel.mesh import (
        compat_shard_map,
    )

    axis = axis or mesh.axis_names[0]
    n_dev = mesh.shape[axis]
    s_total = params.w1.shape[0]
    if s_total != n_dev:
        raise ValueError(
            f"{s_total} stages on a {n_dev}-device '{axis}' axis "
            "(want exactly one stage per device)"
        )
    spec = P(axis)  # stage-stacked leaves shard on their leading axis
    sharded = jax.tree.map(
        lambda a: jax.device_put(a, NamedSharding(mesh, spec)), params)
    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

    def run(p, x):
        stage = jax.lax.axis_index(axis)
        b, h_dim = x.shape
        if b % n_micro:
            raise ValueError(f"batch {b} not divisible by {n_micro}")
        m_rows = b // n_micro
        mb = x.reshape(n_micro, m_rows, h_dim)
        outs0 = jnp.zeros_like(mb)
        h0 = jnp.zeros((m_rows, h_dim), x.dtype)

        def tick(t, carry):
            h_cur, outs = carry
            # stage 0 injects microbatch t (clamped once the feed drains)
            inject = mb[jnp.minimum(t, n_micro - 1)]
            h_in = jnp.where(stage == 0, inject, h_cur)
            h_out = block_apply(p, 0, h_in)  # local shard: stage axis len 1
            # last stage emits into slot t-(S-1) while t is in range
            slot = t - (n_dev - 1)
            emit = (stage == n_dev - 1) & (slot >= 0)
            outs = jax.lax.cond(
                emit,
                lambda o: o.at[jnp.maximum(slot, 0)].set(h_out),
                lambda o: o,
                outs,
            )
            h_next = jax.lax.ppermute(h_out, axis, perm)
            return h_next, outs

        _, outs = jax.lax.fori_loop(
            0, n_micro + n_dev - 1, tick, (h0, outs0))
        # broadcast the last stage's buffer (all others hold zeros)
        outs = jax.lax.psum(
            jnp.where(stage == n_dev - 1, outs, jnp.zeros_like(outs)), axis)
        return outs.reshape(b, h_dim)

    run_sharded = jax.jit(compat_shard_map(
        run, mesh, (jax.tree.map(lambda _: spec, params), P()), P()))
    return sharded, run_sharded
