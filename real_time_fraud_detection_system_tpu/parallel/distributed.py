"""Multi-host distributed runtime: process init + hybrid DCN×ICI meshes.

The reference's "distributed backend" is Kafka + Py4J + Arrow + HTTP on one
host (SURVEY §5.8) — there is no NCCL/MPI to port. The TPU-native fabric is:

- **DCN** (data-center network) between hosts: carries Kafka consumer
  traffic in, and the outer mesh axis of cross-host collectives;
- **ICI** (inter-chip interconnect) within a pod slice: carries the in-step
  collectives (``all_to_all`` terminal routing, ``psum`` gradient sync).

:func:`initialize_distributed` wraps ``jax.distributed.initialize`` with
env-var autodetection (a no-op single-process). :func:`make_hybrid_mesh`
builds the 2-axis ``(dcn, ici)`` mesh — via
``mesh_utils.create_hybrid_device_mesh`` on real multi-host TPU, or by
reshaping visible devices single-process (virtual-CPU testing). The sharded
step (:func:`..parallel.step.make_sharded_step`) accepts the axis pair
``("dcn", "ici")`` directly: batch rows shard over the flattened super-axis
and the collectives ride the ICI fast path within a host, DCN across.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple, Union

import jax
import numpy as np
from jax.sharding import Mesh

AxisName = Union[str, Tuple[str, ...]]

_INITIALIZED = False


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    init_timeout_s: Optional[float] = None,
) -> bool:
    """Initialize multi-process JAX if configured; returns True if active.

    Resolution order: explicit args → standard env vars
    (``JAX_COORDINATOR_ADDRESS``/``JAX_NUM_PROCESSES``/``JAX_PROCESS_ID`` —
    on Cloud TPU ``jax.distributed.initialize()`` autodetects from metadata
    instead). Single-process (nothing configured) is a no-op returning
    False, so the same binary runs a laptop test and a pod.
    ``init_timeout_s`` bounds the all-processes-present barrier (a
    mislaunched fleet fails fast instead of hanging the deploy).
    """
    global _INITIALIZED
    if _INITIALIZED:
        return True
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    if num_processes is None and "JAX_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and "JAX_PROCESS_ID" in os.environ:
        process_id = int(os.environ["JAX_PROCESS_ID"])
    if coordinator_address is None and num_processes is None:
        return False  # single-process mode
    kw = {}
    if init_timeout_s is not None:
        kw["initialization_timeout"] = int(max(init_timeout_s, 1))
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        **kw,
    )
    _INITIALIZED = True
    return True


def make_hybrid_mesh(
    n_hosts: int = 0,
    devices_per_host: int = 0,
    dcn_axis: str = "dcn",
    ici_axis: str = "ici",
) -> Mesh:
    """2-axis ``(dcn, ici)`` mesh: hosts × local devices.

    Multi-process: uses ``mesh_utils.create_hybrid_device_mesh`` so the
    outer axis crosses slices over DCN and the inner axis stays on ICI.
    Single-process (tests, virtual CPU devices): reshapes the visible
    devices row-major into [n_hosts, devices_per_host] — collective
    semantics are identical, only the physical network differs.
    """
    devs = jax.devices()
    n_proc = jax.process_count()
    if n_proc > 1:
        from jax.experimental import mesh_utils

        per_host = devices_per_host or jax.local_device_count()
        hosts = n_hosts or n_proc
        mesh_devs = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=(1, per_host),
            dcn_mesh_shape=(hosts, 1),
        )
        return Mesh(mesh_devs, (dcn_axis, ici_axis))
    # Single process: emulate the host split.
    if n_hosts == 0 and devices_per_host == 0:
        n_hosts = 2 if len(devs) % 2 == 0 and len(devs) > 1 else 1
    if n_hosts == 0:
        n_hosts = len(devs) // devices_per_host
    if devices_per_host == 0:
        devices_per_host = len(devs) // n_hosts
    need = n_hosts * devices_per_host
    if need == 0 or need > len(devs):
        raise ValueError(
            f"mesh {n_hosts}x{devices_per_host} needs {need or 'at least 1'}"
            f" device(s), {len(devs)} visible"
        )
    grid = np.asarray(devs[:need]).reshape(n_hosts, devices_per_host)
    return Mesh(grid, (dcn_axis, ici_axis))


def mesh_axes(mesh: Mesh) -> Tuple[str, ...]:
    """The mesh's axis-name tuple, in collective-flattening order — pass
    this as ``axis=`` to :func:`..parallel.step.make_sharded_step` (a 1-axis
    mesh yields a 1-tuple, which the step treats like the plain name)."""
    return tuple(mesh.axis_names)


def process_local_batch_slice(
    n_rows_global: int, mesh: Mesh
) -> slice:
    """Which rows of the globally-partitioned batch this process feeds.

    With rows laid out [n_dev_total × rows_per_shard] (see
    ``partition_batch_by_customer``), each host's Kafka consumers need only
    its own devices' row range — DCN never carries another host's rows.
    """
    n_dev = mesh.devices.size
    per = n_rows_global // n_dev
    local = jax.local_device_count()
    start = jax.process_index() * local * per
    return slice(start, start + local * per)
