"""Device mesh + sharding layout.

The reference's only scale-out axes are Kafka topic partitions and Spark
``local[*]`` cores (SURVEY §2.3). Here the axis is a 1-D ``jax.sharding.Mesh``
over TPU chips: Kafka partition p maps to mesh position p (DCN carries the
consumer traffic to hosts; ICI carries the in-step collectives).

Sharding layout:
- batch rows: sharded along axis 0 ("data") — each device scores the rows
  of its partitions;
- customer window state: sharded along the slot axis — rows arrive
  partitioned by customer key, so a device's rows only touch its own shard
  (no collective needed);
- terminal window state: sharded along the slot axis by terminal-key
  ownership — rows reference terminals owned by other devices, so the step
  exchanges (key, day, amount, fraud) quadruples via ``all_to_all`` on ICI,
  updates/queries on the owner, and returns features by the inverse
  exchange (see :mod:`.step`);
- model params + scaler: replicated (tiny), gradients ``psum``-reduced for
  the online-SGD path.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from real_time_fraud_detection_system_tpu.features.online import FeatureState


def compat_shard_map(f, mesh: Mesh, in_specs, out_specs):
    """``shard_map`` across jax versions, replication checks off (our
    specs declare replication explicitly; the checker predates several
    of the collectives used here)."""
    try:
        from jax import shard_map as _sm  # jax >= 0.8

        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_vma=False)
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map as _sm

        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=False)


def make_mesh(n_devices: int = 0, axis: str = "data") -> Mesh:
    devs = jax.devices()
    if n_devices == 0:
        n_devices = len(devs)
    if n_devices > len(devs):
        raise ValueError(
            f"requested {n_devices} devices, only {len(devs)} visible "
            f"(set XLA_FLAGS=--xla_force_host_platform_device_count=N for "
            f"virtual CPU devices)"
        )
    return Mesh(np.asarray(devs[:n_devices]), (axis,))


def shard_feature_state(
    state: FeatureState, mesh: Mesh, axis: "str | tuple[str, ...]" = "data"
) -> FeatureState:
    """Place window tables sharded along the slot axis; CMS sharded by
    customer owner.

    The sketch gets a leading device axis ([n_dev, ND, depth, width]):
    rows are partitioned by ``customer_id % n_dev``, so each device keeps
    a private sketch of ITS customers — updates and queries are purely
    device-local (zero collectives on the hot path) and each sketch sees
    ~1/n_dev of the key universe, so collisions (the CMS error term)
    shrink as the mesh grows. A rank-base sketch (single-chip layout,
    e.g. a restored single-chip checkpoint) is broadcast to every device
    as a warm start — estimates stay valid upper bounds.

    ``axis`` may be one mesh axis name or a tuple (hybrid DCN×ICI meshes,
    see :mod:`.distributed`)."""
    row_sharded = NamedSharding(mesh, P(axis, None))
    dev_sharded = NamedSharding(mesh, P(axis))

    def place_windows(ws):
        return jax.tree.map(lambda a: jax.device_put(a, row_sharded), ws)

    cms = state.cms
    if cms is not None:
        n_dev = int(mesh.devices.size)
        if cms.slice_day.ndim == 1:  # single-chip layout: add device axis
            # Build the per-device replicas shard-by-shard: each device
            # materializes ONE [1, ...] copy of the base sketch — never
            # n_dev copies on a single device (a production sketch is
            # hundreds of MB; broadcasting would OOM exactly when the
            # feature matters).
            def _expand(leaf):
                base = np.asarray(leaf)[None]
                return jax.make_array_from_callback(
                    (n_dev,) + leaf.shape, dev_sharded,
                    lambda idx, b=base: b,
                )

            cms = jax.tree.map(_expand, cms)
        else:
            cms = jax.tree.map(
                lambda a: jax.device_put(a, dev_sharded), cms
            )
    return FeatureState(
        customer=place_windows(state.customer),
        terminal=place_windows(state.terminal),
        cms=cms,
    )
