"""Device mesh + sharding layout.

The reference's only scale-out axes are Kafka topic partitions and Spark
``local[*]`` cores (SURVEY §2.3). Here the axis is a 1-D ``jax.sharding.Mesh``
over TPU chips: Kafka partition p maps to mesh position p (DCN carries the
consumer traffic to hosts; ICI carries the in-step collectives).

Sharding layout:
- batch rows: sharded along axis 0 ("data") — each device scores the rows
  of its partitions;
- customer window state: sharded along the slot axis — rows arrive
  partitioned by customer key, so a device's rows only touch its own shard
  (no collective needed);
- terminal window state: sharded along the slot axis by terminal-key
  ownership — rows reference terminals owned by other devices, so the step
  exchanges (key, day, amount, fraud) quadruples via ``all_to_all`` on ICI,
  updates/queries on the owner, and returns features by the inverse
  exchange (see :mod:`.step`);
- model params + scaler: replicated (tiny), gradients ``psum``-reduced for
  the online-SGD path.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from real_time_fraud_detection_system_tpu.features.online import FeatureState


def compat_shard_map(f, mesh: Mesh, in_specs, out_specs):
    """``shard_map`` across jax versions, replication checks off (our
    specs declare replication explicitly; the checker predates several
    of the collectives used here)."""
    try:
        from jax import shard_map as _sm  # jax >= 0.8

        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_vma=False)
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map as _sm

        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=False)


def make_mesh(n_devices: int = 0, axis: str = "data") -> Mesh:
    devs = jax.devices()
    if n_devices == 0:
        n_devices = len(devs)
    if n_devices > len(devs):
        raise ValueError(
            f"requested {n_devices} devices, only {len(devs)} visible "
            f"(set XLA_FLAGS=--xla_force_host_platform_device_count=N for "
            f"virtual CPU devices)"
        )
    return Mesh(np.asarray(devs[:n_devices]), (axis,))


def make_local_mesh(n_devices: int = 0, axis: str = "data") -> Mesh:
    """The PROCESS-LOCAL serving mesh: this process's own devices only.

    Identical to :func:`make_mesh` single-process. Under
    ``jax.distributed`` the two diverge — ``jax.devices()`` spans every
    process, and a per-process engine jitting over non-addressable
    devices is exactly the mistake that turns a host-local step into a
    cross-process computation — so multi-host serving builds its mesh
    here (one engine per process, owner exchange on local ICI) and
    leaves :func:`make_process_mesh` to code that has proven the
    backend's cross-process collectives.
    """
    devs = jax.local_devices()
    if n_devices == 0:
        n_devices = len(devs)
    if n_devices > len(devs):
        raise ValueError(
            f"requested {n_devices} local devices, process "
            f"{jax.process_index()} has {len(devs)}"
        )
    return Mesh(np.asarray(devs[:n_devices]), (axis,))


def make_process_mesh(axis: str = "data") -> Mesh:
    """The process-SPANNING 1-D serving mesh: every process's devices,
    ordered so process p's local devices occupy the contiguous block
    ``[p·L, (p+1)·L)`` — the same block the residue ownership of
    :class:`~..runtime.distributed.ProcessTopology` assigns it, so a
    spanning-mesh step and the partitioned per-process deployment agree
    on which device owns which key.

    Computations over this mesh are cross-process collectives (DCN
    between hosts, ICI within): gate on
    :func:`cross_process_collectives_supported` first — CPU jaxlib
    builds without Gloo/MPI refuse them at dispatch, deep inside
    serving, which is the wrong place to find out.
    """
    devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    return Mesh(np.asarray(devs), (axis,))


def cross_process_collectives_supported(mesh: Mesh) -> Optional[str]:
    """None when the backend can run computations over ``mesh``'s full
    device set; otherwise the backend's capability error string (the
    precise-skip sentinel the multiprocess tests print as ``MPSKIP``).

    Single-process meshes trivially pass. Multi-process, every process
    must call this together (it compiles+runs one tiny SPMD program —
    the cheapest thing that exercises the cross-process dispatch path).
    Only the known capability refusal is swallowed; any other failure
    is a real bug and propagates."""
    if int(jax.process_count()) == 1:
        return None
    import jax.numpy as jnp

    try:
        out = jax.jit(
            lambda: jnp.zeros((int(mesh.devices.size),), jnp.float32),
            out_shardings=NamedSharding(mesh, P(mesh.axis_names[0])),
        )()
        jax.block_until_ready(out)
        return None
    except (RuntimeError, ValueError, NotImplementedError) as e:
        if "Multiprocess computations aren't implemented" in str(e):
            return str(e).splitlines()[-1]
        raise


def shard_feature_state(
    state: FeatureState, mesh: Mesh, axis: "str | tuple[str, ...]" = "data"
) -> FeatureState:
    """Place window tables sharded along the slot axis; CMS sharded by
    customer owner.

    The sketch gets a leading device axis ([n_dev, ND, depth, width]):
    rows are partitioned by ``customer_id % n_dev``, so each device keeps
    a private sketch of ITS customers — updates and queries are purely
    device-local (zero collectives on the hot path) and each sketch sees
    ~1/n_dev of the key universe, so collisions (the CMS error term)
    shrink as the mesh grows. A rank-base sketch (single-chip layout,
    e.g. a restored single-chip checkpoint) is broadcast to every device
    as a warm start — estimates stay valid upper bounds.

    ``axis`` may be one mesh axis name or a tuple (hybrid DCN×ICI meshes,
    see :mod:`.distributed`)."""
    row_sharded = NamedSharding(mesh, P(axis, None))
    dev_sharded = NamedSharding(mesh, P(axis))
    n_dev = int(mesh.devices.size)

    def place_windows(ws):
        return jax.tree.map(lambda a: jax.device_put(a, row_sharded), ws)

    def place_sketch(cms):
        if cms is None:
            return None
        if cms.slice_day.ndim == 1:  # single-chip layout: add device axis
            # Build the per-device replicas shard-by-shard: each device
            # materializes ONE [1, ...] copy of the base sketch — never
            # n_dev copies on a single device (a production sketch is
            # hundreds of MB; broadcasting would OOM exactly when the
            # feature matters).
            def _expand(leaf):
                base = np.asarray(leaf)[None]
                return jax.make_array_from_callback(
                    (n_dev,) + leaf.shape, dev_sharded,
                    lambda idx, b=base: b,
                )

            return jax.tree.map(_expand, cms)
        return jax.tree.map(lambda a: jax.device_put(a, dev_sharded), cms)

    def place_dir(kd, name: str):
        if kd is None:
            return None
        # Per-shard key directories are built stacked ([n_shards, ...]
        # leaves, init_feature_state(n_shards=...)): shapes are
        # layout-carrying, so a width mismatch is detectable here —
        # unlike the window tables, whose permutations are
        # shape-identical.
        if np.ndim(kd.keys) == 1 and n_dev == 1:
            # degenerate mesh: a single-chip directory IS the one
            # shard's directory — adopt it under the stacked layout
            # (shard_map wants the leading shard axis even at width 1)
            kd = jax.tree.map(lambda a: jax.numpy.asarray(a)[None], kd)
        lead = int(np.shape(kd.keys)[0]) if np.ndim(kd.keys) == 2 else 1
        if np.ndim(kd.keys) != 2 or lead != n_dev:
            raise ValueError(
                f"{name} is laid out for {lead} shard(s), mesh has "
                f"{n_dev} — build the state with init_feature_state("
                "n_shards=mesh width) or convert via "
                "reshard_feature_state (pass feature_state_n_old to the "
                "engine)")
        return jax.tree.map(lambda a: jax.device_put(a, dev_sharded), kd)

    return FeatureState(
        customer=place_windows(state.customer),
        terminal=place_windows(state.terminal),
        cms=place_sketch(state.cms),
        customer_dir=place_dir(state.customer_dir, "customer_dir"),
        terminal_dir=place_dir(state.terminal_dir, "terminal_dir"),
        terminal_cms=place_sketch(state.terminal_cms),
    )


def _layout_perm(cap: int, n_dev: int) -> np.ndarray:
    """Global table row of key k under the n-device owner layout.

    Single-chip (n=1): row = k. Sharded: device ``k % n`` owns contiguous
    rows ``[owner * cap/n, (owner+1) * cap/n)`` and places k at local slot
    ``k // n`` (``parallel/step.py``'s ``(key // n) & (cap_local - 1)``,
    a no-op mask for k < cap) — so row = (k % n) * (cap/n) + k // n.
    A bijection for pow2 cap/n, which the sharded step validates."""
    k = np.arange(cap)
    if n_dev == 1:
        return k
    return (k % n_dev) * (cap // n_dev) + k // n_dev


def reshard_feature_state(
    state: FeatureState, cfg, n_old: int, n_new: int
) -> FeatureState:
    """Elastic re-layout of the window feature state between device
    counts — the :func:`..parallel.sequence_step.reshard_history_state`
    analogue for the flagship state (SURVEY §5.3 elastic recovery).

    In ``direct`` key mode the slot maps are bijections, so converting a
    single-chip checkpoint into an 8-way layout (or n→m after a topology
    change) is EXACT for the customer/terminal window tables: restore,
    reshard, and serving continues as if the stream had always run at the
    new width. ``exact`` key mode delegates to :func:`_reshard_exact`
    (directory entries re-homed by ``key % n_new``, bit-exact for every
    admitted key). Layouts are positional, so the CALLER states ``n_old``
    (the checkpoint's device count; shapes alone cannot distinguish
    layouts). Returns host-side arrays; place them with
    :func:`shard_feature_state` (or use directly at ``n_new == 1``).

    The CMS is approximate by nature and its conversion preserves the
    upper-bound guarantee rather than exactness: sharded→single merges
    per-slice with the NEWEST day stamp winning (quiet shards whose ring
    lags contribute zero for days they provably never saw — lag-tolerant
    and exact-preserving), which over-counts any replicated warm-start
    base — still a valid CMS upper bound, noted here because it is the
    one non-exact leg. The returned CMS always carries the SINGLE-chip
    layout: :func:`shard_feature_state` expands it per-device at
    placement time (shard-by-shard, so a production-size sketch is never
    replicated n× in host RAM).
    """
    fcfg = cfg.features
    if fcfg.key_mode == "exact":
        return _reshard_exact(state, fcfg, n_old, n_new)
    if fcfg.key_mode != "direct":
        raise ValueError(
            "elastic re-shard requires key_mode='direct' or 'exact' "
            "(hash mode merges colliding keys — a permutation cannot "
            "un-merge them)")
    for n in (n_old, n_new):
        if n < 1:
            raise ValueError(f"device counts must be >= 1, got {n}")
        for name, cap in (("customer", fcfg.customer_capacity),
                          ("terminal", fcfg.terminal_capacity)):
            if cap % n:
                raise ValueError(
                    f"{name}_capacity {cap} must divide by {n}")
            local = cap // n
            if local & (local - 1):
                raise ValueError(
                    f"{name}_capacity / {n} must be a power of two, "
                    f"got {local}")

    def convert(ws, cap: int):
        p_old = _layout_perm(cap, n_old)
        p_new = _layout_perm(cap, n_new)

        def re(leaf):
            a = np.asarray(leaf)
            if a.shape[0] != cap:
                raise ValueError(
                    f"state table has {a.shape[0]} rows, config says "
                    f"{cap} — re-sharding a checkpoint taken under a "
                    "different capacity would merge or drop keys")
            out = np.empty_like(a)
            out[p_new] = a[p_old]
            return out

        return jax.tree.map(re, ws)

    cms = _merge_sketch(state.cms, n_old)

    # _replace: the tiered-store fields are None on every DIRECT-mode
    # state (exact mode branched into _reshard_exact above); keep the
    # passthrough so the structure survives whatever is attached
    return state._replace(
        customer=convert(state.customer, fcfg.customer_capacity),
        terminal=convert(state.terminal, fcfg.terminal_capacity),
        cms=cms,
    )


def _merge_sketch(cms, n_old: int):
    """Sharded sketch replicas → ONE single-layout sketch (host-side).

    fraud is Optional (None on every pre-tiering config): merge only the
    tables that exist, keep None as None. The returned sketch always
    carries the SINGLE-chip layout: :func:`shard_feature_state` expands
    it per-device at placement time (shard-by-shard, never n_new host
    copies of a production-size sketch — the OOM its ``_expand`` branch
    exists to avoid).

    Warm-start caveat (pre-existing, restated because exact mode now
    SERVES sketch-tier features): expansion replicates the merged
    sketch to every device so per-device estimates stay upper bounds
    for every key; a LATER merge then sums those n warm-start copies
    plus deltas, so repeated merge→expand cycles inflate pre-cycle
    counts by up to n× per cycle. Still a valid upper bound (the CMS
    contract), and the ring bounds it in time — an inflated slice
    rotates out after ``n_day_buckets`` days of traffic. Dropping the
    replication would break the bound (a key would query an empty
    replica after re-homing), so the inflation is the documented cost
    of elastic reshard on the approximate tier; the dense tier — the
    serving majority — re-homes exactly."""
    if cms is None:
        return None
    leaves = [None if a is None else np.asarray(a) for a in cms]
    if n_old > 1 and leaves[0].ndim > 1:
        if leaves[0].shape[0] != n_old:
            raise ValueError(
                f"cms device axis {leaves[0].shape[0]} != n_old "
                f"{n_old}")
        # Disjoint key partitions make counts additive — but a quiet
        # shard's day ring lags (slices only advance when that device
        # sees traffic for the day). Exact-preserving merge: per
        # slice, take the NEWEST stamp and sum only devices holding
        # it (a stale slice would have been reset when that day
        # arrived there, and its device provably saw no such-day
        # traffic).
        days = leaves[0]  # [n, ND]
        max_day = days.max(axis=0)  # [ND]
        fresh = (days == max_day[None]).astype(leaves[1].dtype)
        return type(cms)(
            max_day,
            *[None if a is None
              else (a * fresh[..., None, None]).sum(axis=0)
              for a in leaves[1:]],
        )
    # already single-layout (n_old == 1, or a prior reshard's
    # deferred-expansion output where only the windows carry the
    # n_old layout)
    return type(cms)(*leaves)


def _rebuild_exact_table(name: str, ctx: str, ws_type, kd_type,
                         keys: np.ndarray, vals: dict,
                         cap: int, n_new: int, n_probes: int):
    """Rebuild one (window table, key directory) pair in the
    ``n_new``-shard layout from extracted live entries — the shared tail
    of :func:`_reshard_exact` (elastic N→M) and
    :func:`merge_process_states` (per-process fleets → one state), so
    the slot discipline cannot diverge between them.

    ``keys`` [K] uint32 (must be unique — ownership means a key lives in
    exactly one source shard/process); ``vals`` maps window-leaf name →
    its [K, ...] gathered rows. Owner = ``key % n_new``, slot ids within
    a shard are assigned in sorted-key order (deterministic: two rebuilds
    of the same entries are byte-identical), directories are rebuilt
    with the same double-hash probe discipline ``admit_slots`` uses at
    serve time. Loud failures, never silent state loss; ``ctx`` names
    the operation in every error."""
    from real_time_fraud_detection_system_tpu.ops.keydir import (
        EMPTY_KEY,
        _probe_positions,
    )
    import jax.numpy as jnp

    cap_local_new = cap // n_new
    owner = (keys % np.uint32(n_new)).astype(np.int64)
    order = np.lexsort((keys, owner))
    owner_s, keys_s = owner[order], keys[order]
    if len(keys_s) > 1 and (keys_s[:-1] == keys_s[1:]).any():
        dup = keys_s[:-1][keys_s[:-1] == keys_s[1:]][:4]
        raise ValueError(
            f"{ctx}: duplicate {name} key(s) {dup.tolist()} across "
            "source shards — the ownership contract places each key in "
            "exactly one shard/process, so a duplicate means two "
            "engines served the same key (partition-affinity breach); "
            "merging would corrupt its window history")
    counts = np.bincount(owner_s, minlength=n_new)
    if counts.max(initial=0) > cap_local_new:
        worst = int(np.argmax(counts))
        raise ValueError(
            f"{ctx}: new shard {worst} would own "
            f"{int(counts[worst])} live {name} keys but holds only "
            f"{cap_local_new} slots — run compaction before shrinking "
            "the mesh, or keep more shards")
    rank = (np.arange(len(owner_s))
            - np.concatenate(([0], np.cumsum(counts)))[owner_s])
    new_rows = owner_s * cap_local_new + rank
    # ---- move the window rows (bit-exact copies) ------------------------
    fills = {"bucket_day": -1, "count": 0.0, "amount": 0.0, "fraud": 0.0}

    def rehome(leaf_name):
        src = np.asarray(vals[leaf_name])[order]
        fresh = np.full((cap,) + src.shape[1:], fills[leaf_name],
                        dtype=src.dtype)
        fresh[new_rows] = src
        return fresh

    ws_new = ws_type(**{k: rehome(k) for k in fills})
    # ---- rebuild the per-shard directories ------------------------------
    dir_cap_new = 2 * cap_local_new
    nkeys = np.full((n_new, dir_cap_new), EMPTY_KEY, np.uint32)
    nslots = np.full((n_new, dir_cap_new), -1, np.int32)
    pos = np.asarray(_probe_positions(
        jnp.asarray(keys_s), dir_cap_new, n_probes))  # [K, P]
    flat_keys = nkeys.reshape(-1)
    flat_slots = nslots.reshape(-1)
    placed = np.zeros(len(keys_s), dtype=bool)
    for j in range(n_probes):
        active = ~placed
        if not active.any():
            break
        gpos = owner_s * dir_cap_new + pos[:, j]
        want = active & (flat_keys[gpos] == EMPTY_KEY)
        # scatter-min claim rounds, the np mirror of admit_slots: among
        # same-position racers the smallest key wins (keys are unique
        # per shard, so every key wins exactly one round)
        np.minimum.at(flat_keys, gpos[want], keys_s[want])
        won = want & (flat_keys[gpos] == keys_s)
        flat_slots[gpos[won]] = rank[won].astype(np.int32)
        placed |= won
    if not placed.all():
        miss = int((~placed).sum())
        raise ValueError(
            f"{ctx}: {miss} {name} key(s) could not place within "
            f"{n_probes} probes of the rebuilt directory — raise "
            "keydir_probes or grow the hot tier (admitted-key state "
            "must survive a rebuild bit-exactly, so dropping them is "
            "not an option)")
    free = np.broadcast_to(
        np.arange(cap_local_new - 1, -1, -1, dtype=np.int32),
        (n_new, cap_local_new)).copy()
    kd_new_leaves = dict(
        keys=nkeys, slots=nslots, free=free,
        free_top=(cap_local_new - counts).astype(np.int32))
    if n_new == 1:
        kd_new_leaves = {
            k: (v[0] if k != "free_top" else np.int32(v[0]))
            for k, v in kd_new_leaves.items()}
    return ws_new, kd_type(**kd_new_leaves)


def _extract_exact_table(name: str, ws, kd, n_old: int, cap: int):
    """Live (key, window-row) pairs of one exact-mode table: keys [K],
    vals (leaf name → gathered [K, ...] rows). The extraction half
    shared by reshard and merge."""
    keys = np.asarray(kd.keys)
    slots = np.asarray(kd.slots)
    if keys.ndim == 1:
        keys, slots = keys[None], slots[None]
    if keys.shape[0] != n_old:
        raise ValueError(
            f"{name}_dir is laid out for {keys.shape[0]} shard(s), "
            f"caller says n_old={n_old}")
    bd = np.asarray(ws.bucket_day)
    if bd.shape[0] != cap:
        raise ValueError(
            f"state table has {bd.shape[0]} rows, config says "
            f"{cap} — re-sharding a checkpoint taken under a "
            "different capacity would merge or drop keys")
    cap_local_old = cap // n_old
    shard_idx, entry_idx = np.nonzero(slots >= 0)
    lkeys = keys[shard_idx, entry_idx]
    old_rows = (shard_idx * cap_local_old
                + slots[shard_idx, entry_idx].astype(np.int64))
    vals = {k: np.asarray(getattr(ws, k))[old_rows]
            for k in ("bucket_day", "count", "amount", "fraud")}
    return lkeys, vals


def _reshard_exact(state: FeatureState, fcfg, n_old: int, n_new: int,
                   owner_filter=None) -> FeatureState:
    """Elastic N→M re-home of the TIERED exact state (directories +
    windows + sketches) with bit-exact admitted-key state.

    Unlike direct mode (a fixed layout permutation), exact-mode slot
    placement is dynamic: each shard's directory granted slots in
    admission order. Re-homing therefore works at the (key, window-row)
    level: every live directory entry is extracted, its key's new owner
    is ``key % n_new`` (the SAME modulo the step's owner exchange
    routes by), its window row moves to the new owner's block, and each
    new shard's directory is rebuilt with the same double-hash probe
    discipline ``admit_slots`` uses at serve time. Slot ids within a
    shard are assigned in sorted-key order — deterministic, so two
    reshards of the same checkpoint are byte-identical. Sketches merge
    via the newest-day rule (:func:`_merge_sketch`) and re-expand at
    placement.

    Loud failures, never silent state loss: a new shard whose key set
    exceeds its local slot capacity (ownership skew after shrinking the
    mesh — possible because total occupancy ≤ capacity does not bound
    any single residue class) and a key that cannot place within
    ``keydir_probes`` probes both raise, with the fix named.

    ``owner_filter`` (keys → bool mask): keep only these keys' state —
    the process-adoption path (:func:`adopt_process_slice`): a
    single-process global checkpoint restored into a P-process fleet
    keeps, per process, exactly the residue block it owns.
    """
    n_probes = fcfg.keydir_probes
    ctx = f"elastic reshard {n_old}→{n_new}"
    out = {}
    for name, cap, present in (
            ("customer", fcfg.customer_capacity,
             fcfg.customer_source != "cms"),
            ("terminal", fcfg.terminal_capacity, True)):
        ws = getattr(state, name)
        kd = getattr(state, f"{name}_dir")
        if not present:
            if kd is not None:
                raise ValueError(
                    f"{name}_dir present but customer_source="
                    f"{fcfg.customer_source!r} builds none — the state "
                    "does not match this config")
            out[name] = jax.tree.map(np.asarray, ws)
            out[f"{name}_dir"] = None
            continue
        if kd is None:
            raise ValueError(
                f"key_mode='exact' reshard needs the {name} key "
                "directory; this state carries none (was it built "
                "under a different key_mode?)")
        for n, who in ((n_old, "n_old"), (n_new, "n_new")):
            if n < 1 or cap % n or ((cap // n) & (cap // n - 1)):
                raise ValueError(
                    f"{name}_capacity {cap} / {who}={n} must be a "
                    "power of two")
        lkeys, vals = _extract_exact_table(name, ws, kd, n_old, cap)
        if owner_filter is not None:
            keep = np.asarray(owner_filter(lkeys), dtype=bool)
            lkeys = lkeys[keep]
            vals = {k: v[keep] for k, v in vals.items()}
        out[name], out[f"{name}_dir"] = _rebuild_exact_table(
            name, ctx, type(ws), type(kd), lkeys, vals,
            cap, n_new, n_probes)
    return state._replace(
        customer=out["customer"], terminal=out["terminal"],
        cms=_merge_sketch(state.cms, n_old),
        customer_dir=out["customer_dir"],
        terminal_dir=out["terminal_dir"],
        terminal_cms=_merge_sketch(state.terminal_cms, n_old),
    )


def adopt_process_slice(state: FeatureState, cfg, n_old: int, topology
                        ) -> FeatureState:
    """A single-process GLOBAL feature state (checkpoint written by a
    1-process deployment at ``n_old`` devices) → THIS process's local
    layout — the 1→P leg of multi-host elastic topology changes,
    routed through the same exact re-home machinery as every other
    reshard.

    Exact mode keeps only the keys whose residue block this process
    owns (``topology.owns``, bit-exact for every owned admitted key;
    unowned keys simply move to their own process's adoption of the
    same checkpoint). Direct mode keeps the full tables: unowned slots
    are inert — their keys never arrive on this process, and the
    direct-mode contract (keys < capacity) means they alias nothing an
    owned key probes. Sketches merge to the single layout and stay
    whole (a CMS upper bound holds for every key, owned or not).
    Returns host-side arrays in the stacked local layout."""
    fcfg = cfg.features
    if fcfg.key_mode == "exact":
        return _reshard_exact(state, fcfg, n_old, topology.local_devices,
                              owner_filter=topology.owns)
    return reshard_feature_state(state, cfg, n_old,
                                 topology.local_devices)


def merge_process_states(states, cfg, n_locals) -> FeatureState:
    """Merge a P-process fleet's per-process feature states into ONE
    single-chip-layout global state — the P→1 leg of multi-host
    topology changes (shrink/regrow the fleet: merge every process's
    final checkpoint, then restore the merged state at the new
    topology, where :func:`adopt_process_slice` re-slices it).

    ``n_locals[i]``: process i's local device count (its state's shard
    layout). Exact mode extracts every process's live (key, window-row)
    entries — disjoint by the ownership contract, loudly verified — and
    rebuilds the global directory through the same
    :func:`_rebuild_exact_table` tail as elastic reshard. Direct mode
    combines row-wise by residue ownership (row r holds key ≡ r mod
    capacity under the direct layout, so each row's authoritative copy
    is its owner process's; requires a homogeneous fleet and
    capacity % (P·L) == 0). Hash mode cannot merge (colliding keys
    cannot be attributed to owners) and refuses, like elastic reshard.
    Sketches merge per-process then across processes under the
    newest-day rule (upper bounds preserved). Returns host arrays."""
    fcfg = cfg.features
    if not states or len(states) != len(n_locals):
        raise ValueError(
            f"merge_process_states: {len(states)} state(s) vs "
            f"{len(n_locals)} n_locals")
    n_proc = len(states)
    if n_proc == 1:
        return reshard_feature_state(states[0], cfg, n_locals[0], 1)
    if fcfg.key_mode == "hash":
        raise ValueError(
            "process merge requires key_mode='direct' or 'exact' (hash "
            "mode merges colliding keys — rows cannot be attributed to "
            "their owner process)")

    def merge_cms(getter):
        per = []
        for st, n_loc in zip(states, n_locals):
            m = _merge_sketch(getter(st), n_loc)
            if m is None:
                return None
            per.append(m)
        stacked = type(per[0])(*[
            None if any(le is None for le in leaves)
            else np.stack([np.asarray(le) for le in leaves])
            for leaves in zip(*per)])
        return _merge_sketch(stacked, n_proc)

    if fcfg.key_mode == "exact":
        out = {}
        for name, cap, present in (
                ("customer", fcfg.customer_capacity,
                 fcfg.customer_source != "cms"),
                ("terminal", fcfg.terminal_capacity, True)):
            if not present:
                # customer_source="cms": the table is dead weight (the
                # sketch serves the features) — any process's copy is as
                # good as any other's
                out[name] = jax.tree.map(
                    np.asarray, getattr(states[0], name))
                out[f"{name}_dir"] = None
                continue
            keys_all, vals_all = [], []
            ws = kd = None
            for pid, (st, n_loc) in enumerate(zip(states, n_locals)):
                ws, kd = getattr(st, name), getattr(st, f"{name}_dir")
                if kd is None:
                    raise ValueError(
                        f"process {pid}'s state carries no {name} key "
                        "directory (was it built under a different "
                        "key_mode?)")
                k, v = _extract_exact_table(name, ws, kd, n_loc, cap)
                keys_all.append(k)
                vals_all.append(v)
            keys = np.concatenate(keys_all)
            vals = {k: np.concatenate([v[k] for v in vals_all])
                    for k in vals_all[0]}
            out[name], out[f"{name}_dir"] = _rebuild_exact_table(
                name, f"process merge {n_proc}→1", type(ws), type(kd),
                keys, vals, cap, 1, fcfg.keydir_probes)
        return states[0]._replace(
            customer=out["customer"], terminal=out["terminal"],
            cms=merge_cms(lambda s: s.cms),
            customer_dir=out["customer_dir"],
            terminal_dir=out["terminal_dir"],
            terminal_cms=merge_cms(lambda s: s.terminal_cms))

    # direct mode: fixed layout permutations; merge row-wise by residue
    # ownership (row r ↔ key r under the single-chip direct layout)
    if len(set(int(n) for n in n_locals)) != 1:
        raise ValueError(
            "direct-mode process merge needs a homogeneous fleet (every "
            f"process the same local width), got n_locals={list(n_locals)}"
            " — exact mode re-homes by stored key and has no such limit")
    n_local = int(n_locals[0])
    n_total = n_proc * n_local
    singles = [reshard_feature_state(st, cfg, n_local, 1)
               for st in states]

    def combine(name, cap):
        if cap % n_total:
            raise ValueError(
                f"direct-mode process merge needs {name}_capacity {cap} "
                f"divisible by n_processes×local_devices = {n_total} "
                "(row residue = key residue is what attributes each row "
                "to its owner)")
        owner = (np.arange(cap) % n_total) // n_local
        ws0 = getattr(singles[0], name)

        def one(leaf_name):
            leaves = [np.asarray(getattr(getattr(s, name), leaf_name))
                      for s in singles]
            merged = np.empty_like(leaves[0])
            for p in range(n_proc):
                m = owner == p
                merged[m] = leaves[p][m]
            return merged

        return type(ws0)(**{k: one(k) for k in
                            ("bucket_day", "count", "amount", "fraud")})

    return states[0]._replace(
        customer=combine("customer", fcfg.customer_capacity),
        terminal=combine("terminal", fcfg.terminal_capacity),
        cms=merge_cms(lambda s: s.cms),
    )


def reshard_engine_state(kind: str, state, cfg, n_old: int, n_new: int,
                         stacked: bool = False):
    """Kind-dispatched elastic reshard: window feature state vs sequence
    history state — the ONE conversion path every engine entry point
    uses, so the semantics cannot diverge between call sites.

    ``stacked``: return the ``[n, ...]`` stacked layout even at
    ``n_new == 1`` (the sharded sequence step's form; the single-chip
    engine wants the flat layout). Returns host-side arrays; callers
    place them (``shard_feature_state`` / ``shard_history_state`` or a
    plain ``jnp.asarray`` tree-map).
    """
    if kind == "sequence":
        from real_time_fraud_detection_system_tpu.parallel.sequence_step import (
            reshard_history_state,
        )

        st = reshard_history_state(state, cfg, n_new)
        if stacked and n_new == 1:
            st = jax.tree.map(lambda a: jax.numpy.asarray(a)[None], st)
        return st
    return reshard_feature_state(state, cfg, n_old, n_new)
