"""Device mesh + sharding layout.

The reference's only scale-out axes are Kafka topic partitions and Spark
``local[*]`` cores (SURVEY §2.3). Here the axis is a 1-D ``jax.sharding.Mesh``
over TPU chips: Kafka partition p maps to mesh position p (DCN carries the
consumer traffic to hosts; ICI carries the in-step collectives).

Sharding layout:
- batch rows: sharded along axis 0 ("data") — each device scores the rows
  of its partitions;
- customer window state: sharded along the slot axis — rows arrive
  partitioned by customer key, so a device's rows only touch its own shard
  (no collective needed);
- terminal window state: sharded along the slot axis by terminal-key
  ownership — rows reference terminals owned by other devices, so the step
  exchanges (key, day, amount, fraud) quadruples via ``all_to_all`` on ICI,
  updates/queries on the owner, and returns features by the inverse
  exchange (see :mod:`.step`);
- model params + scaler: replicated (tiny), gradients ``psum``-reduced for
  the online-SGD path.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from real_time_fraud_detection_system_tpu.features.online import FeatureState


def compat_shard_map(f, mesh: Mesh, in_specs, out_specs):
    """``shard_map`` across jax versions, replication checks off (our
    specs declare replication explicitly; the checker predates several
    of the collectives used here)."""
    try:
        from jax import shard_map as _sm  # jax >= 0.8

        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_vma=False)
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map as _sm

        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=False)


def make_mesh(n_devices: int = 0, axis: str = "data") -> Mesh:
    devs = jax.devices()
    if n_devices == 0:
        n_devices = len(devs)
    if n_devices > len(devs):
        raise ValueError(
            f"requested {n_devices} devices, only {len(devs)} visible "
            f"(set XLA_FLAGS=--xla_force_host_platform_device_count=N for "
            f"virtual CPU devices)"
        )
    return Mesh(np.asarray(devs[:n_devices]), (axis,))


def shard_feature_state(
    state: FeatureState, mesh: Mesh, axis: "str | tuple[str, ...]" = "data"
) -> FeatureState:
    """Place window tables sharded along the slot axis; CMS sharded by
    customer owner.

    The sketch gets a leading device axis ([n_dev, ND, depth, width]):
    rows are partitioned by ``customer_id % n_dev``, so each device keeps
    a private sketch of ITS customers — updates and queries are purely
    device-local (zero collectives on the hot path) and each sketch sees
    ~1/n_dev of the key universe, so collisions (the CMS error term)
    shrink as the mesh grows. A rank-base sketch (single-chip layout,
    e.g. a restored single-chip checkpoint) is broadcast to every device
    as a warm start — estimates stay valid upper bounds.

    ``axis`` may be one mesh axis name or a tuple (hybrid DCN×ICI meshes,
    see :mod:`.distributed`)."""
    row_sharded = NamedSharding(mesh, P(axis, None))
    dev_sharded = NamedSharding(mesh, P(axis))

    def place_windows(ws):
        return jax.tree.map(lambda a: jax.device_put(a, row_sharded), ws)

    cms = state.cms
    if cms is not None:
        n_dev = int(mesh.devices.size)
        if cms.slice_day.ndim == 1:  # single-chip layout: add device axis
            # Build the per-device replicas shard-by-shard: each device
            # materializes ONE [1, ...] copy of the base sketch — never
            # n_dev copies on a single device (a production sketch is
            # hundreds of MB; broadcasting would OOM exactly when the
            # feature matters).
            def _expand(leaf):
                base = np.asarray(leaf)[None]
                return jax.make_array_from_callback(
                    (n_dev,) + leaf.shape, dev_sharded,
                    lambda idx, b=base: b,
                )

            cms = jax.tree.map(_expand, cms)
        else:
            cms = jax.tree.map(
                lambda a: jax.device_put(a, dev_sharded), cms
            )
    return FeatureState(
        customer=place_windows(state.customer),
        terminal=place_windows(state.terminal),
        cms=cms,
    )


def _layout_perm(cap: int, n_dev: int) -> np.ndarray:
    """Global table row of key k under the n-device owner layout.

    Single-chip (n=1): row = k. Sharded: device ``k % n`` owns contiguous
    rows ``[owner * cap/n, (owner+1) * cap/n)`` and places k at local slot
    ``k // n`` (``parallel/step.py``'s ``(key // n) & (cap_local - 1)``,
    a no-op mask for k < cap) — so row = (k % n) * (cap/n) + k // n.
    A bijection for pow2 cap/n, which the sharded step validates."""
    k = np.arange(cap)
    if n_dev == 1:
        return k
    return (k % n_dev) * (cap // n_dev) + k // n_dev


def reshard_feature_state(
    state: FeatureState, cfg, n_old: int, n_new: int
) -> FeatureState:
    """Elastic re-layout of the window feature state between device
    counts — the :func:`..parallel.sequence_step.reshard_history_state`
    analogue for the flagship state (SURVEY §5.3 elastic recovery).

    In ``direct`` key mode the slot maps are bijections, so converting a
    single-chip checkpoint into an 8-way layout (or n→m after a topology
    change) is EXACT for the customer/terminal window tables: restore,
    reshard, and serving continues as if the stream had always run at the
    new width. Layouts are positional, so the CALLER states ``n_old``
    (the checkpoint's device count; shapes alone cannot distinguish
    layouts). Returns host-side arrays; place them with
    :func:`shard_feature_state` (or use directly at ``n_new == 1``).

    The CMS is approximate by nature and its conversion preserves the
    upper-bound guarantee rather than exactness: sharded→single merges
    per-slice with the NEWEST day stamp winning (quiet shards whose ring
    lags contribute zero for days they provably never saw — lag-tolerant
    and exact-preserving), which over-counts any replicated warm-start
    base — still a valid CMS upper bound, noted here because it is the
    one non-exact leg. The returned CMS always carries the SINGLE-chip
    layout: :func:`shard_feature_state` expands it per-device at
    placement time (shard-by-shard, so a production-size sketch is never
    replicated n× in host RAM).
    """
    fcfg = cfg.features
    if fcfg.key_mode != "direct":
        raise ValueError("elastic re-shard requires key_mode='direct'")
    for n in (n_old, n_new):
        if n < 1:
            raise ValueError(f"device counts must be >= 1, got {n}")
        for name, cap in (("customer", fcfg.customer_capacity),
                          ("terminal", fcfg.terminal_capacity)):
            if cap % n:
                raise ValueError(
                    f"{name}_capacity {cap} must divide by {n}")
            local = cap // n
            if local & (local - 1):
                raise ValueError(
                    f"{name}_capacity / {n} must be a power of two, "
                    f"got {local}")

    def convert(ws, cap: int):
        p_old = _layout_perm(cap, n_old)
        p_new = _layout_perm(cap, n_new)

        def re(leaf):
            a = np.asarray(leaf)
            if a.shape[0] != cap:
                raise ValueError(
                    f"state table has {a.shape[0]} rows, config says "
                    f"{cap} — re-sharding a checkpoint taken under a "
                    "different capacity would merge or drop keys")
            out = np.empty_like(a)
            out[p_new] = a[p_old]
            return out

        return jax.tree.map(re, ws)

    cms = state.cms
    if cms is not None:
        # fraud is Optional (None on every pre-tiering config): merge
        # only the tables that exist, keep None as None
        leaves = [None if a is None else np.asarray(a) for a in cms]
        if n_old > 1 and leaves[0].ndim > 1:
            if leaves[0].shape[0] != n_old:
                raise ValueError(
                    f"cms device axis {leaves[0].shape[0]} != n_old "
                    f"{n_old}")
            # Disjoint key partitions make counts additive — but a quiet
            # shard's day ring lags (slices only advance when that device
            # sees traffic for the day). Exact-preserving merge: per
            # slice, take the NEWEST stamp and sum only devices holding
            # it (a stale slice would have been reset when that day
            # arrived there, and its device provably saw no such-day
            # traffic).
            days = leaves[0]  # [n, ND]
            max_day = days.max(axis=0)  # [ND]
            fresh = (days == max_day[None]).astype(leaves[1].dtype)
            single = type(cms)(
                max_day,
                *[None if a is None
                  else (a * fresh[..., None, None]).sum(axis=0)
                  for a in leaves[1:]],
            )
        else:
            # already single-layout (n_old == 1, or a prior reshard's
            # deferred-expansion output where only the windows carry the
            # n_old layout)
            single = type(cms)(*leaves)
        # n_new > 1 keeps the SINGLE layout: shard_feature_state expands
        # it per-device at placement time (shard-by-shard, never n_new
        # host copies of a production-size sketch — the OOM its _expand
        # branch exists to avoid).
        cms = single

    # _replace: the tiered-store fields (directories, terminal sketch)
    # pass through untouched — exact mode is single-chip today, so they
    # are None on every state that can reach a reshard, but dropping
    # them silently here would be a trap for the item-1 follow-up
    return state._replace(
        customer=convert(state.customer, fcfg.customer_capacity),
        terminal=convert(state.terminal, fcfg.terminal_capacity),
        cms=cms,
    )


def reshard_engine_state(kind: str, state, cfg, n_old: int, n_new: int,
                         stacked: bool = False):
    """Kind-dispatched elastic reshard: window feature state vs sequence
    history state — the ONE conversion path every engine entry point
    uses, so the semantics cannot diverge between call sites.

    ``stacked``: return the ``[n, ...]`` stacked layout even at
    ``n_new == 1`` (the sharded sequence step's form; the single-chip
    engine wants the flat layout). Returns host-side arrays; callers
    place them (``shard_feature_state`` / ``shard_history_state`` or a
    plain ``jnp.asarray`` tree-map).
    """
    if kind == "sequence":
        from real_time_fraud_detection_system_tpu.parallel.sequence_step import (
            reshard_history_state,
        )

        st = reshard_history_state(state, cfg, n_new)
        if stacked and n_new == 1:
            st = jax.tree.map(lambda a: jax.numpy.asarray(a)[None], st)
        return st
    return reshard_feature_state(state, cfg, n_old, n_new)
