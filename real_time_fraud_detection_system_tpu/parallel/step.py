"""Sharded micro-batch step: shard_map over the mesh, all_to_all on ICI.

Distribution contract (SURVEY §2.3 mapping):

- rows arrive partitioned by **customer** (Kafka partition = customer key
  mod P, one partition per device), so customer window state is updated and
  queried purely device-locally;
- **terminal** windows are owned by ``terminal_key mod n_dev``; since a
  device's rows reference foreign terminals, the step routes
  (key, day, amount, fraud) records to owners with one ``all_to_all``,
  updates/queries the owner's shard, and routes the window aggregates back
  with a second ``all_to_all`` — the ICI exchange that replaces the
  reference's shared Iceberg feature tables (``fraud_detection.py:100-123``);
- params/scaler are replicated; online-SGD gradients are ``psum``-reduced,
  so every device applies the identical update (data-parallel training,
  BASELINE.json config 4).

Everything is static-shape: the exchange buffer is [n_dev × B_local] per
field (worst case: every local row targets one owner).

``key_mode="exact"`` (the tiered feature store) keeps this exact wire
contract — ownership is still ``key % n_dev``, so the host partitioner
and the owner exchange route identically — but the slot WITHIN a shard
comes from that shard's private key directory instead of the
``(key // n_dev) & (cap_local - 1)`` modulo math: each owner resolves
its received (key, row) records through ``admit_slots`` locally,
admission misses are served from the owner's per-device sketch replica,
and per-shard [dense, cms] tier counts leave the step stacked
[n_dev, 2]. :func:`make_sharded_compact` runs the recency-compaction
pass per shard under the same ``shard_map``.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from real_time_fraud_detection_system_tpu.config import Config
from real_time_fraud_detection_system_tpu.core.batch import TxBatch
from real_time_fraud_detection_system_tpu.features.online import (
    FeatureState,
    _flags,
)
from real_time_fraud_detection_system_tpu.features.spec import N_FEATURES
from real_time_fraud_detection_system_tpu.models.scaler import Scaler, transform
from real_time_fraud_detection_system_tpu.ops.windows import (
    query_windows,
    update_windows,
)


def partition_batch_spill(
    cols: dict, n_dev: int, rows_per_shard: int
) -> "list[Tuple[dict, np.ndarray, np.ndarray]]":
    """Host-side partitioner with densely-packed hot-key spill: one or
    more [n_dev × rows_per_shard] layouts.

    Partition of a row is ``customer_id % n_dev`` — the broker's key-hash
    analogue, sticky per customer. Rows that fit their shard's budget form
    chunk 0, laid out owner-locally (``__routed__ = False``): customer
    state is touched with zero collectives. A skewed key distribution can
    put more than ``rows_per_shard`` rows on one shard; the overflow is
    **re-packed densely** across ALL shards into follow-on chunks
    (``__routed__ = True``): every device carries an equal share of the
    hot key's rows, and the step routes customers to their owner over ICI
    exactly like terminals — utilization stays ~100% instead of
    collapsing to 1/n_dev right when load spikes.

    Returns a list of (columns dict with every array length
    n_dev*rows_per_shard plus ``__valid__`` mask and ``__routed__`` flag,
    input_rows, pos): ``input_rows[j]`` is the original row index of the
    chunk's j-th occupied slot and ``pos[j]`` its position in the chunk
    layout — for re-assembling results in input order.
    """
    cust = cols["customer_id"]
    n = len(cust)
    if n_dev == 1:
        # Degenerate mesh: every row lands on the one shard in input
        # order — skip the argsort/searchsorted rank machinery (host
        # cost that buys nothing at width 1).
        part = np.zeros(n, dtype=np.int64)
        rank = np.arange(n, dtype=np.int64)
    else:
        part = (cust % n_dev).astype(np.int64)
        order = np.argsort(part, kind="stable")
        part_sorted = part[order]
        rank_sorted = (
            np.arange(n) - np.searchsorted(part_sorted, part_sorted,
                                           "left")
        )
        rank = np.empty(n, dtype=np.int64)
        rank[order] = rank_sorted
    total = n_dev * rows_per_shard

    def _mk_chunk(rows, pos, routed):
        out = {}
        for k, v in cols.items():
            buf = np.zeros(total, dtype=v.dtype)
            buf[pos] = v[rows]
            out[k] = buf
        valid = np.zeros(total, dtype=bool)
        valid[pos] = True
        out["__valid__"] = valid
        out["__routed__"] = routed
        return out, rows, pos

    fits = rank < rows_per_shard
    rows0 = np.flatnonzero(fits)
    pos0 = part[rows0] * rows_per_shard + rank[rows0]
    chunks = [_mk_chunk(rows0, pos0, False)]
    overflow = np.flatnonzero(~fits)  # original order preserved
    for s in range(0, len(overflow), total):
        rows = overflow[s : s + total]
        i = np.arange(len(rows), dtype=np.int64)
        # Row-robin across devices so even a partial final chunk spreads
        # its rows over the whole mesh.
        pos = (i % n_dev) * rows_per_shard + i // n_dev
        chunks.append(_mk_chunk(rows, pos, True))
    return chunks


def partition_batch_by_customer(
    cols: dict, n_dev: int, rows_per_shard: int
) -> Tuple[dict, np.ndarray]:
    """Single-chunk partitioner: layout rows as [n_dev × rows_per_shard].

    Returns (columns dict with every array length n_dev*rows_per_shard,
    gather_index) where ``gather_index[i]`` is the output position of input
    row i. Raises on shard overflow — callers that must survive hot keys
    use :func:`partition_batch_spill` (the sharded engine does).
    """
    chunks = partition_batch_spill(cols, n_dev, rows_per_shard)
    if len(chunks) > 1:
        raise ValueError(
            f"partition overflow: >{rows_per_shard} rows on one shard; "
            f"raise rows_per_shard, poll smaller batches, or use "
            f"partition_batch_spill"
        )
    out, rows, pos_chunk = chunks[0]
    n = len(cols["customer_id"])
    pos = np.empty(n, dtype=np.int64)
    pos[rows] = pos_chunk
    return out, pos


def _route(
    dest: jnp.ndarray,  # int32 [B] in [0, n_dev)
    valid: jnp.ndarray,  # bool [B]
    n_dev: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Compute (send_pos [B], recv layout capacity) for bucketed all_to_all.

    send_pos[i] = dest[i] * B + rank-of-i-within-its-dest-bucket. Invalid
    rows route to bucket slots but are masked by the caller.
    """
    b = dest.shape[0]
    order = jnp.argsort(dest, stable=True)
    sorted_dest = dest[order]
    rank_sorted = jnp.arange(b, dtype=jnp.int32) - jnp.searchsorted(
        sorted_dest, sorted_dest, side="left"
    ).astype(jnp.int32)
    rank = jnp.zeros(b, dtype=jnp.int32).at[order].set(rank_sorted)
    return dest * b + rank, rank


def _make_xchg(axis, n_dev: int, cap: int):
    """The bucketed all_to_all: [n_dev·cap, ...] laid out owner-major →
    same shape with bucket b holding what every peer sent to owner b.
    Its own inverse (routing results back is ``xchg(...)[send_pos]``);
    carries arbitrary trailing feature dims."""

    def xchg(x):
        rest = x.shape[1:]
        return jax.lax.all_to_all(
            x.reshape((n_dev, cap) + rest), axis, split_axis=0,
            concat_axis=0, tiled=False,
        ).reshape((n_dev * cap,) + rest)

    return xchg


def owner_route(
    dest: jnp.ndarray,  # int32 [bl] owner device per row
    valid: jnp.ndarray,  # bool [bl]
    n_dev: int,
    axis,
    bl: int,
):
    """Bucketed-``all_to_all`` primitives shared by the sequence and
    expert routed paths: → (send_pos, xchg, scatter).

    ``scatter(x)`` lays local rows into the [n_dev × bl, ...] send buffer
    at their owner bucket; ``xchg`` runs the all_to_all. Buckets are
    worst-case-sized (``bl`` per pair — any skew fits); the window path
    (``exchanged_compute``) instead runs capacity-bounded buffers with a
    skew fallback."""
    send_pos, _ = _route(dest, valid, n_dev)
    xchg = _make_xchg(axis, n_dev, bl)

    def scatter(x, fill=0):
        buf = jnp.full((n_dev * bl,) + x.shape[1:], fill, dtype=x.dtype)
        return buf.at[send_pos].set(x)

    return send_pos, xchg, scatter


def make_sharded_step(
    cfg: Config,
    predict_fn: Callable,
    loss_fn: Optional[Callable] = None,
    online_lr: float = 0.0,
    mesh: Optional[Mesh] = None,
    axis: "str | Tuple[str, ...]" = "data",
    route_customers: bool = False,
    packed: bool = False,
):
    """Build the jitted multi-chip step.

    step(feature_state, params, scaler, batch) -> (feature_state, params,
    probs, features); batch leaves are [n_dev*B_local] sharded on axis 0.

    ``packed=True`` makes the built step take ONE ``[7, n_dev*B_local]``
    int32 array (:func:`~..core.batch.pack_batch` layout) instead of a
    TxBatch pytree — a batch then crosses host→device as a single copy
    (one RPC over a remote tunnel instead of seven), and the bitcast
    unpack runs inside the jit before ``shard_map``. The serving engine
    uses this; direct callers that already hold device-side TxBatch
    leaves keep the default.

    ``axis`` may be a single mesh axis name or a tuple of names (e.g.
    ``("dcn", "ici")`` from :func:`.distributed.make_hybrid_mesh`): rows
    shard over the flattened super-axis and every collective runs over the
    pair — cross-host hops ride DCN, intra-host ICI.

    ``route_customers=False`` (the common case) assumes rows are placed on
    their customer-owner device (:func:`partition_batch_spill` chunk 0):
    customer state is touched with zero collectives. ``True`` builds the
    densely-packed spill variant: rows sit on ANY device and customers are
    routed to their owner over ICI exactly like terminals — one extra
    ``all_to_all`` round buys full-mesh utilization under hot keys.
    """
    assert mesh is not None
    n_dev = mesh.devices.size
    fcfg = cfg.features
    use_cms = fcfg.customer_source == "cms"
    exact = fcfg.key_mode == "exact"
    probes = fcfg.keydir_probes
    windows = tuple(fcfg.windows)
    nw = len(windows)
    c_cap_local = fcfg.customer_capacity // n_dev
    t_cap_local = fcfg.terminal_capacity // n_dev
    for nm, cl in (("customer", c_cap_local), ("terminal", t_cap_local)):
        # Local slot placement masks with `& (cap_local - 1)`, which is a
        # modulo only for powers of two; a non-pow2 local capacity would
        # silently alias distinct keys' window state.
        if cl <= 0 or (cl & (cl - 1)):
            raise ValueError(
                f"{nm}_capacity / n_devices must be a power of two, "
                f"got {cl}")

    def _unstack(t):
        """Shard-stacked leaves ([1, ...] local blocks under P(axis)) →
        the per-device view the single-shard ops consume."""
        return (jax.tree.map(lambda x: jnp.squeeze(x, 0), t)
                if t is not None else None)

    def _restack(t):
        return (jax.tree.map(lambda x: x[None], t)
                if t is not None else None)

    def local_step(fstate: FeatureState, params, scaler: Scaler, batch: TxBatch):
        from real_time_fraud_detection_system_tpu.ops.cms import (
            cms_query,
            cms_query_fraud,
            cms_update,
        )
        from real_time_fraud_detection_system_tpu.ops.keydir import (
            admit_slots,
        )

        bl = batch.customer_key.shape[0]
        fraud = jnp.maximum(batch.label, 0).astype(jnp.float32)

        def exchanged_compute(key, fn, state):
            """Route (key, day, amount, fraud, valid) to the key's owner
            device, run ``fn(state, key, day, amount, fraud, valid) ->
            (state', mat)`` there, and route ``mat``'s per-row aggregates
            back to the sending rows: → (state', local_mat [bl, K]).

            Wire format: ONE all_to_all carries the 5 forward fields as
            a packed [*, 5] uint32 matrix (32-bit fields travel as bit
            patterns — all_to_all is pure data movement, bitcasts are
            exact) and ONE carries the result columns back.

            Receive-buffer sizing is the multi-chip scaling lever. A
            bucketed all_to_all with per-(sender,owner) bucket capacity
            ``bl`` is always correct but hands every device an
            [n_dev × bl] buffer — per-device window scatter work then
            equals a SINGLE chip processing the whole batch, so adding
            chips stops helping (measured: the virtual-mesh curve decayed
            ~4× from width 1 → 8). Under the balanced load a uniform key
            hash delivers, each sender holds only ~bl/n_dev rows per
            owner — so the common case runs with bucket capacity
            ``2·ceil(bl/n_dev)`` (2× balanced headroom, receive buffer
            2·bl regardless of width: per-device work now SHRINKS with
            width). Skew beyond the headroom (hot terminal) is detected
            with a psum'd overflow flag — uniform across devices, so the
            ``lax.cond`` fallback to the always-correct full-capacity
            exchange takes the same branch everywhere and the collectives
            inside stay matched. Exactness is never capacity-dependent.
            """
            if n_dev == 1:
                # Width-1 mesh: every key is owner-local already; the
                # exchange machinery is pure overhead (measured as most
                # of the round-4 29% single-device tax).
                return fn(state, key, batch.day, batch.amount, fraud,
                          batch.valid)
            dest = (key % jnp.uint32(n_dev)).astype(jnp.int32)
            # Rank VALID rows only (invalid rows sort into a trailing
            # pseudo-bucket): padding never inflates a valid row's rank
            # into a spurious overflow fallback, never occupies receive
            # slots, and the compact branch's efficiency stops depending
            # on partition_batch_spill's valid-rows-first layout.
            _, rank = _route(
                jnp.where(batch.valid, dest, n_dev).astype(jnp.int32),
                batch.valid, n_dev)
            pk = jnp.stack(
                [
                    key,
                    jax.lax.bitcast_convert_type(batch.day, jnp.uint32),
                    jax.lax.bitcast_convert_type(
                        batch.amount, jnp.uint32),
                    jax.lax.bitcast_convert_type(fraud, jnp.uint32),
                    batch.valid.astype(jnp.uint32),
                ],
                axis=1,
            )

            def run(b_pair):
                def go(st):
                    # invalid rows and overflow rows (rank >= b_pair) get
                    # an out-of-bounds position: scatters DROP them (jax
                    # semantics), the back-gather clamps — harmless,
                    # because the capacity branch is only taken when no
                    # VALID row overflows and invalid rows are masked
                    # downstream
                    pos = jnp.where(
                        batch.valid & (rank < b_pair),
                        dest * b_pair + rank, n_dev * b_pair)
                    xchg = _make_xchg(axis, n_dev, b_pair)
                    r = xchg(jnp.zeros((n_dev * b_pair, 5), jnp.uint32)
                             .at[pos].set(pk))
                    st, mat = fn(
                        st,
                        r[:, 0],
                        jax.lax.bitcast_convert_type(r[:, 1], jnp.int32),
                        jax.lax.bitcast_convert_type(r[:, 2],
                                                     jnp.float32),
                        jax.lax.bitcast_convert_type(r[:, 3],
                                                     jnp.float32),
                        r[:, 4].astype(bool),
                    )
                    return st, xchg(mat)[pos]

                return go

            cap_pair = min(bl, 2 * -(-bl // n_dev))
            if cap_pair >= bl:
                return run(bl)(state)
            over = (batch.valid & (rank >= cap_pair)).any()
            over = jax.lax.psum(over.astype(jnp.int32), axis) > 0
            return jax.lax.cond(over, run(bl), run(cap_pair), state)

        # ---- customer velocity ------------------------------------------
        # Owner-local (chunk 0: rows placed by customer % n_dev) or routed
        # (dense spill chunks: rows anywhere, owner reached over ICI).
        cms = fstate.cms
        local_cms = (
            jax.tree.map(lambda x: jnp.squeeze(x, 0), cms)
            if cms is not None
            else None
        )
        if exact:
            # Tiered exact store over the mesh: ownership stays the cheap
            # stable modulo (key % n_dev — what the host partitioner and
            # the owner exchange already route by), but the slot WITHIN a
            # shard comes from that shard's private key directory. The
            # capacity-bounded exchange ships the same (key, row) wire
            # records as direct mode; each owner resolves slots locally
            # via admit_slots, and admission misses are served from the
            # owner's sketch replica — exactly the single-chip tiering,
            # one instance per shard. Tier counts accumulate OWNER-side
            # (skew is a per-shard property) and leave the step as a
            # [n_dev, 2] stack.
            c_kd = _unstack(fstate.customer_dir)
            t_kd = _unstack(fstate.terminal_dir)
            t_cms = _unstack(fstate.terminal_cms)
            zero2 = jnp.zeros(2, jnp.float32)  # [dense, cms] rows served

            def customer_fn_x(st, c_key, c_day, c_amt, c_fraud, c_valid):
                kd, customer, lcms, cnt = st
                if kd is None:
                    # customer_source="cms": sketch-only velocity (no
                    # dense customer tier, no tier accounting — matching
                    # the single-chip exact engine)
                    lcms = cms_update(lcms, c_key, c_amt, c_day, c_valid)
                    cc, ca = cms_query(lcms, c_key, c_day, windows)
                    return (kd, customer, lcms, cnt), jnp.concatenate(
                        [cc, ca], axis=1)
                kd, c_slot, c_adm = admit_slots(kd, c_key, c_valid,
                                                n_probes=probes)
                customer = update_windows(
                    customer, c_slot, c_day, c_amt, c_fraud,
                    c_valid & c_adm, track_fraud=False)
                lcms = cms_update(lcms, c_key, c_amt, c_day, c_valid)
                cc_t, ca_t, _ = query_windows(customer, c_slot, c_day,
                                              windows)
                cc_s, ca_s = cms_query(lcms, c_key, c_day, windows)
                cc = jnp.where(c_adm[:, None], cc_t, cc_s)
                ca = jnp.where(c_adm[:, None], ca_t, ca_s)
                cnt = cnt + jnp.stack([
                    jnp.sum((c_valid & c_adm).astype(jnp.float32)),
                    jnp.sum((c_valid & ~c_adm).astype(jnp.float32))])
                return (kd, customer, lcms, cnt), jnp.concatenate(
                    [cc, ca], axis=1)

            st0 = (c_kd, fstate.customer, local_cms, zero2)
            if route_customers:
                (c_kd, customer, local_cms, c_cnt), cb = exchanged_compute(
                    batch.customer_key, customer_fn_x, st0)
            else:
                (c_kd, customer, local_cms, c_cnt), cb = customer_fn_x(
                    st0, batch.customer_key, batch.day, batch.amount,
                    fraud, batch.valid)
            c_count, c_amount = cb[:, :nw], cb[:, nw:]
            cms = jax.tree.map(lambda x: x[None], local_cms)

            def terminal_fn_x(st, t_key, t_day, t_amt, t_fraud_in,
                              t_valid):
                kd, terminal, tcms, cnt = st
                kd, t_slot, t_adm = admit_slots(kd, t_key, t_valid,
                                                n_probes=probes)
                terminal = update_windows(
                    terminal, t_slot, t_day, t_amt, t_fraud_in,
                    t_valid & t_adm, track_amount=False)
                tcms = cms_update(tcms, t_key, t_amt, t_day, t_valid,
                                  fraud=t_fraud_in)
                tc_t, _, tf_t = query_windows(
                    terminal, t_slot, t_day, windows,
                    delay=fcfg.delay_days)
                tc_s, _, tf_s = cms_query_fraud(
                    tcms, t_key, t_day, windows, delay=fcfg.delay_days)
                tc = jnp.where(t_adm[:, None], tc_t, tc_s)
                tf = jnp.where(t_adm[:, None], tf_t, tf_s)
                cnt = cnt + jnp.stack([
                    jnp.sum((t_valid & t_adm).astype(jnp.float32)),
                    jnp.sum((t_valid & ~t_adm).astype(jnp.float32))])
                return (kd, terminal, tcms, cnt), jnp.concatenate(
                    [tc, tf], axis=1)

            (t_kd, terminal, t_cms, t_cnt), tb = exchanged_compute(
                batch.terminal_key, terminal_fn_x,
                (t_kd, fstate.terminal, t_cms, zero2))
            t_count_l, t_fraud_l = tb[:, :nw], tb[:, nw:]
            return _assemble_and_score(
                fstate, params, scaler, batch, fraud,
                customer, terminal, cms,
                c_count, c_amount, t_count_l, t_fraud_l,
                customer_dir=_restack(c_kd), terminal_dir=_restack(t_kd),
                terminal_cms=_restack(t_cms),
                tier=(c_cnt + t_cnt)[None])

        def customer_fn(st, c_key, c_day, c_amt, c_fraud, c_valid):
            """Owner-side customer velocity: sketch/window update + query
            on the rows this device owns; returns [*, 2·NW] aggregates."""
            local_cms, customer = st
            if local_cms is not None:
                local_cms = cms_update(local_cms, c_key, c_amt, c_day,
                                       c_valid)
            if use_cms:
                # BASELINE config 3 × config 5: unbounded-key velocity
                # from the per-device sketch (each sketch holds only this
                # device's customers — fewer collisions than one global
                # sketch).
                cc, ca = cms_query(local_cms, c_key, c_day, windows)
            else:
                c_slot = ((c_key // jnp.uint32(n_dev))
                          & jnp.uint32(c_cap_local - 1)).astype(jnp.int32)
                customer = update_windows(
                    customer, c_slot, c_day, c_amt, c_fraud, c_valid,
                    track_fraud=False,  # customer features: count+avg
                )
                cc, ca, _ = query_windows(customer, c_slot, c_day,
                                          windows)
            return (local_cms, customer), jnp.concatenate([cc, ca],
                                                          axis=1)

        if route_customers:
            (local_cms, customer), cb = exchanged_compute(
                batch.customer_key, customer_fn,
                (local_cms, fstate.customer))
        else:
            (local_cms, customer), cb = customer_fn(
                (local_cms, fstate.customer), batch.customer_key,
                batch.day, batch.amount, fraud, batch.valid)
        c_count, c_amount = cb[:, :nw], cb[:, nw:]
        if cms is not None:
            cms = jax.tree.map(lambda x: x[None], local_cms)

        # ---- terminal windows: always routed to owner over ICI ----------
        def terminal_fn(terminal, t_key, t_day, t_amt, t_fraud_in,
                        t_valid):
            t_slot = ((t_key // jnp.uint32(n_dev))
                      & jnp.uint32(t_cap_local - 1)).astype(jnp.int32)
            terminal = update_windows(
                terminal, t_slot, t_day, t_amt, t_fraud_in, t_valid,
                track_amount=False,  # terminal features: count+risk
            )
            t_count, _, t_fraud = query_windows(
                terminal, t_slot, t_day, windows, delay=fcfg.delay_days
            )
            return terminal, jnp.concatenate([t_count, t_fraud], axis=1)

        terminal, tb = exchanged_compute(
            batch.terminal_key, terminal_fn, fstate.terminal)
        t_count_l, t_fraud_l = tb[:, :nw], tb[:, nw:]
        return _assemble_and_score(
            fstate, params, scaler, batch, fraud,
            customer, terminal, cms,
            c_count, c_amount, t_count_l, t_fraud_l)

    def _assemble_and_score(fstate, params, scaler, batch, fraud,
                            customer, terminal, cms,
                            c_count, c_amount, t_count_l, t_fraud_l,
                            customer_dir=None, terminal_dir=None,
                            terminal_cms=None, tier=None):
        """Shared tail of ``local_step``: 15-feature assembly (order =
        features/spec.py), classify, optional psum'd online SGD, and the
        new-state pytree — identical math for the direct/hash and exact
        state planes, so the tiered store cannot drift the scoring
        arithmetic."""
        # ---- assemble the 15-feature matrix (order = features/spec.py)
        c_avg = jnp.where(c_count > 0, c_amount / jnp.maximum(c_count, 1.0), 0.0)
        t_risk = jnp.where(
            t_count_l > 0, t_fraud_l / jnp.maximum(t_count_l, 1.0), 0.0
        )
        is_weekend, is_night = _flags(batch, fcfg)
        cols = [batch.amount, is_weekend, is_night]
        for i in range(nw):
            cols.append(c_count[:, i])
            cols.append(c_avg[:, i])
        for i in range(nw):
            cols.append(t_count_l[:, i])
            cols.append(t_risk[:, i])
        feats = jnp.stack(cols, axis=1)

        # ---- score (+ optional online SGD with psum'd grads)
        x = transform(scaler, feats)
        probs = jnp.where(batch.valid, predict_fn(params, x), 0.0)
        if online_lr > 0.0 and loss_fn is not None:
            labeled = batch.valid & (batch.label >= 0)
            y = jnp.maximum(batch.label, 0)
            g = jax.grad(loss_fn)(params, x, y, labeled)
            g = jax.tree.map(lambda gi: jax.lax.psum(gi, axis) / n_dev, g)
            has = jnp.any(
                jax.lax.psum(labeled.astype(jnp.int32), axis) > 0
            ).astype(jnp.float32)
            params = jax.tree.map(lambda p, gi: p - online_lr * has * gi,
                                  params, g)

        new_state = FeatureState(customer=customer, terminal=terminal,
                                 cms=cms, customer_dir=customer_dir,
                                 terminal_dir=terminal_dir,
                                 terminal_cms=terminal_cms)
        if cfg.runtime.emit_dtype == "bfloat16":
            # halve the emitted matrix's D2H bytes; the classifier above
            # already consumed the f32 features (predictions unaffected)
            feats = feats.astype(jnp.bfloat16)
        if tier is not None:
            return new_state, params, probs, feats, tier
        return new_state, params, probs, feats

    from real_time_fraud_detection_system_tpu.parallel.mesh import (
        compat_shard_map,
    )

    def _shard_map(f, in_specs, out_specs):
        return compat_shard_map(f, mesh, in_specs, out_specs)

    def spec_like(tree, spec):
        return jax.tree.map(lambda _: spec, tree)

    def build(fstate_template, params_template, scaler_template, batch_template):
        from real_time_fraud_detection_system_tpu.core.batch import (
            unpack_batch,
        )

        # specs need only the pytree STRUCTURE; in packed mode the
        # caller's template is the [7, B] array, so synthesize a TxBatch
        batch_t = TxBatch(*([0] * 7)) if packed else batch_template

        def dev_stacked(t):
            # per-shard leaves with a leading device axis (directories,
            # sketch replicas): shard axis 0, one block per device
            return (spec_like(t, P(axis)) if t is not None else None)

        in_specs = (
            FeatureState(
                customer=spec_like(fstate_template.customer, P(axis, None)),
                terminal=spec_like(fstate_template.terminal, P(axis, None)),
                # Owner-sharded sketch: leading device axis (mesh.py).
                cms=dev_stacked(fstate_template.cms),
                customer_dir=dev_stacked(fstate_template.customer_dir),
                terminal_dir=dev_stacked(fstate_template.terminal_dir),
                terminal_cms=dev_stacked(fstate_template.terminal_cms),
            ),
            spec_like(params_template, P()),
            spec_like(scaler_template, P()),
            spec_like(batch_t, P(axis)),
        )
        out_specs = (
            in_specs[0],
            in_specs[1],
            P(axis),
            P(axis, None),
        ) + ((P(axis, None),) if exact else ())  # [n_dev, 2] tier rows
        fn = _shard_map(local_step, in_specs, out_specs)
        thresh = float(cfg.runtime.emit_threshold)
        selective = cfg.runtime.emit_features and thresh > 0.0
        cap_frac = cfg.runtime.emit_cap_fraction

        def outer(fstate, params, scaler, batch_in):
            batch = unpack_batch(batch_in) if packed else batch_in
            out = fn(fstate, params, scaler, batch)
            tier = out[4] if exact else None
            fstate, params, probs, feats = out[:4]
            if not selective:
                if exact:
                    return fstate, params, probs, feats, tier
                return fstate, params, probs, feats
            # Selective emission over the mesh: the same packed-transfer
            # contract as the single-chip engine (engine.py step tail) —
            # probs for every row, feature vectors compacted to flagged
            # rows, one flat f32 array per chunk. The compaction runs on
            # the GLOBAL arrays outside shard_map (XLA inserts the gather
            # collectives); indices are global chunk slots, exact in f32
            # for any chunk ≤ 2^24 slots.
            pad = batch.valid.shape[0]
            cap = max(8, int(pad * cap_frac))
            flagged = batch.valid & (probs >= thresh)
            idx = jnp.nonzero(flagged, size=cap, fill_value=0)[0]
            count = jnp.sum(flagged).astype(jnp.float32)
            packed_out = jnp.concatenate([
                probs, count[None], idx.astype(jnp.float32),
                feats[idx].reshape(-1),
            ])
            emit = {"packed": packed_out, "full": feats}
            if exact:
                return fstate, params, probs, emit, tier
            return fstate, params, probs, emit

        return jax.jit(outer, donate_argnums=(0,))

    return build


def make_sharded_compact(
    cfg: Config,
    mesh: Mesh,
    axis: "str | Tuple[str, ...]" = "data",
    demote_slots: int = 0,
):
    """Per-shard recency compaction under ``shard_map`` — the sharded
    twin of the single-chip ``("compact",)`` dispatch variant.

    ``compact(fstate, now_day) -> (fstate', reclaimed [n_dev, 2])``:
    every device runs :func:`~..features.online.compact_feature_state`
    over ITS window-table block and ITS key directory (purely local —
    zero collectives; a shard's dead slots are its own business), and
    the per-shard reclaim counts come back stacked so the engine can
    meter skew per shard. Fixed shapes throughout: one more
    ``DispatchSignature``, AOT-compiled at warmup, never a recompile.

    With ``demote_slots`` > 0 (``features.cold_store`` configured) the
    per-shard compaction also emits its demotion payload — each shard's
    oldest live keys and their exact window rows, gathered BEFORE the
    slots are vacated — stacked on a leading device axis
    (``keys [n_dev, K]``, rows ``[n_dev, K, NB]``) so the engine can
    append every shard's evictions to the host cold store. Routing is
    free: a key demoted by shard *i* re-promotes to shard *i* because
    owner-modulo placement is a pure function of the key.
    """
    from real_time_fraud_detection_system_tpu.features.online import (
        compact_feature_state,
    )
    from real_time_fraud_detection_system_tpu.parallel.mesh import (
        compat_shard_map,
    )

    fcfg = cfg.features
    has_cdir = fcfg.customer_source != "cms"

    def spec_like(tree, spec):
        return jax.tree.map(lambda _: spec, tree)

    def _payload_spec():
        # per-table (keys [n_dev, K], bd/cnt/amt/frd [n_dev, K, NB])
        leaf = (P(axis, None),) + (P(axis, None, None),) * 4
        return {
            "customer": leaf if has_cdir else None,
            "terminal": leaf,
        }

    def outer(fstate: FeatureState, now_day: jnp.ndarray):
        def local(customer, terminal, c_kd, t_kd, day):
            st = FeatureState(
                customer=customer, terminal=terminal, cms=None,
                customer_dir=jax.tree.map(lambda x: jnp.squeeze(x, 0),
                                          c_kd)
                if c_kd is not None else None,
                terminal_dir=jax.tree.map(lambda x: jnp.squeeze(x, 0),
                                          t_kd),
                terminal_cms=None,
            )
            out = compact_feature_state(st, day, fcfg,
                                        demote_slots=demote_slots)
            if demote_slots > 0:
                new, reclaimed, payload = out
            else:
                new, reclaimed = out
            parts = (
                new.customer,
                new.terminal,
                jax.tree.map(lambda x: x[None], new.customer_dir)
                if new.customer_dir is not None else None,
                jax.tree.map(lambda x: x[None], new.terminal_dir),
                reclaimed[None],  # [1, 2] → [n_dev, 2]
            )
            if demote_slots > 0:
                parts += (jax.tree.map(lambda x: x[None], payload),)
            return parts

        row = P(axis, None)
        dev = P(axis)
        in_specs = (
            spec_like(fstate.customer, row),
            spec_like(fstate.terminal, row),
            spec_like(fstate.customer_dir, dev) if has_cdir else None,
            spec_like(fstate.terminal_dir, dev),
            P(),
        )
        out_specs = in_specs[:4] + (row,)
        if demote_slots > 0:
            out_specs += (_payload_spec(),)
        fn = compat_shard_map(local, mesh, in_specs, out_specs)
        outs = fn(
            fstate.customer, fstate.terminal,
            fstate.customer_dir if has_cdir else None,
            fstate.terminal_dir, now_day)
        customer, terminal, c_kd, t_kd, reclaimed = outs[:5]
        new_state = fstate._replace(
            customer=customer, terminal=terminal,
            customer_dir=c_kd if has_cdir else fstate.customer_dir,
            terminal_dir=t_kd)
        if demote_slots > 0:
            return new_state, reclaimed, outs[5]
        return new_state, reclaimed

    return jax.jit(outer, donate_argnums=(0,))


def make_sharded_promote(
    cfg: Config,
    mesh: Mesh,
    axis: "str | Tuple[str, ...]" = "data",
):
    """Per-shard cold-tier promotion under ``shard_map`` — the sharded
    twin of the single-chip ``("promote",)`` dispatch variant.

    ``promote(fstate, payload) -> (fstate', stats [n_dev, 2, 2])``: the
    engine groups promoted keys host-side by owner shard (the same
    ``key % n_shards`` modulo the ingest router uses) and pads each
    shard's block to the fixed ``K`` with ``EMPTY_KEY``, so every device
    runs :func:`~..features.online.promote_rows` over ITS block and ITS
    directory — purely local, zero collectives, one fixed shape. Stats
    come back stacked per shard ([admitted, dropped] per table) for the
    promotion counters.
    """
    from real_time_fraud_detection_system_tpu.features.online import (
        promote_rows,
    )
    from real_time_fraud_detection_system_tpu.parallel.mesh import (
        compat_shard_map,
    )

    fcfg = cfg.features
    has_cdir = fcfg.customer_source != "cms"

    def spec_like(tree, spec):
        return jax.tree.map(lambda _: spec, tree)

    def _payload_spec():
        leaf = (P(axis, None),) + (P(axis, None, None),) * 4
        return {
            "customer": leaf if has_cdir else None,
            "terminal": leaf,
        }

    def outer(fstate: FeatureState, payload):
        def local(customer, terminal, c_kd, t_kd, pay):
            st = FeatureState(
                customer=customer, terminal=terminal, cms=None,
                customer_dir=jax.tree.map(lambda x: jnp.squeeze(x, 0),
                                          c_kd)
                if c_kd is not None else None,
                terminal_dir=jax.tree.map(lambda x: jnp.squeeze(x, 0),
                                          t_kd),
                terminal_cms=None,
            )
            new, stats = promote_rows(
                st, jax.tree.map(lambda x: jnp.squeeze(x, 0), pay),
                fcfg)
            return (
                new.customer,
                new.terminal,
                jax.tree.map(lambda x: x[None], new.customer_dir)
                if new.customer_dir is not None else None,
                jax.tree.map(lambda x: x[None], new.terminal_dir),
                stats[None],  # [1, 2, 2] → [n_dev, 2, 2]
            )

        row = P(axis, None)
        dev = P(axis)
        in_specs = (
            spec_like(fstate.customer, row),
            spec_like(fstate.terminal, row),
            spec_like(fstate.customer_dir, dev) if has_cdir else None,
            spec_like(fstate.terminal_dir, dev),
            _payload_spec(),
        )
        out_specs = in_specs[:4] + (P(axis, None, None),)
        fn = compat_shard_map(local, mesh, in_specs, out_specs)
        customer, terminal, c_kd, t_kd, stats = fn(
            fstate.customer, fstate.terminal,
            fstate.customer_dir if has_cdir else None,
            fstate.terminal_dir, payload)
        return fstate._replace(
            customer=customer, terminal=terminal,
            customer_dir=c_kd if has_cdir else fstate.customer_dir,
            terminal_dir=t_kd), stats

    return jax.jit(outer, donate_argnums=(0,))
