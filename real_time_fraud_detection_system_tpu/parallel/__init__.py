from real_time_fraud_detection_system_tpu.parallel.mesh import (  # noqa: F401
    make_mesh,
    reshard_feature_state,
    shard_feature_state,
)
from real_time_fraud_detection_system_tpu.parallel.step import (  # noqa: F401
    make_sharded_step,
    partition_batch_by_customer,
)
from real_time_fraud_detection_system_tpu.parallel.distributed import (  # noqa: F401
    initialize_distributed,
    make_hybrid_mesh,
    mesh_axes,
    process_local_batch_slice,
)
from real_time_fraud_detection_system_tpu.parallel.tensor_parallel import (  # noqa: F401
    make_dp_tp_step,
    make_tp_mlp,
    make_tp_step,
    make_tp_transformer,
    make_tp_transformer_step,
)
from real_time_fraud_detection_system_tpu.parallel.pipeline_parallel import (  # noqa: F401
    make_pipeline,
)
from real_time_fraud_detection_system_tpu.parallel.sequence_step import (  # noqa: F401
    init_sharded_history_state,
    make_sharded_sequence_step,
    reshard_history_state,
)
from real_time_fraud_detection_system_tpu.parallel.expert_parallel import (  # noqa: F401
    init_moe,
    make_ep_apply,
    moe_apply_dense,
)
