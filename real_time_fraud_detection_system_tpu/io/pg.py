"""Live Postgres OLTP boundary (the reference's upstream of CDC).

The reference seeds and streams its OLTP store with per-row INSERT loops
(``datagen/data_gen.py:67-147``: psycopg2, ON CONFLICT upserts, one commit
+ 10 s sleep per transaction) against the DDL in ``postgres/init.sql:8-42``;
Debezium then turns those rows into the envelope stream this framework
ingests. This module is the framework-side equivalent of that boundary:

- :func:`ddl_statements` — the same schema/table layout (SERIAL keys,
  DECIMAL(10,2) amounts, REPLICA IDENTITY FULL so Debezium emits full
  before-images), generated from the typed :mod:`core.schema` tables;
- :class:`PgLive` — vectorized ``executemany`` upserts (batched, one
  commit per batch instead of per row) with an optional paced mode that
  reproduces the reference's demo drip-feed;
- pure row-conversion helpers (int64 cents/µs ↔ DECIMAL/TIMESTAMP) kept
  separate so the fidelity logic is unit-testable without a server.

psycopg2 is import-gated exactly like boto3 in :mod:`io.store`: absent in
the sandbox image, required only when a live database is actually used
(``tests/integration/test_real_postgres.py``).
"""

from __future__ import annotations

import datetime as _dt
from typing import Dict, List, Optional, Sequence

import numpy as np

_EPOCH = _dt.datetime(1970, 1, 1)


def ddl_statements(schema: str = "payment") -> List[str]:
    """Reference-compatible DDL (``postgres/init.sql:8-42``), one statement
    per list entry. REPLICA IDENTITY FULL keeps Debezium UPDATE events
    carrying full row images — the envelope codec relies on that."""
    return [
        f"CREATE SCHEMA IF NOT EXISTS {schema}",
        f"""CREATE TABLE IF NOT EXISTS {schema}.customers (
            customer_id BIGINT PRIMARY KEY,
            x_location FLOAT NOT NULL,
            y_location FLOAT NOT NULL)""",
        f"""CREATE TABLE IF NOT EXISTS {schema}.terminals (
            terminal_id BIGINT PRIMARY KEY,
            x_location FLOAT NOT NULL,
            y_location FLOAT NOT NULL)""",
        f"""CREATE TABLE IF NOT EXISTS {schema}.transactions (
            tx_id BIGINT PRIMARY KEY,
            tx_datetime TIMESTAMP NOT NULL,
            customer_id BIGINT NOT NULL,
            terminal_id BIGINT NOT NULL,
            tx_amount DECIMAL(10,2) NOT NULL)""",
        f"ALTER TABLE {schema}.customers REPLICA IDENTITY FULL",
        f"ALTER TABLE {schema}.terminals REPLICA IDENTITY FULL",
        f"ALTER TABLE {schema}.transactions REPLICA IDENTITY FULL",
    ]


def transactions_to_pg_rows(cols: Dict[str, np.ndarray]) -> List[tuple]:
    """Columnar int64 cents/µs → (tx_id, datetime, cust, term, Decimal-str).

    Amounts travel as strings ('123.45') so DECIMAL(10,2) stores the exact
    cents value — float would re-introduce the representation error the
    int64-cents design exists to avoid."""
    us = cols["tx_datetime_us"]
    return [
        (
            int(t), _EPOCH + _dt.timedelta(microseconds=int(u)),
            int(c), int(m),
            f"{int(a) // 100}.{int(a) % 100:02d}",
        )
        for t, u, c, m, a in zip(
            cols["tx_id"], us, cols["customer_id"], cols["terminal_id"],
            cols["tx_amount_cents"],
        )
    ]


def pg_rows_to_transactions(rows: Sequence[tuple]) -> Dict[str, np.ndarray]:
    """Inverse of :func:`transactions_to_pg_rows` (µs/cents exact)."""
    n = len(rows)
    out = {
        "tx_id": np.zeros(n, np.int64),
        "tx_datetime_us": np.zeros(n, np.int64),
        "customer_id": np.zeros(n, np.int64),
        "terminal_id": np.zeros(n, np.int64),
        "tx_amount_cents": np.zeros(n, np.int64),
    }
    for i, (t, ts, c, m, a) in enumerate(rows):
        out["tx_id"][i] = int(t)
        out["tx_datetime_us"][i] = (
            (ts - _EPOCH) // _dt.timedelta(microseconds=1))
        out["customer_id"][i] = int(c)
        out["terminal_id"][i] = int(m)
        # DECIMAL comes back as decimal.Decimal (or str): exact cents
        out["tx_amount_cents"][i] = round(float(a) * 100)
    return out


_UPSERT_TX = """INSERT INTO {s}.transactions
    (tx_id, tx_datetime, customer_id, terminal_id, tx_amount)
    VALUES (%s, %s, %s, %s, %s)
    ON CONFLICT (tx_id) DO UPDATE SET
    tx_datetime = EXCLUDED.tx_datetime,
    customer_id = EXCLUDED.customer_id,
    terminal_id = EXCLUDED.terminal_id,
    tx_amount = EXCLUDED.tx_amount"""

_UPSERT_DIM = """INSERT INTO {s}.{table} ({key}, x_location, y_location)
    VALUES (%s, %s, %s)
    ON CONFLICT ({key}) DO UPDATE SET
    x_location = EXCLUDED.x_location,
    y_location = EXCLUDED.y_location"""


class PgLive:
    """Batched live writer/reader for the payment OLTP schema.

    ``connection`` is injectable (DB-API 2.0 duck type) for hermetic
    tests; production use passes a DSN and lets psycopg2 connect.
    """

    def __init__(self, dsn: Optional[str] = None, schema: str = "payment",
                 connection=None):
        if connection is None:
            try:
                import psycopg2
            except ImportError as e:
                raise ImportError(
                    "psycopg2 is not installed; the live-Postgres boundary "
                    "needs it (pip install psycopg2-binary), or inject a "
                    "DB-API connection."
                ) from e
            connection = psycopg2.connect(dsn)
        self.conn = connection
        self.schema = schema

    def ensure_schema(self) -> None:
        cur = self.conn.cursor()
        for stmt in ddl_statements(self.schema):
            cur.execute(stmt)
        self.conn.commit()

    def upsert_dimension(self, table: str, key: str,
                         ids: np.ndarray, x: np.ndarray,
                         y: np.ndarray) -> None:
        cur = self.conn.cursor()
        cur.executemany(
            _UPSERT_DIM.format(s=self.schema, table=table, key=key),
            [(int(i), float(a), float(b)) for i, a, b in zip(ids, x, y)],
        )
        self.conn.commit()

    def upsert_transactions(
        self,
        cols: Dict[str, np.ndarray],
        batch_rows: int = 5000,
        rate_per_s: float = 0.0,
    ) -> int:
        """Vectorized upsert; ``rate_per_s > 0`` paces row visibility like
        the reference's demo drip (one commit per batch, sleeping to hold
        the average rate — not one commit + 10 s sleep per row)."""
        import time

        rows = transactions_to_pg_rows(cols)
        cur = self.conn.cursor()
        sql = _UPSERT_TX.format(s=self.schema)
        done = 0
        for s in range(0, len(rows), batch_rows):
            chunk = rows[s:s + batch_rows]
            t0 = time.perf_counter()
            cur.executemany(sql, chunk)
            self.conn.commit()
            done += len(chunk)
            if rate_per_s > 0:
                min_wall = len(chunk) / rate_per_s
                time.sleep(max(0.0, min_wall -
                               (time.perf_counter() - t0)))
        return done

    def read_transactions(self, limit: int = 0) -> Dict[str, np.ndarray]:
        cur = self.conn.cursor()
        q = (f"SELECT tx_id, tx_datetime, customer_id, terminal_id, "
             f"tx_amount FROM {self.schema}.transactions ORDER BY tx_id")
        if limit:
            q += f" LIMIT {int(limit)}"
        cur.execute(q)
        return pg_rows_to_transactions(cur.fetchall())
