"""Model/dataset artifact persistence — pickle-free.

The reference round-trips artifacts as pickles (``trained_model.pkl`` via
boto3 upload in ``load_initial_data.py:269-287``, ``scaler.pkl`` via joblib,
daily ``data/raw/transaction/*.pkl``). Pickle executes arbitrary code at
load time; this framework stores plain ``.npz`` arrays plus a JSON header —
loadable anywhere, no code execution, and directly mmap-friendly.

Artifact format v1 — verified content
-------------------------------------
``dump_model_bytes`` stamps every artifact with a **content hash**
(sha256 over each array's key/shape/dtype/bytes plus the kind metadata)
and a format version; ``load_model_bytes`` recomputes the hash over what
it actually read and raises :class:`CorruptModelError` on any mismatch —
a bit-flipped or torn artifact can never be silently served (the same
trust-nothing-on-restore contract checkpoint format v2 gives the state
plane). v0 artifacts (pre-hash) still load — existing deployments
upgrade in place on their next save. Local-file loads quarantine the
corrupt artifact (``stale-…`` rename, bytes preserved for forensics)
before raising, mirroring the checkpoint lineage's quarantine.
"""

from __future__ import annotations

import hashlib
import json
import os
import uuid
import zipfile
from typing import Tuple

import jax.numpy as jnp
import numpy as np

from real_time_fraud_detection_system_tpu.data.generator import Transactions
from real_time_fraud_detection_system_tpu.models.forest import TreeEnsemble
from real_time_fraud_detection_system_tpu.models.logreg import LogRegParams
from real_time_fraud_detection_system_tpu.models.scaler import Scaler
from real_time_fraud_detection_system_tpu.models.train import TrainedModel

ARTIFACT_FORMAT = 1

ARTIFACT_CORRUPT_REASONS = ("checksum", "truncated")


class CorruptModelError(Exception):
    """A model artifact failed load-time verification.

    ``reason`` is ``checksum`` (bytes present but the content hash does
    not match what the writer stamped — bit-flip, tampering) or
    ``truncated`` (bytes missing/unreadable — torn write, partial PUT).
    """

    def __init__(self, reason: str, detail: str = ""):
        assert reason in ARTIFACT_CORRUPT_REASONS, reason
        self.reason = reason
        self.detail = detail
        super().__init__(f"{reason}: {detail}" if detail else reason)


def _corrupt_from_badzip(e: zipfile.BadZipFile) -> CorruptModelError:
    """One classification of the zip layer's failure modes: an entry CRC
    mismatch is bit-rot (``checksum``); anything else — bad magic, short
    central directory — is missing bytes (``truncated``)."""
    reason = "checksum" if "CRC-32" in str(e) else "truncated"
    return CorruptModelError(reason, str(e))


def _content_sha256(meta: dict, arrays: dict) -> str:
    """Content hash over everything that defines the model: the kind
    metadata (minus the hash/format fields themselves) and each array's
    key, shape, dtype and raw bytes, in sorted key order. Recomputable
    from a LOADED artifact, so verification checks what was read, not
    what the zip container claims."""
    h = hashlib.sha256()
    clean = {k: v for k, v in sorted(meta.items())
             if k not in ("content_sha256", "format")}
    h.update(json.dumps(clean, sort_keys=True,
                        separators=(",", ":")).encode())
    for k in sorted(arrays):
        a = np.ascontiguousarray(arrays[k])
        h.update(k.encode())
        h.update(str(a.shape).encode())
        h.update(str(a.dtype).encode())
        h.update(memoryview(a).cast("B"))
    return h.hexdigest()


def dump_model_bytes(model: TrainedModel) -> bytes:
    """Serialize a model to npz bytes (pickle-free)."""
    import io as _io

    arrays = {
        "scaler_mean": np.asarray(model.scaler.mean),
        "scaler_scale": np.asarray(model.scaler.scale),
    }
    meta = {"kind": model.kind}
    p = model.params
    if model.kind == "logreg":
        arrays["w"] = np.asarray(p.w)
        arrays["b"] = np.asarray(p.b)
    elif model.kind == "mlp":
        meta["n_layers"] = len(p)
        for i, (w, b) in enumerate(p):
            arrays[f"w{i}"] = np.asarray(w)
            arrays[f"b{i}"] = np.asarray(b)
    elif model.kind in ("tree", "forest", "gbt"):
        trees = p.trees if model.kind == "gbt" else p
        meta["max_depth"] = int(trees.max_depth)
        if model.kind == "gbt":
            arrays["base_score"] = np.asarray(p.base_score)
        for f in ("feat", "thresh", "left", "right", "prob"):
            arrays[f] = np.asarray(getattr(trees, f))
    elif model.kind == "autoencoder":
        meta["n_layers"] = len(p.layers)
        arrays["err_scale"] = np.asarray(p.err_scale)
        for i, (w, b) in enumerate(p.layers):
            arrays[f"w{i}"] = np.asarray(w)
            arrays[f"b{i}"] = np.asarray(b)
    elif model.kind == "sequence":
        import jax

        blk = p.blocks[0]
        meta["seq"] = {
            "d_model": int(p.embed_w.shape[1]),
            "n_in": int(p.embed_w.shape[0]),
            "n_heads": int(blk.wq.shape[1]),
            "n_layers": len(p.blocks),
            "d_ff": int(blk.w1.shape[1]),
        }
        # leaves in canonical flatten order; structure is rebuilt from an
        # init_transformer skeleton of the same dims at load
        for i, leaf in enumerate(jax.tree_util.tree_leaves(p)):
            arrays[f"seq{i}"] = np.asarray(leaf)
    else:
        raise ValueError(f"unknown model kind {model.kind}")
    meta["format"] = ARTIFACT_FORMAT
    meta["content_sha256"] = _content_sha256(meta, arrays)
    buf = _io.BytesIO()
    np.savez(buf, __meta__=json.dumps(meta), **arrays)
    return buf.getvalue()


def _split_s3_url(path: str):
    """``s3://bucket/some/key`` → ("s3://bucket/some", "key").

    Rejects bucket-only URLs: silently writing a local directory named
    ``s3:`` (which a naive rpartition would do) is worse than an error.
    """
    rest = path[len("s3://"):]
    bucket, _, key = rest.partition("/")
    if not bucket or not key or key.endswith("/"):
        # Trailing slash = empty object basename: a silent upload under
        # key "" is worse than an error.
        raise ValueError(
            f"object-store URL needs s3://<bucket>/<key>, got {path!r}"
        )
    url, _, name = path.rpartition("/")
    return url, name


def save_model(path: str, model: TrainedModel) -> None:
    """Save to a local path or an object-store URL (``s3://…``)."""
    if path.startswith("s3://"):
        from real_time_fraud_detection_system_tpu.io.store import make_store

        url, key = _split_s3_url(path)  # validate before serializing
        make_store(url).put(key, dump_model_bytes(model))
        return
    data = dump_model_bytes(model)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)


def load_model_bytes(data: bytes) -> TrainedModel:
    """Parse + verify artifact bytes. Raises :class:`CorruptModelError`
    (``truncated`` for unreadable bytes, ``checksum`` when the content
    hash a v1 writer stamped does not match what was read); v0 artifacts
    carry no hash and load trusting the zip layer's own entry CRCs."""
    import io as _io

    try:
        with np.load(_io.BytesIO(data), allow_pickle=False) as z:
            return _load_model_npz(z)
    except zipfile.BadZipFile as e:
        raise _corrupt_from_badzip(e) from None


def _count_corrupt(reason: str) -> None:
    from real_time_fraud_detection_system_tpu.utils.metrics import (
        get_registry,
    )

    get_registry().counter(
        "rtfds_model_artifact_corrupt_total",
        "model artifacts that failed load-time verification",
        reason=reason).inc()


def _quarantine_artifact(path: str) -> str:
    """Local-file quarantine (the artifact twin of the checkpoint
    lineage's ``stale-…`` stash): rename, never delete — the corrupt
    bytes are forensics."""
    d, base = os.path.split(path)
    stale = os.path.join(d, f"stale-{uuid.uuid4().hex[:8]}-{base}")
    try:
        os.replace(path, stale)
    except OSError:
        return path  # best-effort: the raise below still stops serving
    return stale


def load_model(path: str) -> TrainedModel:
    """Load from a local path or an object-store URL (``s3://…``).

    A local artifact that fails its CONTENT hash is quarantined
    (``stale-…`` rename) before :class:`CorruptModelError` propagates —
    the serving path can never keep re-loading a bit-rotted file. A
    ``truncated`` failure raises WITHOUT quarantining: it can be a torn
    read of a file an operator is shipping non-atomically over the
    served path, and renaming it away would steal the destination from
    the in-flight copy — the next reload poll retries and succeeds once
    the write completes. Both reasons are counted in
    ``rtfds_model_artifact_corrupt_total{reason=…}``."""
    if path.startswith("s3://"):
        from real_time_fraud_detection_system_tpu.io.store import make_store

        url, key = _split_s3_url(path)
        try:
            return load_model_bytes(make_store(url).get(key))
        except CorruptModelError as e:
            # no local bytes to quarantine; the registry/reload pollers
            # swallow the raise, so the counter is the operator's signal
            _count_corrupt(e.reason)
            raise
    try:
        try:
            with np.load(path, allow_pickle=False) as z:
                return _load_model_npz(z)
        except zipfile.BadZipFile as e:
            raise _corrupt_from_badzip(e) from None
    except CorruptModelError as e:
        _count_corrupt(e.reason)
        if e.reason != "checksum":
            raise
        stale = _quarantine_artifact(path)
        raise CorruptModelError(
            e.reason, f"{e.detail} (quarantined to {stale})") from None


def upload_model(store, key: str, model: TrainedModel) -> None:
    """The reference's artifact upload (``load_initial_data.py:269-287``)."""
    store.put(key, dump_model_bytes(model))


def download_model(store, key: str, default=None):
    """404-tolerant model download (``fraud_detection.py:59-82``): a
    missing artifact returns ``default`` instead of crashing — the scorer
    can start before the first training run has published a model."""
    try:
        data = store.get(key)
    except KeyError:
        return default
    try:
        return load_model_bytes(data)
    except CorruptModelError as e:
        _count_corrupt(e.reason)
        raise


def _load_model_npz(npz) -> TrainedModel:
    # Materialize + verify BEFORE building any params: the zip layer's
    # entry CRCs fire here on bit-flips, and the v1 content hash is
    # recomputed over exactly what was read.
    try:
        meta = json.loads(str(npz["__meta__"]))
        z = {k: npz[k] for k in npz.files if k != "__meta__"}
    except zipfile.BadZipFile as e:
        raise _corrupt_from_badzip(e) from None
    except (KeyError, EOFError, OSError, ValueError) as e:
        raise CorruptModelError(
            "truncated", f"{type(e).__name__}: {e}") from None
    want = meta.get("content_sha256")
    if want is not None and _content_sha256(meta, z) != want:
        raise CorruptModelError(
            "checksum", "content hash does not match the stamped "
            f"sha256 {want[:12]}…")
    kind = meta["kind"]
    scaler = Scaler(
        mean=jnp.asarray(z["scaler_mean"]), scale=jnp.asarray(z["scaler_scale"])
    )
    if kind == "logreg":
        params = LogRegParams(w=jnp.asarray(z["w"]), b=jnp.asarray(z["b"]))
    elif kind == "mlp":
        params = [
            (jnp.asarray(z[f"w{i}"]), jnp.asarray(z[f"b{i}"]))
            for i in range(meta["n_layers"])
        ]
    elif kind in ("tree", "forest", "gbt"):
        trees = TreeEnsemble(
            feat=jnp.asarray(z["feat"]),
            thresh=jnp.asarray(z["thresh"]),
            left=jnp.asarray(z["left"]),
            right=jnp.asarray(z["right"]),
            prob=jnp.asarray(z["prob"]),
            max_depth=int(meta["max_depth"]),
        )
        if kind == "gbt":
            from real_time_fraud_detection_system_tpu.models.gbt import (
                GBTModel,
            )

            params = GBTModel(
                trees=trees, base_score=jnp.asarray(z["base_score"])
            )
        else:
            params = trees
    elif kind == "autoencoder":
        from real_time_fraud_detection_system_tpu.models.autoencoder import (
            AutoencoderParams,
        )

        params = AutoencoderParams(
            layers=[
                (jnp.asarray(z[f"w{i}"]), jnp.asarray(z[f"b{i}"]))
                for i in range(meta["n_layers"])
            ],
            err_scale=jnp.asarray(z["err_scale"]),
        )
    elif kind == "sequence":
        import jax

        from real_time_fraud_detection_system_tpu.models.sequence import (
            init_transformer,
        )

        dims = meta["seq"]
        skeleton = init_transformer(
            d_model=dims["d_model"], n_heads=dims["n_heads"],
            n_layers=dims["n_layers"], d_ff=dims["d_ff"],
            n_in=dims["n_in"],
        )
        treedef = jax.tree_util.tree_structure(skeleton)
        n_leaves = treedef.num_leaves
        params = jax.tree_util.tree_unflatten(
            treedef, [jnp.asarray(z[f"seq{i}"]) for i in range(n_leaves)]
        )
    else:
        raise ValueError(f"unknown model kind {kind}")
    return TrainedModel(kind=kind, scaler=scaler, params=params)


_TX_FIELDS = (
    "tx_id", "tx_time_seconds", "tx_time_days", "customer_id",
    "terminal_id", "amount_cents", "tx_fraud", "tx_fraud_scenario",
)


def save_transactions(path: str, txs: Transactions) -> None:
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez_compressed(f, **{k: getattr(txs, k) for k in _TX_FIELDS})
    os.replace(tmp, path)


def load_transactions(path: str) -> Transactions:
    with np.load(path, allow_pickle=False) as z:
        return Transactions(*[z[k] for k in _TX_FIELDS])
