"""Model/dataset artifact persistence — pickle-free.

The reference round-trips artifacts as pickles (``trained_model.pkl`` via
boto3 upload in ``load_initial_data.py:269-287``, ``scaler.pkl`` via joblib,
daily ``data/raw/transaction/*.pkl``). Pickle executes arbitrary code at
load time; this framework stores plain ``.npz`` arrays plus a JSON header —
loadable anywhere, no code execution, and directly mmap-friendly.
"""

from __future__ import annotations

import json
import os
from typing import Tuple

import jax.numpy as jnp
import numpy as np

from real_time_fraud_detection_system_tpu.data.generator import Transactions
from real_time_fraud_detection_system_tpu.models.forest import TreeEnsemble
from real_time_fraud_detection_system_tpu.models.logreg import LogRegParams
from real_time_fraud_detection_system_tpu.models.scaler import Scaler
from real_time_fraud_detection_system_tpu.models.train import TrainedModel


def dump_model_bytes(model: TrainedModel) -> bytes:
    """Serialize a model to npz bytes (pickle-free)."""
    import io as _io

    arrays = {
        "scaler_mean": np.asarray(model.scaler.mean),
        "scaler_scale": np.asarray(model.scaler.scale),
    }
    meta = {"kind": model.kind}
    p = model.params
    if model.kind == "logreg":
        arrays["w"] = np.asarray(p.w)
        arrays["b"] = np.asarray(p.b)
    elif model.kind == "mlp":
        meta["n_layers"] = len(p)
        for i, (w, b) in enumerate(p):
            arrays[f"w{i}"] = np.asarray(w)
            arrays[f"b{i}"] = np.asarray(b)
    elif model.kind in ("tree", "forest", "gbt"):
        trees = p.trees if model.kind == "gbt" else p
        meta["max_depth"] = int(trees.max_depth)
        if model.kind == "gbt":
            arrays["base_score"] = np.asarray(p.base_score)
        for f in ("feat", "thresh", "left", "right", "prob"):
            arrays[f] = np.asarray(getattr(trees, f))
    elif model.kind == "autoencoder":
        meta["n_layers"] = len(p.layers)
        arrays["err_scale"] = np.asarray(p.err_scale)
        for i, (w, b) in enumerate(p.layers):
            arrays[f"w{i}"] = np.asarray(w)
            arrays[f"b{i}"] = np.asarray(b)
    elif model.kind == "sequence":
        import jax

        blk = p.blocks[0]
        meta["seq"] = {
            "d_model": int(p.embed_w.shape[1]),
            "n_in": int(p.embed_w.shape[0]),
            "n_heads": int(blk.wq.shape[1]),
            "n_layers": len(p.blocks),
            "d_ff": int(blk.w1.shape[1]),
        }
        # leaves in canonical flatten order; structure is rebuilt from an
        # init_transformer skeleton of the same dims at load
        for i, leaf in enumerate(jax.tree_util.tree_leaves(p)):
            arrays[f"seq{i}"] = np.asarray(leaf)
    else:
        raise ValueError(f"unknown model kind {model.kind}")
    buf = _io.BytesIO()
    np.savez(buf, __meta__=json.dumps(meta), **arrays)
    return buf.getvalue()


def _split_s3_url(path: str):
    """``s3://bucket/some/key`` → ("s3://bucket/some", "key").

    Rejects bucket-only URLs: silently writing a local directory named
    ``s3:`` (which a naive rpartition would do) is worse than an error.
    """
    rest = path[len("s3://"):]
    bucket, _, key = rest.partition("/")
    if not bucket or not key or key.endswith("/"):
        # Trailing slash = empty object basename: a silent upload under
        # key "" is worse than an error.
        raise ValueError(
            f"object-store URL needs s3://<bucket>/<key>, got {path!r}"
        )
    url, _, name = path.rpartition("/")
    return url, name


def save_model(path: str, model: TrainedModel) -> None:
    """Save to a local path or an object-store URL (``s3://…``)."""
    if path.startswith("s3://"):
        from real_time_fraud_detection_system_tpu.io.store import make_store

        url, key = _split_s3_url(path)  # validate before serializing
        make_store(url).put(key, dump_model_bytes(model))
        return
    data = dump_model_bytes(model)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)


def load_model_bytes(data: bytes) -> TrainedModel:
    import io as _io

    return _load_model_npz(np.load(_io.BytesIO(data), allow_pickle=False))


def load_model(path: str) -> TrainedModel:
    """Load from a local path or an object-store URL (``s3://…``)."""
    if path.startswith("s3://"):
        from real_time_fraud_detection_system_tpu.io.store import make_store

        url, key = _split_s3_url(path)
        return load_model_bytes(make_store(url).get(key))
    with np.load(path, allow_pickle=False) as z:
        return _load_model_npz(z)


def upload_model(store, key: str, model: TrainedModel) -> None:
    """The reference's artifact upload (``load_initial_data.py:269-287``)."""
    store.put(key, dump_model_bytes(model))


def download_model(store, key: str, default=None):
    """404-tolerant model download (``fraud_detection.py:59-82``): a
    missing artifact returns ``default`` instead of crashing — the scorer
    can start before the first training run has published a model."""
    try:
        data = store.get(key)
    except KeyError:
        return default
    return load_model_bytes(data)


def _load_model_npz(z) -> TrainedModel:
    meta = json.loads(str(z["__meta__"]))
    kind = meta["kind"]
    scaler = Scaler(
        mean=jnp.asarray(z["scaler_mean"]), scale=jnp.asarray(z["scaler_scale"])
    )
    if kind == "logreg":
        params = LogRegParams(w=jnp.asarray(z["w"]), b=jnp.asarray(z["b"]))
    elif kind == "mlp":
        params = [
            (jnp.asarray(z[f"w{i}"]), jnp.asarray(z[f"b{i}"]))
            for i in range(meta["n_layers"])
        ]
    elif kind in ("tree", "forest", "gbt"):
        trees = TreeEnsemble(
            feat=jnp.asarray(z["feat"]),
            thresh=jnp.asarray(z["thresh"]),
            left=jnp.asarray(z["left"]),
            right=jnp.asarray(z["right"]),
            prob=jnp.asarray(z["prob"]),
            max_depth=int(meta["max_depth"]),
        )
        if kind == "gbt":
            from real_time_fraud_detection_system_tpu.models.gbt import (
                GBTModel,
            )

            params = GBTModel(
                trees=trees, base_score=jnp.asarray(z["base_score"])
            )
        else:
            params = trees
    elif kind == "autoencoder":
        from real_time_fraud_detection_system_tpu.models.autoencoder import (
            AutoencoderParams,
        )

        params = AutoencoderParams(
            layers=[
                (jnp.asarray(z[f"w{i}"]), jnp.asarray(z[f"b{i}"]))
                for i in range(meta["n_layers"])
            ],
            err_scale=jnp.asarray(z["err_scale"]),
        )
    elif kind == "sequence":
        import jax

        from real_time_fraud_detection_system_tpu.models.sequence import (
            init_transformer,
        )

        dims = meta["seq"]
        skeleton = init_transformer(
            d_model=dims["d_model"], n_heads=dims["n_heads"],
            n_layers=dims["n_layers"], d_ff=dims["d_ff"],
            n_in=dims["n_in"],
        )
        treedef = jax.tree_util.tree_structure(skeleton)
        n_leaves = treedef.num_leaves
        params = jax.tree_util.tree_unflatten(
            treedef, [jnp.asarray(z[f"seq{i}"]) for i in range(n_leaves)]
        )
    else:
        raise ValueError(f"unknown model kind {kind}")
    return TrainedModel(kind=kind, scaler=scaler, params=params)


_TX_FIELDS = (
    "tx_id", "tx_time_seconds", "tx_time_days", "customer_id",
    "terminal_id", "amount_cents", "tx_fraud", "tx_fraud_scenario",
)


def save_transactions(path: str, txs: Transactions) -> None:
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez_compressed(f, **{k: getattr(txs, k) for k in _TX_FIELDS})
    os.replace(tmp, path)


def load_transactions(path: str) -> Transactions:
    with np.load(path, allow_pickle=False) as z:
        return Transactions(*[z[k] for k in _TX_FIELDS])
