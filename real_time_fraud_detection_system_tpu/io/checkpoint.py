"""Verified, atomic checkpoint/resume of the full streaming state.

The reference's recovery story is Spark's ``checkpointLocation`` (Kafka
offsets + commit log per job, ``fraud_detection.py:63``) plus pickled model
artifacts. Here ONE checkpoint captures everything the step function closes
over — (source offsets, feature-state pytree, model params, scaler, batch
counter) — written atomically (tmp file + rename / atomic object PUT) so a
crash mid-write leaves the previous checkpoint intact. Restore rebuilds the
exact pytree structure from a template, so replay resumes with identical
state (exactly-once at micro-batch granularity: offsets and state are saved
together).

Format v2 — trust nothing on restore
------------------------------------
A v1 checkpoint was trusted blindly: a torn write, a bit-flip, or a flaky
GET either killed the stream or silently resurrected bad state. v2 embeds a
**verified manifest** next to the arrays (``__manifest__`` npz entry):

- a CRC32 per logical-state leaf (the npz arrays ``fs_i``/``p_i``/``s_i``);
- a **structural fingerprint** (sha256 over every leaf's key/shape/dtype —
  the materialized feature-spec + model-shape contract a restore template
  must match);
- the writer's **incarnation token** (which process wrote this lineage);
- for **delta** checkpoints: the base entry's name and the CRC32 of the
  base's manifest — the chain link that makes a delta restorable only
  against the exact object it was built from.

``restore()`` verifies checksums and structural compatibility and, on ANY
mismatch, quarantines the corrupt checkpoint (the same ``stale-…`` stash
the fresh-start fence uses) and **falls back down the lineage** to the
newest valid entry — ``rtfds_checkpoint_corrupt_total{reason=checksum|
truncated|incompatible}`` counts why, a ``checkpoint_fallback`` flight
event records what was skipped, and the supervisor replays from the older
fence instead of dying. v1 (pre-manifest) checkpoints still restore —
existing deployments upgrade in place.

Delta checkpoints — bounded save cost
-------------------------------------
With ``full_every=K > 1``, a full snapshot is written every K saves and the
saves between carry only the leaves whose bytes changed since the previous
save (params/scaler are static between hot-reloads; feature_state churns
every batch). Restore composes newest-valid-full + the verified delta
chain and re-checksums the COMPOSED state against the tip manifest, so a
delta restore is bit-identical to a full one or it is rejected; any broken
link falls back to the last valid full. ``rtfds_checkpoint_bytes{kind=
full|delta}`` meters the save-size win.

Flaky-store hardening
---------------------
``StoreCheckpointer`` ops (PUT/GET/LIST/DELETE/HEAD) run through
:func:`~..runtime.faults.with_retries` with original-typed error
propagation and an optional per-op timeout — a flaky S3 GET retries
instead of killing the stream, and a hung one surfaces as a transient
within the timeout instead of wedging the supervisor.
"""

from __future__ import annotations

import hashlib
import io as _io
import json
import os
import threading
import time
import uuid
import zipfile
import zlib
from typing import List, Optional, Tuple

import jax
import numpy as np

from real_time_fraud_detection_system_tpu.utils.metrics import (
    active_recorder,
    get_registry,
)

CORRUPT_REASONS = ("checksum", "truncated", "incompatible")


class CorruptCheckpointError(Exception):
    """A checkpoint (or its delta chain) failed restore verification.

    ``reason`` is one of :data:`CORRUPT_REASONS`: ``checksum`` (bytes
    present but wrong — bit-flip, tampering, broken chain link),
    ``truncated`` (bytes missing/unreadable — torn write, partial PUT,
    missing base), ``incompatible`` (readable but structurally wrong for
    the restore template — config/feature-spec drift).
    """

    def __init__(self, reason: str, detail: str = ""):
        assert reason in CORRUPT_REASONS, reason
        self.reason = reason
        self.detail = detail
        super().__init__(f"{reason}: {detail}" if detail else reason)


class CheckpointTopologyError(ValueError):
    """The checkpoint is HEALTHY but was written under a different
    multi-host process topology than the restoring engine serves.

    Deliberately not a :class:`CorruptCheckpointError`: the lineage
    fallback quarantines corrupt entries and serves an older one, which
    for a topology mismatch would silently rewind a healthy fleet (every
    entry in the lineage has the same topology). Restore REFUSES
    instead, with the elastic-reshard fix in the message."""


def _observe_checkpoint(op: str, backend: str, t0: float, nbytes: int,
                        batches_done: int, kind: str = "full") -> None:
    """Shared save/restore instrumentation + the flight-record event a
    checkpoint IS (the exactly-once fence every replay reasons from)."""
    dt = time.perf_counter() - t0
    reg = get_registry()
    reg.histogram("rtfds_checkpoint_seconds",
                  "checkpoint save/restore wall time", op=op,
                  backend=backend).observe(dt)
    reg.counter("rtfds_checkpoint_ops_total", "checkpoint operations",
                op=op, backend=backend).inc()
    if nbytes:
        reg.gauge("rtfds_checkpoint_bytes",
                  "size of the last checkpoint").set(nbytes)
        reg.gauge("rtfds_checkpoint_bytes",
                  "size of the last checkpoint", kind=kind).set(nbytes)
    rec = active_recorder()
    if rec is not None:
        # NB: "kind" is the flight recorder's own record discriminator
        rec.record_event("checkpoint", op=op, batches_done=batches_done,
                         bytes=nbytes, seconds=round(dt, 6),
                         ckpt_kind=kind)


# ---------------------------------------------------------------------------
# State (de)serialization
# ---------------------------------------------------------------------------


def _state_arrays(engine_state) -> Tuple[dict, dict]:
    """Flatten an EngineState into the npz array dict + meta dict — the
    ONE place the on-disk leaf naming (``fs_i``/``p_i``/``s_i``) lives."""
    leaves_fs, _ = jax.tree_util.tree_flatten(engine_state.feature_state)
    leaves_p, _ = jax.tree_util.tree_flatten(engine_state.params)
    leaves_s, _ = jax.tree_util.tree_flatten(engine_state.scaler)
    arrays = {}
    for i, leaf in enumerate(leaves_fs):
        arrays[f"fs_{i}"] = np.asarray(leaf)
    for i, leaf in enumerate(leaves_p):
        arrays[f"p_{i}"] = np.asarray(leaf)
    for i, leaf in enumerate(leaves_s):
        arrays[f"s_{i}"] = np.asarray(leaf)
    meta = {
        "offsets": list(map(int, engine_state.offsets)),
        "batches_done": int(engine_state.batches_done),
        "rows_done": int(engine_state.rows_done),
        "n_fs": len(leaves_fs),
        "n_p": len(leaves_p),
        "n_s": len(leaves_s),
        # human/CLI leaf naming: fs_i -> pytree path, so `rtfds ckpt
        # --inspect` can attribute bytes to named state planes
        # (directories, tiers) without loading the arrays
        "fs_leaves": _fs_leaf_names(engine_state.feature_state),
        # layouts are shape-identical permutations: the writer's device
        # count must travel with the state for cross-width restores
        "layout_devices": int(
            getattr(engine_state, "layout_devices", 1) or 1),
        # multi-host: the writer's fleet topology. A per-process
        # checkpoint holds only its residue block's keys, so restore
        # refuses any topology change except the sanctioned 1→P
        # adoption (see Checkpointer._check_topology).
        "process_count": int(
            getattr(engine_state, "process_count", 1) or 1),
        "process_id": int(
            getattr(engine_state, "process_id", 0) or 0),
        # registry version the params descend from (None outside
        # continuous learning) — restore hands it back so the learning
        # loop can tell restored params from the current champion
        "model_version": getattr(engine_state, "model_version", None),
    }
    occ = _directory_occupancy(engine_state.feature_state)
    if occ:
        # per-shard hot-tier occupancy at save time (tiered exact
        # store): the state-skew signal `rtfds ckpt --inspect` surfaces
        # from the manifest alone (shapes are static per shard — only
        # the VALUES betray skew, and free_top is one int per shard)
        meta["feature_state_occupancy"] = occ
    cl = getattr(engine_state, "cold_lineage", None)
    if cl:
        # cold-tier segment lineage (io/coldstore.py): which LIVE
        # segments this checkpoint's hot state pairs with. Restore hands
        # it to ColdStore.sync_to so post-checkpoint segments are pruned
        # (replay regenerates them — exactly-once across the tier
        # boundary) and `rtfds ckpt --inspect` surfaces the cold plane
        # from the manifest alone.
        meta["cold_lineage"] = cl
    re = getattr(engine_state, "resize_epochs", None)
    if re:
        # Elastic-fleet lineage: one record per fleet resize this state
        # has lived through (generation, from/to process counts, reason,
        # per-old-owner resume floors). `rtfds ckpt --inspect` surfaces
        # the resize history from the manifest alone, and a restored
        # worker re-derives its OwnershipFloorSource floors from the
        # newest record.
        meta["resize_epochs"] = re
    return arrays, meta


def _fs_leaf_names(feature_state) -> dict:
    """``fs_i`` → dotted pytree path of the feature-state leaf."""
    try:
        flat, _ = jax.tree_util.tree_flatten_with_path(feature_state)
        return {
            f"fs_{i}": jax.tree_util.keystr(path)
            for i, (path, _leaf) in enumerate(flat)
        }
    except (TypeError, AttributeError):  # exotic pytree: names optional
        return {}


def _directory_occupancy(feature_state) -> dict:
    """Per-table, per-shard occupied hot-tier slot counts (``{} `` when
    the state carries no key directories — direct/hash/sequence)."""
    out = {}
    for table in ("customer", "terminal"):
        kd = getattr(feature_state, f"{table}_dir", None)
        if kd is None:
            continue
        tops = np.asarray(kd.free_top)
        free = np.asarray(kd.free)
        if tops.ndim == 0:  # single-chip layout
            out[table] = [int(free.shape[0]) - int(tops)]
        else:  # stacked per-shard layout
            cap_local = int(free.shape[1])
            out[table] = [cap_local - int(t) for t in tops]
    return out


def _apply_arrays(engine_state, meta: dict, arrays: dict):
    """Rebuild an EngineState template from the (composed) array dict —
    the restore tail shared by v1 files and v2 full/delta chains."""
    fs_leaves = [arrays[f"fs_{i}"] for i in range(meta["n_fs"])]
    p_leaves = [arrays[f"p_{i}"] for i in range(meta["n_p"])]
    s_leaves = [arrays[f"s_{i}"] for i in range(meta["n_s"])]
    _, fs_def = jax.tree_util.tree_flatten(engine_state.feature_state)
    _, p_def = jax.tree_util.tree_flatten(engine_state.params)
    _, s_def = jax.tree_util.tree_flatten(engine_state.scaler)
    engine_state.feature_state = jax.tree_util.tree_unflatten(
        fs_def, [jax.numpy.asarray(a) for a in fs_leaves]
    )
    engine_state.params = jax.tree_util.tree_unflatten(
        p_def, [jax.numpy.asarray(a) for a in p_leaves]
    )
    engine_state.scaler = jax.tree_util.tree_unflatten(
        s_def, [jax.numpy.asarray(a) for a in s_leaves]
    )
    engine_state.offsets = meta["offsets"]
    engine_state.batches_done = meta["batches_done"]
    engine_state.rows_done = meta["rows_done"]
    if meta.get("layout_devices") is not None:
        engine_state.layout_devices = int(meta["layout_devices"])
    # pre-layout-aware checkpoints: leave the template's value (the old
    # same-width-restore assumption)
    # Multi-host stamps reflect the WRITER (pre-multihost checkpoints
    # were single-process by construction, so the default is honest —
    # leaving a multi-process template's stamps would skip the 1→P
    # adoption the restored global state needs).
    engine_state.process_count = int(meta.get("process_count", 1) or 1)
    engine_state.process_id = int(meta.get("process_id", 0) or 0)
    if meta.get("model_version") is not None:
        engine_state.model_version = int(meta["model_version"])
    # pre-learning checkpoints carry no stamp: keep the template's value
    # (the version the fresh engine was built from), which makes a
    # champion-pointer mismatch err toward re-applying the champion
    if meta.get("cold_lineage") is not None:
        engine_state.cold_lineage = meta["cold_lineage"]
    if meta.get("resize_epochs") is not None:
        engine_state.resize_epochs = meta["resize_epochs"]
    return engine_state


def write_state_npz(fileobj, engine_state) -> None:
    """Stream an EngineState (or any object with feature_state/params/
    scaler/offsets/batches_done/rows_done) as npz into a file object.

    This is the RAW (v1-shaped) payload — no manifest — used for
    in-memory snapshots (poison-isolation probes) and object-store PUT
    bodies where the manifest is added by the checkpointer."""
    arrays, meta = _state_arrays(engine_state)
    np.savez(fileobj, __meta__=json.dumps(meta), **arrays)


def state_to_bytes(engine_state) -> bytes:
    """npz bytes of an EngineState (object-store PUT payload)."""
    buf = _io.BytesIO()
    write_state_npz(buf, engine_state)
    return buf.getvalue()


def bytes_to_state(data: bytes, engine_state):
    """Restore npz bytes into an EngineState template (same shapes);
    returns the mutated engine_state."""
    return read_state_npz(_io.BytesIO(data), engine_state)


def read_state_npz(fileobj, engine_state):
    """Restore npz from a file object into an EngineState template —
    streaming (np.load reads arrays directly; no whole-file bytes copy).
    No verification: this is the trusting raw reader (snapshots, v1)."""
    with np.load(fileobj, allow_pickle=False) as z:
        meta = json.loads(str(z["__meta__"]))
        arrays = {k: z[k] for k in z.files if k != "__meta__"
                  and k != "__manifest__"}
    return _apply_arrays(engine_state, meta, arrays)


# ---------------------------------------------------------------------------
# v2 manifest
# ---------------------------------------------------------------------------


def _crc(arr: np.ndarray) -> int:
    # buffer-protocol view, not .tobytes(): no per-leaf bytes copy on
    # the save path (feature state can be the bulk of host memory)
    return zlib.crc32(memoryview(np.ascontiguousarray(arr)).cast("B"))


def _spec_of_arrays(arrays: dict) -> dict:
    return {k: [list(np.shape(a)), str(np.asarray(a).dtype)]
            for k, a in sorted(arrays.items())}


def _fingerprint(spec: dict) -> str:
    """Structural fingerprint: sha256 over every leaf's key/shape/dtype.
    This IS the materialized config/feature-spec contract — a window
    count, capacity, model width, or dtype change all change it."""
    blob = json.dumps(spec, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()


def _template_spec(engine_state) -> dict:
    """Leaf spec of a restore template WITHOUT materializing device
    arrays to host (shape/dtype attributes only)."""
    out = {}
    for prefix, tree in (("fs", engine_state.feature_state),
                         ("p", engine_state.params),
                         ("s", engine_state.scaler)):
        for i, leaf in enumerate(jax.tree_util.tree_leaves(tree)):
            dt = getattr(leaf, "dtype", None)
            if dt is None:
                dt = np.asarray(leaf).dtype
            out[f"{prefix}_{i}"] = [list(np.shape(leaf)), str(dt)]
    return dict(sorted(out.items()))


def _parse_entry(data: bytes):
    """npz bytes → (meta, manifest|None, manifest_raw|None, arrays).

    Raises :class:`CorruptCheckpointError` with reason ``truncated`` for
    unreadable/partial bytes and ``checksum`` when the zip layer's own
    entry CRC catches a bit-flip."""
    try:
        with np.load(_io.BytesIO(data), allow_pickle=False) as z:
            files = set(z.files)
            meta = json.loads(str(z["__meta__"]))
            man_raw = (str(z["__manifest__"])
                       if "__manifest__" in files else None)
            arrays = {k: z[k] for k in files
                      if k not in ("__meta__", "__manifest__")}
    except zipfile.BadZipFile as e:
        reason = "checksum" if "CRC-32" in str(e) else "truncated"
        raise CorruptCheckpointError(reason, str(e)) from None
    except (KeyError, EOFError, OSError, ValueError) as e:
        raise CorruptCheckpointError(
            "truncated", f"{type(e).__name__}: {e}") from None
    manifest = None
    if man_raw is not None:
        try:
            manifest = json.loads(man_raw)
        except ValueError as e:
            raise CorruptCheckpointError(
                "truncated", f"manifest unparseable: {e}") from None
    return meta, manifest, man_raw, arrays


def _write_checkpoint_npz(fileobj, arrays: dict, meta: dict,
                          manifest: dict) -> None:
    """Stream the checkpoint npz into ``fileobj`` (np.savez writes one
    zip entry per array — peak memory stays one leaf, not the whole
    checkpoint)."""
    np.savez(fileobj,
             __meta__=json.dumps(meta),
             __manifest__=json.dumps(manifest, sort_keys=True,
                                     separators=(",", ":")),
             **arrays)


# ---------------------------------------------------------------------------
# Storage backends
# ---------------------------------------------------------------------------


class _LocalBackend:
    """Flat-directory file storage for the checkpoint lineage. Names are
    bare filenames; the lineage API exposes full paths."""

    kind = "local"

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def path_of(self, name: str) -> str:
        return os.path.join(self.directory, name)

    def name_of(self, path: str) -> str:
        return os.path.basename(path)

    def read(self, name: str) -> bytes:
        try:
            with open(self.path_of(name), "rb") as f:
                return f.read()
        except FileNotFoundError:
            raise KeyError(name) from None

    def write(self, name: str, data: bytes) -> None:
        self.write_via(name, lambda f: f.write(data))

    def write_via(self, name: str, writer) -> int:
        """tmp-write + atomic rename around a streaming ``writer(f)``
        callback; returns the committed byte size."""
        path = self.path_of(name)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            writer(f)
        os.replace(tmp, path)  # atomic on POSIX
        return os.path.getsize(path)

    def delete(self, name: str) -> None:
        try:
            os.remove(self.path_of(name))
        except FileNotFoundError:
            pass

    def move(self, name: str, new_name: str) -> None:
        os.replace(self.path_of(name), self.path_of(new_name))

    def exists(self, name: str) -> bool:
        return os.path.exists(self.path_of(name))

    def list_names(self) -> List[str]:
        return sorted(os.listdir(self.directory))

    def info(self, name: str) -> dict:
        try:
            st = os.stat(self.path_of(name))
            return {"size": st.st_size, "mtime": st.st_mtime}
        except OSError:
            return {"size": None, "mtime": None}

    def sweep_orphan_tmps(self) -> List[str]:
        """Crash hygiene: a crash between the tmp write and os.replace
        leaks ``ckpt-*.npz.tmp`` forever — remove them at construction
        (they are by definition not part of the committed lineage)."""
        swept = []
        for f in self.list_names():
            if f.startswith("ckpt-") and f.endswith(".tmp"):
                self.delete(f)
                swept.append(f)
        return swept


class _StoreBackend:
    """Object-store storage with flaky-store hardening: every op runs
    through ``with_retries`` (original-typed error propagation — a
    KeyError for a missing key is NOT retried) and an optional per-op
    timeout that surfaces a hung call as a transient within the budget
    instead of wedging the caller. Object PUTs are atomic, so no
    tmp+rename dance is needed."""

    kind = "store"

    def __init__(self, store, prefix: str, op_timeout_s: float = 0.0,
                 op_attempts: int = 3):
        self.store = store
        self.prefix = prefix.strip("/")
        self.op_timeout_s = float(op_timeout_s)
        self.op_attempts = max(1, int(op_attempts))

    def _retrying(self, fn):
        from real_time_fraud_detection_system_tpu.runtime.faults import (
            RetryPolicy,
            TransientError,
            with_retries,
        )

        def attempt():
            if self.op_timeout_s <= 0:
                return fn()
            box: dict = {}

            def run():
                try:
                    box["v"] = fn()
                # rtfdslint: disable=broad-exception-catch (thread-boundary transport: the op-timeout thread parks the ORIGINAL exception for the caller to re-raise through the typed retry policy)
                except BaseException as e:  # reported to the caller thread
                    box["e"] = e

            t = threading.Thread(target=run, daemon=True,
                                 name="ckpt-store-op")
            t.start()
            t.join(self.op_timeout_s)
            if t.is_alive():
                # the op keeps running in its abandoned thread — the
                # retry opens a fresh attempt rather than waiting forever
                raise TransientError(
                    f"store op timed out after {self.op_timeout_s:.1f}s")
            if "e" in box:
                raise box["e"]
            return box.get("v")

        return with_retries(
            attempt,
            RetryPolicy(max_attempts=self.op_attempts, base_delay_s=0.1,
                        multiplier=2.0, max_delay_s=2.0),
            retry_on=(TransientError, ConnectionError, TimeoutError,
                      OSError),
        )

    def path_of(self, name: str) -> str:
        return f"{self.prefix}/{name}" if self.prefix else name

    def name_of(self, path: str) -> str:
        pre = self.prefix + "/" if self.prefix else ""
        return path[len(pre):] if path.startswith(pre) else path

    def read(self, name: str) -> bytes:
        return self._retrying(lambda: self.store.get(self.path_of(name)))

    def write(self, name: str, data: bytes) -> None:
        self._retrying(lambda: self.store.put(self.path_of(name), data))

    def write_via(self, name: str, writer) -> int:
        # an object PUT needs the whole body up front, so the store
        # plane buffers; only the local plane gets true streaming
        buf = _io.BytesIO()
        writer(buf)
        data = buf.getvalue()
        self.write(name, data)
        return len(data)

    def delete(self, name: str) -> None:
        self._retrying(lambda: self.store.delete(self.path_of(name)))

    def move(self, name: str, new_name: str) -> None:
        src, dst = self.path_of(name), self.path_of(new_name)
        move = getattr(self.store, "move", None)
        if move is not None:
            self._retrying(lambda: move(src, dst))
        else:  # duck-typed store without move: copy-then-delete
            data = self._retrying(lambda: self.store.get(src))
            self._retrying(lambda: self.store.put(dst, data))
            self._retrying(lambda: self.store.delete(src))

    def exists(self, name: str) -> bool:
        return self._retrying(
            lambda: self.store.exists(self.path_of(name)))

    def list_names(self) -> List[str]:
        pre = self.prefix + "/" if self.prefix else ""
        keys = self._retrying(lambda: self.store.list(pre))
        # Flat-directory semantics (matching _LocalBackend's listdir):
        # keys nested deeper under the prefix belong to OTHER lineages
        # (e.g. a sibling job's prefix) and must not be GC'd/restored.
        return sorted(k[len(pre):] for k in keys
                      if "/" not in k[len(pre):])

    def info(self, name: str) -> dict:
        head = getattr(self.store, "head", None)
        if head is None:
            return {"size": None, "mtime": None}
        try:
            h = self._retrying(lambda: head(self.path_of(name)))
        except KeyError:
            return {"size": None, "mtime": None}
        mtime = None
        etag = str(h.get("etag", ""))
        if etag.isdigit():  # LocalStore etag = mtime_ns
            mtime = int(etag) / 1e9
        return {"size": h.get("size"), "mtime": mtime}


# ---------------------------------------------------------------------------
# Checkpointers
# ---------------------------------------------------------------------------


class _CheckpointerBase:
    """Shared lineage logic over a storage backend: v2 manifests, delta
    chains, verified restore with quarantine + fallback, chain-aware
    retention GC. Subclasses bind the backend and keep their historical
    constructor signatures."""

    def __init__(self, backend, keep: int = 3, full_every: int = 1):
        self._backend = backend
        self.keep = keep
        self.full_every = max(1, int(full_every))
        self.incarnation = uuid.uuid4().hex[:12]
        # (name, manifest_raw, manifest) of the last save THIS writer
        # made — the delta base. A fresh process always starts full.
        self._last: Optional[Tuple[str, str, dict]] = None
        self._since_full = 0
        self._manifest_cache: dict = {}

    # -- lineage API ------------------------------------------------------

    def _live_names(self) -> List[str]:
        return [
            f for f in self._backend.list_names()
            if f.startswith("ckpt-") and f.endswith(".npz")
            and ".tmp" not in f
        ]

    def list_checkpoints(self) -> list:
        """Live checkpoint paths, oldest → newest (lineage API used by
        the crash-recovery fence, ``runtime/faults._FencedCheckpointer``)."""
        return [self._backend.path_of(n) for n in self._live_names()]

    def latest(self) -> Optional[str]:
        ckpts = self.list_checkpoints()
        return ckpts[-1] if ckpts else None

    def exists(self, path: str) -> bool:
        return self._backend.exists(self._backend.name_of(path))

    def quarantine(self, paths, token: str,
                   clear_previous: bool = True) -> None:
        """Hide checkpoints from ``latest()``/GC: rename to
        ``stale-<token>-…`` (bytes preserved — forensics, not deletion).
        The fresh-start fence clears any earlier stash first so repeated
        fresh runs keep one quarantine, not a pile; the corruption path
        passes ``clear_previous=False`` so a fallback cascade never
        destroys the evidence it just stashed."""
        if clear_previous:
            for old in self._backend.list_names():
                if old.startswith("stale-") and old.endswith(".npz"):
                    self._backend.delete(old)
        for p in paths:
            name = self._backend.name_of(p)
            if self._backend.exists(name):
                self._backend.move(name, f"stale-{token}-{name}")
            self._manifest_cache.pop(name, None)
            if self._last is not None and self._last[0] == name:
                # the writer's delta base just left the lineage — the
                # next save must be a full, never a delta chained to a
                # quarantined entry
                self._last = None

    # -- save -------------------------------------------------------------

    def save(self, engine_state) -> str:
        t0 = time.perf_counter()
        arrays, meta = _state_arrays(engine_state)
        crcs = {k: _crc(a) for k, a in arrays.items()}
        spec = _spec_of_arrays(arrays)
        fp = _fingerprint(spec)
        step = meta["batches_done"]
        kind = "full"
        name = f"ckpt-{step:010d}.npz"
        stored = arrays
        base = base_crc = None
        if (self.full_every > 1 and self._last is not None
                and self._since_full + 1 < self.full_every):
            last_name, last_raw, last_man = self._last
            dname = f"ckpt-{step:010d}-delta.npz"
            if (last_man.get("fingerprint") == fp
                    and dname != last_name
                    and not self._backend.exists(dname)
                    # the base may have been quarantined/GC'd since the
                    # writer last saw it (fallback restore in the same
                    # process); chaining to a gone base would make every
                    # later delta unrestorable until the next full
                    and self._backend.exists(last_name)):
                kind = "delta"
                name = dname
                base = last_name
                base_crc = zlib.crc32(last_raw.encode())
                last_crcs = last_man.get("crcs", {})
                stored = {k: a for k, a in arrays.items()
                          if crcs[k] != last_crcs.get(k)}
        manifest = {
            "format": 2,
            "kind": kind,
            "incarnation": self.incarnation,
            "batches_done": step,
            "fingerprint": fp,
            "spec": spec,
            "crcs": crcs,
            "stored": sorted(stored),
            "base": base,
            "base_manifest_crc": base_crc,
        }
        nbytes = self._backend.write_via(
            name, lambda f: _write_checkpoint_npz(f, stored, meta,
                                                  manifest))
        man_raw = json.dumps(manifest, sort_keys=True,
                             separators=(",", ":"))
        self._last = (name, man_raw, manifest)
        self._since_full = 0 if kind == "full" else self._since_full + 1
        self._manifest_cache[name] = manifest
        self._gc()
        reg = get_registry()
        reg.gauge("rtfds_last_checkpoint_unix_seconds",
                  "wall-clock time of the last checkpoint save").set(
            time.time())
        reg.gauge("rtfds_checkpoint_lineage_depth",
                  "live checkpoints in the lineage").set(
            len(self._live_names()))
        # a fresh save supersedes any fallback restore: the durable
        # plane is healthy again (healthz drops "degraded")
        reg.gauge("rtfds_checkpoint_serving_fallback",
                  "1 while the engine serves off a fallback (non-newest) "
                  "checkpoint restore").set(0)
        _observe_checkpoint("save", self._backend.kind, t0, nbytes,
                            step, kind=kind)
        return self._backend.path_of(name)

    # -- restore ----------------------------------------------------------

    def _manifest_of(self, name: str) -> Optional[dict]:
        man = self._manifest_cache.get(name)
        if man is not None:
            return man
        try:
            _, man, _, _ = _parse_entry(self._backend.read(name))
        except (KeyError, CorruptCheckpointError):
            return None
        if man is not None:
            self._manifest_cache[name] = man
        return man

    def _resolve_chain(self, name: str, template=None) -> Tuple[dict, dict]:
        """Load + verify the checkpoint at ``name`` (following its delta
        chain) → (meta, composed arrays). Raises
        :class:`CorruptCheckpointError` on any broken invariant."""
        entries = []  # tip-first: (name, meta, manifest, arrays)
        seen = set()
        cur: Optional[str] = name
        expect_crc: Optional[int] = None
        while cur is not None:
            if cur in seen:
                raise CorruptCheckpointError(
                    "checksum", f"delta chain cycle at {cur}")
            seen.add(cur)
            try:
                data = self._backend.read(cur)
            except KeyError:
                raise CorruptCheckpointError(
                    "truncated", f"chain entry {cur} is missing") from None
            meta, man, man_raw, arrays = _parse_entry(data)
            if expect_crc is not None:
                if man_raw is None or zlib.crc32(
                        man_raw.encode()) != expect_crc:
                    raise CorruptCheckpointError(
                        "checksum",
                        f"chain link mismatch: {cur} is not the base its "
                        f"delta was built from")
            entries.append((cur, meta, man, arrays))
            if man is not None and man.get("kind") == "delta":
                base = man.get("base")
                if not base:
                    raise CorruptCheckpointError(
                        "truncated", f"delta {cur} names no base")
                expect_crc = man.get("base_manifest_crc")
                cur = base
            else:
                cur = None
        tip_name, tip_meta, tip_man, _ = entries[0]
        # compose oldest → newest: the full provides every leaf, deltas
        # overlay the leaves they stored
        composed: dict = {}
        for _, _, _, arrays in reversed(entries):
            composed.update(arrays)
        if tip_man is not None:
            crcs = tip_man.get("crcs", {})
            missing = [k for k in crcs if k not in composed]
            if missing:
                raise CorruptCheckpointError(
                    "truncated",
                    f"composed state is missing leaves {missing[:4]}")
            for k, want in crcs.items():
                if _crc(composed[k]) != int(want):
                    raise CorruptCheckpointError(
                        "checksum", f"leaf {k} fails its manifest CRC32")
        if template is not None:
            self._check_template(tip_name, tip_meta, tip_man, composed,
                                 template)
        return tip_meta, composed

    @staticmethod
    def _check_topology(name, meta, template) -> None:
        """Refuse a healthy checkpoint written under a different process
        topology (vs quarantine-and-fallback, which is for corruption).

        Allowed: identical topology (count + this process's id), and a
        single-process GLOBAL checkpoint restored by a multi-process
        fleet — the engine's elastic adoption re-slices it per process
        (``parallel.mesh.adopt_process_slice``, the same reshard
        machinery as width changes). Everything else names its fix."""
        ck_pc = int(meta.get("process_count", 1) or 1)
        ck_pid = int(meta.get("process_id", 0) or 0)
        tpl_pc = int(getattr(template, "process_count", 1) or 1)
        tpl_pid = int(getattr(template, "process_id", 0) or 0)
        if ck_pc == tpl_pc and (ck_pc == 1 or ck_pid == tpl_pid):
            if ck_pc > 1:
                # Same fleet, same process — but a per-process WIDTH
                # change moves residue blocks (ownership is
                # key % (P·L)): keys migrate BETWEEN processes, which
                # no per-process reshard can do. Refuse, naming the
                # merge path, instead of silently splitting histories.
                ck_ld = int(meta.get("layout_devices", 1) or 1)
                tpl_ld = int(getattr(template, "layout_devices", 1)
                             or 1)
                if ck_ld != tpl_ld:
                    raise CheckpointTopologyError(
                        f"{name} was written at {ck_ld} device(s) per "
                        f"process but this engine serves {tpl_ld} — in "
                        f"a {ck_pc}-process fleet that changes the "
                        "residue-block ownership (key % (P·L)), moving "
                        "keys BETWEEN processes: merge the fleet's "
                        "checkpoints to a global state (parallel.mesh."
                        "merge_process_states → save single-process) "
                        "and let the new fleet's elastic 1→N adoption "
                        "re-slice it, or relaunch at the original "
                        f"--devices {ck_ld}")
            return
        if ck_pc == 1 and tpl_pc > 1:
            return  # sanctioned 1→P adoption (engine re-slices)
        if ck_pc == tpl_pc:
            raise CheckpointTopologyError(
                f"{name} was written by process {ck_pid} of the "
                f"{ck_pc}-process fleet, but this engine is process "
                f"{tpl_pid} — each process restores its OWN residue "
                "block; point every worker at its own proc-NN "
                "checkpoint directory (the launcher does this when the "
                "checkpoint root and process ids are unchanged)")
        raise CheckpointTopologyError(
            f"{name} was written by a {ck_pc}-process fleet; this "
            f"engine serves a {tpl_pc}-process topology. A per-process "
            "checkpoint holds only its residue block's keys, so a "
            "process-count change cannot restore directly: merge every "
            "process's final checkpoint into one global state "
            "(parallel.mesh.merge_process_states), save it from a "
            "single-process engine, and let the new fleet's elastic "
            "1→N adoption re-slice it — or relaunch at the original "
            f"--num-processes {ck_pc}")

    @staticmethod
    def _check_template(name, meta, manifest, arrays, template) -> None:
        """Structural compatibility vs the restore template: leaf counts
        and shapes always; dtypes + the config/feature-spec fingerprint
        for v2 entries (v1 keeps its historical trusting shape check).

        One sanctioned shape exception: when the checkpoint's recorded
        ``layout_devices`` differs from the template engine's, the
        FEATURE-STATE leaves may legitimately carry different shapes
        (the exact store's per-shard directories are width-dependent —
        stacked ``[n, ...]`` leaves). Those leaves skip the shape
        equality (dtypes and per-leaf CRCs still hold, so corruption is
        still caught) and the engine's ``_ensure_layout`` re-homes them
        via the elastic reshard — which itself hard-fails on a genuine
        capacity mismatch, loudly, instead of this path quarantining a
        healthy cross-width checkpoint."""
        spec = _template_spec(template)
        n_fs = sum(1 for k in spec if k.startswith("fs_"))
        n_p = sum(1 for k in spec if k.startswith("p_"))
        n_s = sum(1 for k in spec if k.startswith("s_"))
        if (meta.get("n_fs"), meta.get("n_p"), meta.get("n_s")) != (
                n_fs, n_p, n_s):
            raise CorruptCheckpointError(
                "incompatible",
                f"{name}: leaf counts {meta.get('n_fs')}/{meta.get('n_p')}"
                f"/{meta.get('n_s')} vs template {n_fs}/{n_p}/{n_s}")
        cross_width = (
            meta.get("layout_devices") is not None
            and int(meta["layout_devices"]) != int(
                getattr(template, "layout_devices", 1) or 1))
        fs_names = meta.get("fs_leaves") or {}

        def width_dependent(k: str) -> bool:
            # Only the per-shard planes legitimately change shape with
            # width: key directories (stacked [n, ...] leaves) and
            # sketch replicas. Window tables are global [cap, NB] at
            # EVERY width, so a capacity mismatch there must stay an
            # 'incompatible' quarantine-and-fallback, not leak through
            # to a hard reshard crash. Writers without leaf names
            # (pre-sharded-exact) never produced width-dependent
            # shapes, so they keep the strict check.
            path = fs_names.get(k, "")
            return "_dir" in path or "cms" in path

        for k, (shape, dtype) in spec.items():
            a = arrays.get(k)
            if a is None:
                raise CorruptCheckpointError(
                    "truncated", f"{name}: leaf {k} absent")
            if list(np.shape(a)) != list(shape) and not (
                    cross_width and k.startswith("fs_")
                    and width_dependent(k)):
                raise CorruptCheckpointError(
                    "incompatible",
                    f"{name}: leaf {k} shape {list(np.shape(a))} vs "
                    f"template {list(shape)}")
            if manifest is not None and str(a.dtype) != str(dtype):
                raise CorruptCheckpointError(
                    "incompatible",
                    f"{name}: leaf {k} dtype {a.dtype} vs template "
                    f"{dtype}")

    def _note_corrupt(self, name: str, err: CorruptCheckpointError) -> None:
        reg = get_registry()
        reg.counter(
            "rtfds_checkpoint_corrupt_total",
            "checkpoints that failed restore verification, by reason",
            reason=err.reason).inc()
        rec = active_recorder()
        if rec is not None:
            rec.record_event("checkpoint_fallback", path=name,
                             reason=err.reason, detail=err.detail[:200])
        from real_time_fraud_detection_system_tpu.utils.logging import (
            get_logger,
        )

        get_logger("checkpoint").error(
            "corrupt checkpoint %s (%s: %s) — quarantining and falling "
            "back down the lineage", name, err.reason, err.detail[:200])
        self.quarantine([self._backend.path_of(name)],
                        uuid.uuid4().hex[:8], clear_previous=False)

    def restore(self, engine_state, path: Optional[str] = None):
        """Restore into an EngineState template (same model/config
        shapes). Verifies the manifest (checksums + structural
        compatibility + delta chain) and, on any mismatch, quarantines
        the corrupt entry and falls back to the next-newest valid one.

        Returns the mutated engine_state, or None when no (valid)
        checkpoint exists.
        """
        names = self._live_names()
        if path is not None:
            want = self._backend.name_of(path)
            names = [n for n in names if n <= want]
            if want not in names and self._backend.exists(want):
                names.append(want)
        if not names:
            return None
        tip = names[-1]
        corrupt = 0
        for n in reversed(names):
            t0 = time.perf_counter()
            try:
                meta, arrays = self._resolve_chain(n, template=engine_state)
            except CorruptCheckpointError as e:
                corrupt += 1
                self._note_corrupt(n, e)
                continue
            # AFTER the corruption verdict, BEFORE the template is
            # mutated: a topology mismatch is a refusal (raises), never
            # a quarantine — the checkpoint is healthy and the whole
            # lineage shares its topology, so falling back would only
            # rewind the fleet
            self._check_topology(n, meta, engine_state)
            out = _apply_arrays(engine_state, meta, arrays)
            nbytes = sum(a.nbytes for a in arrays.values())
            _observe_checkpoint("restore", self._backend.kind, t0, nbytes,
                                int(out.batches_done))
            if corrupt:
                reg = get_registry()
                reg.counter(
                    "rtfds_checkpoint_fallbacks_total",
                    "restores that fell back past corrupt checkpoints"
                ).inc()
                reg.gauge(
                    "rtfds_checkpoint_serving_fallback",
                    "1 while the engine serves off a fallback "
                    "(non-newest) checkpoint restore").set(1)
                rec = active_recorder()
                if rec is not None:
                    rec.record_event(
                        "checkpoint_fallback", restored=n, skipped=corrupt,
                        from_tip=tip,
                        batches_done=int(out.batches_done))
            return out
        return None  # every lineage entry failed verification

    # -- verification (CLI preflight) -------------------------------------

    def verify_all(self, deep: bool = True) -> List[dict]:
        """Report on every live checkpoint WITHOUT quarantining or
        counting metrics. ``deep=True`` re-checksums each entry AND its
        composed delta chain (the deploy preflight behind ``rtfds ckpt
        --verify``: O(chain) reads per tip). ``deep=False`` is the cheap
        listing verdict: one read per entry — the zip layer's own entry
        CRCs still catch bit-flips in the entry itself, but a broken
        chain link only surfaces under ``deep``."""
        now = time.time()  # vs backend mtime: cross-process wall age
        out = []
        for n in self._live_names():
            info = self._backend.info(n)
            entry = {
                "path": self._backend.path_of(n),
                "size": info.get("size"),
                # rtfdslint: disable=wall-clock-duration (age vs the backend's mtime — a wall-clock stamp written by ANOTHER process; perf_counter has no cross-process meaning)
                "age_s": (round(now - info["mtime"], 1)
                          if info.get("mtime") else None),
            }
            try:
                meta, man, _, _ = _parse_entry(self._backend.read(n))
                entry["kind"] = (man.get("kind", "full") if man else "v1")
                entry["batches_done"] = meta.get("batches_done")
                entry["incarnation"] = (man or {}).get("incarnation")
                if deep:
                    self._resolve_chain(n)
                entry["valid"] = True
            except CorruptCheckpointError as e:
                entry["valid"] = False
                entry["reason"] = e.reason
                entry["detail"] = e.detail[:200]
            except KeyError:
                entry["valid"] = False
                entry["reason"] = "truncated"
                entry["detail"] = "entry vanished mid-verify"
            out.append(entry)
        return out

    def manifest(self, path: str) -> dict:
        """Meta + manifest of one checkpoint (``rtfds ckpt --inspect``).
        v1 entries return their meta under ``{"format": 1}``."""
        name = self._backend.name_of(path)
        meta, man, _, _ = _parse_entry(self._backend.read(name))
        if man is None:
            return {"format": 1, "meta": meta}
        return {**man, "meta": meta}

    # -- retention --------------------------------------------------------

    def _gc(self) -> None:
        names = self._live_names()
        if len(names) <= self.keep:
            return
        keep_set = set(names[-self.keep:])
        # chain-aware: never GC a base some kept delta still composes
        # from — deleting it would break every restore of that delta
        frontier = list(keep_set)
        live = set(names)
        while frontier:
            n = frontier.pop()
            man = self._manifest_of(n)
            base = (man or {}).get("base") if (man or {}).get(
                "kind") == "delta" else None
            if base and base in live and base not in keep_set:
                keep_set.add(base)
                frontier.append(base)
        for n in names:
            if n not in keep_set:
                self._backend.delete(n)
                self._manifest_cache.pop(n, None)


class Checkpointer(_CheckpointerBase):
    """Filesystem checkpointer (tmp write + atomic rename). Construction
    sweeps ``ckpt-*.npz.tmp`` orphans a crash between the tmp write and
    ``os.replace`` would otherwise leak forever."""

    def __init__(self, directory: str, keep: int = 3, full_every: int = 1):
        self.directory = directory
        super().__init__(_LocalBackend(directory), keep=keep,
                         full_every=full_every)
        self._backend.sweep_orphan_tmps()


class StoreCheckpointer(_CheckpointerBase):
    """Checkpointer over an object store — the reference's
    ``checkpointLocation`` on s3a (``fraud_detection.py:63``,
    ``kafka_s3_sink_*.py:11``): streaming state durable in MinIO/S3, not
    on an ephemeral host disk. Object PUTs are atomic. Same
    save/restore/latest contract as :class:`Checkpointer`; ``store`` is
    any :mod:`..io.store` object. Store ops are hardened: retried with
    original-typed error propagation, optional per-op timeout
    (``op_timeout_s``; 0 = wait)."""

    def __init__(self, store, prefix: str = "checkpoints", keep: int = 3,
                 full_every: int = 1, op_timeout_s: float = 0.0,
                 op_attempts: int = 3):
        self.store = store
        self.prefix = prefix.strip("/")
        super().__init__(
            _StoreBackend(store, prefix, op_timeout_s=op_timeout_s,
                          op_attempts=op_attempts),
            keep=keep, full_every=full_every)

    def _list(self):
        """Historical internal API (tests + retention introspection):
        live checkpoint KEYS under the prefix."""
        return [self._backend.path_of(n) for n in self._live_names()]


def make_checkpointer(path_or_url: str, keep: int = 3, full_every: int = 1,
                      op_timeout_s: float = 0.0, op_attempts: int = 3):
    """``s3://bucket/prefix`` → :class:`StoreCheckpointer`; local path →
    :class:`Checkpointer`."""
    if path_or_url.startswith("s3://"):
        from real_time_fraud_detection_system_tpu.io.store import make_store

        return StoreCheckpointer(make_store(path_or_url), prefix="",
                                 keep=keep, full_every=full_every,
                                 op_timeout_s=op_timeout_s,
                                 op_attempts=op_attempts)
    return Checkpointer(path_or_url, keep=keep, full_every=full_every)


def feature_state_report(man: dict) -> Optional[dict]:
    """Operator view of a checkpoint's feature-state plane from the
    MANIFEST alone (no array loads): named leaves with per-shard byte
    attribution, plus the per-shard directory occupancy the writer
    recorded — so state skew across shards is visible from ``rtfds ckpt
    --inspect`` without restoring the checkpoint.

    Returns None when the entry predates leaf naming (v1, or a pre-
    sharded-state v2 manifest)."""
    meta = man.get("meta") or {}
    names = meta.get("fs_leaves") or {}
    spec = man.get("spec") or {}
    if not names or not spec:
        return None
    layout = int(meta.get("layout_devices", 1) or 1)
    stored = set(man.get("stored") or [])
    leaves = []
    total = 0
    for k in sorted(names, key=lambda k: int(k.split("_")[1])):
        if k not in spec:
            continue
        shape, dtype = spec[k]
        nbytes = int(np.prod(shape, dtype=np.int64) if shape else 1) \
            * np.dtype(dtype).itemsize
        total += nbytes
        row = {"leaf": k, "path": names[k], "shape": shape,
               "dtype": dtype, "bytes": nbytes}
        if stored:
            # delta checkpoints: which state leaves actually churned
            row["stored_in_entry"] = k in stored
        if layout > 1 and shape and int(shape[0]) == layout:
            # stacked per-shard leaf (directories, sketch replicas)
            row["per_shard_bytes"] = nbytes // layout
        leaves.append(row)
    out: dict = {"layout_devices": layout, "total_bytes": total,
                 "leaves": leaves}
    pc = int(meta.get("process_count", 1) or 1)
    if pc > 1:
        # fleet writer: this entry holds ONE process's residue block
        out["process_count"] = pc
        out["process_id"] = int(meta.get("process_id", 0) or 0)
        out["fleet_shards_total"] = pc * layout
    occ = meta.get("feature_state_occupancy")
    if occ:
        out["occupancy_per_shard"] = occ
        worst = {
            t: int(max(range(len(v)), key=lambda s: v[s]))
            for t, v in occ.items() if v}
        out["worst_shard"] = {
            t: {"shard": s, "occupied": occ[t][s]}
            for t, s in worst.items()}
    cold = cold_tier_report(meta.get("cold_lineage"))
    if cold is not None:
        out["cold"] = cold
    return out


def cold_tier_report(lineage: Optional[dict]) -> Optional[dict]:
    """Cold-tier plane of ``rtfds ckpt --inspect``, from MANIFESTS alone
    (no segment-blob reads): the lineage the checkpoint recorded, plus a
    per-segment CRC VERDICT against the cold store's on-disk manifests —
    ``ok`` (manifest present, crc matches the lineage), ``mismatch``
    (the segment was rewritten/corrupted since the save), ``missing``
    (segment gone — e.g. gc after a newer checkpoint; its keys degrade
    to CMS on restore), ``unavailable`` (cold store unreachable)."""
    if not lineage:
        return None
    segs = list(lineage.get("segments", []))
    out = {
        "cold_store": lineage.get("cold_store", ""),
        "segments": len(segs),
        "total_keys": int(lineage.get("total_keys", 0) or 0),
        "total_bytes": int(lineage.get("total_bytes", 0) or 0),
    }
    rows = []
    for s in segs:
        seq = int(s["seq"])
        row = {"seq": seq, "blob": s.get("blob"),
               "bytes": int(s.get("bytes", 0) or 0),
               "keys": s.get("keys", {})}
        row["crc_verdict"] = _cold_seg_verdict(
            lineage.get("cold_store", ""), seq, s.get("crc"))
        rows.append(row)
    out["segment_rows"] = rows
    verdicts = {r["crc_verdict"] for r in rows}
    out["crc_verdict"] = ("ok" if not verdicts or verdicts == {"ok"}
                          else "mismatch" if "mismatch" in verdicts
                          else "missing" if "missing" in verdicts
                          else "unavailable")
    return out


def _cold_seg_verdict(cold_store: str, seq: int, crc) -> str:
    """Best-effort on-disk manifest check for one lineage segment."""
    if not cold_store:
        return "unavailable"
    name = f"seg-{seq:08d}.json"
    try:
        if cold_store.startswith("s3://"):
            from real_time_fraud_detection_system_tpu.io.store import (
                make_store,
            )

            data = _StoreBackend(make_store(cold_store),
                                 prefix="").read(name)
        else:
            data = _LocalBackend(cold_store).read(name)
        man = json.loads(data.decode("utf-8"))
    except KeyError:
        return "missing"
    # rtfdslint: disable=broad-exception-catch (inspect is read-only forensics: ANY failure to reach/parse the cold store must degrade to a verdict, never kill the inspect)
    except Exception:
        return "unavailable"
    return "ok" if crc is not None and int(man.get("crc", -1)) == \
        int(crc) else "mismatch"
