"""Atomic checkpoint/resume of the full streaming state.

The reference's recovery story is Spark's ``checkpointLocation`` (Kafka
offsets + commit log per job, ``fraud_detection.py:63``) plus pickled model
artifacts. Here ONE checkpoint captures everything the step function closes
over — (source offsets, feature-state pytree, model params, scaler, batch
counter) — written atomically (tmp file + rename) so a crash mid-write
leaves the previous checkpoint intact. Restore rebuilds the exact pytree
structure from a template, so replay resumes with identical state
(exactly-once at micro-batch granularity: offsets and state are saved
together).
"""

from __future__ import annotations

import io as _io
import json
import os
import time
from typing import Optional

import jax
import numpy as np

from real_time_fraud_detection_system_tpu.utils.metrics import (
    active_recorder,
    get_registry,
)


def _observe_checkpoint(op: str, backend: str, t0: float, nbytes: int,
                        batches_done: int) -> None:
    """Shared save/restore instrumentation + the flight-record event a
    checkpoint IS (the exactly-once fence every replay reasons from)."""
    dt = time.perf_counter() - t0
    reg = get_registry()
    reg.histogram("rtfds_checkpoint_seconds",
                  "checkpoint save/restore wall time", op=op,
                  backend=backend).observe(dt)
    reg.counter("rtfds_checkpoint_ops_total", "checkpoint operations",
                op=op, backend=backend).inc()
    if nbytes:
        reg.gauge("rtfds_checkpoint_bytes",
                  "size of the last checkpoint").set(nbytes)
    rec = active_recorder()
    if rec is not None:
        rec.record_event("checkpoint", op=op, batches_done=batches_done,
                         bytes=nbytes, seconds=round(dt, 6))


def write_state_npz(fileobj, engine_state) -> None:
    """Stream an EngineState (or any object with feature_state/params/
    scaler/offsets/batches_done/rows_done) as npz into a file object."""
    leaves_fs, _ = jax.tree_util.tree_flatten(engine_state.feature_state)
    leaves_p, _ = jax.tree_util.tree_flatten(engine_state.params)
    leaves_s, _ = jax.tree_util.tree_flatten(engine_state.scaler)
    arrays = {}
    for i, leaf in enumerate(leaves_fs):
        arrays[f"fs_{i}"] = np.asarray(leaf)
    for i, leaf in enumerate(leaves_p):
        arrays[f"p_{i}"] = np.asarray(leaf)
    for i, leaf in enumerate(leaves_s):
        arrays[f"s_{i}"] = np.asarray(leaf)
    meta = {
        "offsets": list(map(int, engine_state.offsets)),
        "batches_done": int(engine_state.batches_done),
        "rows_done": int(engine_state.rows_done),
        "n_fs": len(leaves_fs),
        "n_p": len(leaves_p),
        "n_s": len(leaves_s),
        # layouts are shape-identical permutations: the writer's device
        # count must travel with the state for cross-width restores
        "layout_devices": int(
            getattr(engine_state, "layout_devices", 1) or 1),
    }
    np.savez(fileobj, __meta__=json.dumps(meta), **arrays)


def state_to_bytes(engine_state) -> bytes:
    """npz bytes of an EngineState (object-store PUT payload)."""
    buf = _io.BytesIO()
    write_state_npz(buf, engine_state)
    return buf.getvalue()


def bytes_to_state(data: bytes, engine_state):
    """Restore npz bytes into an EngineState template (same shapes);
    returns the mutated engine_state."""
    return read_state_npz(_io.BytesIO(data), engine_state)


def read_state_npz(fileobj, engine_state):
    """Restore npz from a file object into an EngineState template —
    streaming (np.load reads arrays directly; no whole-file bytes copy)."""
    with np.load(fileobj, allow_pickle=False) as z:
        meta = json.loads(str(z["__meta__"]))
        fs_leaves = [z[f"fs_{i}"] for i in range(meta["n_fs"])]
        p_leaves = [z[f"p_{i}"] for i in range(meta["n_p"])]
        s_leaves = [z[f"s_{i}"] for i in range(meta["n_s"])]
    _, fs_def = jax.tree_util.tree_flatten(engine_state.feature_state)
    _, p_def = jax.tree_util.tree_flatten(engine_state.params)
    _, s_def = jax.tree_util.tree_flatten(engine_state.scaler)
    engine_state.feature_state = jax.tree_util.tree_unflatten(
        fs_def, [jax.numpy.asarray(a) for a in fs_leaves]
    )
    engine_state.params = jax.tree_util.tree_unflatten(
        p_def, [jax.numpy.asarray(a) for a in p_leaves]
    )
    engine_state.scaler = jax.tree_util.tree_unflatten(
        s_def, [jax.numpy.asarray(a) for a in s_leaves]
    )
    engine_state.offsets = meta["offsets"]
    engine_state.batches_done = meta["batches_done"]
    engine_state.rows_done = meta["rows_done"]
    if meta.get("layout_devices") is not None:
        engine_state.layout_devices = int(meta["layout_devices"])
    # pre-layout-aware checkpoints: leave the template's value (the old
    # same-width-restore assumption)
    return engine_state


class Checkpointer:
    def __init__(self, directory: str, keep: int = 3):
        self.directory = directory
        self.keep = keep
        os.makedirs(directory, exist_ok=True)

    def _path(self, step: int) -> str:
        return os.path.join(self.directory, f"ckpt-{step:010d}.npz")

    def save(self, engine_state) -> str:
        t0 = time.perf_counter()
        path = self._path(engine_state.batches_done)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            write_state_npz(f, engine_state)  # streamed, no bytes copy
        nbytes = os.path.getsize(tmp)
        os.replace(tmp, path)  # atomic on POSIX
        self._gc()
        _observe_checkpoint("save", "local", t0, nbytes,
                            int(engine_state.batches_done))
        return path

    def list_checkpoints(self) -> list:
        """Live checkpoint paths, oldest → newest (lineage API used by the
        crash-recovery fence, ``runtime/faults._FencedCheckpointer``)."""
        return [
            os.path.join(self.directory, f)
            for f in sorted(os.listdir(self.directory))
            if f.startswith("ckpt-") and f.endswith(".npz")
        ]

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def quarantine(self, paths, token: str) -> None:
        """Hide a previous run's lineage from ``latest()``/GC: rename to
        ``stale-<token>-…`` (bytes preserved). Clears any earlier stash
        first so repeated fresh runs keep one quarantine, not a pile."""
        for old in os.listdir(self.directory):
            if old.startswith("stale-") and old.endswith(".npz"):
                os.remove(os.path.join(self.directory, old))
        for p in paths:
            if os.path.exists(p):
                d, f = os.path.split(p)
                os.replace(p, os.path.join(d, f"stale-{token}-{f}"))

    def latest(self) -> Optional[str]:
        ckpts = self.list_checkpoints()
        return ckpts[-1] if ckpts else None

    def restore(self, engine_state, path: Optional[str] = None):
        """Restore into an EngineState template (same model/config shapes).

        Returns the mutated engine_state, or None if no checkpoint exists.
        """
        path = path or self.latest()
        if path is None:
            return None
        t0 = time.perf_counter()
        nbytes = os.path.getsize(path)
        with open(path, "rb") as f:
            out = read_state_npz(f, engine_state)
        _observe_checkpoint("restore", "local", t0, nbytes,
                            int(out.batches_done))
        return out

    def _gc(self) -> None:
        for p in self.list_checkpoints()[: -self.keep]:
            os.remove(p)


class StoreCheckpointer:
    """Checkpointer over an object store — the reference's
    ``checkpointLocation`` on s3a (``fraud_detection.py:63``,
    ``kafka_s3_sink_*.py:11``): streaming state durable in MinIO/S3, not
    on an ephemeral host disk. Object PUTs are atomic, so no tmp+rename
    dance is needed. Same save/restore/latest contract as
    :class:`Checkpointer`; ``store`` is any :mod:`..io.store` object.
    """

    def __init__(self, store, prefix: str = "checkpoints", keep: int = 3):
        self.store = store
        self.prefix = prefix.strip("/")
        self.keep = keep

    def _key(self, step: int) -> str:
        name = f"ckpt-{step:010d}.npz"
        return f"{self.prefix}/{name}" if self.prefix else name

    def _list(self):
        # Flat-directory semantics (matching Checkpointer's os.listdir):
        # keys nested deeper under the prefix belong to OTHER lineages
        # (e.g. a sibling job's prefix) and must not be GC'd/restored.
        pre = self.prefix + "/" if self.prefix else ""
        return [
            k for k in self.store.list(pre)
            if k[len(pre):].startswith("ckpt-") and k.endswith(".npz")
            and "/" not in k[len(pre):]
        ]

    def save(self, engine_state) -> str:
        t0 = time.perf_counter()
        key = self._key(engine_state.batches_done)
        data = state_to_bytes(engine_state)
        self.store.put(key, data)
        for old in sorted(self._list())[: -self.keep]:
            self.store.delete(old)
        _observe_checkpoint("save", "store", t0, len(data),
                            int(engine_state.batches_done))
        return key

    def list_checkpoints(self) -> list:
        return sorted(self._list())

    def exists(self, key: str) -> bool:
        return self.store.exists(key)

    def quarantine(self, keys, token: str) -> None:
        """Hide a previous run's lineage (fresh-start fence): move keys to
        ``stale-<token>-…`` names, invisible to ``_list``'s ``ckpt-``
        filter — so this run's retention GC can't be tricked into deleting
        its own saves by stale higher-numbered checkpoints, and
        ``latest()`` never resurrects them. Clears earlier stashes first;
        live bytes are moved (server-side copy on S3), never deleted
        before the copy lands."""
        pre = self.prefix + "/" if self.prefix else ""
        for k in self.store.list(pre):
            name = k[len(pre):]
            if name.startswith("stale-") and "/" not in name:
                self.store.delete(k)
        for k in keys:
            if not self.store.exists(k):
                continue
            head, _, name = k.rpartition("/")
            stale = (f"{head}/" if head else "") + f"stale-{token}-{name}"
            move = getattr(self.store, "move", None)
            if move is not None:
                move(k, stale)
            else:  # duck-typed store without move: copy-then-delete
                self.store.put(stale, self.store.get(k))
                self.store.delete(k)

    def latest(self) -> Optional[str]:
        keys = sorted(self._list())
        return keys[-1] if keys else None

    def restore(self, engine_state, path: Optional[str] = None):
        key = path or self.latest()
        if key is None:
            return None
        t0 = time.perf_counter()
        data = self.store.get(key)
        out = bytes_to_state(data, engine_state)
        _observe_checkpoint("restore", "store", t0, len(data),
                            int(out.batches_done))
        return out


def make_checkpointer(path_or_url: str, keep: int = 3):
    """``s3://bucket/prefix`` → :class:`StoreCheckpointer`; local path →
    :class:`Checkpointer`."""
    if path_or_url.startswith("s3://"):
        from real_time_fraud_detection_system_tpu.io.store import make_store

        return StoreCheckpointer(make_store(path_or_url), prefix="",
                                 keep=keep)
    return Checkpointer(path_or_url, keep=keep)
