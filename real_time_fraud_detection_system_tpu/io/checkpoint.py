"""Atomic checkpoint/resume of the full streaming state.

The reference's recovery story is Spark's ``checkpointLocation`` (Kafka
offsets + commit log per job, ``fraud_detection.py:63``) plus pickled model
artifacts. Here ONE checkpoint captures everything the step function closes
over — (source offsets, feature-state pytree, model params, scaler, batch
counter) — written atomically (tmp file + rename) so a crash mid-write
leaves the previous checkpoint intact. Restore rebuilds the exact pytree
structure from a template, so replay resumes with identical state
(exactly-once at micro-batch granularity: offsets and state are saved
together).
"""

from __future__ import annotations

import json
import os
from typing import Optional

import jax
import numpy as np


class Checkpointer:
    def __init__(self, directory: str, keep: int = 3):
        self.directory = directory
        self.keep = keep
        os.makedirs(directory, exist_ok=True)

    def _path(self, step: int) -> str:
        return os.path.join(self.directory, f"ckpt-{step:010d}.npz")

    def save(self, engine_state) -> str:
        """Serialize an EngineState (or any object with feature_state/params/
        scaler/offsets/batches_done/rows_done)."""
        leaves_fs, _ = jax.tree_util.tree_flatten(engine_state.feature_state)
        leaves_p, _ = jax.tree_util.tree_flatten(engine_state.params)
        leaves_s, _ = jax.tree_util.tree_flatten(engine_state.scaler)
        arrays = {}
        for i, leaf in enumerate(leaves_fs):
            arrays[f"fs_{i}"] = np.asarray(leaf)
        for i, leaf in enumerate(leaves_p):
            arrays[f"p_{i}"] = np.asarray(leaf)
        for i, leaf in enumerate(leaves_s):
            arrays[f"s_{i}"] = np.asarray(leaf)
        meta = {
            "offsets": list(map(int, engine_state.offsets)),
            "batches_done": int(engine_state.batches_done),
            "rows_done": int(engine_state.rows_done),
            "n_fs": len(leaves_fs),
            "n_p": len(leaves_p),
            "n_s": len(leaves_s),
        }
        path = self._path(engine_state.batches_done)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            np.savez(f, __meta__=json.dumps(meta), **arrays)
        os.replace(tmp, path)  # atomic on POSIX
        self._gc()
        return path

    def latest(self) -> Optional[str]:
        ckpts = sorted(
            f for f in os.listdir(self.directory)
            if f.startswith("ckpt-") and f.endswith(".npz")
        )
        return os.path.join(self.directory, ckpts[-1]) if ckpts else None

    def restore(self, engine_state, path: Optional[str] = None):
        """Restore into an EngineState template (same model/config shapes).

        Returns the mutated engine_state, or None if no checkpoint exists.
        """
        path = path or self.latest()
        if path is None:
            return None
        with np.load(path, allow_pickle=False) as z:
            meta = json.loads(str(z["__meta__"]))
            fs_leaves = [z[f"fs_{i}"] for i in range(meta["n_fs"])]
            p_leaves = [z[f"p_{i}"] for i in range(meta["n_p"])]
            s_leaves = [z[f"s_{i}"] for i in range(meta["n_s"])]
        _, fs_def = jax.tree_util.tree_flatten(engine_state.feature_state)
        _, p_def = jax.tree_util.tree_flatten(engine_state.params)
        _, s_def = jax.tree_util.tree_flatten(engine_state.scaler)
        engine_state.feature_state = jax.tree_util.tree_unflatten(
            fs_def, [jax.numpy.asarray(a) for a in fs_leaves]
        )
        engine_state.params = jax.tree_util.tree_unflatten(
            p_def, [jax.numpy.asarray(a) for a in p_leaves]
        )
        engine_state.scaler = jax.tree_util.tree_unflatten(
            s_def, [jax.numpy.asarray(a) for a in s_leaves]
        )
        engine_state.offsets = meta["offsets"]
        engine_state.batches_done = meta["batches_done"]
        engine_state.rows_done = meta["rows_done"]
        return engine_state

    def _gc(self) -> None:
        ckpts = sorted(
            f for f in os.listdir(self.directory)
            if f.startswith("ckpt-") and f.endswith(".npz")
        )
        for f in ckpts[: -self.keep]:
            os.remove(os.path.join(self.directory, f))
