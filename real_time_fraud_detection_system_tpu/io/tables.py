"""Keyed upsert tables — the Iceberg ``MERGE INTO`` role, in-process.

The reference's sink jobs land every CDC micro-batch in Iceberg with
``MERGE INTO … WHEN MATCHED THEN UPDATE / WHEN NOT MATCHED THEN INSERT``
after a ROW_NUMBER latest-wins dedup (``kafka_s3_sink_transactions.py:
173-222``; same pattern in jobs 1/2). :class:`UpsertTable` provides those
semantics for dev/test deployments without a lakehouse: columnar numpy
storage, a key→row index, per-row versions for idempotent replay, and the
same within-batch latest-wins rule (greatest timestamp, ties broken by batch
position).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from real_time_fraud_detection_system_tpu.core.schema import TableSchema
from real_time_fraud_detection_system_tpu.ops.dedup import latest_wins_mask_np

_GROW = 1024


class UpsertTable:
    """Latest-wins keyed table with MERGE upsert + delete semantics."""

    def __init__(self, schema: TableSchema, capacity: int = _GROW):
        self.schema = schema
        self.key = schema.key
        self._cols: Dict[str, np.ndarray] = {
            name: np.zeros(capacity, dtype=dt) for name, dt in schema.fields
        }
        self._version = np.full(capacity, np.iinfo(np.int64).min, np.int64)
        self._live = np.zeros(capacity, dtype=bool)
        # key → slot index, kept as parallel sorted arrays so a whole
        # micro-batch resolves in one vectorized searchsorted instead of a
        # per-row dict probe (the raw-transactions table merges millions of
        # rows; a Python loop here was the round-2 bottleneck).
        self._sorted_keys = np.empty(0, dtype=np.int64)
        self._sorted_slots = np.empty(0, dtype=np.int64)
        # Deletes for keys never inserted: version-only tombstones (no row
        # slot — a stream of unknown-key deletes must not grow the column
        # arrays). Consulted on insert to filter out-of-order stale rows.
        self._tombstones: Dict[int, int] = {}
        self._n = 0
        self._seq = 0  # monotonic fallback version counter across merges
        self.last_merged_slots = np.empty(0, dtype=np.int64)

    def __len__(self) -> int:
        return int(self._live[: self._n].sum())

    def _grow(self, need: int) -> None:
        cap = len(self._live)
        if self._n + need <= cap:
            return
        new_cap = max(cap * 2, self._n + need + _GROW)
        for name in self._cols:
            arr = np.zeros(new_cap, dtype=self._cols[name].dtype)
            arr[: self._n] = self._cols[name][: self._n]
            self._cols[name] = arr
        version = np.full(new_cap, np.iinfo(np.int64).min, np.int64)
        version[: self._n] = self._version[: self._n]
        self._version = version
        live = np.zeros(new_cap, dtype=bool)
        live[: self._n] = self._live[: self._n]
        self._live = live

    def merge(
        self,
        cols: Dict[str, np.ndarray],
        ts: Optional[np.ndarray] = None,
        op: Optional[np.ndarray] = None,
        valid: Optional[np.ndarray] = None,
    ) -> Tuple[int, int, int]:
        """MERGE a micro-batch; returns (inserted, updated, deleted).

        ``ts`` orders versions; rows whose ts is <= the stored version of
        their key are ignored — replaying an already-merged batch after
        checkpoint restore is a no-op (idempotent exactly-once, SURVEY §5.4;
        requires real event timestamps). Version resolution: explicit ``ts``
        → the batch's ``kafka_ts_ms`` column if it carries any non-zero
        value → an internal arrival-order counter that is monotone ACROSS
        merges, so cross-batch updates are never mistaken for stale replays
        (replay idempotence then isn't available — arrival order can't
        distinguish a replay from an update).
        """
        keys = np.asarray(cols[self.key], dtype=np.int64)
        b = len(keys)
        if ts is None:
            kts = cols.get("kafka_ts_ms")
            if kts is not None and np.any(np.asarray(kts) != 0):
                ts = np.asarray(kts, dtype=np.int64)
            else:
                ts = self._seq + np.arange(b, dtype=np.int64)
        self._seq = max(self._seq, int(np.max(ts)) + 1 if b else self._seq)
        if op is None:
            op_arr = cols.get("op")
            op = (
                np.asarray(op_arr, dtype=np.int8)
                if op_arr is not None
                else np.zeros(b, dtype=np.int8)
            )
        ts = np.asarray(ts, dtype=np.int64)
        op = np.asarray(op, dtype=np.int8)
        mask = latest_wins_mask_np(keys, ts, valid)
        idx = np.flatnonzero(mask)  # one surviving row per key
        if idx.size == 0:
            self.last_merged_slots = np.empty(0, dtype=np.int64)
            return 0, 0, 0
        k = keys[idx]
        v = ts[idx]
        o = op[idx]
        slots = self._lookup(k)
        known = slots >= 0

        # Freshness: stale replays (version <= stored) are no-ops.
        fresh = np.ones(idx.size, dtype=bool)
        fresh[known] = v[known] > self._version[slots[known]]
        unknown = ~known
        if self._tombstones and unknown.any():
            # Unknown keys are checked against delete tombstones; the
            # tombstone map stays tiny (unknown-key deletes only), so a
            # loop over just those rows is cheap.
            floor = np.iinfo(np.int64).min
            for j in np.flatnonzero(unknown):
                if v[j] <= self._tombstones.get(int(k[j]), floor):
                    fresh[j] = False

        deletes = fresh & (o == 2)
        upserts = fresh & (o != 2)

        # -- deletes on known slots: flip live, advance version -----------
        del_known = deletes & known
        dslots = slots[del_known]
        deleted = int(self._live[dslots].sum())
        self._live[dslots] = False
        self._version[dslots] = v[del_known]
        # -- deletes on never-seen keys: record tombstones -----------------
        for j in np.flatnonzero(deletes & unknown):
            self._tombstones[int(k[j])] = int(v[j])

        # -- updates / re-inserts on known slots ---------------------------
        upd = upserts & known
        uslots = slots[upd]
        updated = int(self._live[uslots].sum())
        reinserted = int(upd.sum()) - updated
        src = idx[upd]
        for name, _ in self.schema.fields:
            if name in cols:
                self._cols[name][uslots] = np.asarray(cols[name])[src]
        self._live[uslots] = True
        self._version[uslots] = v[upd]

        # -- inserts of new keys -------------------------------------------
        ins = upserts & unknown
        n_new = int(ins.sum())
        new_slots = np.empty(0, dtype=np.int64)
        if n_new:
            self._grow(n_new)
            new_slots = np.arange(self._n, self._n + n_new, dtype=np.int64)
            self._n += n_new
            src = idx[ins]
            for name, _ in self.schema.fields:
                if name in cols:
                    self._cols[name][new_slots] = np.asarray(cols[name])[src]
            self._live[new_slots] = True
            self._version[new_slots] = v[ins]
            nk = k[ins]
            if self._tombstones:
                for key_ in nk:
                    self._tombstones.pop(int(key_), None)
            order = np.argsort(nk, kind="stable")
            nk = nk[order]
            ns = new_slots[order]
            pos = np.searchsorted(self._sorted_keys, nk)
            self._sorted_keys = np.insert(self._sorted_keys, pos, nk)
            self._sorted_slots = np.insert(self._sorted_slots, pos, ns)
        # Slots whose row content changed this merge (inserts + updates,
        # not deletes) — incremental persistence layers read this to write
        # only the delta instead of rescanning the table.
        self.last_merged_slots = np.concatenate([uslots, new_slots])
        return n_new + reinserted, updated, deleted

    def _lookup(self, keys: np.ndarray) -> np.ndarray:
        """Vectorized key→slot resolution; -1 where absent."""
        if self._sorted_keys.size == 0:
            return np.full(len(keys), -1, dtype=np.int64)
        pos = np.searchsorted(self._sorted_keys, keys)
        pos_c = np.minimum(pos, self._sorted_keys.size - 1)
        found = self._sorted_keys[pos_c] == keys
        return np.where(found, self._sorted_slots[pos_c], -1)

    def get(self, key: int) -> Optional[dict]:
        slot = int(self._lookup(np.asarray([key], dtype=np.int64))[0])
        if slot < 0 or not self._live[slot]:
            return None
        return {name: self._cols[name][slot] for name, _ in self.schema.fields}

    def to_columns(self) -> Dict[str, np.ndarray]:
        """Snapshot of live rows, insertion-ordered."""
        live = np.flatnonzero(self._live[: self._n])
        return {
            name: self._cols[name][live] for name, _ in self.schema.fields
        }

    def rows_at(self, slots: np.ndarray) -> Dict[str, np.ndarray]:
        """Live rows at the given slot indices (dead slots dropped) —
        the incremental-persistence read used with ``last_merged_slots``."""
        slots = slots[self._live[slots]]
        return {
            name: self._cols[name][slots] for name, _ in self.schema.fields
        }


_US_PER_DAY = 86400 * 1_000_000


class RawTransactionsTable:
    """Persistent day-partitioned raw-transactions table.

    The reference maintains a queryable ``nessie.payment.transactions``
    Iceberg table ``partitioned by (date(tx_datetime))``
    (``load_initial_data.py:231``), MERGE-fed by sink job 3
    (``kafka_s3_sink_transactions.py:147-158,193-222``). Here: an
    in-memory :class:`UpsertTable` gives the MERGE/latest-wins/tombstone
    semantics, and :meth:`flush` writes only the rows merged since the
    last flush, as an incremental Hive-layout Parquet part per touched
    day — ``<dir>/tx_date=YYYY-MM-DD/part-<seq>.parquet`` — so steady
    streaming costs O(rows), not a partition rewrite per flush.
    Trino/DuckDB/Spark mount the directory directly; a row updated across
    flushes appears in several parts, resolved latest-part-wins at read
    (:meth:`read_all`) — the same MERGE-on-read contract lakehouse
    engines use. (A transaction's day never changes, so all versions of
    a row live in one partition.)

    Implements the sink protocol (``append(BatchResult)``) so the scoring
    engine's ingest feeds it, and ``merge(cols)`` for direct job-3-style
    CDC ingestion upstream of scoring.
    """

    def __init__(self, directory: Optional[str] = None,
                 flush_every_batches: int = 0):
        from real_time_fraud_detection_system_tpu.core.schema import (
            TRANSACTIONS,
        )

        self.directory = directory
        self.flush_every_batches = flush_every_batches
        self._table = UpsertTable(TRANSACTIONS)
        self._pending: set = set()  # slots merged since last flush
        self._batches = 0
        self._flush_seq = 0
        if directory is not None:
            import os as _os

            # Resume the part sequence; directory creation is deferred to
            # the first flush so read-only uses (query reports) never
            # create paths as a side effect.
            for f in _glob_parts(directory):
                seq = int(_os.path.basename(f).split("-")[1].split(".")[0])
                self._flush_seq = max(self._flush_seq, seq + 1)

    def __len__(self) -> int:
        return len(self._table)

    @staticmethod
    def day_str(day: int) -> str:
        import datetime

        return (
            datetime.datetime(1970, 1, 1)
            + datetime.timedelta(days=int(day))
        ).strftime("%Y-%m-%d")

    def merge(self, cols: Dict[str, np.ndarray], **kw) -> Tuple[int, int, int]:
        out = self._table.merge(cols, **kw)
        self._pending.update(self._table.last_merged_slots.tolist())
        self._batches += 1
        if (
            self.flush_every_batches
            and self._batches % self.flush_every_batches == 0
        ):
            self.flush()
        return out

    def append(self, res) -> None:
        """Sink protocol: land the engine's ingested (pre-dedup'd) rows."""
        self.merge(
            {
                "tx_id": res.tx_id,
                "tx_datetime_us": res.tx_datetime_us,
                "customer_id": res.customer_id,
                "terminal_id": res.terminal_id,
                "tx_amount_cents": res.amount_cents,
            },
            # Event time versions the rows: replaying the same batch after
            # checkpoint restore is a no-op (same guarantee the engine's
            # own dedup provides, held here across restarts too).
            ts=np.asarray(res.tx_datetime_us, np.int64) // 1000,
        )

    def flush(self) -> int:
        """Write rows merged since last flush; returns partitions touched."""
        if self.directory is None or not self._pending:
            self._pending.clear()
            return 0
        import os as _os

        import pyarrow as pa
        import pyarrow.parquet as pq

        slots = np.fromiter(self._pending, dtype=np.int64,
                            count=len(self._pending))
        # Dead slots dropped: deletes don't emit parts (CDC tx never dies).
        rows = self._table.rows_at(slots)
        days = rows["tx_datetime_us"] // _US_PER_DAY
        seq = self._flush_seq
        self._flush_seq += 1
        written = 0
        for day in np.unique(days):
            sel = np.flatnonzero(days == day)
            part_dir = _os.path.join(
                self.directory, f"tx_date={self.day_str(int(day))}"
            )
            _os.makedirs(part_dir, exist_ok=True)
            pq.write_table(
                pa.table({k: pa.array(v[sel]) for k, v in rows.items()}),
                _os.path.join(part_dir, f"part-{seq:06d}.parquet"),
            )
            written += 1
        self._pending.clear()
        return written

    def read_all(self) -> Dict[str, np.ndarray]:
        """Read flushed partitions, resolving updates latest-part-wins."""
        import pyarrow as pa
        import pyarrow.parquet as pq

        if self.directory is None:
            return self._table.to_columns()
        files = _glob_parts(self.directory)
        if not files:
            return {}
        t = pa.concat_tables([pq.read_table(f) for f in files])
        cols = {c: t[c].to_numpy() for c in t.column_names}
        # Keep the LAST occurrence of each tx_id: files are concatenated
        # in (day, part-seq) order and a tx's day never changes, so the
        # last occurrence is the newest merged version.
        ids = cols["tx_id"]
        _, last_rev = np.unique(ids[::-1], return_index=True)
        keep = np.sort(len(ids) - 1 - last_rev)
        return {c: v[keep] for c, v in cols.items()}


def _glob_parts(directory: str) -> list:
    import glob as _glob
    import os as _os

    return sorted(
        _glob.glob(_os.path.join(directory, "tx_date=*", "part-*.parquet"))
    )
