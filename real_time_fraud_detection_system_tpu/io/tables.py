"""Keyed upsert tables — the Iceberg ``MERGE INTO`` role, in-process.

The reference's sink jobs land every CDC micro-batch in Iceberg with
``MERGE INTO … WHEN MATCHED THEN UPDATE / WHEN NOT MATCHED THEN INSERT``
after a ROW_NUMBER latest-wins dedup (``kafka_s3_sink_transactions.py:
173-222``; same pattern in jobs 1/2). :class:`UpsertTable` provides those
semantics for dev/test deployments without a lakehouse: columnar numpy
storage, a key→row index, per-row versions for idempotent replay, and the
same within-batch latest-wins rule (greatest timestamp, ties broken by batch
position).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from real_time_fraud_detection_system_tpu.core.schema import TableSchema
from real_time_fraud_detection_system_tpu.ops.dedup import latest_wins_mask_np

_GROW = 1024


class UpsertTable:
    """Latest-wins keyed table with MERGE upsert + delete semantics."""

    def __init__(self, schema: TableSchema, capacity: int = _GROW):
        self.schema = schema
        self.key = schema.key
        self._cols: Dict[str, np.ndarray] = {
            name: np.zeros(capacity, dtype=dt) for name, dt in schema.fields
        }
        self._version = np.full(capacity, np.iinfo(np.int64).min, np.int64)
        self._live = np.zeros(capacity, dtype=bool)
        self._index: Dict[int, int] = {}
        # Deletes for keys never inserted: version-only tombstones (no row
        # slot — a stream of unknown-key deletes must not grow the column
        # arrays). Consulted on insert to filter out-of-order stale rows.
        self._tombstones: Dict[int, int] = {}
        self._n = 0
        self._seq = 0  # monotonic fallback version counter across merges

    def __len__(self) -> int:
        return int(self._live[: self._n].sum())

    def _grow(self, need: int) -> None:
        cap = len(self._live)
        if self._n + need <= cap:
            return
        new_cap = max(cap * 2, self._n + need + _GROW)
        for name in self._cols:
            arr = np.zeros(new_cap, dtype=self._cols[name].dtype)
            arr[: self._n] = self._cols[name][: self._n]
            self._cols[name] = arr
        version = np.full(new_cap, np.iinfo(np.int64).min, np.int64)
        version[: self._n] = self._version[: self._n]
        self._version = version
        live = np.zeros(new_cap, dtype=bool)
        live[: self._n] = self._live[: self._n]
        self._live = live

    def merge(
        self,
        cols: Dict[str, np.ndarray],
        ts: Optional[np.ndarray] = None,
        op: Optional[np.ndarray] = None,
        valid: Optional[np.ndarray] = None,
    ) -> Tuple[int, int, int]:
        """MERGE a micro-batch; returns (inserted, updated, deleted).

        ``ts`` orders versions; rows whose ts is <= the stored version of
        their key are ignored — replaying an already-merged batch after
        checkpoint restore is a no-op (idempotent exactly-once, SURVEY §5.4;
        requires real event timestamps). Version resolution: explicit ``ts``
        → the batch's ``kafka_ts_ms`` column if it carries any non-zero
        value → an internal arrival-order counter that is monotone ACROSS
        merges, so cross-batch updates are never mistaken for stale replays
        (replay idempotence then isn't available — arrival order can't
        distinguish a replay from an update).
        """
        keys = np.asarray(cols[self.key], dtype=np.int64)
        b = len(keys)
        if ts is None:
            kts = cols.get("kafka_ts_ms")
            if kts is not None and np.any(np.asarray(kts) != 0):
                ts = np.asarray(kts, dtype=np.int64)
            else:
                ts = self._seq + np.arange(b, dtype=np.int64)
        self._seq = max(self._seq, int(np.max(ts)) + 1 if b else self._seq)
        if op is None:
            op_arr = cols.get("op")
            op = (
                np.asarray(op_arr, dtype=np.int8)
                if op_arr is not None
                else np.zeros(b, dtype=np.int8)
            )
        mask = latest_wins_mask_np(keys, ts, valid)
        inserted = updated = deleted = 0
        self._grow(int(mask.sum()))
        for i in np.flatnonzero(mask):
            k = int(keys[i])
            v = int(ts[i])
            slot = self._index.get(k)
            if slot is not None and v <= int(self._version[slot]):
                continue  # stale replay
            if slot is None and v <= self._tombstones.get(k, np.iinfo(np.int64).min):
                continue  # stale vs an unknown-key delete's tombstone
            if op[i] == 2:  # delete
                if slot is None:
                    # Never-seen key: record the delete's version as a
                    # tombstone, so an out-of-order STALE insert (lower
                    # ts) replayed later is still filtered — latest-wins
                    # must hold for delete-then-insert arriving out of
                    # order.
                    self._tombstones[k] = v
                elif self._live[slot]:
                    self._live[slot] = False
                    self._version[slot] = v
                    deleted += 1
                else:
                    self._version[slot] = v
                continue
            if slot is None:
                self._tombstones.pop(k, None)
                slot = self._n
                self._n += 1
                self._index[k] = slot
                inserted += 1
            elif self._live[slot]:
                updated += 1
            else:
                inserted += 1  # re-insert after delete
            for name, _ in self.schema.fields:
                if name in cols:
                    self._cols[name][slot] = cols[name][i]
            self._live[slot] = True
            self._version[slot] = v
        return inserted, updated, deleted

    def get(self, key: int) -> Optional[dict]:
        slot = self._index.get(int(key))
        if slot is None or not self._live[slot]:
            return None
        return {name: self._cols[name][slot] for name, _ in self.schema.fields}

    def to_columns(self) -> Dict[str, np.ndarray]:
        """Snapshot of live rows, insertion-ordered."""
        live = np.flatnonzero(self._live[: self._n])
        return {
            name: self._cols[name][live] for name, _ in self.schema.fields
        }
