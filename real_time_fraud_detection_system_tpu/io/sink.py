"""Output sinks — the ``analyzed_transactions`` append path.

The reference appends scored rows to an Iceberg table that Trino/Superset
read (``fraud_detection.py:204-211``). The framework writes the same
column layout (``core/schema.py::ANALYZED_TRANSACTIONS_FIELDS``):

- :class:`ParquetSink` — one Parquet part-file per micro-batch under a
  directory; any Iceberg/Trino/DuckDB reader can mount it. Columns are
  byte-compatible with the reference table (µs timestamps, f64 amounts).
- :class:`MemorySink` — accumulates in RAM (tests, metrics).
- :class:`ConsoleSink` — the reference's ``.show()`` debugging analogue.

An ``IcebergSink`` (pyiceberg catalog append) belongs here too; pyiceberg is
not in this image, so it is import-gated the same way KafkaSource is.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import List, Optional

import numpy as np

from real_time_fraud_detection_system_tpu.features.spec import FEATURE_NAMES
from real_time_fraud_detection_system_tpu.utils.metrics import get_registry


class _SinkTelemetry:
    """Shared sink instrumentation: write latency, rows, bytes, failures
    (labeled by sink kind). Series resolve once per sink instance."""

    def _init_sink_metrics(self, sink_kind: str) -> None:
        from real_time_fraud_detection_system_tpu.utils.trace import (
            get_tracer,
        )

        reg = get_registry()
        self._tracer = get_tracer()
        self._sink_kind = sink_kind
        self._m_write = reg.histogram(
            "rtfds_sink_write_seconds", "sink append wall time",
            sink=sink_kind)
        self._m_rows = reg.counter(
            "rtfds_sink_rows_total", "rows written", sink=sink_kind)
        self._m_bytes = reg.counter(
            "rtfds_sink_bytes_total", "bytes written", sink=sink_kind)
        self._m_failures = reg.counter(
            "rtfds_sink_failures_total", "failed appends", sink=sink_kind)

    def _observe_write(self, t0: float, rows: int, nbytes: int) -> None:
        t1 = time.perf_counter()
        self._m_write.observe(t1 - t0)
        self._m_rows.inc(rows)
        if nbytes:
            self._m_bytes.inc(nbytes)
        if self._tracer.enabled:
            # Timeline-only (batch=""): the engine's sink_write span
            # carries the batch attribution — with pipelining the
            # tracer's CURRENT batch can be newer than the one whose
            # rows are being written, so claiming it would lie. On the
            # Perfetto timeline the span still nests under sink_write.
            self._tracer.add_span(f"sink/{self._sink_kind}", t0, t1,
                                  batch="", rows=rows, bytes=nbytes)


def _result_to_columns(res) -> dict:
    """BatchResult → analyzed_transactions column dict."""
    now_us = int(time.time() * 1e6)
    n = len(res.tx_id)
    cols = {
        "tx_id": res.tx_id.astype(np.int64),
        "tx_datetime_us": res.tx_datetime_us.astype(np.int64),
        "customer_id": res.customer_id.astype(np.int64),
        "terminal_id": res.terminal_id.astype(np.int64),
        "tx_amount": res.amount_cents.astype(np.float64) / 100.0,
    }
    # feature columns, lower-cased like the reference table DDL
    for i, name in enumerate(FEATURE_NAMES):
        if name == "TX_AMOUNT":
            continue
        dt = np.int32 if ("NB_TX" in name or "DURING" in name) else np.float64
        cols[name.lower()] = res.features[:, i].astype(dt)
    cols["processed_at_us"] = np.full(n, now_us, dtype=np.int64)
    cols["prediction"] = res.probs.astype(np.float64)
    return cols


class FanoutSink:
    """Append to several sinks; ``flush()`` propagates to those that have it
    (the raw-transactions table needs a flush; Parquet/memory don't)."""

    def __init__(self, *sinks):
        self.sinks = [s for s in sinks if s is not None]

    def append(self, res) -> None:
        for s in self.sinks:
            s.append(res)

    def flush(self) -> None:
        for s in self.sinks:
            f = getattr(s, "flush", None)
            if f is not None:
                f()

    def truncate_after(self, batch_index: int) -> None:
        for s in self.sinks:
            f = getattr(s, "truncate_after", None)
            if f is not None:
                f(batch_index)


class _SinkError:
    """Box for the writer thread's first failure (kept with its batch
    index so the re-raise on the loop thread names what was lost)."""

    __slots__ = ("exc", "batch_index")

    def __init__(self, exc: BaseException, batch_index: int):
        self.exc = exc
        self.batch_index = batch_index


class AsyncSink:
    """Offload ``append`` to a background writer thread — the engine
    loop's ``sink_write`` phase collapses to one bounded-queue enqueue.

    The serving loop previously paid every sink write (parquet encode +
    fsync-ish rename, an object-store PUT, an Iceberg commit) inline on
    the loop thread between device steps — the largest remaining
    synchronous I/O in the hot path. This wrapper keeps the device hot:

    - **Ordered**: one writer thread drains a FIFO queue, so the inner
      sink sees appends in exactly the loop's order (part-file naming,
      raw-table flush cadence, and fanout ordering are unchanged).
    - **Bounded + backpressured**: the queue holds at most
      ``max_queue`` batch results; a full queue blocks the loop thread
      (never unbounded host memory), and the blocked time is accounted
      in ``rtfds_sink_backpressure_seconds_total`` so a sink that can't
      keep up is visible, not silent. Queue occupancy rides
      ``rtfds_sink_queue_depth``.
    - **Errors propagate**: a writer-thread failure is re-raised on the
      loop thread at the next ``append``/``drain``/``flush`` — with its
      ORIGINAL exception type, so the supervisor's type-based
      ``recover_on`` policy (OSError is recoverable, a bug is not)
      applies exactly as it would to an inline write. The stream crashes
      (and recovery replays) instead of silently dropping output; while
      the failure is pending the writer discards queued results (their
      batches replay from the checkpoint anyway), and the re-raise
      clears it so a recovered incarnation resumes writing.
    - **Drain contract**: ``drain()`` blocks until every queued append
      has landed in the inner sink. ``flush``/``truncate_after``/
      ``read_all``/``concat`` drain first, and the engine drains before
      every checkpoint save — so checkpointed offsets keep TRAILING
      durable sink output (the exactly-once invariant in
      ``runtime/engine.py``'s checkpoint block: a crash replays rows,
      never skips them, and replayed ``batch_index`` parts overwrite).
    """

    _STOP = object()

    def __init__(self, inner, max_queue: int = 8, registry=None):
        if inner is None:
            raise ValueError("AsyncSink needs an inner sink")
        self.inner = inner
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, int(max_queue)))
        self._error: Optional[_SinkError] = None
        # injectable like the engine's registry, so per-run before/after
        # measurements don't cross-contaminate the process-wide series
        reg = registry if registry is not None else get_registry()
        kind = type(inner).__name__
        self._m_depth = reg.gauge(
            "rtfds_sink_queue_depth",
            "batch results queued for the async sink writer", sink=kind)
        self._m_backpressure = reg.counter(
            "rtfds_sink_backpressure_seconds_total",
            "loop-thread seconds blocked on a full async sink queue",
            sink=kind)
        self._thread = threading.Thread(
            target=self._writer, daemon=True, name="rtfds-sink-writer")
        self._thread.start()

    # -- writer thread -----------------------------------------------------

    def _writer(self) -> None:
        while True:
            item = self._q.get()
            try:
                if item is self._STOP:
                    return
                if self._error is None:
                    try:
                        # rtfdslint: disable=cross-thread-race (drain() is the guard: every loop-side inner access — flush/truncate_after/read_all/concat — calls drain() first, and q.join() orders every writer append strictly before it; crash/replay lineage tests pin the contract)
                        self.inner.append(item)
                    # rtfdslint: disable=broad-exception-catch (thread-boundary transport: the writer parks the ORIGINAL exception; append/drain re-raise it typed on the loop thread for the supervisor's recover_on policy)
                    except BaseException as e:  # propagate to loop thread
                        self._error = _SinkError(
                            e, int(getattr(item, "batch_index", -1)))
                        from real_time_fraud_detection_system_tpu.utils \
                            import get_logger

                        get_logger("sink").warning(
                            "async sink write failed on batch %d (%s: %s);"
                            " surfacing to the serving loop",
                            self._error.batch_index, type(e).__name__, e)
                # while a failure is pending: keep draining (so drain()
                # never deadlocks) but write nothing — those batches
                # replay from the checkpoint after recovery
            finally:
                self._q.task_done()
                self._m_depth.set(self._q.qsize())

    def _raise_pending(self) -> None:
        err = self._error
        if err is not None:
            # Clear-then-raise: the raise hands ownership to the engine/
            # supervisor; a recovered incarnation (same sink object,
            # replayed batches) must resume writing, not re-crash on a
            # stale box. The ORIGINAL exception object is raised so the
            # supervisor's recover_on type policy sees what an inline
            # write would have thrown.
            self._error = None
            raise err.exc

    # -- sink API (loop thread) --------------------------------------------

    def append(self, res) -> None:
        self._raise_pending()
        t0 = time.perf_counter()
        self._q.put(res)  # blocks when full: bounded-memory backpressure
        waited = time.perf_counter() - t0
        if waited > 1e-4:  # an uncontended put is ~µs; only count blocks
            self._m_backpressure.inc(waited)
        self._m_depth.set(self._q.qsize())

    def drain(self) -> None:
        """Block until every queued append has landed (or failed) in the
        inner sink; re-raise any writer failure on this thread."""
        self._q.join()
        self._raise_pending()

    def flush(self) -> None:
        self.drain()
        f = getattr(self.inner, "flush", None)
        if f is not None:
            f()

    def truncate_after(self, batch_index: int) -> None:
        # drain first: a queued part beyond the fence must land before
        # the fence can see (and remove) it
        self.drain()
        f = getattr(self.inner, "truncate_after", None)
        if f is not None:
            f(batch_index)

    def read_all(self) -> dict:
        self.drain()
        return self.inner.read_all()

    def concat(self) -> dict:
        self.drain()
        return self.inner.concat()

    def close(self) -> None:
        """Drain, stop the writer thread, and surface any pending error."""
        if self._thread.is_alive():
            self._q.join()
            self._q.put(self._STOP)
            self._thread.join(timeout=30.0)
        self._raise_pending()


class MemorySink:
    def __init__(self):
        self.batches: List[dict] = []

    def append(self, res) -> None:
        self.batches.append(_result_to_columns(res))

    def concat(self) -> dict:
        if not self.batches:
            return {}
        keys = self.batches[0].keys()
        return {k: np.concatenate([b[k] for b in self.batches]) for k in keys}


class ConsoleSink:
    def __init__(self, every: int = 1, limit: int = 5):
        self.every = every
        self.limit = limit
        self._n = 0

    def append(self, res) -> None:
        self._n += 1
        if self._n % self.every:
            return
        n = len(res.tx_id)
        print(f"[batch {self._n}] rows={n} p(fraud): "
              f"mean={res.probs.mean():.4f} max={res.probs.max():.4f}")
        for i in range(min(self.limit, n)):
            print(
                f"  tx {res.tx_id[i]} cust {res.customer_id[i]} "
                f"amt {res.amount_cents[i] / 100:.2f} -> {res.probs[i]:.4f}"
            )


def _part_order(name: str):
    """Deterministic part ordering for mixed naming schemes.

    Indexed parts (``part-<batch_index>``, checkpointed runs) sort
    NUMERICALLY first — lexicographic order breaks once an 8-digit index
    and a 13-digit ms-timestamp stem share a leading digit. Timestamp
    parts (``part-<ms>-<seq>``, un-checkpointed runs) follow, by name
    (their stems are zero-padded, so name order is write order). Mixing
    the two schemes under one directory/prefix means the run switched
    checkpointing mid-lineage; ``truncate_after`` fences only the indexed
    lineage (timestamp parts carry no replay semantics to fence).
    """
    base = name.rsplit("/", 1)[-1]
    stem = base[len("part-"):-len(".parquet")] \
        if base.startswith("part-") and base.endswith(".parquet") else ""
    if stem.isdigit():
        return (0, int(stem), "")
    return (1, 0, name)


class ParquetSink(_SinkTelemetry):
    """One part file per batch: ``<dir>/part-<batch_index>.parquet``.

    Exactly-once across crash-replay: part files are named by the
    engine's monotone ``batch_index`` (which survives checkpoint
    restore), so a replayed batch atomically OVERWRITES its own part
    instead of appending a duplicate — the role Spark's sink commit
    protocol plays for the reference's Iceberg append
    (``fraud_detection.py:204-211``). Writes are tmp+rename, never
    torn for concurrent readers. Results without an index (direct
    ``append`` of hand-built batches) fall back to sequence naming.
    """

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._seq = 0
        self._init_sink_metrics("parquet")

    def append(self, res) -> None:
        import pyarrow as pa
        import pyarrow.parquet as pq

        t0 = time.perf_counter()
        try:
            cols = _result_to_columns(res)
            table = pa.table({k: pa.array(v) for k, v in cols.items()})
            idx = getattr(res, "batch_index", -1)
            if idx >= 0:
                name = f"part-{idx:08d}.parquet"
            else:
                name = (f"part-{int(time.time() * 1e3)}-"
                        f"{self._seq:06d}.parquet")
                self._seq += 1
            path = os.path.join(self.directory, name)
            tmp = path + ".tmp"
            pq.write_table(table, tmp)
            nbytes = os.path.getsize(tmp)
            os.replace(tmp, path)
        except Exception:
            self._m_failures.inc()
            raise
        self._observe_write(t0, len(res.tx_id), nbytes)

    def truncate_after(self, batch_index: int) -> None:
        """Drop indexed parts beyond ``batch_index`` — the sink-side
        restore fence. Replay after a checkpoint restore may re-batch the
        backlog differently (e.g. a Kafka drain coalescing into fewer,
        larger batches), so parts the replay won't overwrite must go, or
        their rows would double on disk. A fresh run (restore to 0)
        clears the whole indexed lineage."""
        for f in os.listdir(self.directory):
            if not (f.startswith("part-") and f.endswith(".parquet")):
                continue
            stem = f[len("part-"):-len(".parquet")]
            if stem.isdigit() and int(stem) > batch_index:
                os.remove(os.path.join(self.directory, f))

    def read_all(self) -> dict:
        import pyarrow.parquet as pq
        import pyarrow as pa

        files = sorted(
            (os.path.join(self.directory, f)
             for f in os.listdir(self.directory)
             if f.endswith(".parquet")),
            key=_part_order,
        )
        if not files:
            return {}
        table = pa.concat_tables([pq.read_table(f) for f in files])
        return {c: table[c].to_numpy() for c in table.column_names}


class StoreParquetSink(_SinkTelemetry):
    """:class:`ParquetSink` semantics over an object store (S3/MinIO).

    The reference lands all streaming output on MinIO
    (``s3a://commerce/warehouse``, ``kafka_s3_sink_transactions.py`` /
    ``fraud_detection.py:204-211``); this sink writes the same
    part-per-batch parquet layout through any :mod:`..io.store` object.
    Exactly-once naming is identical to :class:`ParquetSink`
    (``part-<batch_index>`` overwrite-on-replay); object PUTs are atomic,
    so there is no tmp+rename dance. ``truncate_after`` is the same
    sink-side restore fence.
    """

    def __init__(self, store):
        self.store = store
        self._seq = 0
        self._init_sink_metrics("store_parquet")

    def append(self, res) -> None:
        import pyarrow as pa
        import pyarrow.parquet as pq

        t0 = time.perf_counter()
        try:
            cols = _result_to_columns(res)
            table = pa.table({k: pa.array(v) for k, v in cols.items()})
            idx = getattr(res, "batch_index", -1)
            if idx >= 0:
                name = f"part-{idx:08d}.parquet"
            else:
                name = (f"part-{int(time.time() * 1e3)}-"
                        f"{self._seq:06d}.parquet")
                self._seq += 1
            buf = pa.BufferOutputStream()
            pq.write_table(table, buf)
            data = buf.getvalue().to_pybytes()
            self.store.put(name, data)
        except Exception:
            self._m_failures.inc()
            raise
        self._observe_write(t0, len(res.tx_id), len(data))

    def truncate_after(self, batch_index: int) -> None:
        for key in self.store.list(""):
            f = key.rsplit("/", 1)[-1]
            if not (f.startswith("part-") and f.endswith(".parquet")):
                continue
            stem = f[len("part-"):-len(".parquet")]
            if stem.isdigit() and int(stem) > batch_index:
                self.store.delete(key)

    def read_all(self) -> dict:
        import io as _io

        import pyarrow as pa
        import pyarrow.parquet as pq

        keys = sorted((k for k in self.store.list("")
                       if k.endswith(".parquet")), key=_part_order)
        if not keys:
            return {}
        table = pa.concat_tables(
            [pq.read_table(_io.BytesIO(self.store.get(k))) for k in keys]
        )
        return {c: table[c].to_numpy() for c in table.column_names}


def _dlq_row_record(cols: dict, i: int, *, reason: str, error: str,
                    batch_index: int, offsets, trace_id: str,
                    envelope: Optional[bytes]) -> dict:
    """One quarantined row as a JSON-able record: decoded columns where
    available, the raw envelope bytes when the caller still has them,
    and the error/lineage metadata an operator needs to triage it."""
    def scalar(v):
        x = v[i]
        try:
            return x.item()
        except AttributeError:
            return x

    rec = {
        "tx_id": int(cols["tx_id"][i]),
        "reason": reason,
        "error": str(error)[:500],
        "batch_index": int(batch_index),
        "offsets": [int(o) for o in offsets] if offsets is not None
        else None,
        "trace_id": trace_id or "",
        "t": time.time(),
        "columns": {k: scalar(v) for k, v in cols.items()},
    }
    if envelope is not None:
        import base64

        rec["envelope_b64"] = base64.b64encode(bytes(envelope)).decode()
    return rec


class _DeadLetterTelemetry:
    """Shared DLQ instrumentation + flight-record events. The absolute
    row gauge (``rtfds_dead_letter_rows``) is what ``/healthz`` keys its
    ``degraded`` state on.

    ``recorder_fn`` overrides where flight events land (a zero-arg
    callable returning a recorder or None): the overload spill reuses
    this machinery with a private registry and its own ``shed`` events —
    deferred-for-replay rows are NOT a triage backlog and must not trip
    the DLQ ``degraded`` state or the dead-letter dashboard tile."""

    def _init_dlq_metrics(self, registry=None, recorder_fn=None) -> None:
        from real_time_fraud_detection_system_tpu.utils.metrics import (
            active_recorder,
        )

        self._reg = registry if registry is not None else get_registry()
        self._recorder = (recorder_fn if recorder_fn is not None
                          else active_recorder)
        self._m_gauge = self._reg.gauge(
            "rtfds_dead_letter_rows",
            "rows currently quarantined in the dead-letter queue")

    def _observe_put(self, written: int, reason: str, batch_index: int,
                     total: int) -> None:
        if written:
            self._reg.counter(
                "rtfds_dead_letter_rows_total",
                "rows quarantined to the dead-letter queue by reason",
                reason=reason).inc(written)
        self._m_gauge.set(total)
        rec = self._recorder()
        if rec is not None and written:
            rec.record_event("dead_letter", rows=written, reason=reason,
                             batch=int(batch_index))


class DeadLetterSink(_DeadLetterTelemetry):
    """JSONL dead-letter queue — one record per quarantined row.

    The quarantine side of the supervisor's poison-isolation path
    (``runtime/faults.run_with_recovery``) and the engine's non-finite
    guard: instead of a poison row killing the stream (or silently
    contaminating feature state), its raw envelope bytes (when known),
    decoded columns, error type/message, batch index, offsets, and trace
    id land here and the stream continues past it. **Idempotent by
    tx_id**: already-quarantined rows are skipped on write (the seen-set
    is rebuilt from the file on open), so a crash mid-bisection followed
    by checkpoint replay neither loses nor duplicates DLQ rows, and
    ``read_all`` additionally dedups latest-wins. Inspect/replay with
    ``rtfds dlq``.
    """

    def __init__(self, path: str, registry=None, recorder_fn=None):
        self.path = path
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._lock = threading.Lock()
        self._seen: set = set()
        self._init_dlq_metrics(registry, recorder_fn)
        if os.path.exists(path):
            for rec in self._iter_file():
                self._seen.add(int(rec["tx_id"]))
        self._f = open(path, "a", encoding="utf-8")
        self._m_gauge.set(len(self._seen))

    def _iter_file(self):
        import json

        with open(self.path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn tail after a crash: skip
                if "tx_id" in rec:
                    yield rec

    def put_rows(self, cols: dict, *, reason: str, error: str = "",
                 errors: Optional[List[str]] = None, batch_index: int = -1,
                 offsets=None, trace_id: str = "",
                 envelopes: Optional[List[bytes]] = None) -> int:
        """Quarantine every row of ``cols`` (a columnar dict as polled);
        rows whose tx_id is already quarantined are skipped. ``errors``
        optionally carries a per-row message (bisection knows each row's
        exception); ``error`` is the shared fallback. Returns the number
        of rows actually written."""
        import json

        n = len(cols["tx_id"])
        written = 0
        with self._lock:
            for i in range(n):
                tx = int(cols["tx_id"][i])
                if tx in self._seen:
                    continue
                rec = _dlq_row_record(
                    cols, i, reason=reason,
                    error=errors[i] if errors is not None else error,
                    batch_index=batch_index, offsets=offsets,
                    trace_id=trace_id,
                    envelope=envelopes[i] if envelopes is not None
                    else None)
                self._f.write(json.dumps(rec, separators=(",", ":"),
                                         default=str) + "\n")
                self._seen.add(tx)
                written += 1
            self._f.flush()
        self._observe_put(written, reason, batch_index, len(self._seen))
        return written

    def read_all(self) -> List[dict]:
        """Quarantined rows, deduped by tx_id (latest record wins),
        ordered by (batch_index, tx_id)."""
        with self._lock:
            self._f.flush()
        by_tx = {}
        for rec in self._iter_file():
            by_tx[int(rec["tx_id"])] = rec
        return sorted(by_tx.values(),
                      key=lambda r: (r.get("batch_index", -1), r["tx_id"]))

    def tx_ids(self) -> List[int]:
        return sorted(self._seen)

    def __len__(self) -> int:
        return len(self._seen)

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()


class ParquetDeadLetterSink(_DeadLetterTelemetry):
    """:class:`DeadLetterSink` semantics as parquet parts under a
    directory — the variant whose output any Iceberg/Trino/DuckDB reader
    can mount next to the analyzed table. One part per quarantine call
    (``dlq-<batch_index>-<reason>.parquet``), so a checkpoint replay
    that re-isolates the same batch atomically OVERWRITES its own part
    instead of duplicating rows — the same exactly-once naming trick as
    :class:`ParquetSink`. The tx_id seen-set is rebuilt from the parts
    on open (write-side idempotence across restarts)."""

    def __init__(self, directory: str, registry=None, recorder_fn=None):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()
        self._seen: set = set()
        self._init_dlq_metrics(registry, recorder_fn)
        for rec in self.read_all():
            self._seen.add(int(rec["tx_id"]))
        self._m_gauge.set(len(self._seen))

    def put_rows(self, cols: dict, *, reason: str, error: str = "",
                 errors: Optional[List[str]] = None, batch_index: int = -1,
                 offsets=None, trace_id: str = "",
                 envelopes: Optional[List[bytes]] = None) -> int:
        import json

        import pyarrow as pa
        import pyarrow.parquet as pq

        n = len(cols["tx_id"])
        recs = []
        with self._lock:
            for i in range(n):
                tx = int(cols["tx_id"][i])
                if tx in self._seen:
                    continue
                recs.append(_dlq_row_record(
                    cols, i, reason=reason,
                    error=errors[i] if errors is not None else error,
                    batch_index=batch_index, offsets=offsets,
                    trace_id=trace_id,
                    envelope=envelopes[i] if envelopes is not None
                    else None))
            if recs:
                flat = [{
                    **{k: v for k, v in r.items()
                       if k not in ("columns", "offsets")},
                    "columns_json": json.dumps(r["columns"], default=str),
                    "offsets_json": json.dumps(r["offsets"]),
                } for r in recs]
                name = f"dlq-{max(int(batch_index), 0):08d}-{reason}.parquet"
                path = os.path.join(self.directory, name)
                if os.path.exists(path):
                    # A later quarantine for the SAME (batch, reason) —
                    # e.g. the nan-guard rescore flushing out a second
                    # row — must MERGE with the part, not replace it:
                    # the seen-set skips rows already on disk, so a
                    # plain overwrite would silently drop them.
                    new_ids = {int(r["tx_id"]) for r in flat}
                    keys = list(flat[0])
                    flat = [{k: row.get(k) for k in keys}
                            for row in pq.read_table(path).to_pylist()
                            if int(row.get("tx_id", -1)) not in new_ids
                            ] + flat
                table = pa.table({
                    k: pa.array([r.get(k) for r in flat])
                    for k in flat[0]
                })
                tmp = path + ".tmp"
                pq.write_table(table, tmp)
                os.replace(tmp, path)
                for r in recs:
                    self._seen.add(int(r["tx_id"]))
        self._observe_put(len(recs), reason, batch_index, len(self._seen))
        return len(recs)

    def read_all(self) -> List[dict]:
        import json

        import pyarrow.parquet as pq

        by_tx = {}
        if not os.path.isdir(self.directory):
            return []
        for f in sorted(os.listdir(self.directory)):
            if not (f.startswith("dlq-") and f.endswith(".parquet")):
                continue
            table = pq.read_table(os.path.join(self.directory, f))
            for row in table.to_pylist():
                rec = dict(row)
                rec["columns"] = json.loads(rec.pop("columns_json", "{}"))
                off = rec.pop("offsets_json", "null")
                rec["offsets"] = json.loads(off) if off else None
                by_tx[int(rec["tx_id"])] = rec
        return sorted(by_tx.values(),
                      key=lambda r: (r.get("batch_index", -1), r["tx_id"]))

    def tx_ids(self) -> List[int]:
        return sorted(self._seen)

    def __len__(self) -> int:
        return len(self._seen)

    def close(self) -> None:
        pass


def make_dead_letter_sink(path: str, registry=None, recorder_fn=None):
    """``*.jsonl`` (or an existing plain file) → :class:`DeadLetterSink`;
    anything else → :class:`ParquetDeadLetterSink` directory."""
    if path.endswith(".jsonl") or os.path.isfile(path):
        return DeadLetterSink(path, registry=registry,
                              recorder_fn=recorder_fn)
    return ParquetDeadLetterSink(path, registry=registry,
                                 recorder_fn=recorder_fn)


def read_dead_letter(path: str) -> List[dict]:
    """Read-only DLQ load for inspection/replay (``rtfds dlq``): never
    creates the file/directory, raises FileNotFoundError when absent."""
    if os.path.isfile(path):
        s = DeadLetterSink(path)
        try:
            return s.read_all()
        finally:
            s.close()
    if os.path.isdir(path):
        return ParquetDeadLetterSink(path).read_all()
    raise FileNotFoundError(f"no dead-letter queue at {path!r}")


def make_parquet_sink(path_or_url: str, **store_kwargs):
    """``s3://bucket/prefix`` → :class:`StoreParquetSink` (via
    :func:`..io.store.make_store`, which honors ``RTFDS_S3_ENDPOINT`` for
    MinIO); local path → :class:`ParquetSink`."""
    if path_or_url.startswith("s3://"):
        from real_time_fraud_detection_system_tpu.io.store import make_store

        return StoreParquetSink(make_store(path_or_url, **store_kwargs))
    return ParquetSink(path_or_url)


class IcebergSink(_SinkTelemetry):
    """Append scored rows to an Iceberg ``analyzed_transactions`` table.

    The reference's scorer streams into ``nessie.payment.
    analyzed_transactions`` (DDL at ``fraud_detection.py:136-163``,
    appended at ``:204-211``), which Trino/Superset read. This sink
    appends the same column layout through a pyiceberg catalog:
    timestamps as µs-precision Arrow timestamps, amount/prediction as
    doubles, window counts as int32.

    ``catalog`` is injectable (duck-typed ``load_table``/``create_table``)
    so tests run against a fake without pyiceberg; production use goes
    through :func:`make_iceberg_sink`, which builds a real catalog from
    ``pyiceberg.catalog.load_catalog``.
    """

    TABLE_DEFAULT = "payment.analyzed_transactions"

    def __init__(self, catalog, table_name: str = TABLE_DEFAULT):
        self.catalog = catalog
        self.table_name = table_name
        self.table = self._load_or_create(catalog, table_name)
        self._init_sink_metrics("iceberg")

    @staticmethod
    def arrow_schema():
        import pyarrow as pa

        fields = [
            ("tx_id", pa.int64()),
            ("tx_datetime", pa.timestamp("us")),
            ("customer_id", pa.int64()),
            ("terminal_id", pa.int64()),
            ("tx_amount", pa.float64()),
        ]
        for name in FEATURE_NAMES:
            if name == "TX_AMOUNT":
                continue
            t = (
                pa.int32()
                if ("NB_TX" in name or "DURING" in name)
                else pa.float64()
            )
            fields.append((name.lower(), t))
        fields += [
            ("processed_at", pa.timestamp("us")),
            ("prediction", pa.float64()),
        ]
        return pa.schema(fields)

    def _load_or_create(self, catalog, name: str):
        exists = getattr(catalog, "table_exists", None)
        if exists is not None and not exists(name):
            return catalog.create_table(name, schema=self.arrow_schema())
        try:
            return catalog.load_table(name)
        except Exception as e:
            # Only a missing table warrants create; transient catalog
            # errors (network/auth) must surface, not turn into a
            # confusing create-conflict downstream.
            if type(e).__name__ in ("NoSuchTableError", "KeyError"):
                return catalog.create_table(name, schema=self.arrow_schema())
            raise

    def _to_arrow(self, res):
        import pyarrow as pa

        cols = _result_to_columns(res)
        arrays, names = [], []
        for field in self.arrow_schema():
            if field.name == "tx_datetime":
                v = cols["tx_datetime_us"]
            elif field.name == "processed_at":
                v = cols["processed_at_us"]
            else:
                v = cols[field.name]
            arrays.append(pa.array(v).cast(field.type))
            names.append(field.name)
        return pa.table(dict(zip(names, arrays)))

    def append(self, res) -> None:
        t0 = time.perf_counter()
        try:
            tbl = self._to_arrow(res)
            self.table.append(tbl)
        except Exception:
            self._m_failures.inc()
            raise
        self._observe_write(t0, len(res.tx_id), tbl.nbytes)


def make_iceberg_sink(
    table_name: str = IcebergSink.TABLE_DEFAULT,
    catalog_name: str = "default",
    catalog: Optional[object] = None,
    **catalog_props,
) -> IcebergSink:
    """Production Iceberg sink factory (import-gated on pyiceberg).

    ``catalog_props`` go straight to ``pyiceberg.catalog.load_catalog``
    (URI, warehouse, credentials — the values the reference spreads over
    ``docker-compose.yml:58-68`` and every SparkConf block).
    """
    if catalog is None:
        try:
            from pyiceberg.catalog import load_catalog
        except ImportError as e:
            raise ImportError(
                "pyiceberg is not installed; ParquetSink output is Iceberg-"
                "compatible (add files to a table via any catalog), or "
                "install pyiceberg in production images."
            ) from e
        catalog = load_catalog(catalog_name, **catalog_props)
    return IcebergSink(catalog, table_name)
