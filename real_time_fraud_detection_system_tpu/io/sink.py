"""Output sinks — the ``analyzed_transactions`` append path.

The reference appends scored rows to an Iceberg table that Trino/Superset
read (``fraud_detection.py:204-211``). The framework writes the same
column layout (``core/schema.py::ANALYZED_TRANSACTIONS_FIELDS``):

- :class:`ParquetSink` — one Parquet part-file per micro-batch under a
  directory; any Iceberg/Trino/DuckDB reader can mount it. Columns are
  byte-compatible with the reference table (µs timestamps, f64 amounts).
- :class:`MemorySink` — accumulates in RAM (tests, metrics).
- :class:`ConsoleSink` — the reference's ``.show()`` debugging analogue.

An ``IcebergSink`` (pyiceberg catalog append) belongs here too; pyiceberg is
not in this image, so it is import-gated the same way KafkaSource is.
"""

from __future__ import annotations

import os
import time
from typing import List, Optional

import numpy as np

from real_time_fraud_detection_system_tpu.features.spec import FEATURE_NAMES


def _result_to_columns(res) -> dict:
    """BatchResult → analyzed_transactions column dict."""
    now_us = int(time.time() * 1e6)
    n = len(res.tx_id)
    cols = {
        "tx_id": res.tx_id.astype(np.int64),
        "tx_datetime_us": res.tx_datetime_us.astype(np.int64),
        "customer_id": res.customer_id.astype(np.int64),
        "terminal_id": res.terminal_id.astype(np.int64),
        "tx_amount": res.amount_cents.astype(np.float64) / 100.0,
    }
    # feature columns, lower-cased like the reference table DDL
    for i, name in enumerate(FEATURE_NAMES):
        if name == "TX_AMOUNT":
            continue
        dt = np.int32 if ("NB_TX" in name or "DURING" in name) else np.float64
        cols[name.lower()] = res.features[:, i].astype(dt)
    cols["processed_at_us"] = np.full(n, now_us, dtype=np.int64)
    cols["prediction"] = res.probs.astype(np.float64)
    return cols


class MemorySink:
    def __init__(self):
        self.batches: List[dict] = []

    def append(self, res) -> None:
        self.batches.append(_result_to_columns(res))

    def concat(self) -> dict:
        if not self.batches:
            return {}
        keys = self.batches[0].keys()
        return {k: np.concatenate([b[k] for b in self.batches]) for k in keys}


class ConsoleSink:
    def __init__(self, every: int = 1, limit: int = 5):
        self.every = every
        self.limit = limit
        self._n = 0

    def append(self, res) -> None:
        self._n += 1
        if self._n % self.every:
            return
        n = len(res.tx_id)
        print(f"[batch {self._n}] rows={n} p(fraud): "
              f"mean={res.probs.mean():.4f} max={res.probs.max():.4f}")
        for i in range(min(self.limit, n)):
            print(
                f"  tx {res.tx_id[i]} cust {res.customer_id[i]} "
                f"amt {res.amount_cents[i] / 100:.2f} -> {res.probs[i]:.4f}"
            )


class ParquetSink:
    """One part file per batch: ``<dir>/part-<epoch_ms>-<seq>.parquet``."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._seq = 0

    def append(self, res) -> None:
        import pyarrow as pa
        import pyarrow.parquet as pq

        cols = _result_to_columns(res)
        table = pa.table({k: pa.array(v) for k, v in cols.items()})
        path = os.path.join(
            self.directory, f"part-{int(time.time() * 1e3)}-{self._seq:06d}.parquet"
        )
        pq.write_table(table, path)
        self._seq += 1

    def read_all(self) -> dict:
        import pyarrow.parquet as pq
        import pyarrow as pa

        files = sorted(
            os.path.join(self.directory, f)
            for f in os.listdir(self.directory)
            if f.endswith(".parquet")
        )
        if not files:
            return {}
        table = pa.concat_tables([pq.read_table(f) for f in files])
        return {c: table[c].to_numpy() for c in table.column_names}


def make_iceberg_sink(*args, **kwargs):  # pragma: no cover - gated
    """Iceberg catalog append (pyiceberg not present in this image)."""
    try:
        import pyiceberg  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "pyiceberg is not installed; ParquetSink output is Iceberg-"
            "compatible (add files to a table via any catalog), or install "
            "pyiceberg in production images."
        ) from e
    raise NotImplementedError
