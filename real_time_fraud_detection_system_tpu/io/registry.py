"""Versioned model registry — the continuous-learning artifact plane.

The reference retrains offline and restarts the Spark job to pick up a
new ``trained_model.pkl`` (``fraud_detection.py:59-82``); MLlib's answer
(arXiv:1505.06807) is pipeline persistence with no operational story for
*which* model is serving or how to get back to the previous one. Here
every artifact that can ever serve gets:

- a **monotonically increasing version** (``model-v0000001.npz``) — the
  registry never overwrites an artifact in place;
- a **content hash** (sha256 over the artifact bytes, recorded in the
  side manifest ``model-v0000001.json``) verified on every ``get`` — a
  corrupt candidate can never be promoted (quarantined ``stale-…`` +
  ``rtfds_model_artifact_corrupt_total{reason=…}``, mirroring checkpoint
  format v2), on top of the artifact's own internal content hash
  (:mod:`.artifacts` format v1);
- **training-window metadata** (labels trained on, source, wall time);
- **lineage** (parent version — the champion a candidate was warm-started
  from).

The **champion pointer** (``champion.json``) records which version is
serving plus the promotion history, so ``rollback()`` is one atomic
pointer move back to the previous champion — no artifact bytes move.
``rtfds_model_version{role=champion|candidate}`` exports both sides of
the canary.

Storage reuses the checkpoint lineage backends (:mod:`.checkpoint`):
local directory (tmp write + atomic rename) or any :mod:`.store` object
store — the store plane inherits PR 6's flaky-store hardening (retries
with original-typed propagation, optional per-op timeout) for free.
"""

from __future__ import annotations

import hashlib
import json
import re
import threading
import time
import uuid
from typing import List, Optional

from real_time_fraud_detection_system_tpu.io.artifacts import (
    CorruptModelError,
    dump_model_bytes,
    load_model_bytes,
)
from real_time_fraud_detection_system_tpu.io.checkpoint import (
    _LocalBackend,
    _StoreBackend,
)
from real_time_fraud_detection_system_tpu.utils.metrics import (
    active_recorder,
    get_registry,
)

CHAMPION_KEY = "champion.json"
_ENTRY_RE = re.compile(r"^model-v(\d{7})\.json$")


def _name_of(version: int, ext: str) -> str:
    return f"model-v{int(version):07d}.{ext}"


class ModelRegistry:
    """Append-only versioned artifacts + an atomic champion pointer.

    One writer per version (the streaming learner publishes candidates;
    the controller/CLI moves the pointer); reads verify everything.
    Thread-safe: ``publish`` runs on the learner's worker thread while
    the serving loop promotes/gets.
    """

    def __init__(self, backend):
        self._backend = backend
        # Two narrow locks instead of one registry-wide lock: version
        # allocation (shared by the learner's worker-thread publish and
        # the loop thread's reload publish) and the champion pointer
        # (loop thread / CLI). The artifact PUTs themselves run OUTSIDE
        # any lock — on a store backend they carry retries and per-op
        # timeouts, and a hung learner PUT must never block the serving
        # loop's promote()/rollback() for the whole retry budget.
        self._alloc_lock = threading.Lock()
        self._ptr_lock = threading.Lock()
        # Intra-process allocation floor: versions handed out by THIS
        # process whose writes may still be in flight (the PUTs run
        # outside the lock). Allocation re-lists the backend every time
        # instead of caching a next-version counter: another PROCESS
        # (`rtfds registry --publish` beside a serving run) may have
        # taken versions since, and a stale cached counter would
        # silently overwrite its artifact. The remaining cross-process
        # window is one exists-check→write race between two truly
        # simultaneous publishes — far outside the one-serving-loop +
        # occasional-CLI operational model.
        self._alloc_floor = 0
        reg = get_registry()
        self._m_ops = {
            op: reg.counter("rtfds_model_registry_ops_total",
                            "model registry operations", op=op)
            for op in ("publish", "get", "promote", "rollback")
        }
        self._m_corrupt = {
            r: reg.counter(
                "rtfds_model_artifact_corrupt_total",
                "model artifacts that failed load-time verification",
                reason=r)
            for r in ("checksum", "truncated")
        }
        self._g_version = {
            role: reg.gauge(
                "rtfds_model_version",
                "registry model version by role (champion = serving, "
                "candidate = newest published)", role=role)
            for role in ("champion", "candidate")
        }
        ch = self.champion_version()
        if ch is not None:
            self._g_version["champion"].set(ch)
        vs = self.versions()
        if vs:
            self._g_version["candidate"].set(vs[-1])

    # -- listing ----------------------------------------------------------

    def versions(self) -> List[int]:
        """Live versions, oldest → newest."""
        out = []
        for n in self._backend.list_names():
            m = _ENTRY_RE.match(n)
            if m is not None:
                out.append(int(m.group(1)))
        return sorted(out)

    def meta(self, version: int) -> dict:
        """The side manifest of one version. Raises ``KeyError`` when the
        version does not exist and :class:`CorruptModelError` (reason
        ``truncated``) when the manifest bytes exist but do not parse —
        a torn manifest PUT must surface as corruption the promotion
        gate refuses, never as a stray ``ValueError`` that kills the
        serving loop."""
        data = self._backend.read(_name_of(version, "json"))
        try:
            man = json.loads(data.decode())
        except (ValueError, UnicodeDecodeError) as e:
            raise CorruptModelError(
                "truncated",
                f"manifest for v{version} is unreadable "
                f"({type(e).__name__}: {e})") from None
        if not isinstance(man, dict):
            raise CorruptModelError(
                "truncated", f"manifest for v{version} is not an object")
        return man

    # -- publish ----------------------------------------------------------

    def publish(self, model, parent: Optional[int] = None,
                source: str = "learner", labels_trained: int = 0,
                note: str = "") -> int:
        """Serialize + register a new version; returns it.

        The artifact npz lands FIRST, the side manifest second — a crash
        in between leaves an unlisted orphan npz, never a manifest that
        names missing bytes."""
        data = dump_model_bytes(model)
        sha = hashlib.sha256(data).hexdigest()
        with self._alloc_lock:
            vs = self.versions()
            version = max((vs[-1] + 1) if vs else 1,
                          self._alloc_floor + 1)
            while (self._backend.exists(_name_of(version, "npz"))
                   or self._backend.exists(_name_of(version, "json"))):
                # an unlisted orphan npz (concurrent publish mid-write,
                # or a crash between npz and manifest): never reuse its
                # number
                version += 1
            self._alloc_floor = version
        # The (possibly slow, retried) artifact writes run unlocked: the
        # allocated version is already unique, and the loop thread's
        # pointer ops must not queue behind a hung store PUT.
        self._backend.write(_name_of(version, "npz"), data)
        manifest = {
            "version": version,
            "kind": model.kind,
            "sha256": sha,
            "size": len(data),
            "created_unix": time.time(),
            "parent": parent,
            "source": source,
            "labels_trained": int(labels_trained),
            "note": note,
        }
        self._backend.write(
            _name_of(version, "json"),
            json.dumps(manifest, sort_keys=True,
                       separators=(",", ":")).encode())
        self._m_ops["publish"].inc()
        self._g_version["candidate"].set(version)
        rec = active_recorder()
        if rec is not None:
            rec.record_event("model_published", version=version,
                             kind=model.kind, parent=parent, source=source,
                             labels_trained=int(labels_trained))
        return version

    # -- verified get -----------------------------------------------------

    def _note_corrupt(self, version: int, err: CorruptModelError) -> None:
        self._m_corrupt[err.reason].inc()
        rec = active_recorder()
        if rec is not None:
            rec.record_event("model_artifact_corrupt", version=version,
                             reason=err.reason, detail=err.detail[:200])
        from real_time_fraud_detection_system_tpu.utils.logging import (
            get_logger,
        )

        get_logger("registry").error(
            "corrupt model artifact v%d (%s: %s) — quarantining",
            version, err.reason, err.detail[:200])
        token = uuid.uuid4().hex[:8]
        for ext in ("npz", "json"):
            name = _name_of(version, ext)
            if self._backend.exists(name):
                self._backend.move(name, f"stale-{token}-{name}")

    @staticmethod
    def _verify_bytes(man: dict, data: bytes):
        """The ONE verification core (promotion gate and deploy preflight
        must agree): manifest size, manifest sha256, then the artifact's
        own internal content hash via ``load_model_bytes``. Raises
        :class:`CorruptModelError`; returns the loaded model."""
        if man.get("size") is not None and len(data) != int(man["size"]):
            raise CorruptModelError(
                "truncated",
                f"artifact is {len(data)} bytes, manifest says "
                f"{man['size']}")
        if hashlib.sha256(data).hexdigest() != man.get("sha256"):
            raise CorruptModelError(
                "checksum", "artifact bytes do not match the "
                "manifest sha256")
        return load_model_bytes(data)  # internal hash re-verified

    def get(self, version: int):
        """Load version → ``TrainedModel``, verifying the registry-level
        sha256 AND the artifact's internal content hash. On any mismatch
        the entry is quarantined (``stale-…``, bytes preserved) and
        :class:`CorruptModelError` raises — the caller (promotion gate,
        shadow install) must refuse, never serve, a bad artifact.
        Raises ``KeyError`` for a version that does not exist."""
        try:
            man = self.meta(version)
        except CorruptModelError as e:
            self._note_corrupt(version, e)
            raise
        try:
            data = self._backend.read(_name_of(version, "npz"))
        except KeyError:
            err = CorruptModelError(
                "truncated", f"artifact bytes for v{version} are missing")
            self._note_corrupt(version, err)
            raise err from None
        try:
            model = self._verify_bytes(man, data)
        except CorruptModelError as e:
            self._note_corrupt(version, e)
            raise
        self._m_ops["get"].inc()
        return model

    # -- champion pointer -------------------------------------------------

    def _read_pointer(self) -> Optional[dict]:
        """The champion pointer, or None when none was ever written.

        A pointer whose bytes exist but do not parse (torn PUT) is NOT
        absence — treating it as absence would silently revert serving
        to the bootstrap model and let the next ``promote`` rebuild an
        empty history, destroying rollback. It is quarantined
        (``stale-…``, bytes preserved), counted
        (``rtfds_model_artifact_corrupt_total{reason=truncated}``) and
        logged; only then does the registry proceed as pointerless —
        loud degradation, the same contract as a corrupt artifact."""
        try:
            data = self._backend.read(CHAMPION_KEY)
        except KeyError:
            return None
        try:
            ptr = json.loads(data.decode())
            if not isinstance(ptr, dict) or "version" not in ptr:
                raise ValueError("not a pointer object")
            return ptr
        except (ValueError, UnicodeDecodeError) as e:
            self._m_corrupt["truncated"].inc()
            rec = active_recorder()
            if rec is not None:
                rec.record_event("model_pointer_corrupt",
                                 detail=str(e)[:200])
            from real_time_fraud_detection_system_tpu.utils.logging import (
                get_logger,
            )

            token = uuid.uuid4().hex[:8]
            stale = f"stale-{token}-{CHAMPION_KEY}"
            try:
                self._backend.move(CHAMPION_KEY, stale)
            # rtfdslint: disable=broad-exception-catch (quarantine of an unreadable champion pointer is best-effort forensics; the fallback-to-bootstrap path below is the real handling and must run regardless of what the move raised)
            except Exception:
                stale = "(could not quarantine)"
            get_logger("registry").error(
                "champion pointer is unreadable (%s: %s) — quarantined "
                "to %s; serving falls back to the bootstrap model and "
                "promotion history is lost (recover it from the "
                "quarantined file, then `rtfds registry --promote`)",
                type(e).__name__, e, stale)
            return None

    def _write_pointer(self, ptr: dict) -> None:
        self._backend.write(
            CHAMPION_KEY,
            json.dumps(ptr, sort_keys=True, separators=(",", ":")).encode())

    def champion_version(self) -> Optional[int]:
        ptr = self._read_pointer()
        return int(ptr["version"]) if ptr else None

    def champion(self):
        """Verified ``TrainedModel`` of the serving champion, or None."""
        v = self.champion_version()
        return self.get(v) if v is not None else None

    def promote(self, version: int, by: str = "controller") -> dict:
        """Move the champion pointer to ``version`` (must exist). The
        previous champion is pushed on the pointer's history stack so
        :meth:`rollback` is one pointer move. Does NOT verify bytes —
        the promotion gate calls :meth:`get` first (a promote of
        unverified bytes is the caller's bug)."""
        self.meta(version)  # existence check: KeyError on a ghost
        with self._ptr_lock:
            ptr = self._read_pointer() or {"history": []}
            prev = ptr.get("version")
            hist = list(ptr.get("history", []))
            if prev is not None:
                hist.append(int(prev))
            ptr = {"version": int(version), "history": hist,
                   "promoted_unix": time.time(), "by": by}
            self._write_pointer(ptr)
        self._m_ops["promote"].inc()
        self._g_version["champion"].set(version)
        return ptr

    def rollback(self) -> Optional[int]:
        """Pop the pointer back to the previous champion; returns the
        restored version, or None when there is no history to return
        to. The abandoned champion's artifact stays in the registry
        (forensics + the lineage record of what served when)."""
        with self._ptr_lock:
            ptr = self._read_pointer()
            if not ptr or not ptr.get("history"):
                return None
            hist = list(ptr["history"])
            prev = int(hist.pop())
            self._write_pointer({"version": prev, "history": hist,
                                 "promoted_unix": time.time(),
                                 "by": "rollback"})
        self._m_ops["rollback"].inc()
        self._g_version["champion"].set(prev)
        return prev

    # -- verification (CLI preflight) -------------------------------------

    def list_versions(self) -> List[dict]:
        """One row per live version (cheap: manifests only), champion
        flagged."""
        ch = self.champion_version()
        out = []
        for v in self.versions():
            try:
                man = self.meta(v)
            except (KeyError, CorruptModelError):
                man = {"version": v, "error": "manifest unreadable"}
            man["role"] = "champion" if v == ch else "candidate"
            out.append(man)
        return out

    def verify_all(self) -> List[dict]:
        """Re-hash every live artifact against its manifest WITHOUT
        quarantining or counting metrics (``rtfds registry --verify`` —
        the deploy preflight; exit 1 on any corruption)."""
        out = []
        ch = self.champion_version()
        for v in self.versions():
            entry = {"version": v,
                     "role": "champion" if v == ch else "candidate"}
            try:
                man = self.meta(v)
                data = self._backend.read(_name_of(v, "npz"))
                self._verify_bytes(man, data)
                entry.update(kind=man.get("kind"), size=man.get("size"),
                             parent=man.get("parent"),
                             source=man.get("source"),
                             labels_trained=man.get("labels_trained"),
                             valid=True)
            except CorruptModelError as e:
                entry.update(valid=False, reason=e.reason,
                             detail=e.detail[:200])
            except KeyError:
                entry.update(valid=False, reason="truncated",
                             detail="artifact or manifest missing")
            out.append(entry)
        return out


def make_model_registry(path_or_url: str, op_timeout_s: float = 0.0,
                        op_attempts: int = 3) -> ModelRegistry:
    """``s3://bucket/prefix`` → store-backed registry (flaky-store
    hardened); local path → directory-backed registry."""
    if path_or_url.startswith("s3://"):
        from real_time_fraud_detection_system_tpu.io.store import make_store

        return ModelRegistry(
            _StoreBackend(make_store(path_or_url), prefix="",
                          op_timeout_s=op_timeout_s,
                          op_attempts=op_attempts))
    return ModelRegistry(_LocalBackend(path_or_url))
