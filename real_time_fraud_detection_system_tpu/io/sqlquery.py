"""SQL over the analyzed Parquet output — the Trino role, in-process.

The reference serves analysts through Superset → Trino → Iceberg
(``superset/entrypoint.sh:19``, ``trino-config/catalog/nessie.properties``).
This module mounts a :class:`~.sink.ParquetSink` directory as a queryable
``analyzed`` table for plain SQL:

- **DuckDB** when installed (same Parquet-scan architecture Trino uses);
- otherwise **pyarrow.dataset → in-memory sqlite3** (both ship with the
  base image, so SQL access needs zero extra services).

Either engine sees the table through a latest-wins-by-``tx_id`` view
(ROW_NUMBER over ``processed_at_us`` — the reference's own dedup pattern,
``kafka_s3_sink_transactions.py:173-186``), so crash-replay re-scorings
count once, exactly like :func:`io.query.load_analyzed`.

Used by ``rtfds sql`` and by ``tools/parquet_sql_check.py`` (which also
cross-checks the SQL answers against the numpy query layer).
"""

from __future__ import annotations

import os
from typing import List, Tuple


def _dedup_view_sql(columns: List[str]) -> str:
    """Latest-wins-by-tx_id view over ``analyzed_raw`` (see module
    docstring), projecting exactly the table's columns so the internal
    ``rn`` ranking column never reaches user queries."""
    collist = ", ".join(columns)
    return f"""
CREATE VIEW analyzed AS
SELECT {collist} FROM (
    SELECT *, ROW_NUMBER() OVER (
        PARTITION BY tx_id ORDER BY processed_at_us DESC) AS rn
    FROM analyzed_raw
) WHERE rn = 1
"""


def parquet_files(directory: str) -> List[str]:
    """Sorted ``*.parquet`` part files (ignores crashed-write ``.tmp``)."""
    return sorted(
        os.path.join(directory, f) for f in os.listdir(directory)
        if f.endswith(".parquet")
    )


class AnalyzedSql:
    """A mounted analyzed directory; ``query(sql)`` → (column_names, rows).

    ``engine`` is "duckdb" or "sqlite" (auto-detected at mount time).
    """

    def __init__(self, directory: str):
        files = parquet_files(directory)
        if not files:
            raise FileNotFoundError(
                f"no *.parquet part files under {directory!r}")
        try:
            import duckdb

            self.engine = "duckdb"
            self._con = duckdb.connect()
            quoted = ", ".join("'" + f.replace("'", "''") + "'"
                               for f in files)
            self._con.execute(
                f"CREATE VIEW analyzed_raw AS "
                f"SELECT * FROM read_parquet([{quoted}])")
            names = [r[0] for r in self._con.execute(
                "SELECT * FROM analyzed_raw LIMIT 0").description]
        except ImportError:
            import sqlite3

            import pyarrow.dataset as ds

            self.engine = "sqlite"
            table = ds.dataset(files, format="parquet").to_table()
            self._con = sqlite3.connect(":memory:")
            # every column, types derived from the arrow schema — the
            # fallback must answer the same queries DuckDB would
            import pyarrow.types as pt

            names, decls = [], []
            for field in table.schema:
                if pt.is_integer(field.type) or pt.is_boolean(field.type):
                    t = "INTEGER"
                elif pt.is_floating(field.type):
                    t = "REAL"
                else:
                    t = "TEXT"
                names.append(field.name)
                decls.append(f"{field.name} {t}")
            self._con.execute(
                f"CREATE TABLE analyzed_raw ({', '.join(decls)})")
            cols = [table[c].to_numpy(zero_copy_only=False) for c in names]
            self._con.executemany(
                f"INSERT INTO analyzed_raw VALUES "
                f"({','.join('?' * len(names))})",
                zip(*[c.tolist() for c in cols]),
            )
        self.columns = names
        self._con.execute(_dedup_view_sql(names))

    def query(self, sql: str,
              max_rows: int = 0) -> Tuple[List[str], List[tuple]]:
        """``max_rows > 0`` bounds the fetch (memory stays O(max_rows)
        however large the result); 0 fetches everything."""
        cur = self._con.execute(sql)
        names = [d[0] for d in cur.description] if cur.description else []
        rows = cur.fetchmany(max_rows) if max_rows > 0 else cur.fetchall()
        return names, rows

    def close(self) -> None:
        self._con.close()


def run_queries(directory: str, queries: dict) -> Tuple[str, dict]:
    """Mount once, run several; → (engine, {name: rows})."""
    db = AnalyzedSql(directory)
    try:
        return db.engine, {name: db.query(sql)[1]
                           for name, sql in queries.items()}
    finally:
        db.close()
