from real_time_fraud_detection_system_tpu.io.sink import (  # noqa: F401
    AsyncSink,
    ConsoleSink,
    DeadLetterSink,
    IcebergSink,
    MemorySink,
    ParquetDeadLetterSink,
    ParquetSink,
    StoreParquetSink,
    make_dead_letter_sink,
    make_iceberg_sink,
    make_parquet_sink,
    read_dead_letter,
)
from real_time_fraud_detection_system_tpu.io.checkpoint import (  # noqa: F401
    Checkpointer,
    StoreCheckpointer,
    make_checkpointer,
)
from real_time_fraud_detection_system_tpu.io.store import (  # noqa: F401
    LocalStore,
    S3Store,
    make_store,
)
from real_time_fraud_detection_system_tpu.io.tables import (  # noqa: F401
    RawTransactionsTable,
    UpsertTable,
)
from real_time_fraud_detection_system_tpu.io.dashboard import (  # noqa: F401
    render_dashboard_html,
    write_dashboard,
)
