from real_time_fraud_detection_system_tpu.io.sink import (  # noqa: F401
    ConsoleSink,
    MemorySink,
    ParquetSink,
)
from real_time_fraud_detection_system_tpu.io.checkpoint import (  # noqa: F401
    Checkpointer,
)
