"""Static-HTML fraud-ops dashboard over the analyzed output.

The reference ships Superset pre-wired to Trino over
``nessie.payment.analyzed_transactions`` (``superset/entrypoint.sh:19``,
``docker-compose.yml:141-161``) as its L5 visualization layer. This module
is the in-process equivalent: it renders the canned aggregations from
:mod:`.query` into ONE self-contained HTML file — no server, no JS/CSS
dependencies, works offline and over ``file://`` — so a deployment without
the Trino/Superset stack still gets the dashboard, and one WITH the stack
can keep using Superset on the unchanged Parquet output.

Views (mirroring the reference dashboard's charts over
``analyzed_transactions``):

- headline stat tiles (volume, flags, amounts, score tail)
- transactions-per-bucket and flag-rate-per-bucket time series
  (two charts, one y-axis each — never dual-axis)
- top risky terminals / customers (the scenario-2 / scenario-3 detection
  surfaces, ``data_generator.ipynb · cell 42``) as bar charts
- the recent-alerts work queue as a table

Every chart carries a hover tooltip layer, a ``<details>`` table-view twin
(values are never color- or hover-gated), and light/dark theming driven by
``prefers-color-scheme``.
"""

from __future__ import annotations

import html
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from real_time_fraud_detection_system_tpu.io.query import (
    drift_report,
    fraud_rate_over_time,
    load_analyzed,
    recent_alerts,
    summary_stats,
    top_risky_customers,
    top_risky_terminals,
)

_US = 1_000_000

# Chart geometry (CSS px). Bars stay <= 24px thick per the mark spec.
_W, _H = 640, 200
_PAD_L, _PAD_R, _PAD_T, _PAD_B = 46, 14, 10, 22
_BAR_H = 18


def _esc(v) -> str:
    return html.escape(str(v), quote=True)


def _compact(v: float, money: bool = False) -> str:
    """1,284 / 12.9K / $4.2M — stat-tile value formatting."""
    sign = "-" if v < 0 else ""
    a = abs(float(v))
    pre = "$" if money else ""
    if a >= 1e9:
        s = f"{a / 1e9:.1f}B"
    elif a >= 1e6:
        s = f"{a / 1e6:.1f}M"
    elif a >= 10_000:
        s = f"{a / 1e3:.1f}K"
    elif money:
        s = f"{a:,.2f}"
    elif a == int(a):
        s = f"{int(a):,}"
    else:
        s = f"{a:,.3g}"
    return f"{sign}{pre}{s}"


def _nice_max(v: float) -> float:
    """Round up to a clean axis maximum (1/2/2.5/5 × 10^k)."""
    if v <= 0:
        return 1.0
    exp = np.floor(np.log10(v))
    for m in (1.0, 2.0, 2.5, 5.0, 10.0):
        top = m * 10.0 ** exp
        if v <= top:
            return float(top)
    return float(10.0 ** (exp + 1))


def _day_label(us: int) -> str:
    t = time.gmtime(int(us) // _US)
    return f"{t.tm_year:04d}-{t.tm_mon:02d}-{t.tm_mday:02d}"


def _hour_label(us: int) -> str:
    t = time.gmtime(int(us) // _US)
    return (f"{t.tm_year:04d}-{t.tm_mon:02d}-{t.tm_mday:02d} "
            f"{t.tm_hour:02d}:00")


def _ts_label(us: int) -> str:
    """Full minute-resolution timestamp (alert rows, not bucket labels)."""
    t = time.gmtime(int(us) // _US)
    return (f"{t.tm_year:04d}-{t.tm_mon:02d}-{t.tm_mday:02d} "
            f"{t.tm_hour:02d}:{t.tm_min:02d}")


def _table_twin(headers: Sequence[str], rows: Sequence[Sequence[str]]) -> str:
    """The <details> table view — the WCAG-clean twin of every chart."""
    head = "".join(f"<th>{_esc(h)}</th>" for h in headers)
    body = "".join(
        "<tr>" + "".join(f"<td>{_esc(c)}</td>" for c in r) + "</tr>"
        for r in rows
    )
    return ("<details class='twin'><summary>Table view</summary>"
            f"<table><thead><tr>{head}</tr></thead>"
            f"<tbody>{body}</tbody></table></details>")


def _grid_and_yticks(vmax: float, fmt=lambda v: _compact(v)) -> str:
    """4 hairline gridlines + clean tick labels along the left edge."""
    out = []
    ph = _H - _PAD_T - _PAD_B
    for i in range(5):
        frac = i / 4
        y = _PAD_T + ph * (1 - frac)
        out.append(
            f"<line class='grid' x1='{_PAD_L}' y1='{y:.1f}' "
            f"x2='{_W - _PAD_R}' y2='{y:.1f}'/>"
        )
        out.append(
            f"<text class='tick' x='{_PAD_L - 6}' y='{y + 3:.1f}' "
            f"text-anchor='end'>{_esc(fmt(vmax * frac))}</text>"
        )
    return "".join(out)


def _line_chart(
    xs_label: List[str],
    ys: np.ndarray,
    *,
    unit: str = "",
    percent: bool = False,
) -> str:
    """Single-series line with area wash, end marker, hover layer.

    One series → no legend box (the card title names it); the endpoint
    value is the one direct label.
    """
    n = len(ys)
    if n == 0:
        return "<p class='empty'>no data</p>"
    pw = _W - _PAD_L - _PAD_R
    ph = _H - _PAD_T - _PAD_B
    vmax = _nice_max(float(np.max(ys)) if n else 1.0)
    if percent:
        vmax = max(vmax, 0.05)

    def px(i: int) -> float:
        return _PAD_L + (pw * (i + 0.5) / n)

    def py(v: float) -> float:
        return _PAD_T + ph * (1.0 - float(v) / vmax)

    fmt = (lambda v: f"{100 * v:.3g}%") if percent else _compact
    pts = " ".join(f"{px(i):.1f},{py(ys[i]):.1f}" for i in range(n))
    area = (f"{_PAD_L + pw * 0.5 / n:.1f},{_PAD_T + ph} {pts} "
            f"{px(n - 1):.1f},{_PAD_T + ph}")
    ex, ey = px(n - 1), py(ys[n - 1])
    # keep the one direct label inside the plot even at the axis maximum
    label_y = max(ey - 8.0, _PAD_T + 10.0)
    # Full-band transparent hit columns: targets far bigger than the mark.
    hits = "".join(
        f"<rect class='hit' x='{_PAD_L + pw * i / n:.1f}' y='{_PAD_T}' "
        f"width='{pw / n:.2f}' height='{ph}' tabindex='0' "
        f"data-tip='{_esc(xs_label[i])}: {_esc(fmt(ys[i]))}{_esc(unit)}'>"
        "</rect>"
        for i in range(n)
    )
    x_first, x_last = _esc(xs_label[0]), _esc(xs_label[-1])
    return f"""<svg viewBox='0 0 {_W} {_H}' role='img'>
{_grid_and_yticks(vmax, fmt)}
<line class='axis' x1='{_PAD_L}' y1='{_PAD_T + ph}' x2='{_W - _PAD_R}' y2='{_PAD_T + ph}'/>
<polygon class='wash' points='{area}'/>
<polyline class='line' points='{pts}'/>
<circle class='dot' cx='{ex:.1f}' cy='{ey:.1f}' r='4'/>
<text class='endlabel' x='{ex - 6:.1f}' y='{label_y:.1f}' text-anchor='end'>{_esc(fmt(ys[-1]))}</text>
<text class='tick' x='{_PAD_L}' y='{_H - 6}'>{x_first}</text>
<text class='tick' x='{_W - _PAD_R}' y='{_H - 6}' text-anchor='end'>{x_last}</text>
{hits}
</svg>"""


def _bar_path(x: float, y: float, w: float, h: float, r: float = 4.0) -> str:
    """Horizontal bar: square at the baseline (left), 4px rounded data-end."""
    r = min(r, w / 2, h / 2)
    return (f"M{x:.1f},{y:.1f} h{w - r:.1f} "
            f"a{r},{r} 0 0 1 {r},{r} v{h - 2 * r:.1f} "
            f"a{r},{r} 0 0 1 -{r},{r} h-{w - r:.1f} z")


def _hbar_chart(labels: List[str], values: np.ndarray, counts: np.ndarray,
                *, vmax: float = 1.0, key_name: str = "key") -> str:
    """Horizontal single-series bars (mean score 0..vmax), value at the tip."""
    n = len(labels)
    if n == 0:
        return "<p class='empty'>no data</p>"
    label_w = 90
    pw = _W - label_w - 60
    h = n * (_BAR_H + 8) + 8
    rows = []
    for i in range(n):
        y = 4 + i * (_BAR_H + 8)
        w = max(2.0, pw * float(values[i]) / vmax)
        tip = (f"{key_name} {labels[i]}: score {values[i]:.3f} "
               f"over {int(counts[i])} txs")
        rows.append(
            f"<text class='lab' x='{label_w - 8}' y='{y + _BAR_H - 5}' "
            f"text-anchor='end'>{_esc(labels[i])}</text>"
            f"<path class='bar' d='{_bar_path(label_w, y, w, _BAR_H)}'/>"
            f"<text class='val' x='{label_w + w + 6:.1f}' "
            f"y='{y + _BAR_H - 5}'>{values[i]:.3f}</text>"
            f"<rect class='hit' x='0' y='{y - 4}' width='{_W}' "
            f"height='{_BAR_H + 8}' tabindex='0' data-tip='{_esc(tip)}'>"
            "</rect>"
        )
    return (f"<svg viewBox='0 0 {_W} {h}' role='img'>"
            f"<line class='axis' x1='{label_w}' y1='0' x2='{label_w}' "
            f"y2='{h}'/>" + "".join(rows) + "</svg>")


def _tiles(s: dict, drift: Optional[dict] = None) -> str:
    if s.get("transactions", 0) == 0:
        return "<p class='empty'>no analyzed transactions</p>"
    thr = s["threshold"]
    tiles = [
        ("Transactions", _compact(s["transactions"]), ""),
        ("Flagged", _compact(s["flagged"]),
         f"{100 * s['flagged_rate']:.2f}% at threshold {thr:g}"),
        ("Flagged amount", _compact(s["flagged_amount"], money=True),
         f"of {_compact(s['total_amount'], money=True)} total"),
        ("Customers", _compact(s["customers"]), ""),
        ("Terminals", _compact(s["terminals"]), ""),
        ("Score p99", f"{s['score_p99']:.3f}",
         f"median {s['score_p50']:.3f}"),
    ]
    out = []
    for label, value, sub in tiles:
        subdiv = f"<div class='sub'>{_esc(sub)}</div>" if sub else ""
        out.append(f"<div class='tile'><div class='lbl'>{_esc(label)}</div>"
                   f"<div class='num'>{_esc(value)}</div>{subdiv}</div>")
    if drift and drift.get("valid"):
        # the documented PSI bands (_psi docstring): <0.1 stable,
        # 0.1–0.25 drifting (early warning), >0.25 shifted. Status color
        # rides ONLY the icon glyph; the word stays in text ink (status
        # colors are sub-contrast for text on the light surface).
        psi = drift["prediction_psi"]
        if psi > 0.25:
            badge = "<span class='ico serious'>▲</span> shifted"
        elif psi > 0.1:
            badge = "<span class='ico warning'>▲</span> drifting"
        else:
            badge = "<span class='ico good'>●</span> stable"
        out.append(
            "<div class='tile'><div class='lbl'>Score drift (PSI)</div>"
            f"<div class='num'>{psi:.3f}</div>"
            f"<div class='sub'>{badge} vs first half · amount PSI "
            f"{drift['amount_psi']:.3f}</div></div>")
    return "<div class='tiles'>" + "".join(out) + "</div>"


_CSS = """
:root { color-scheme: light dark; }
.viz {
  --surface: #fcfcfb; --plane: #f9f9f7;
  --ink: #0b0b0b; --ink2: #52514e; --muted: #898781;
  --grid: #e1e0d9; --axis: #c3c2b7;
  --s1: #2a78d6; --border: rgba(11,11,11,0.10);
  --st-good: #0ca30c; --st-warn: #fab219; --st-serious: #ec835a;
  font: 14px/1.45 system-ui, -apple-system, "Segoe UI", sans-serif;
  color: var(--ink); background: var(--plane);
  margin: 0; padding: 24px; min-height: 100vh; box-sizing: border-box;
}
@media (prefers-color-scheme: dark) { .viz {
  --surface: #1a1a19; --plane: #0d0d0d;
  --ink: #ffffff; --ink2: #c3c2b7;
  --grid: #2c2c2a; --axis: #383835;
  --s1: #3987e5; --border: rgba(255,255,255,0.10);
}}
.viz h1 { font-size: 20px; margin: 0 0 2px; }
.viz .meta { color: var(--ink2); margin-bottom: 20px; }
.tiles { display: flex; flex-wrap: wrap; gap: 12px; margin-bottom: 20px; }
.tile { background: var(--surface); border: 1px solid var(--border);
  border-radius: 8px; padding: 12px 16px; min-width: 132px; }
.tile .lbl { color: var(--ink2); font-size: 12px; }
.tile .num { font-size: 26px; font-weight: 600; }
.tile .sub { color: var(--muted); font-size: 12px; }
.ico.good { color: var(--st-good); }
.ico.warning { color: var(--st-warn); }
.ico.serious { color: var(--st-serious); }
.cards { display: grid; gap: 16px;
  grid-template-columns: repeat(auto-fit, minmax(360px, 1fr)); }
.card { background: var(--surface); border: 1px solid var(--border);
  border-radius: 8px; padding: 14px 16px; overflow: hidden; }
.card h2 { font-size: 14px; font-weight: 600; margin: 0 0 10px; }
.card svg { width: 100%; height: auto; display: block; }
.grid { stroke: var(--grid); stroke-width: 1; }
.axis { stroke: var(--axis); stroke-width: 1; }
.line { fill: none; stroke: var(--s1); stroke-width: 2;
  stroke-linejoin: round; stroke-linecap: round; }
.wash { fill: var(--s1); opacity: 0.1; }
.dot { fill: var(--s1); stroke: var(--surface); stroke-width: 2; }
.bar { fill: var(--s1); }
.tick, .lab, .val, .endlabel { font-size: 11px; fill: var(--muted); }
.tick { font-variant-numeric: tabular-nums; }
.lab { fill: var(--ink2); }
.val, .endlabel { fill: var(--ink2); font-variant-numeric: tabular-nums; }
.hit { fill: transparent; outline: none; }
.hit:focus-visible { stroke: var(--s1); stroke-width: 1; }
.empty { color: var(--muted); }
.twin summary { color: var(--ink2); font-size: 12px; cursor: pointer;
  margin-top: 8px; }
.twin table { border-collapse: collapse; margin-top: 6px; width: 100%;
  font-size: 12px; font-variant-numeric: tabular-nums; }
.twin th, .twin td, .alerts th, .alerts td {
  text-align: left; padding: 3px 10px 3px 0;
  border-bottom: 1px solid var(--grid); }
.twin th, .alerts th { color: var(--ink2); font-weight: 600; }
.alerts table { border-collapse: collapse; width: 100%; font-size: 13px;
  font-variant-numeric: tabular-nums; }
#tip { position: fixed; display: none; pointer-events: none;
  background: var(--ink); color: var(--surface); padding: 4px 8px;
  border-radius: 4px; font-size: 12px; z-index: 10; max-width: 320px; }
"""

_JS = """
var tip = document.getElementById('tip');
function show(el, x, y) {
  tip.textContent = el.getAttribute('data-tip');
  tip.style.display = 'block';
  var w = tip.offsetWidth, vw = window.innerWidth;
  tip.style.left = Math.min(x + 12, vw - w - 8) + 'px';
  tip.style.top = (y + 14) + 'px';
}
document.querySelectorAll('[data-tip]').forEach(function (el) {
  el.addEventListener('mousemove', function (e) { show(el, e.clientX, e.clientY); });
  el.addEventListener('mouseleave', function () { tip.style.display = 'none'; });
  el.addEventListener('focus', function () {
    var r = el.getBoundingClientRect(); show(el, r.left, r.top + r.height / 2);
  });
  el.addEventListener('blur', function () { tip.style.display = 'none'; });
});
"""


def render_dashboard_html(
    cols: Dict[str, np.ndarray],
    *,
    threshold: float = 0.5,
    top_k: int = 10,
    bucket: str = "day",
    title: str = "Fraud detection — analyzed transactions",
) -> str:
    """Render the full dashboard for an analyzed column dict."""
    s = summary_stats(cols, threshold)
    n = s.get("transactions", 0)
    drift = drift_report(cols, threshold=threshold) if n else None
    gen = time.strftime("%Y-%m-%d %H:%M:%S UTC", time.gmtime())
    parts = [
        "<!doctype html><html><head><meta charset='utf-8'>",
        f"<title>{_esc(title)}</title>",
        "<meta name='viewport' content='width=device-width, initial-scale=1'>",
        f"<style>{_CSS}</style></head><body class='viz'>",
        f"<h1>{_esc(title)}</h1>",
        f"<div class='meta'>generated {gen} · threshold "
        f"{threshold:g} · bucket {_esc(bucket)}</div>",
        _tiles(s, drift),
    ]
    if n:
        lab = _day_label if bucket == "day" else _hour_label
        ts = fraud_rate_over_time(cols, bucket, threshold)
        xs = [lab(u) for u in ts["bucket_start_us"]]
        vol_twin = _table_twin(
            (bucket, "transactions", "amount"),
            [(xs[i], int(ts["transactions"][i]), f"{ts['amount'][i]:,.2f}")
             for i in range(len(xs))])
        rate_twin = _table_twin(
            (bucket, "flagged", "flag rate"),
            [(xs[i], int(ts["flagged"][i]),
              f"{100 * ts['flag_rate'][i]:.2f}%")
             for i in range(len(xs))])
        def top_card(title: str, key_name: str, top: dict) -> str:
            key_col = f"{key_name}_id"
            chart = _hbar_chart([str(k) for k in top[key_col]],
                                top["mean_score"], top["transactions"],
                                key_name=key_name)
            twin = _table_twin(
                (key_name, "txs", "mean score", "flagged", "amount"),
                [(int(top[key_col][i]), int(top["transactions"][i]),
                  f"{top['mean_score'][i]:.3f}", int(top["flagged"][i]),
                  f"{top['amount'][i]:,.2f}")
                 for i in range(len(top[key_col]))])
            return (f"<div class='card'><h2>{_esc(title)}</h2>"
                    f"{chart}{twin}</div>")

        term = top_risky_terminals(cols, top_k, threshold)
        cust = top_risky_customers(cols, top_k, threshold)
        alerts = recent_alerts(cols, threshold, limit=top_k)
        alert_rows = "".join(
            "<tr>"
            f"<td>{int(alerts['tx_id'][i])}</td>"
            f"<td>{_esc(_ts_label(alerts['tx_datetime_us'][i]))}</td>"
            f"<td>{int(alerts['customer_id'][i])}</td>"
            f"<td>{int(alerts['terminal_id'][i])}</td>"
            f"<td>{alerts['tx_amount'][i]:,.2f}</td>"
            f"<td>{alerts['prediction'][i]:.3f}</td></tr>"
            for i in range(len(alerts["tx_id"]))
        ) or "<tr><td colspan='6'>none</td></tr>"
        parts += [
            "<div class='cards'>",
            "<div class='card'><h2>Transactions per "
            f"{_esc(bucket)}</h2>",
            _line_chart(xs, ts["transactions"].astype(np.float64)),
            vol_twin, "</div>",
            "<div class='card'><h2>Flag rate per "
            f"{_esc(bucket)}</h2>",
            _line_chart(xs, ts["flag_rate"], percent=True),
            rate_twin, "</div>",
            top_card("Top risky terminals (mean score)", "terminal", term),
            top_card("Top risky customers (mean score)", "customer", cust),
            "<div class='card alerts'><h2>Recent alerts</h2>",
            "<table><thead><tr><th>tx</th><th>time</th><th>customer</th>"
            "<th>terminal</th><th>amount</th><th>score</th></tr></thead>"
            f"<tbody>{alert_rows}</tbody></table></div>",
            "</div>",
        ]
    parts += [f"<div id='tip'></div><script>{_JS}</script></body></html>"]
    return "".join(parts)


# ---------------------------------------------------------------------------
# Ops-health view: the flight-record twin of the analyzed-output dashboard
# ---------------------------------------------------------------------------

# Engine loop-time decomposition, in pipeline order (matches
# runtime.engine.PHASES; duplicated here so the io layer renders flight
# records from any producer without importing the runtime).
_OPS_PHASES = ("source_poll", "host_prep", "dispatch", "result_wait",
               "sink_write")

_EVENT_CLASS = {"fault": "serious", "restart": "serious",
                "poison": "serious", "dead_letter": "serious",
                "gave_up": "serious", "checkpoint_fallback": "serious",
                "checkpoint": "info", "feedback": "good",
                # overload ladder (runtime/overload.py): climbs and
                # rung-3 deferral are warnings (degraded, surviving);
                # descents and in-order replays are recovery
                "overload_climb": "warning", "shed": "warning",
                "overload_descend": "good", "replay": "good",
                # continuous-learning plane (runtime/learner.py)
                "model_published": "info", "model_candidate": "info",
                "model_reload": "info", "model_promoted": "good",
                "model_canary_passed": "good",
                "model_rollback": "serious",
                "model_promote_refused": "serious",
                "model_artifact_corrupt": "serious"}


def _downsample_max(ys: np.ndarray, limit: int = 240):
    """Aggregate to <= limit points by windowed MAX (spikes — the thing
    an ops view exists to show — survive; means would flatten them).
    Returns (values, window) where window is the batches-per-point."""
    n = len(ys)
    if n <= limit:
        return ys, 1
    w = -(-n // limit)
    pad = (-n) % w
    padded = np.concatenate([ys, np.full(pad, -np.inf)]) if pad else ys
    return padded.reshape(-1, w).max(axis=1), w


def _event_strip(events: List[dict], t0: float, t1: float) -> str:
    """Fault/feedback/checkpoint/restart markers on the run's time axis."""
    if not events:
        return "<p class='empty'>no events</p>"
    h = 46
    span = max(t1 - t0, 1e-9)
    marks = []
    for ev in events:
        # clamp: events outside the batch span (e.g. a checkpoint
        # restore before the first batch finished) stay on-axis
        frac = min(max((float(ev.get("t", t0)) - t0) / span, 0.0), 1.0)
        x = _PAD_L + (_W - _PAD_L - _PAD_R) * frac
        kind = str(ev.get("event", "?"))
        cls = _EVENT_CLASS.get(kind, "info")
        detail = ", ".join(
            f"{k}={v}" for k, v in ev.items()
            if k not in ("kind", "t", "event"))
        tip = f"{kind}" + (f" ({detail})" if detail else "")
        marks.append(
            f"<line class='ev {cls}' x1='{x:.1f}' y1='8' x2='{x:.1f}' "
            f"y2='{h - 16}'/>"
            f"<rect class='hit' x='{x - 5:.1f}' y='0' width='10' "
            f"height='{h}' tabindex='0' data-tip='{_esc(tip)}'></rect>"
        )
    axis = (f"<line class='axis' x1='{_PAD_L}' y1='{h - 14}' "
            f"x2='{_W - _PAD_R}' y2='{h - 14}'/>")
    return (f"<svg viewBox='0 0 {_W} {h}' role='img'>{axis}"
            + "".join(marks) + "</svg>")


def _cluster_tile(events: List[dict], man: dict):
    """Cluster tile (multi-host fleets): worst process leads, mirroring
    the worst-shard convention — the slowest/most-restarted process is
    the one gating fleet throughput. None unless the record carries
    cluster events (the launcher's flight record), so single-process
    runs keep a clean tile row. Shared by the full ops view and the
    no-batch-records path: the launcher's own record has no batch lines
    by construction, and a fleet that died before serving is exactly
    when the tile matters."""
    cl_workers = [e for e in events
                  if e.get("event") == "cluster_worker"]
    fleet_restarts = [e for e in events
                      if e.get("event") == "fleet_restart"]
    worker_restarts = [e for e in events
                       if e.get("event") == "cluster_worker_restart"]
    if not (cl_workers or fleet_restarts or worker_restarts):
        return None
    # last exit record per process (a restarted worker reports twice)
    by_proc = {}
    for e in cl_workers:
        by_proc[e.get("process")] = e
    n_proc = (man.get("multihost") or {}).get("processes", len(by_proc))
    sub_bits = []
    failed = [p for p, e in by_proc.items()
              if e.get("rc") not in (0, None)]
    if by_proc:
        worst_p, worst_e = min(
            by_proc.items(),
            key=lambda kv: float(kv[1].get("rows_per_s", 0.0) or 0.0))
        sub_bits.append(
            f"worst p{worst_p}: "
            f"{_compact(float(worst_e.get('rows_per_s', 0.0) or 0.0))}"
            "/s")
    if failed:
        sub_bits.insert(0, f"{len(failed)} worker(s) FAILED "
                           f"{sorted(failed)[:4]}")
    if fleet_restarts:
        sub_bits.append(f"{len(fleet_restarts)} fleet restart(s)")
    if worker_restarts:
        sub_bits.append(f"{len(worker_restarts)} worker restart(s)")
    return ("Cluster", f"{n_proc} proc", " · ".join(sub_bits))


def _elasticity_tile(events: List[dict], man: dict):
    """Elasticity tile (autoscaled fleets): every resize the launcher
    walked — completed, rolled back (and at which phase), the last
    topology change and how long it took. None unless the record
    carries resize events or the manifest says the run was autoscaled,
    so fixed fleets keep a clean tile row."""
    begins = [e for e in events if e.get("event") == "resize_begin"]
    completes = [e for e in events
                 if e.get("event") == "resize_complete"]
    rollbacks = [e for e in events
                 if e.get("event") == "resize_rollback"]
    autoscaled = bool((man.get("multihost") or {}).get("autoscale"))
    if not (begins or completes or rollbacks or autoscaled):
        return None
    sub_bits = []
    if rollbacks:
        stages = sorted({str(e.get("stage", "?")) for e in rollbacks})
        sub_bits.append(f"{len(rollbacks)} rolled back "
                        f"at {'/'.join(stages)}")
    if completes:
        last = completes[-1]
        sub_bits.append(
            f"last {last.get('direction', '?')} -> "
            f"{last.get('processes', '?')} proc in "
            f"{float(last.get('seconds', 0.0) or 0.0):.1f}s "
            f"(gen {last.get('generation', '?')})")
    elif begins:
        last = begins[-1]
        sub_bits.append(f"last attempt {last.get('current', '?')} -> "
                        f"{last.get('target', '?')}")
    if not sub_bits:
        sub_bits.append("no resizes: pressure never held a dwell")
    value = (f"{len(completes)} resize(s)" if not rollbacks
             else f"{len(completes)} ok / {len(rollbacks)} back")
    return ("Elasticity", value, " · ".join(sub_bits))


def render_ops_html(
    manifest: Optional[dict],
    records: List[dict],
    *,
    title: str = "Fraud detection — ops health",
) -> str:
    """Render the flight-record ops view: run manifest tiles, per-phase
    latency time series (one chart per phase, batch-indexed), and the
    fault/feedback/checkpoint/restart event strip."""
    batches = [r for r in records if r.get("kind") == "batch"]
    events = [r for r in records if r.get("kind") == "event"]
    gen = time.strftime("%Y-%m-%d %H:%M:%S UTC", time.gmtime())
    man = manifest or {}
    meta_bits = [f"generated {gen}"]
    for k in ("backend", "model_kind", "n_devices", "config_hash"):
        if man.get(k) not in (None, ""):
            meta_bits.append(f"{k} {man[k]}")
    parts = [
        "<!doctype html><html><head><meta charset='utf-8'>",
        f"<title>{_esc(title)}</title>",
        "<meta name='viewport' content='width=device-width, "
        "initial-scale=1'>",
        f"<style>{_CSS}"
        ".ev { stroke-width: 2; }"
        ".ev.serious { stroke: var(--st-serious); }"
        ".ev.warning { stroke: var(--st-warn); }"
        ".ev.good { stroke: var(--st-good); }"
        ".ev.info { stroke: var(--s1); }"
        "</style></head><body class='viz'>",
        f"<h1>{_esc(title)}</h1>",
        f"<div class='meta'>{_esc(' · '.join(meta_bits))}</div>",
    ]
    if not batches:
        # A run that died before its first batch completed is exactly
        # where the event strip matters most (the fault/restart events
        # explain the death) — render them even with no batch records.
        # A launcher flight record is batch-less by construction: its
        # Cluster tile still renders.
        lead_tiles = [t for t in (_cluster_tile(events, man),
                                  _elasticity_tile(events, man))
                      if t is not None]
        if lead_tiles:
            cells = []
            for label, value, sub in lead_tiles:
                subdiv = (f"<div class='sub'>{_esc(sub)}</div>"
                          if sub else "")
                cells.append(
                    "<div class='tile'>"
                    f"<div class='lbl'>{_esc(label)}</div>"
                    f"<div class='num'>{_esc(value)}</div>{subdiv}"
                    "</div>")
            parts.append(f"<div class='tiles'>{''.join(cells)}</div>")
        parts.append("<p class='empty'>no batch records</p>")
        if events:
            t0 = float(events[0].get("t", 0.0))
            t1 = float(events[-1].get("t", t0))
            ev_twin = _table_twin(
                ("time", "event", "detail"),
                [(_ts_label(int(float(e.get("t", t0)) * _US)),
                  str(e.get("event", "?")),
                  ", ".join(f"{k}={v}" for k, v in e.items()
                            if k not in ("kind", "t", "event")))
                 for e in events])
            parts += [
                "<div class='cards'><div class='card'><h2>Events"
                "</h2>", _event_strip(events, t0, t1), ev_twin,
                "</div></div>",
            ]
        parts += [f"<div id='tip'></div><script>{_JS}</script>"
                  "</body></html>"]
        return "".join(parts)

    rows_total = sum(int(b.get("rows", 0)) for b in batches)
    lat = np.asarray([float(b.get("latency_s", 0.0)) for b in batches])
    t_first = float(batches[0].get("t", 0.0))
    t_last = float(batches[-1].get("t", t_first))
    span_s = t_last - t_first
    if span_s <= 0:
        # single-batch record: timestamps carry no span — fall back to
        # the batches' own latency rather than headline nonsense
        span_s = float(lat.sum())
    throughput = (f"{_compact(rows_total / span_s)}/s" if span_s > 0
                  else "—")
    n_faults = sum(1 for e in events if e.get("event") == "fault")
    n_restarts = sum(1 for e in events if e.get("event") == "restart")
    n_dlq = sum(int(e.get("rows", 0)) for e in events
                if e.get("event") == "dead_letter")
    n_poison = sum(1 for e in events if e.get("event") == "poison"
                   and e.get("phase") == "detected")
    tiles = [
        ("Batches", _compact(len(batches)), ""),
        ("Rows", _compact(rows_total), ""),
        ("Throughput", throughput, "rows over the record span"),
        ("Batch p50", f"{np.percentile(lat, 50) * 1e3:.2f} ms",
         f"p99 {np.percentile(lat, 99) * 1e3:.2f} ms"),
        ("Faults injected", _compact(n_faults),
         f"{n_restarts} restarts" if n_restarts else ""),
        ("Dead-letter rows", _compact(n_dlq),
         f"{n_poison} crash loop(s)" if n_poison else
         "quarantined (crash + nonfinite)"),
        ("Checkpoints", _compact(sum(
            1 for e in events if e.get("event") == "checkpoint"
            and e.get("op") == "save")), ""),
    ]
    # Durable-state tile: corrupt checkpoints stepped over on restore.
    # A clean run earns a quiet "verified" tile; any fallback paints the
    # count of quarantined entries plus what finally served.
    ck_fallbacks = [e for e in events
                    if e.get("event") == "checkpoint_fallback"]
    n_quarantined = sum(1 for e in ck_fallbacks if e.get("path"))
    restored = [e for e in ck_fallbacks if e.get("restored")]
    if ck_fallbacks:
        sub = (f"restored {restored[-1]['restored']}"
               if restored else "no valid checkpoint survived")
        tiles.append(("Durable state",
                      f"{_compact(n_quarantined)} corrupt", sub))
    else:
        tiles.append(("Durable state", "verified",
                      "restores re-checksummed, no fallback"))
    # Overload tile: did the run degrade, how far, and did everything
    # deferred come back? Only rendered when the ladder actually moved
    # (any overload_* / shed / replay event), so steady runs keep a
    # clean tile row. Replay deficit (shed > replayed) is the headline
    # problem state: deferred rows never re-entered the stream.
    climbs = [e for e in events if e.get("event") == "overload_climb"]
    descends = [e for e in events
                if e.get("event") == "overload_descend"]
    shed_rows = sum(int(e.get("rows", 0)) for e in events
                    if e.get("event") == "shed")
    replayed_rows = sum(int(e.get("rows", 0)) for e in events
                        if e.get("event") == "replay")
    if climbs or descends or shed_rows or replayed_rows:
        top_rung = max([int(e.get("rung", 0)) for e in climbs],
                       default=0)
        # chronological last transition (the events list is in record
        # order): climbs+descends concatenated would misreport any run
        # whose second overload episode climbed after a full recovery
        moves = [e for e in events
                 if e.get("event") in ("overload_climb",
                                       "overload_descend")]
        final_rung = int(moves[-1].get("rung", 0)) if moves else 0
        if shed_rows > replayed_rows:
            sub = (f"{_compact(shed_rows - replayed_rows)} shed rows "
                   "NEVER replayed")
        elif final_rung > 0:
            sub = f"ended degraded at rung {final_rung}"
        else:
            sub = (f"{len(climbs)} climb(s) · "
                   f"{_compact(shed_rows)} shed, all replayed"
                   if shed_rows else
                   f"{len(climbs)} climb(s), fully recovered")
        tiles.append(("Overload", f"rung {top_rung} peak", sub))
    # Feature-store tile (tiered exact mode): hot-tier occupancy at the
    # last compaction, total reclaimed slots, and the dense-tier hit
    # rate. Only rendered when the run compacted (any feature_state
    # event), so direct/hash runs keep a clean tile row.
    fs_events = [e for e in events if e.get("event") == "feature_state"]
    if fs_events:
        last = fs_events[-1]
        occ = int(last.get("occupied", 0))
        cap = int(last.get("capacity", 0))
        reclaimed = sum(int(e.get("reclaimed", 0)) for e in fs_events)
        dense = float(last.get("dense_rows", 0.0))
        cms_r = float(last.get("cms_rows", 0.0))
        served = dense + cms_r
        sub_bits = [f"{_compact(reclaimed)} slot(s) reclaimed"]
        if served:
            sub_bits.append(f"{dense / served:.1%} dense")
        per_shard = last.get("occupied_per_shard")
        if per_shard:
            # sharded exact serving: skew is the failure mode the modulo
            # ownership hides — lead with the WORST shard's occupancy
            # (its hot tier overflows to the sketch first)
            worst = int(max(range(len(per_shard)),
                            key=lambda s: per_shard[s]))
            cap_shard = cap // max(len(per_shard), 1)
            sub_bits.insert(0, (
                f"worst shard {worst}: "
                f"{_compact(int(per_shard[worst]))}/"
                f"{_compact(cap_shard)}"))
        if last.get("cold_keys") is not None:
            # host cold tier armed: depth of the demoted key set and the
            # promotion backlog at the last compaction — a growing
            # backlog means returning keys are being served from the
            # sketch longer than the promoter can land them
            cold_bits = f"cold {_compact(int(last['cold_keys']))} key(s)"
            backlog = int(last.get("promote_backlog", 0))
            if backlog:
                cold_bits += f", {_compact(backlog)} promoting"
            sub_bits.append(cold_bits)
        tiles.append((
            "Feature store",
            f"{_compact(occ)}/{_compact(cap)} slots" if cap
            else _compact(occ),
            " · ".join(sub_bits)))
    # Learning tile: which model versions served/shadowed and how the
    # canary ended. Only rendered when the run had a learning loop (any
    # model_* event), so plain serving runs keep a clean tile row.
    promos = [e for e in events if e.get("event") == "model_promoted"]
    rollbacks = [e for e in events if e.get("event") == "model_rollback"]
    cands = [e for e in events if e.get("event") == "model_candidate"]
    pubs = [e for e in events if e.get("event") == "model_published"]
    # refusals by cause: "corrupt" sends the operator hunting bit-rot,
    # which is wrong advice for a kind-mismatched or vanished artifact
    refusals = [e for e in events
                if e.get("event") == "model_promote_refused"]
    refused_corrupt = sum(1 for e in refusals
                          if e.get("reason") in ("checksum", "truncated"))
    refused_other = len(refusals) - refused_corrupt
    refused = len(refusals)
    if promos or rollbacks or cands or pubs or refused:
        if rollbacks and (not promos
                          or rollbacks[-1].get("t", 0.0)
                          >= promos[-1].get("t", 0.0)):
            champ = rollbacks[-1].get("version", "?")
            verdict = f"rolled back from v{rollbacks[-1].get('regressed')}"
        elif promos:
            champ = promos[-1].get("version", "?")
            verdict = f"promoted over v{promos[-1].get('previous')}"
        else:
            champ = man.get("model_kind", "champion")
            verdict = f"{len(pubs)} candidate(s) published"
        sub_bits = [verdict]
        if cands:
            sub_bits.append(f"shadow v{cands[-1].get('version')}")
        if refused_corrupt:
            sub_bits.append(f"{refused_corrupt} corrupt refused")
        if refused_other:
            sub_bits.append(f"{refused_other} refused "
                            "(kind/missing)")
        tiles.append(("Learning", f"v{champ}" if promos or rollbacks
                      else str(champ), " · ".join(sub_bits)))
    cluster = _cluster_tile(events, man)
    if cluster is not None:
        tiles.append(cluster)
    elasticity = _elasticity_tile(events, man)
    if elasticity is not None:
        tiles.append(elasticity)
    tile_html = []
    for label, value, sub in tiles:
        subdiv = f"<div class='sub'>{_esc(sub)}</div>" if sub else ""
        tile_html.append(
            f"<div class='tile'><div class='lbl'>{_esc(label)}</div>"
            f"<div class='num'>{_esc(value)}</div>{subdiv}</div>")
    parts.append("<div class='tiles'>" + "".join(tile_html) + "</div>")

    parts.append("<div class='cards'>")
    idx = [str(int(b.get("batch", i))) for i, b in enumerate(batches)]
    for phase in _OPS_PHASES:
        ys_ms = np.asarray([
            1e3 * float(b.get("phases", {}).get(phase, 0.0))
            for b in batches
        ])
        if not ys_ms.any():
            continue  # e.g. sink_write with no sink attached
        ds, w = _downsample_max(ys_ms)
        labels = [idx[min(i * w, len(idx) - 1)] for i in range(len(ds))]
        note = f" (max per {w} batches)" if w > 1 else ""
        twin = _table_twin(
            ("batch", f"{phase} ms"),
            [(labels[i], f"{ds[i]:.3f}") for i in range(len(ds))])
        parts += [
            f"<div class='card'><h2>{_esc(phase)} per batch{_esc(note)}"
            "</h2>",
            _line_chart(labels, ds, unit=" ms"),
            twin, "</div>",
        ]
    # event strip + table twin (values never color-gated)
    ev_twin = _table_twin(
        ("time", "event", "detail"),
        [(_ts_label(int(float(e.get("t", t_first)) * _US)),
          str(e.get("event", "?")),
          ", ".join(f"{k}={v}" for k, v in e.items()
                    if k not in ("kind", "t", "event")))
         for e in events]) if events else ""
    parts += [
        "<div class='card'><h2>Events (faults · feedback · checkpoints "
        "· restarts)</h2>",
        _event_strip(events, t_first, t_last),
        ev_twin, "</div>",
        "</div>",
        f"<div id='tip'></div><script>{_JS}</script></body></html>",
    ]
    return "".join(parts)


def write_ops_dashboard(
    flight_path: str,
    out_path: str,
    *,
    title: Optional[str] = None,
) -> dict:
    """Load a flight-record JSONL and write the ops-health dashboard."""
    from real_time_fraud_detection_system_tpu.utils.metrics import (
        FlightRecorder,
    )

    manifest, records = FlightRecorder.read(flight_path)
    htm = render_ops_html(
        manifest, records,
        title=title or "Fraud detection — ops health")
    with open(out_path, "w", encoding="utf-8") as f:
        f.write(htm)
    return {
        "dashboard": out_path,
        "batches": sum(1 for r in records if r.get("kind") == "batch"),
        "events": sum(1 for r in records if r.get("kind") == "event"),
        "bytes": len(htm.encode()),
    }


# ---------------------------------------------------------------------------
# ASCII span waterfall: the terminal twin of the Perfetto timeline
# ---------------------------------------------------------------------------

def render_trace_waterfall(trace: dict, trace_id: Optional[str] = None,
                           width: int = 56) -> str:
    """Render one batch's span waterfall from a Chrome-trace JSON object
    (as exported by ``utils/trace.py``) as plain ASCII — the
    no-browser view `rtfds trace` prints.

    ``trace_id`` picks the batch; default is the batch with the largest
    total span time (the one an operator is hunting). Spans render in
    start order, each bar positioned on the batch's time extent::

        trace b00000003 — 3 spans, 12.42 ms span extent
        source_poll    |##....................|    0.18 ms
        host_prep      |..####................|    4.73 ms
        dispatch       |......############....|    7.51 ms
    """
    events = [e for e in trace.get("traceEvents", [])
              if e.get("ph") == "X"
              and (e.get("args") or {}).get("trace_id")]
    if not events:
        return "no spans in trace"
    by_id: Dict[str, List[dict]] = {}
    for e in events:
        by_id.setdefault(str(e["args"]["trace_id"]), []).append(e)
    if trace_id is None:
        trace_id = max(
            by_id,
            key=lambda t: sum(float(e.get("dur", 0.0)) for e in by_id[t]))
    evs = by_id.get(str(trace_id))
    if not evs:
        known = ", ".join(sorted(by_id)[:8])
        return (f"trace id {trace_id!r} not in trace "
                f"(known ids: {known}{'…' if len(by_id) > 8 else ''})")
    evs = sorted(evs, key=lambda e: float(e.get("ts", 0.0)))
    t0 = min(float(e["ts"]) for e in evs)
    t1 = max(float(e["ts"]) + float(e.get("dur", 0.0)) for e in evs)
    span_us = max(t1 - t0, 1e-9)
    name_w = max(len(str(e["name"])) for e in evs)
    lines = [
        f"trace {trace_id} — {len(evs)} spans, "
        f"{span_us / 1e3:.2f} ms span extent"
    ]
    for e in evs:
        s = int(width * (float(e["ts"]) - t0) / span_us)
        w = max(1, int(round(width * float(e.get("dur", 0.0)) / span_us)))
        s = min(s, width - 1)
        w = min(w, width - s)
        bar = "." * s + "#" * w + "." * (width - s - w)
        lines.append(
            f"{str(e['name']):<{name_w}} |{bar}| "
            f"{float(e.get('dur', 0.0)) / 1e3:>9.3f} ms")
    return "\n".join(lines)


def write_dashboard(
    analyzed_dir: str,
    out_path: str,
    *,
    threshold: float = 0.5,
    top_k: int = 10,
    bucket: str = "day",
    title: Optional[str] = None,
) -> dict:
    """Load an analyzed output directory and write the dashboard HTML.

    Returns a small manifest (path, transaction count) for CLI printing.
    """
    cols = load_analyzed(analyzed_dir)
    htm = render_dashboard_html(
        cols, threshold=threshold, top_k=top_k, bucket=bucket,
        title=title or "Fraud detection — analyzed transactions")
    with open(out_path, "w", encoding="utf-8") as f:
        f.write(htm)
    return {
        "dashboard": out_path,
        "transactions": int(len(cols.get("tx_id", ()))),
        "bytes": len(htm.encode()),
    }
