"""Dashboard queries over the analyzed-transactions output.

The reference serves its results through Trino SQL over the Iceberg
``nessie.payment.analyzed_transactions`` table into a Superset dashboard
(``trino-config/catalog/nessie.properties:1-14``,
``superset/entrypoint.sh:19``). This module is the in-process equivalent for
deployments without that stack: the canned aggregations a fraud-ops
dashboard is built from, computed columnar over the ParquetSink output (or
any analyzed column dict). Trino/Superset still work unchanged on the
Parquet files for full-SQL deployments.

All functions take the analyzed column dict produced by
``io.sink._result_to_columns`` (keys: ``tx_id``, ``tx_datetime_us``,
``customer_id``, ``terminal_id``, ``tx_amount``, 15 feature columns,
``prediction``, ``processed_at_us``).
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import numpy as np

_US_PER_HOUR = 3_600_000_000
_US_PER_DAY = 24 * _US_PER_HOUR


def load_analyzed(directory: str) -> Dict[str, np.ndarray]:
    """Read every parquet part file of an analyzed output directory.

    Latest-wins by ``tx_id`` across parts (file order): a transaction
    re-scored by a replay/restart counts once — MERGE-on-read, the same
    contract as the raw-transactions table."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from real_time_fraud_detection_system_tpu.io.sqlquery import (
        parquet_files,
    )

    files = parquet_files(directory)
    if not files:
        return {}
    table = pa.concat_tables([pq.read_table(f) for f in files])
    cols = {c: table[c].to_numpy() for c in table.column_names}
    ids = cols.get("tx_id")
    if ids is not None and len(ids):
        from real_time_fraud_detection_system_tpu.ops.dedup import (
            latest_wins_mask_np,
        )

        keep = latest_wins_mask_np(ids, np.arange(len(ids)))
        if not keep.all():
            cols = {c: v[keep] for c, v in cols.items()}
    return cols


def summary_stats(cols: Dict[str, np.ndarray],
                  threshold: float = 0.5) -> dict:
    """Headline tiles: volumes, amounts, flag rate, score distribution."""
    n = len(cols.get("tx_id", ()))
    if n == 0:
        return {"transactions": 0}
    pred = cols["prediction"]
    amount = cols["tx_amount"]
    flagged = pred >= threshold
    return {
        "transactions": int(n),
        "customers": int(len(np.unique(cols["customer_id"]))),
        "terminals": int(len(np.unique(cols["terminal_id"]))),
        "total_amount": float(amount.sum()),
        "flagged": int(flagged.sum()),
        "flagged_rate": float(flagged.mean()),
        "flagged_amount": float(amount[flagged].sum()),
        "score_mean": float(pred.mean()),
        "score_p50": float(np.percentile(pred, 50)),
        "score_p99": float(np.percentile(pred, 99)),
        "threshold": float(threshold),
    }


def fraud_rate_over_time(
    cols: Dict[str, np.ndarray],
    bucket: str = "hour",
    threshold: float = 0.5,
) -> Dict[str, np.ndarray]:
    """Time series of volume / flags / mean score per hour or day bucket."""
    div = _US_PER_HOUR if bucket == "hour" else _US_PER_DAY
    if bucket not in ("hour", "day"):
        raise ValueError("bucket must be 'hour' or 'day'")
    t = cols["tx_datetime_us"] // div
    pred = cols["prediction"]
    uniq, inv = np.unique(t, return_inverse=True)
    count = np.bincount(inv, minlength=len(uniq))
    flags = np.bincount(inv, weights=(pred >= threshold), minlength=len(uniq))
    score_sum = np.bincount(inv, weights=pred, minlength=len(uniq))
    amount = np.bincount(inv, weights=cols["tx_amount"], minlength=len(uniq))
    return {
        "bucket_start_us": uniq * div,
        "transactions": count.astype(np.int64),
        "flagged": flags.astype(np.int64),
        "flag_rate": flags / np.maximum(count, 1),
        "mean_score": score_sum / np.maximum(count, 1),
        "amount": amount,
    }


def _top_by_key(
    cols: Dict[str, np.ndarray],
    key_col: str,
    k: int,
    threshold: float,
    min_transactions: int,
) -> Dict[str, np.ndarray]:
    keys = cols[key_col]
    pred = cols["prediction"]
    uniq, inv = np.unique(keys, return_inverse=True)
    count = np.bincount(inv, minlength=len(uniq))
    score_sum = np.bincount(inv, weights=pred, minlength=len(uniq))
    flags = np.bincount(inv, weights=(pred >= threshold), minlength=len(uniq))
    amount = np.bincount(inv, weights=cols["tx_amount"], minlength=len(uniq))
    mean_score = score_sum / np.maximum(count, 1)
    eligible = count >= min_transactions
    rank_score = np.where(eligible, mean_score, -np.inf)
    top = np.argsort(-rank_score, kind="mergesort")[:k]
    top = top[np.isfinite(rank_score[top])]
    return {
        key_col: uniq[top],
        "transactions": count[top].astype(np.int64),
        "mean_score": mean_score[top],
        "flagged": flags[top].astype(np.int64),
        "amount": amount[top],
    }


def top_risky_terminals(
    cols: Dict[str, np.ndarray],
    k: int = 10,
    threshold: float = 0.5,
    min_transactions: int = 3,
) -> Dict[str, np.ndarray]:
    """Terminals ranked by mean fraud score (the compromised-terminal view —
    scenario 2's detection surface, ``data_generator.ipynb · cell 42``)."""
    return _top_by_key(cols, "terminal_id", k, threshold, min_transactions)


def top_risky_customers(
    cols: Dict[str, np.ndarray],
    k: int = 10,
    threshold: float = 0.5,
    min_transactions: int = 3,
) -> Dict[str, np.ndarray]:
    """Customers ranked by mean fraud score (scenario-3 view; the per-card
    ranking that Card Precision@k assesses)."""
    return _top_by_key(cols, "customer_id", k, threshold, min_transactions)


def recent_alerts(
    cols: Dict[str, np.ndarray],
    threshold: float = 0.5,
    limit: int = 100,
) -> Dict[str, np.ndarray]:
    """Most recent flagged transactions — the ops work queue."""
    pred = cols["prediction"]
    idx = np.flatnonzero(pred >= threshold)
    order = np.argsort(-cols["tx_datetime_us"][idx], kind="mergesort")[:limit]
    pick = idx[order]
    keep = ("tx_id", "tx_datetime_us", "customer_id", "terminal_id",
            "tx_amount", "prediction")
    return {c: cols[c][pick] for c in keep if c in cols}


def raw_transactions_report(directory: str) -> dict:
    """Per-day counts/volume over the persistent raw-transactions table
    (the reference's queryable day-partitioned ``nessie.payment.
    transactions``, ``load_initial_data.py:231``). Reads the Hive-layout
    partitions written by :class:`~.tables.RawTransactionsTable`."""
    from real_time_fraud_detection_system_tpu.io.tables import (
        RawTransactionsTable,
    )

    import os

    if not os.path.isdir(directory):
        raise FileNotFoundError(
            f"raw-transactions table directory not found: {directory!r} "
            "(expected the day-partitioned tx_date=*/ layout written by "
            "the engine's --raw-table / demo output)"
        )
    cols = RawTransactionsTable(directory).read_all()
    if not cols:
        return {"transactions": 0, "days": []}
    us_per_day = 86400 * 1_000_000
    days = cols["tx_datetime_us"] // us_per_day
    uniq, inv = np.unique(days, return_inverse=True)
    counts = np.bincount(inv)
    amounts = np.bincount(inv, weights=cols["tx_amount_cents"]) / 100.0
    return {
        "transactions": int(len(cols["tx_id"])),
        "customers": int(len(np.unique(cols["customer_id"]))),
        "terminals": int(len(np.unique(cols["terminal_id"]))),
        "total_amount": round(float(cols["tx_amount_cents"].sum()) / 100.0,
                              2),
        "days": [
            {"day": RawTransactionsTable.day_str(int(d)),
             "transactions": int(c), "amount": round(float(a), 2)}
            for d, c, a in zip(uniq, counts, amounts)
        ],
    }


def _psi(ref: np.ndarray, cur: np.ndarray, n_bins: int = 10) -> float:
    """Population stability index between two samples of one variable.

    Bins are the reference deciles; probabilities are floored at 1e-4 so
    empty bins contribute a large-but-finite term. Common reading:
    < 0.1 stable, 0.1–0.25 drifting, > 0.25 shifted.
    """
    if len(ref) == 0 or len(cur) == 0:
        return 0.0
    edges = np.quantile(ref, np.linspace(0, 1, n_bins + 1)[1:-1])
    edges = np.unique(edges)
    if len(edges) < n_bins // 2:
        # Heavily tied reference (common for fraud scores clustered near
        # 0): duplicate decile edges collapse into one bin and PSI reads
        # ~0 regardless of the shift. Fall back to fixed-width bins over
        # the pooled range so movement within the tied region registers.
        lo = min(float(ref.min()), float(cur.min()))
        hi = max(float(ref.max()), float(cur.max()))
        if hi <= lo:
            return 0.0
        edges = np.linspace(lo, hi, n_bins + 1)[1:-1]
    nb = len(edges) + 1
    p_ref = np.bincount(np.searchsorted(edges, ref), minlength=nb)
    p_cur = np.bincount(np.searchsorted(edges, cur), minlength=nb)
    p_ref = np.maximum(p_ref / len(ref), 1e-4)
    p_cur = np.maximum(p_cur / len(cur), 1e-4)
    return float(((p_cur - p_ref) * np.log(p_cur / p_ref)).sum())


def drift_report(
    cols: Dict[str, np.ndarray],
    split_us: Optional[int] = None,
    threshold: float = 0.5,
) -> dict:
    """Score/volume drift between a reference window and the current one.

    The serving-side health check the reference's stack has no analogue
    for: compares the analyzed output BEFORE ``split_us`` (default: the
    time-midpoint) against AFTER it — PSI of the prediction
    distribution, amount distribution, and the flag-rate/volume deltas.
    A shifted score distribution (PSI > 0.25) is the canonical retrain
    trigger."""
    n = len(cols.get("tx_id", ()))
    if n == 0:
        return {"transactions": 0}
    t = cols["tx_datetime_us"]
    if split_us is None:
        split_us = int((int(t.min()) + int(t.max())) // 2)
    before = t < split_us
    after = ~before
    pred, amount = cols["prediction"], cols["tx_amount"]
    out = {
        "split_us": int(split_us),
        "reference_rows": int(before.sum()),
        "current_rows": int(after.sum()),
        "threshold": float(threshold),
    }
    if not (before.any() and after.any()):
        # one window is empty (e.g. all rows share a timestamp): there is
        # no comparison — say so, never a confident "stable"
        out["valid"] = False
        out["drifting"] = None
        return out
    out["valid"] = True
    out["prediction_psi"] = round(_psi(pred[before], pred[after]), 4)
    out["amount_psi"] = round(_psi(amount[before], amount[after]), 4)
    out["mean_score_delta"] = round(
        float(pred[after].mean() - pred[before].mean()), 4)
    out["flag_rate_before"] = round(
        float((pred[before] >= threshold).mean()), 4)
    out["flag_rate_after"] = round(
        float((pred[after] >= threshold).mean()), 4)
    out["drifting"] = bool(out["prediction_psi"] > 0.25)
    return out


def report(
    cols: Dict[str, np.ndarray],
    kind: str = "summary",
    threshold: float = 0.5,
    k: int = 10,
    bucket: str = "day",
) -> dict:
    """Dispatch a named dashboard report; arrays JSON-ready (lists)."""
    if kind == "summary":
        return summary_stats(cols, threshold)
    if kind == "drift":
        return drift_report(cols, threshold=threshold)
    if kind not in ("timeseries", "terminals", "customers", "alerts"):
        raise ValueError(f"unknown report kind {kind}")
    if not cols or len(cols.get("tx_id", ())) == 0:
        return {}
    if kind == "timeseries":
        out = fraud_rate_over_time(cols, bucket, threshold)
    elif kind == "terminals":
        out = top_risky_terminals(cols, k, threshold)
    elif kind == "customers":
        out = top_risky_customers(cols, k, threshold)
    else:
        out = recent_alerts(cols, threshold, k)
    return {key: v.tolist() for key, v in out.items()}
