"""Object-store abstraction — the MinIO/S3 artifact layer.

The reference keeps model artifacts and the lakehouse warehouse in MinIO
(S3 API): ``load_initial_data.py:269-287`` uploads ``trained_model.pkl``
with boto3, ``fraud_detection.py:59-82`` downloads it at scorer startup
and **tolerates a 404** (serves without a model rather than crashing).
This module provides that role behind one tiny interface:

- :class:`LocalStore` — filesystem-backed (dev/test; also what a mounted
  volume looks like);
- :class:`S3Store` — boto3-gated S3/MinIO client (the client object is
  injectable, so tests run against a fake without boto3);
- :func:`make_store` — ``"s3://bucket/prefix"`` → :class:`S3Store`,
  anything else → :class:`LocalStore`.

Missing keys raise ``KeyError`` everywhere; callers that tolerate absence
(the reference's 404 path) catch it — see
:func:`..io.artifacts.download_model`.
"""

from __future__ import annotations

import os
from typing import List, Optional


class LocalStore:
    """Filesystem object store rooted at a directory."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        root = os.path.normpath(self.root)
        p = os.path.normpath(os.path.join(root, key))
        if os.path.commonpath([root, p]) != root:
            raise ValueError(f"key escapes store root: {key!r}")
        return p

    def put(self, key: str, data: bytes) -> None:
        p = self._path(key)
        os.makedirs(os.path.dirname(p) or ".", exist_ok=True)
        tmp = p + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, p)

    def get(self, key: str) -> bytes:
        try:
            with open(self._path(key), "rb") as f:
                return f.read()
        except FileNotFoundError:
            raise KeyError(key) from None

    def get_with_meta(self, key: str) -> "tuple[bytes, dict]":
        """Body + change-detection metadata from ONE consistent read: the
        open fd is fstat'ed before reading, so under atomic-replace
        writers (:meth:`put`) the etag always describes the bytes
        returned — the gate the serving-loop model reloader needs (a
        separate HEAD before or after the GET can describe a different
        object version)."""
        try:
            with open(self._path(key), "rb") as f:
                st = os.fstat(f.fileno())
                return f.read(), {"etag": str(st.st_mtime_ns),
                                  "size": st.st_size}
        except FileNotFoundError:
            raise KeyError(key) from None

    def exists(self, key: str) -> bool:
        return os.path.isfile(self._path(key))

    def head(self, key: str) -> dict:
        """Change-detection metadata without reading the body:
        ``{"etag", "size"}`` (etag = mtime_ns here). Raises KeyError on a
        missing key, like :meth:`get`."""
        try:
            st = os.stat(self._path(key))
        except FileNotFoundError:
            raise KeyError(key) from None
        return {"etag": str(st.st_mtime_ns), "size": st.st_size}

    def delete(self, key: str) -> None:
        try:
            os.remove(self._path(key))
        except FileNotFoundError:
            pass

    def move(self, src: str, dst: str) -> None:
        """Atomic rename within the store."""
        d = self._path(dst)
        os.makedirs(os.path.dirname(d) or ".", exist_ok=True)
        os.replace(self._path(src), d)

    def list(self, prefix: str = "") -> List[str]:
        out = []
        for dirpath, _, files in os.walk(self.root):
            for f in files:
                if f.endswith(".tmp"):
                    continue
                key = os.path.relpath(os.path.join(dirpath, f), self.root)
                key = key.replace(os.sep, "/")
                if key.startswith(prefix):
                    out.append(key)
        return sorted(out)


_MISSING_CODES = ("404", "NoSuchKey", "NotFound")


def _is_missing(exc: Exception) -> bool:
    """True when an S3-client exception means 'key does not exist'.

    Recognizes botocore's ClientError shape (``.response["Error"]["Code"]``)
    duck-typed, so fakes work without botocore installed."""
    err = getattr(exc, "response", None)
    if isinstance(err, dict):
        return err.get("Error", {}).get("Code") in _MISSING_CODES
    return False


class S3Store:
    """S3/MinIO object store (boto3-gated; client injectable for tests).

    ``client_kwargs`` pass straight to ``boto3.client("s3", ...)`` —
    ``endpoint_url``, credentials, region; the values the reference
    hard-codes in every job (``load_initial_data.py:269-287``)."""

    def __init__(self, bucket: str, prefix: str = "", client=None,
                 **client_kwargs):
        if client is None:
            try:
                import boto3
            except ImportError as e:
                raise ImportError(
                    "boto3 is not installed; use LocalStore for dev, or "
                    "install boto3 (pip install boto3) in production "
                    "images."
                ) from e
            client = boto3.client("s3", **client_kwargs)
        self.bucket = bucket
        self.prefix = prefix.strip("/")
        self.client = client

    def _key(self, key: str) -> str:
        return f"{self.prefix}/{key}" if self.prefix else key

    def put(self, key: str, data: bytes) -> None:
        self.client.put_object(Bucket=self.bucket, Key=self._key(key),
                               Body=data)

    def get(self, key: str) -> bytes:
        try:
            obj = self.client.get_object(Bucket=self.bucket,
                                         Key=self._key(key))
        except Exception as e:
            if _is_missing(e):
                raise KeyError(key) from None
            raise
        body = obj["Body"]
        return body.read() if hasattr(body, "read") else body

    def get_with_meta(self, key: str) -> "tuple[bytes, dict]":
        """Body + metadata from the SAME GetObject response — the etag is
        guaranteed to describe the returned bytes even if the key is
        overwritten concurrently (S3 GETs are atomic per version)."""
        try:
            obj = self.client.get_object(Bucket=self.bucket,
                                         Key=self._key(key))
        except Exception as e:
            if _is_missing(e):
                raise KeyError(key) from None
            raise
        body = obj["Body"]
        data = body.read() if hasattr(body, "read") else body
        # EXACTLY head()'s extraction: callers gate on sig equality
        # across the two methods, so a response without metadata (some
        # fakes) must degrade to the same empty/None shape head() never
        # produces differently — not to a fabricated signature.
        return data, {
            "etag": str(obj.get("ETag", "")) or str(
                obj.get("LastModified", "")),
            "size": obj.get("ContentLength"),
        }

    def exists(self, key: str) -> bool:
        try:
            self.client.head_object(Bucket=self.bucket, Key=self._key(key))
            return True
        except Exception as e:
            if _is_missing(e):
                return False
            raise

    def head(self, key: str) -> dict:
        """Change-detection metadata without the body (one HEAD request):
        ``{"etag", "size"}``. Raises KeyError on a missing key. Lets
        pollers (the serving-loop model reloader) detect no-change
        without re-downloading the artifact every interval."""
        try:
            resp = self.client.head_object(Bucket=self.bucket,
                                           Key=self._key(key))
        except Exception as e:
            if _is_missing(e):
                raise KeyError(key) from None
            raise
        return {
            "etag": str(resp.get("ETag", "")) or str(
                resp.get("LastModified", "")),
            "size": resp.get("ContentLength"),
        }

    def delete(self, key: str) -> None:
        self.client.delete_object(Bucket=self.bucket, Key=self._key(key))

    def move(self, src: str, dst: str) -> None:
        """Server-side copy + delete (no byte round-trip through the
        host; S3 has no native rename)."""
        self.client.copy_object(
            Bucket=self.bucket, Key=self._key(dst),
            CopySource={"Bucket": self.bucket, "Key": self._key(src)},
        )
        self.client.delete_object(Bucket=self.bucket, Key=self._key(src))

    def list(self, prefix: str = "") -> List[str]:
        full = self._key(prefix)
        keys: List[str] = []
        token: Optional[str] = None
        while True:
            kw = {"Bucket": self.bucket, "Prefix": full}
            if token:
                kw["ContinuationToken"] = token
            resp = self.client.list_objects_v2(**kw)
            for item in resp.get("Contents", []):
                k = item["Key"]
                if self.prefix:
                    k = k[len(self.prefix) + 1:]
                keys.append(k)
            if not resp.get("IsTruncated"):
                break
            token = resp.get("NextContinuationToken")
        return sorted(keys)


def make_store(url: str, **kwargs):
    """``s3://bucket[/prefix]`` → :class:`S3Store`; else :class:`LocalStore`.

    ``RTFDS_S3_ENDPOINT`` (when set and no explicit ``endpoint_url`` /
    ``client`` is given) points the S3 client at MinIO — the reference's
    object store (``docker-compose.yml`` minio service) — uniformly for
    sinks, checkpoints, and artifacts.
    """
    if url.startswith("s3://"):
        rest = url[len("s3://"):]
        bucket, _, prefix = rest.partition("/")
        if ("endpoint_url" not in kwargs and "client" not in kwargs
                and os.environ.get("RTFDS_S3_ENDPOINT")):
            kwargs["endpoint_url"] = os.environ["RTFDS_S3_ENDPOINT"]
        return S3Store(bucket, prefix=prefix, **kwargs)
    return LocalStore(url)
