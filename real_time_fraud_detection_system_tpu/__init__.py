"""TPU-native real-time fraud detection framework.

A brand-new JAX/XLA/Pallas framework with the capabilities of the reference
``sauravtanwar786/Real-time_fraud_detection_system`` (a Spark Structured
Streaming + sklearn pipeline): CDC envelope decoding, stateful rolling-window
velocity features, micro-batch classification, online model updates and
lakehouse-compatible sinks — rebuilt TPU-first:

- the per-transaction hot path (reference ``pyspark/scripts/fraud_detection.py``)
  is a single jitted ``step(state, batch) -> (state, preds)``;
- rolling 1/7/30-day per-customer / per-terminal features (reference
  ``fraud_detection_model/feature_transformation.ipynb``) live in HBM as
  day-bucket ring buffers + count-min sketch, updated by scatter kernels;
- scoring is ``vmap``-batched and ``shard_map``-sharded across a TPU mesh,
  one Kafka partition per device (reference: Spark ``local[*]`` executors);
- the CPU (sklearn) path is retained as a parity oracle behind
  ``--scorer {cpu,tpu}``.

Import as::

    import real_time_fraud_detection_system_tpu as rtfds
"""

__version__ = "0.1.0"

from real_time_fraud_detection_system_tpu.config import (  # noqa: F401
    Config,
    DataConfig,
    FeatureConfig,
    MeshConfig,
    ModelConfig,
    RuntimeConfig,
    TrainConfig,
)
