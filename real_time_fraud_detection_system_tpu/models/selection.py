"""Model selection: prequential validation, grid search, k-fold CV.

Re-implements the reference's model-selection machinery from
``fraud_detection_model/shared_functions.py``:

- ``prequentialSplit`` (``:265-292``) → :func:`prequential_split` — n
  time-shifted train/delay/test folds, most recent first;
- ``prequential_grid_search`` (``:774-814``) → :func:`prequential_grid_search`
  — hyper-parameter sweep where every candidate is scored on every
  prequential fold, with fit/predict wall-clock recorded per fold (the
  reference's ``training_execution_time`` / ``prediction_execution_time``
  hooks, ``:312-320``);
- ``model_selection_wrapper`` (``:824-872``) → :func:`model_selection_wrapper`
  — the validation+test double sweep;
- ``kfold_cv_with_classifier`` (``:882-911``) → :func:`kfold_cv_with_classifier`
  — stratified k-fold CV for non-temporal sanity checks;
- ``get_summary_performances`` (``:597-648``) → :func:`summarize_performances`
  — mean±std per candidate, best-by-validation choice, and the test
  performance of that choice.

Everything operates on plain numpy + the typed :class:`..config.Config`; no
pandas DataFrames in the loop.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from real_time_fraud_detection_system_tpu.config import Config
from real_time_fraud_detection_system_tpu.data.generator import Transactions
from real_time_fraud_detection_system_tpu.models.metrics import (
    performance_assessment,
)
from real_time_fraud_detection_system_tpu.models.scaler import (
    fit_scaler,
    transform,
)
from real_time_fraud_detection_system_tpu.models.train import (
    TrainedModel,
    fit_classifier,
    scale_split_to_txs,
    train_delay_test_split,
)

METRIC_KEYS = ("auc_roc", "average_precision", "card_precision@100")


def prequential_split(
    txs: Transactions,
    start_day_training: int,
    n_folds: int = 4,
    delta_train: int = 153,
    delta_delay: int = 30,
    delta_assessment: int = 30,
) -> List[Tuple[np.ndarray, np.ndarray]]:
    """n (train_mask, test_mask) folds, fold i shifted back i*delta_assessment.

    Fold 0 is the most recent window, matching ``shared_functions.py:265-292``
    where ``start_date_training - fold_index*delta_assessment`` walks
    backwards in time. Folds whose training window would start before day 0
    are dropped (the reference would silently produce empty frames), and
    spans that don't fit the dataset are auto-scaled like
    :func:`~.train.fit_split_to_days` does for ``train_model`` — the
    default 153/30/30 on a short dataset would otherwise give every fold
    an empty test window (NaN metric rows across the whole sweep).
    """
    delta_train, delta_delay, delta_assessment = scale_split_to_txs(
        txs, delta_train, delta_delay, delta_assessment,
        start_day=start_day_training, logger_name="selection",
    )
    folds = []
    for i in range(n_folds):
        sd = start_day_training - i * delta_assessment
        if sd < 0:
            break
        folds.append(
            train_delay_test_split(
                txs,
                start_day=sd,
                delta_train=delta_train,
                delta_delay=delta_delay,
                delta_test=delta_assessment,
            )
        )
    return folds


def expand_param_grid(param_grid: Dict[str, Sequence]) -> List[Dict]:
    """{'forest_max_depth': [2, 8], ...} → list of single-value dicts
    (cartesian product, like sklearn's ParameterGrid)."""
    if not param_grid:
        return [{}]
    keys = sorted(param_grid)
    return [
        dict(zip(keys, combo))
        for combo in itertools.product(*(param_grid[k] for k in keys))
    ]


def _apply_params(cfg: Config, params: Dict) -> Config:
    """Override ModelConfig/TrainConfig fields named in ``params``."""
    model_fields = {f.name for f in dataclasses.fields(cfg.model)}
    train_fields = {f.name for f in dataclasses.fields(cfg.train)}
    m_over = {k: v for k, v in params.items() if k in model_fields}
    t_over = {k: v for k, v in params.items() if k in train_fields}
    unknown = set(params) - m_over.keys() - t_over.keys()
    if unknown:
        raise ValueError(f"unknown hyper-parameters: {sorted(unknown)}")
    return cfg.replace(
        model=dataclasses.replace(cfg.model, **m_over),
        train=dataclasses.replace(cfg.train, **t_over),
    )


@dataclass
class FoldPerformance:
    """One (candidate, fold) evaluation row."""

    params: Dict
    fold: int
    expe_type: str  # "validation" | "test"
    metrics: Dict[str, float]
    fit_seconds: float
    predict_seconds: float
    n_train: int
    n_test: int


def prequential_grid_search(
    txs: Transactions,
    features: np.ndarray,
    cfg: Config,
    kind: str,
    param_grid: Dict[str, Sequence],
    start_day_training: int,
    n_folds: int = 4,
    expe_type: str = "test",
    delta_train: Optional[int] = None,
    delta_delay: Optional[int] = None,
    delta_assessment: Optional[int] = None,
) -> List[FoldPerformance]:
    """Every candidate × every prequential fold → a FoldPerformance row."""
    delta_train = cfg.train.delta_train_days if delta_train is None else delta_train
    delta_delay = cfg.train.delta_delay_days if delta_delay is None else delta_delay
    delta_assessment = (
        cfg.train.delta_test_days if delta_assessment is None else delta_assessment
    )
    folds = prequential_split(
        txs,
        start_day_training,
        n_folds=n_folds,
        delta_train=delta_train,
        delta_delay=delta_delay,
        delta_assessment=delta_assessment,
    )
    import jax.numpy as jnp

    # Validate every candidate up front (fail before any expensive fit).
    candidates = [
        (cand, _apply_params(cfg, cand)) for cand in expand_param_grid(param_grid)
    ]
    rows: List[FoldPerformance] = []
    # Fold-major loop: scaling is hyper-parameter-independent, so each fold's
    # scaler fit + train-set transform happens once, not once per candidate.
    for i, (train_mask, test_mask) in enumerate(folds):
        x_train = features[train_mask]
        y_train = txs.tx_fraud[train_mask].astype(np.float32)
        scaler = fit_scaler(x_train)
        xs = np.asarray(
            transform(scaler, jnp.asarray(x_train, dtype=jnp.float32))
        )
        for cand, cand_cfg in candidates:
            t0 = time.perf_counter()
            params = fit_classifier(kind, xs, y_train, cand_cfg)
            fit_s = time.perf_counter() - t0
            model = TrainedModel(kind=kind, scaler=scaler, params=params)
            t0 = time.perf_counter()
            probs = model.predict_proba(features[test_mask])
            pred_s = time.perf_counter() - t0
            metrics = performance_assessment(
                txs.tx_fraud[test_mask],
                probs,
                days=txs.tx_time_days[test_mask],
                customer_ids=txs.customer_id[test_mask],
            )
            rows.append(
                FoldPerformance(
                    params=cand,
                    fold=i,
                    expe_type=expe_type,
                    metrics=metrics,
                    fit_seconds=fit_s,
                    predict_seconds=pred_s,
                    n_train=int(train_mask.sum()),
                    n_test=int(test_mask.sum()),
                )
            )
    return rows


def model_selection_wrapper(
    txs: Transactions,
    features: np.ndarray,
    cfg: Config,
    kind: str,
    param_grid: Dict[str, Sequence],
    start_day_training_for_valid: int,
    start_day_training_for_test: int,
    n_folds: int = 4,
    **deltas,
) -> List[FoldPerformance]:
    """Validation sweep + test sweep (``shared_functions.py:824-872``).

    Validation folds end before the test period starts, so choosing
    hyper-parameters on them is unbiased; the matching test rows report what
    that choice would have achieved.

    Short datasets: the spans are scaled ONCE here, anchored at the later
    (test) sweep, and shared by both sweeps. Per-sweep scaling would let
    each sweep fill the data to its last day, overlapping the validation
    windows into the test period — selection would leak held-out days.
    With shared spans, the windows stay disjoint whenever the anchors are
    at least one (scaled) assessment span apart — the reference's own
    ``start_valid = start_test - delta_test`` convention.
    """
    dtr, dde, dte = scale_split_to_txs(
        txs,
        deltas.pop("delta_train", cfg.train.delta_train_days),
        deltas.pop("delta_delay", cfg.train.delta_delay_days),
        deltas.pop("delta_assessment", cfg.train.delta_test_days),
        start_day=start_day_training_for_test,
        logger_name="selection",
    )
    rows = prequential_grid_search(
        txs, features, cfg, kind, param_grid,
        start_day_training_for_valid, n_folds=n_folds,
        expe_type="validation", delta_train=dtr, delta_delay=dde,
        delta_assessment=dte, **deltas,
    )
    rows += prequential_grid_search(
        txs, features, cfg, kind, param_grid,
        start_day_training_for_test, n_folds=n_folds,
        expe_type="test", delta_train=dtr, delta_delay=dde,
        delta_assessment=dte, **deltas,
    )
    return rows


@dataclass
class SelectionSummary:
    """Per-metric selection outcome (``shared_functions.py:597-648``)."""

    metric: str
    best_params: Dict
    validation_mean: float
    validation_std: float
    test_mean: float
    test_std: float
    candidates: Dict[str, Dict[str, float]] = field(default_factory=dict)


def _param_key(params: Dict) -> str:
    return repr(sorted(params.items()))


def _mean_std(rows: List[FoldPerformance], metric: str) -> Tuple[float, float]:
    vals = np.array(
        [r.metrics[metric] for r in rows if np.isfinite(r.metrics.get(metric, np.nan))]
    )
    if len(vals) == 0:
        return float("nan"), float("nan")
    return float(vals.mean()), float(vals.std())


def summarize_performances(
    rows: List[FoldPerformance],
    metrics: Sequence[str] = METRIC_KEYS,
) -> Dict[str, SelectionSummary]:
    """For each metric: candidate means±stds, the best-by-validation
    candidate, and its test performance."""
    by_params: Dict[str, Tuple[Dict, List[FoldPerformance]]] = {}
    for r in rows:
        by_params.setdefault(_param_key(r.params), (r.params, []))[1].append(r)

    out: Dict[str, SelectionSummary] = {}
    for metric in metrics:
        candidates: Dict[str, Dict[str, float]] = {}
        best_key, best_val = None, -np.inf
        for key, (params, prs) in by_params.items():
            v_mean, v_std = _mean_std(
                [r for r in prs if r.expe_type == "validation"], metric
            )
            t_mean, t_std = _mean_std(
                [r for r in prs if r.expe_type == "test"], metric
            )
            candidates[key] = {
                "validation_mean": v_mean,
                "validation_std": v_std,
                "test_mean": t_mean,
                "test_std": t_std,
            }
            if np.isfinite(v_mean) and v_mean > best_val:
                best_key, best_val = key, v_mean
        if best_key is None:  # no validation rows: fall back to test
            for key, c in candidates.items():
                if np.isfinite(c["test_mean"]) and c["test_mean"] > best_val:
                    best_key, best_val = key, c["test_mean"]
        params = by_params[best_key][0] if best_key else {}
        c = candidates.get(best_key, {}) if best_key else {}
        out[metric] = SelectionSummary(
            metric=metric,
            best_params=params,
            validation_mean=c.get("validation_mean", float("nan")),
            validation_std=c.get("validation_std", float("nan")),
            test_mean=c.get("test_mean", float("nan")),
            test_std=c.get("test_std", float("nan")),
            candidates=candidates,
        )
    return out


def execution_times(rows: List[FoldPerformance]) -> Dict[str, Dict[str, float]]:
    """Mean fit/predict wall-clock per candidate
    (``shared_functions.py:499-512``)."""
    by_params: Dict[str, List[FoldPerformance]] = {}
    for r in rows:
        by_params.setdefault(_param_key(r.params), []).append(r)
    return {
        key: {
            "fit_seconds": float(np.mean([r.fit_seconds for r in prs])),
            "predict_seconds": float(np.mean([r.predict_seconds for r in prs])),
        }
        for key, prs in by_params.items()
    }


def kfold_cv_with_classifier(
    features: np.ndarray,
    labels: np.ndarray,
    cfg: Config,
    kind: str,
    n_folds: int = 5,
    seed: int = 0,
) -> Dict[str, float]:
    """Stratified k-fold CV (``shared_functions.py:882-911``) — the
    non-temporal sanity check. Returns mean±std AUC/AP over folds."""
    import jax.numpy as jnp

    y = np.asarray(labels).astype(np.float32)
    bad = set(np.unique(y)) - {0.0, 1.0}
    if bad:
        raise ValueError(f"labels must be 0/1, got extra values {sorted(bad)}")
    rng = np.random.default_rng(seed)
    # Stratified fold assignment: shuffle within each class, deal round-robin.
    fold_of = np.empty(len(y), dtype=np.int64)
    for cls in (0, 1):
        idx = np.flatnonzero(y == cls)
        rng.shuffle(idx)
        fold_of[idx] = np.arange(len(idx)) % n_folds
    aucs, aps = [], []
    for f in range(n_folds):
        test_mask = fold_of == f
        train_mask = ~test_mask
        x_train = features[train_mask]
        scaler = fit_scaler(x_train)
        xs = np.asarray(
            transform(scaler, jnp.asarray(x_train, dtype=jnp.float32))
        )
        params = fit_classifier(kind, xs, y[train_mask], cfg)
        model = TrainedModel(kind=kind, scaler=scaler, params=params)
        probs = model.predict_proba(features[test_mask])
        m = performance_assessment(y[test_mask], probs)
        aucs.append(m["auc_roc"])
        aps.append(m["average_precision"])
    return {
        "auc_roc_mean": float(np.nanmean(aucs)),
        "auc_roc_std": float(np.nanstd(aucs)),
        "average_precision_mean": float(np.nanmean(aps)),
        "average_precision_std": float(np.nanstd(aps)),
        "n_folds": float(n_folds),
    }
