"""Feature standardization — sklearn-StandardScaler-compatible, jnp transform.

The reference fits a ``StandardScaler`` offline and applies it per batch
inside the scoring UDF (``shared_functions.py:114-120`` scaleData,
``fraud_detection.py:183-195``). Here the (mean, scale) pair is a pytree that
lives on device, and the transform fuses into the scoring kernel under jit.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np


class Scaler(NamedTuple):
    mean: jnp.ndarray  # float32 [F]
    scale: jnp.ndarray  # float32 [F] — stddev, zero-variance cols → 1.0


def fit_scaler(x: np.ndarray) -> Scaler:
    """Fit on host (numpy), matching sklearn: ddof=0, zero-var → scale 1."""
    mean = np.asarray(x, dtype=np.float64).mean(axis=0)
    std = np.asarray(x, dtype=np.float64).std(axis=0)
    std[std == 0.0] = 1.0
    return Scaler(
        mean=jnp.asarray(mean, dtype=jnp.float32),
        scale=jnp.asarray(std, dtype=jnp.float32),
    )


def transform(scaler: Scaler, x: jnp.ndarray) -> jnp.ndarray:
    return (x - scaler.mean) / scaler.scale


def inverse_transform(scaler: Scaler, x: jnp.ndarray) -> jnp.ndarray:
    return x * scaler.scale + scaler.mean
