"""Tree-ensemble inference on TPU — the reference's flagship model family.

The reference's production scorer is a pickled sklearn RandomForest applied
row-wise in a pandas UDF (``fraud_detection.py:183-195``;
``model_training.ipynb · cell 59`` picks the RF as ``trained_model.pkl``).
A branchy per-row tree walk is hostile to TPU, so inference is re-cast as a
**vectorized level-synchronous descent**: all B rows × T trees advance one
level per step with three flat gathers (feature id, threshold, children) and
a select — no data-dependent control flow, `lax.fori_loop` over max_depth
steps, leaves self-loop so ragged depths need no masking. Exact (bit-equal
decisions vs sklearn on f32 inputs) and O(B·T·depth) work instead of the
O(B·T·nodes·leaves) FLOP inflation of the matmul ("Hummingbird GEMM")
formulation — which is also provided (:func:`to_gemm`,
:func:`gemm_predict_proba`) for MXU-utilization experiments.

Training stays on host (sklearn, mirroring the reference's offline
notebook); the fitted estimator compiles once into flat node tables shipped
to HBM. Trees must be depth-bounded to give the loop a static trip count
(config ``model.forest_max_depth``) — a documented deviation from the
reference's unbounded-depth RF, with equivalent accuracy on this data.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class TreeEnsemble(NamedTuple):
    """Flat node tables, padded to (T trees × N nodes). Leaves self-loop."""

    feat: jnp.ndarray  # int32 [T, N] — feature index tested at node (0 at leaves)
    thresh: jnp.ndarray  # float32 [T, N] — go left iff x[feat] <= thresh
    left: jnp.ndarray  # int32 [T, N] — left child (node itself at leaves)
    right: jnp.ndarray  # int32 [T, N]
    prob: jnp.ndarray  # float32 [T, N] — P(class 1) at node (leaves used)
    max_depth: int  # static trip count for the descent loop

    @property
    def n_trees(self) -> int:
        return int(self.feat.shape[0])


def ftz_safe_thresholds(t32: np.ndarray) -> np.ndarray:
    """Replace denormal thresholds with their flush-to-zero-safe stand-in.

    XLA (TPU and CPU) flushes f32 denormals to zero in comparisons, so a
    threshold like ``-1e-45`` — which ``nextafter``-below-0.0 produces —
    behaves as ``-0.0`` and flips ``x <= thresh`` for ``x == 0.0``
    exactly. Under FTZ the representable inputs are normals and zero, so
    the exact stand-ins are: positive denormal → ``0.0`` (x <= denorm ⟺
    x <= 0), negative denormal → ``-FLT_MIN`` (x <= -denorm ⟺ x < 0 ⟺
    x <= -smallest-normal). Found by the randomized xgboost-dump parity
    test (a split_condition of exactly 0.0 routed wrong).

    Caveat (non-FTZ backends): the stand-ins are exact only when the
    comparison INPUTS are normals or zero — true under FTZ, where
    denormal features cannot reach the comparator. On a backend that
    does NOT flush denormals in comparisons, a denormal input
    ``x ∈ (-FLT_MIN, 0)`` routes differently against the ``-FLT_MIN``
    stand-in (``x <= -FLT_MIN`` is False though ``x < 0``) than it did
    against the original ``nextafter`` threshold. Accepted tradeoff: the
    engineered features (counts, averages of cent-quantized amounts,
    risk ratios) make denormal inputs practically impossible."""
    t32 = np.asarray(t32, dtype=np.float32).copy()
    tiny = np.float32(np.finfo(np.float32).tiny)
    denorm = (t32 != 0.0) & (np.abs(t32) < tiny)
    t32[denorm & (t32 > 0)] = np.float32(0.0)
    t32[denorm & (t32 < 0)] = -tiny
    return t32


def _f32_round_down(t64: np.ndarray) -> np.ndarray:
    """Round float64 thresholds DOWN to float32 so that for any f32 input x:
    (x <= t32) == (x <= t64) — decisions stay bit-identical to sklearn on
    f32-quantized features."""
    t32 = t64.astype(np.float32)
    over = t32.astype(np.float64) > t64
    t32[over] = np.nextafter(t32[over], np.float32(-np.inf), dtype=np.float32)
    return ftz_safe_thresholds(t32)


def ensemble_from_sklearn(model, n_features: int) -> TreeEnsemble:
    """Compile a fitted sklearn DecisionTree/RandomForest/ExtraTrees into
    flat node tables."""
    trees = getattr(model, "estimators_", None)
    if trees is None:
        trees = [model]
    else:
        trees = [t for t in np.asarray(trees).ravel()]

    T = len(trees)
    N = max(t.tree_.node_count for t in trees)
    feat = np.zeros((T, N), dtype=np.int32)
    thresh = np.zeros((T, N), dtype=np.float32)
    left = np.zeros((T, N), dtype=np.int32)
    right = np.zeros((T, N), dtype=np.int32)
    prob = np.zeros((T, N), dtype=np.float32)
    depth = 0
    for ti, est in enumerate(trees):
        tr = est.tree_
        n = tr.node_count
        is_leaf = tr.children_left == -1
        feat[ti, :n] = np.where(is_leaf, 0, tr.feature)
        thresh[ti, :n] = _f32_round_down(np.where(is_leaf, 0.0, tr.threshold))
        idx = np.arange(n, dtype=np.int32)
        left[ti, :n] = np.where(is_leaf, idx, tr.children_left).astype(np.int32)
        right[ti, :n] = np.where(is_leaf, idx, tr.children_right).astype(np.int32)
        v = tr.value[:, 0, :]  # [n, n_classes] (fractions or counts)
        if v.shape[1] > 1:
            tot = v.sum(axis=1)
            prob[ti, :n] = np.where(tot > 0, v[:, -1] / np.maximum(tot, 1e-12), 0.0)
        else:
            prob[ti, :n] = v[:, 0]
        depth = max(depth, int(tr.max_depth))
    return TreeEnsemble(
        feat=jnp.asarray(feat),
        thresh=jnp.asarray(thresh),
        left=jnp.asarray(left),
        right=jnp.asarray(right),
        prob=jnp.asarray(prob),
        max_depth=depth,
    )


def ensemble_leaf_values(ens: TreeEnsemble, x: jnp.ndarray) -> jnp.ndarray:
    """[B, F] → per-tree leaf value [B, T].

    Level-synchronous descent: node[b,t] advances one level per iteration;
    leaves self-loop, so ``max_depth`` iterations land every lane on its
    leaf. Three gathers + one compare + one select per step, all [B, T].
    """
    b = x.shape[0]
    t, n = ens.feat.shape
    tree_base = (jnp.arange(t, dtype=jnp.int32) * n)[None, :]  # [1, T]
    feat = ens.feat.reshape(-1)
    thresh = ens.thresh.reshape(-1)
    left = ens.left.reshape(-1)
    right = ens.right.reshape(-1)

    def body(_, node):
        flat = tree_base + node  # [B, T]
        f = feat[flat]
        xv = jnp.take_along_axis(x, f, axis=1)  # [B, T]
        go_left = xv <= thresh[flat]
        return jnp.where(go_left, left[flat], right[flat])

    node0 = jnp.zeros((b, t), dtype=jnp.int32)
    node = jax.lax.fori_loop(0, ens.max_depth, body, node0)
    return ens.prob.reshape(-1)[tree_base + node]  # [B, T]


def ensemble_predict_proba(ens: TreeEnsemble, x: jnp.ndarray) -> jnp.ndarray:
    """[B, F] → fraud probability [B] (bagging: mean of per-tree probs)."""
    return jnp.mean(ensemble_leaf_values(ens, x), axis=1)


class GemmEnsemble(NamedTuple):
    """Matmul ("Hummingbird GEMM") formulation — see :func:`to_gemm`."""

    sel: jnp.ndarray  # float32 [T, F, I] one-hot feature selector per node
    thresh: jnp.ndarray  # float32 [T, I]
    path: jnp.ndarray  # float32 [T, I, L] — +1 left-required, -1 right, 0 off-path
    target: jnp.ndarray  # float32 [T, L] — #left-required per leaf (pad 1e9)
    leaf_val: jnp.ndarray  # float32 [T, L]

    @property
    def n_trees(self) -> int:
        return int(self.sel.shape[0])


def to_gemm(ens: TreeEnsemble, n_features: int) -> GemmEnsemble:
    """Compile node tables into the 3-matmul formulation.

    Leaf l is reached iff every on-path node decision matches; with the ±1
    path encoding, Z[l] = Σ path[i,l]·D[i] equals target[l] (= #left-required)
    exactly in that case and only then.
    """
    feat = np.asarray(ens.feat)
    thresh = np.asarray(ens.thresh)
    left = np.asarray(ens.left)
    right = np.asarray(ens.right)
    prob = np.asarray(ens.prob)
    T, N = feat.shape

    per_tree = []
    for t in range(T):
        is_leaf = left[t] == np.arange(N)
        # restrict to reachable nodes of this tree (padding is unreachable)
        internal = []
        leaves = []
        stack = [0]
        seen = set()
        while stack:
            nd = stack.pop()
            if nd in seen:
                continue
            seen.add(nd)
            if is_leaf[nd]:
                leaves.append(nd)
            else:
                internal.append(nd)
                stack.append(int(left[t, nd]))
                stack.append(int(right[t, nd]))
        i_of = {nd: i for i, nd in enumerate(sorted(internal))}
        l_of = {nd: i for i, nd in enumerate(sorted(leaves))}
        I, L = len(internal), len(leaves)
        sel = np.zeros((n_features, max(I, 1)), dtype=np.float32)
        th = np.full(max(I, 1), np.float32(np.inf))
        path = np.zeros((max(I, 1), max(L, 1)), dtype=np.float32)
        target = np.zeros(max(L, 1), dtype=np.float32)
        leaf_val = np.zeros(max(L, 1), dtype=np.float32)
        # iterative root→leaf walk collecting requirements
        stack2 = [(0, [])]
        while stack2:
            nd, req = stack2.pop()
            if is_leaf[nd]:
                li = l_of[nd]
                for i, sign in req:
                    path[i, li] = sign
                target[li] = sum(1 for _, s in req if s > 0)
                leaf_val[li] = prob[t, nd]
            else:
                i = i_of[nd]
                sel[feat[t, nd], i] = 1.0
                th[i] = thresh[t, nd]
                stack2.append((int(left[t, nd]), req + [(i, +1)]))
                stack2.append((int(right[t, nd]), req + [(i, -1)]))
        per_tree.append((sel, th, path, target, leaf_val))

    I = max(p[0].shape[1] for p in per_tree)
    L = max(p[2].shape[1] for p in per_tree)
    F = n_features
    sel = np.zeros((T, F, I), dtype=np.float32)
    th = np.full((T, I), np.float32(np.inf))
    path = np.zeros((T, I, L), dtype=np.float32)
    target = np.full((T, L), 1e9, dtype=np.float32)
    leaf_val = np.zeros((T, L), dtype=np.float32)
    for t, (s, t_, p, tg, lv) in enumerate(per_tree):
        i, l = s.shape[1], p.shape[1]
        sel[t, :, :i] = s
        th[t, :i] = t_
        path[t, :i, :l] = p
        target[t, :l] = tg
        leaf_val[t, :l] = lv
    return GemmEnsemble(
        sel=jnp.asarray(sel), thresh=jnp.asarray(th), path=jnp.asarray(path),
        target=jnp.asarray(target), leaf_val=jnp.asarray(leaf_val),
    )


def resolve_z_mode(mode: str | None) -> str:
    """``RuntimeConfig.z_mode`` → a concrete :func:`gemm_leaf_sum` mode.

    ``"auto"`` (and None) picks int8 on TPU — the measured MXU winner
    (bench ``detail.z_mode``: int8 peaks ~2× bf16 on v5e with
    ``max_abs_delta_int8_vs_f32 == 0``) — and f32 elsewhere (the only
    float mode CPU XLA lowers natively). Every mode is decision-exact by
    the contract documented on :func:`gemm_leaf_sum`; int8 is
    additionally BIT-identical to f32 (integer z arithmetic, same
    onehot, same f32-HIGHEST leaf contraction)."""
    if mode is None or mode == "auto":
        return "int8" if jax.default_backend() == "tpu" else "f32"
    if mode not in ("f32", "bf16", "int8"):
        raise ValueError(f"unknown z_mode {mode!r}")
    return mode


def gemm_leaf_sum(
    g: GemmEnsemble, x: jnp.ndarray, z_mode: str | None = None
) -> jnp.ndarray:
    """[B, F] → Σ_t leaf value [B] via three contractions (MXU formulation).

    Sum-reduction shared by bagging (÷ n_trees) and boosting (+ base logit).

    Mixed precision, chosen to stay bit-exact (verified on v5e: max |Δ| = 0
    vs all-HIGHEST, incl. inputs placed exactly on thresholds):

    - proj MUST be f32 HIGHEST: the decision ``proj <= thresh`` flips for
      inputs near thresholds under any bf16-pass scheme (measured: HIGH
      flips ~1% of decisions on threshold-valued inputs);
    - the dominant z contraction is exact in EVERY reduced-precision mode
      because its operands are tiny integers: d is 0/1, path is ±1/0, and
      z counts ≤ depth. ``z_mode`` selects the arithmetic:
        * ``"bf16"`` — bf16×bf16→f32 (integers ≪ 2^8 are bf16-exact);
          ~15% faster than f32 end-to-end on v5e. TPU default.
        * ``"int8"`` — int8×int8→int32 on the MXU's int8 path (2× bf16
          peak on v5e); ``target`` compares exactly in int32, with the
          1e9 leaf padding still unmatched.
        * ``"f32"`` — plain f32; the only float mode CPU XLA lowers
          (no BF16×BF16→F32 dot thunk there, so ``"bf16"`` silently
          degrades to f32 off-TPU — same values by construction).
          CPU default.
    - the leaf gather keeps leaf_val in f32 (probabilities are not
      bf16-exact; onehot is 0/1 so f32 HIGHEST here is exact and cheap —
      L ≪ I·L work).
    """
    hi = jax.lax.Precision.HIGHEST
    if z_mode is None:
        z_mode = "bf16" if jax.default_backend() == "tpu" else "f32"
    if z_mode not in ("bf16", "int8", "f32"):
        raise ValueError(f"unknown z_mode {z_mode!r}")
    proj = jnp.einsum("bf,tfi->bti", x, g.sel, precision=hi)
    if z_mode == "int8":
        d = (proj <= g.thresh[None]).astype(jnp.int8)
        z = jnp.einsum(
            "bti,til->btl", d, g.path.astype(jnp.int8),
            preferred_element_type=jnp.int32,
        )
        onehot = (z == g.target.astype(jnp.int32)[None]).astype(jnp.float32)
    else:
        on_tpu = jax.default_backend() == "tpu"
        zdt = jnp.bfloat16 if (z_mode == "bf16" and on_tpu) else jnp.float32
        d = (proj <= g.thresh[None]).astype(zdt)
        z = jnp.einsum(
            "bti,til->btl", d, g.path.astype(zdt),
            preferred_element_type=jnp.float32,
        )
        onehot = (jnp.abs(z - g.target[None]) < 0.5).astype(jnp.float32)
    return jnp.einsum("btl,tl->b", onehot, g.leaf_val, precision=hi)


def gemm_predict_proba(
    g: GemmEnsemble, x: jnp.ndarray, z_mode: str | None = None
) -> jnp.ndarray:
    """[B, F] → probability [B] (bagging mean over trees)."""
    return gemm_leaf_sum(g, x, z_mode) / g.n_trees


def predict_proba(
    params, x: jnp.ndarray, z_mode: str | None = None
) -> jnp.ndarray:
    """Unified forest scorer: dispatches on the ensemble form.

    The GEMM form is ~100× faster than the gather-based descent on TPU
    (measured on v5e: 3.2M vs 31k rows/s at B=32k, T=100, depth 8) because
    XLA lowers [B, T]-indexed table gathers to a slow serial path while the
    three contractions tile straight onto the MXU. Both are decision-exact
    vs sklearn on f32 inputs. ``z_mode`` selects the GEMM form's z
    arithmetic (the descent form has no contraction and ignores it).
    """
    if isinstance(params, GemmEnsemble):
        return gemm_predict_proba(params, x, z_mode)
    return ensemble_predict_proba(params, x)


def for_device(
    ens: TreeEnsemble, n_features: int, max_gemm_bytes: int = 256 * 1024 * 1024
) -> "TreeEnsemble | GemmEnsemble":
    """Pick the fastest exact device form for a compiled ensemble.

    GEMM inflates memory as O(T·N²) for the path matrix, which is fine for
    depth-bounded forests (the reference's production RF) but explodes for
    unbounded trees (the reference's DT-∞ experiment,
    ``model_training.ipynb · cell 50``) — those keep the descent form.
    """
    t, n = ens.feat.shape
    if 4 * t * n * n <= max_gemm_bytes:
        return to_gemm(ens, n_features)
    return ens


def synthetic_ensemble(
    n_trees: int = 4,
    max_depth: int = 3,
    n_features: int = 15,
    seed: int = 0,
) -> TreeEnsemble:
    """A shape-faithful ensemble with NO training dependency.

    Complete binary trees of exactly ``max_depth`` levels with random
    (but valid) feature indices, thresholds and leaf probabilities —
    structurally indistinguishable from an ``ensemble_from_sklearn``
    product, so anything that needs an ensemble's SHAPES and traced
    program (``tools/rtfdsverify``'s device-contract proofs, template
    tests, ``to_gemm``/``to_pallas`` padding math) can build one without
    sklearn or data. The probabilities are arbitrary: do not score real
    traffic with it.
    """
    rng = np.random.default_rng(seed)
    n = 2 ** (max_depth + 1) - 1  # complete binary tree node count
    n_internal = 2 ** max_depth - 1
    idx = np.arange(n, dtype=np.int32)
    is_leaf = idx >= n_internal
    feat = np.where(
        is_leaf[None, :], 0,
        rng.integers(0, n_features, size=(n_trees, n)),
    ).astype(np.int32)
    thresh = np.where(
        is_leaf[None, :], 0.0,
        rng.normal(size=(n_trees, n)),
    ).astype(np.float32)
    left = np.where(is_leaf, idx, idx * 2 + 1).astype(np.int32)
    right = np.where(is_leaf, idx, idx * 2 + 2).astype(np.int32)
    prob = rng.uniform(size=(n_trees, n)).astype(np.float32)
    return TreeEnsemble(
        feat=jnp.asarray(feat),
        thresh=jnp.asarray(ftz_safe_thresholds(thresh)),
        left=jnp.asarray(np.broadcast_to(left, (n_trees, n)).copy()),
        right=jnp.asarray(np.broadcast_to(right, (n_trees, n)).copy()),
        prob=jnp.asarray(prob),
        max_depth=max_depth,
    )


def fit_forest(
    x: np.ndarray,
    y: np.ndarray,
    n_trees: int = 100,
    max_depth: int = 8,
    seed: int = 0,
    kind: str = "forest",
) -> TreeEnsemble:
    """Host-side fit (sklearn, mirroring the reference's offline training)
    then compile to the TPU ensemble."""
    from sklearn.ensemble import RandomForestClassifier
    from sklearn.tree import DecisionTreeClassifier

    if kind == "tree":
        clf = DecisionTreeClassifier(max_depth=max_depth, random_state=seed)
    else:
        clf = RandomForestClassifier(
            n_estimators=n_trees, max_depth=max_depth, random_state=seed, n_jobs=-1
        )
    clf.fit(x, y)
    return ensemble_from_sklearn(clf, x.shape[1])
