"""Gradient-boosted trees — histogram trainer + TPU inference.

Covers the reference's XGBoost model family (``model_training.ipynb ·
cell 50`` fits XGBClassifier as one of its 5 classifiers) with a first-party
implementation, since this framework avoids the xgboost dependency: a
histogram-based level-wise booster with logistic loss and second-order
(Newton) leaf weights — the standard XGBoost objective:

    gain = ½·(GL²/(HL+λ) + GR²/(HR+λ) − G²/(H+λ)),  w* = −G/(H+λ)

Features are quantile-binned once (default 64 bins); each level's split
search is one vectorized (node × feature × bin) histogram pass. The fitted
trees compile into the same flat node tables as :mod:`.forest`, so TPU
inference reuses the level-synchronous descent kernel — only the reduction
differs (sum of raw scores + sigmoid instead of a probability mean).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from real_time_fraud_detection_system_tpu.models.forest import (
    GemmEnsemble,
    TreeEnsemble,
    _f32_round_down,
    ensemble_leaf_values,
    for_device,
    gemm_leaf_sum,
)


class GBTModel(NamedTuple):
    # prob/leaf_val field holds raw leaf scores (lr pre-applied)
    trees: "TreeEnsemble | GemmEnsemble"
    base_score: jnp.ndarray  # float32 [] — initial logit


def gbt_predict_proba(
    model: GBTModel, x: jnp.ndarray, z_mode: str | None = None
) -> jnp.ndarray:
    if isinstance(model.trees, GemmEnsemble):
        raw = gemm_leaf_sum(model.trees, x, z_mode)
    else:
        raw = jnp.sum(ensemble_leaf_values(model.trees, x), axis=1)
    return jax.nn.sigmoid(model.base_score + raw)


def gbt_for_device(model: GBTModel, n_features: int) -> GBTModel:
    """GEMM-form trees for fast TPU inference (see forest.predict_proba)."""
    if isinstance(model.trees, TreeEnsemble):
        return model._replace(trees=for_device(model.trees, n_features))
    return model


def synthetic_gbt(
    n_trees: int = 4,
    max_depth: int = 3,
    n_features: int = 15,
    seed: int = 0,
) -> GBTModel:
    """Shape-faithful GBT with no training dependency (the boosting twin
    of :func:`~.forest.synthetic_ensemble` — see its caveats: valid
    structure, arbitrary values, built for shape/traced-program
    consumers like ``tools/rtfdsverify``)."""
    from real_time_fraud_detection_system_tpu.models.forest import (
        synthetic_ensemble,
    )

    return GBTModel(
        trees=synthetic_ensemble(n_trees, max_depth, n_features, seed),
        base_score=jnp.float32(-2.0),
    )


class _Node(NamedTuple):
    feat: int
    thresh: float
    left: int
    right: int
    value: float


def _bin_features(x: np.ndarray, n_bins: int) -> Tuple[np.ndarray, np.ndarray]:
    """Quantile-bin each feature. Returns (binned uint8 [N,F], edges [F, n_bins-1])."""
    n, f = x.shape
    edges = np.zeros((f, n_bins - 1), dtype=np.float64)
    binned = np.zeros((n, f), dtype=np.int32)
    qs = np.linspace(0, 1, n_bins + 1)[1:-1]
    for j in range(f):
        e = np.unique(np.quantile(x[:, j], qs))
        pad = np.full(n_bins - 1, np.inf)
        pad[: len(e)] = e
        edges[j] = pad
        binned[:, j] = np.searchsorted(e, x[:, j], side="left")
    return binned, edges


def train_gbt(
    x: np.ndarray,
    y: np.ndarray,
    n_trees: int = 100,
    max_depth: int = 5,
    learning_rate: float = 0.1,
    n_bins: int = 64,
    reg_lambda: float = 1.0,
    min_child_weight: float = 1.0,
    gamma: float = 0.0,
) -> GBTModel:
    x = np.ascontiguousarray(x, dtype=np.float64)
    y = np.asarray(y, dtype=np.float64)
    n, f = x.shape
    binned, edges = _bin_features(x, n_bins)

    p0 = float(np.clip(y.mean(), 1e-6, 1 - 1e-6))
    base = float(np.log(p0 / (1 - p0)))
    logits = np.full(n, base)

    all_trees = []
    depth_used = 0
    for _ in range(n_trees):
        p = 1.0 / (1.0 + np.exp(-logits))
        g = p - y  # gradient of logistic loss
        h = p * (1.0 - p)  # hessian

        nodes, sample_leaf_value, d = _grow_tree(
            binned, edges, g, h, f, n_bins, max_depth, reg_lambda,
            min_child_weight, gamma, learning_rate,
        )
        depth_used = max(depth_used, d)
        all_trees.append(nodes)
        logits += sample_leaf_value

    # pack into flat node tables
    t = len(all_trees)
    nmax = max(len(tr) for tr in all_trees)
    feat = np.zeros((t, nmax), dtype=np.int32)
    thresh = np.zeros((t, nmax), dtype=np.float32)
    left = np.zeros((t, nmax), dtype=np.int32)
    right = np.zeros((t, nmax), dtype=np.int32)
    prob = np.zeros((t, nmax), dtype=np.float32)
    for ti, tr in enumerate(all_trees):
        for ni, nd in enumerate(tr):
            feat[ti, ni] = nd.feat
            # Round the float64 split edge DOWN to float32 so f32 inference
            # reproduces the training-time partition (x <= edge in float64)
            # exactly — same guard as forest.py's sklearn compiler.
            thresh[ti, ni] = _f32_round_down(np.asarray([nd.thresh]))[0]
            left[ti, ni] = nd.left if nd.left >= 0 else ni
            right[ti, ni] = nd.right if nd.right >= 0 else ni
            prob[ti, ni] = nd.value
    trees = TreeEnsemble(
        feat=jnp.asarray(feat),
        thresh=jnp.asarray(thresh),
        left=jnp.asarray(left),
        right=jnp.asarray(right),
        prob=jnp.asarray(prob),
        max_depth=max(depth_used, 1),
    )
    return GBTModel(trees=trees, base_score=jnp.float32(base))


def _grow_tree(
    binned: np.ndarray,  # int32 [N, F]
    edges: np.ndarray,  # [F, n_bins-1]
    g: np.ndarray,
    h: np.ndarray,
    f: int,
    n_bins: int,
    max_depth: int,
    lam: float,
    min_child_weight: float,
    gamma: float,
    lr: float,
):
    """Level-wise growth. Returns (node list, per-sample value, depth used)."""
    n = len(g)
    node_of = np.zeros(n, dtype=np.int64)  # current node id per sample
    nodes = [_Node(0, 0.0, -1, -1, 0.0)]  # placeholder root
    frontier = [0]  # node ids at current level
    depth_used = 0

    for depth in range(max_depth):
        if not frontier:
            break
        k = len(frontier)
        remap = -np.ones(len(nodes), dtype=np.int64)
        for i, nid in enumerate(frontier):
            remap[nid] = i
        slot = remap[node_of]  # [-1 for settled samples]
        active = slot >= 0
        # histogram over (active-node-slot, feature, bin)
        idx = (
            slot[active, None] * (f * n_bins)
            + np.arange(f)[None, :] * n_bins
            + binned[active]
        ).ravel()
        size = k * f * n_bins
        gh = np.bincount(idx, weights=np.repeat(g[active], f), minlength=size)
        hh = np.bincount(idx, weights=np.repeat(h[active], f), minlength=size)
        gh = gh.reshape(k, f, n_bins)
        hh = hh.reshape(k, f, n_bins)

        gl = np.cumsum(gh, axis=2)[:, :, :-1]  # left sums per split bin
        hl = np.cumsum(hh, axis=2)[:, :, :-1]
        gt = gh.sum(axis=2, keepdims=True)  # [k, f, 1] (same total per feature)
        ht = hh.sum(axis=2, keepdims=True)
        gr = gt - gl
        hr = ht - hl
        gain = 0.5 * (
            gl**2 / (hl + lam) + gr**2 / (hr + lam) - gt**2 / (ht + lam)
        ) - gamma
        ok = (hl >= min_child_weight) & (hr >= min_child_weight)
        gain = np.where(ok, gain, -np.inf)

        flat = gain.reshape(k, -1)
        best = flat.argmax(axis=1)
        best_gain = flat[np.arange(k), best]
        best_feat = best // (n_bins - 1)
        best_bin = best % (n_bins - 1)

        new_frontier = []
        for i, nid in enumerate(frontier):
            gsum = float(gt[i, 0, 0])
            hsum = float(ht[i, 0, 0])
            if best_gain[i] <= 0 or not np.isfinite(best_gain[i]):
                nodes[nid] = _Node(0, 0.0, -1, -1,
                                   -lr * gsum / (hsum + lam))
                continue
            fj = int(best_feat[i])
            bj = int(best_bin[i])
            lid = len(nodes)
            rid = lid + 1
            nodes[nid] = _Node(fj, float(edges[fj, bj]), lid, rid, 0.0)
            nodes.append(_Node(0, 0.0, -1, -1, 0.0))
            nodes.append(_Node(0, 0.0, -1, -1, 0.0))
            sel = active & (node_of == nid)
            go_left = binned[:, fj] <= bj
            node_of[sel & go_left] = lid
            node_of[sel & ~go_left] = rid
            new_frontier += [lid, rid]
        frontier = new_frontier
        depth_used = depth + 1

    # settle any remaining frontier nodes as leaves
    for nid in frontier:
        sel = node_of == nid
        gsum = float(g[sel].sum())
        hsum = float(h[sel].sum())
        nodes[nid] = _Node(0, 0.0, -1, -1, -lr * gsum / (hsum + lam))

    value_of_node = np.asarray([nd.value for nd in nodes])
    return nodes, value_of_node[node_of], depth_used


def _trees_from_xgb_dump(dumps, n_features: int) -> TreeEnsemble:
    """Compile xgboost JSON tree dumps into flat node tables.

    Pure parser (no xgboost import), so it is unit-testable on images
    without the dependency. xgboost routes LEFT ("yes") iff
    ``x < split_condition`` (strict); the descent kernel tests
    ``x <= thresh``, so each threshold becomes the largest f32 strictly
    below the stored f32 condition — decisions stay bit-identical for
    f32 inputs. Leaf values are the raw logit contributions (learning
    rate pre-applied by xgboost). The ``missing`` branch is ignored:
    engine features are never NaN.
    """
    import json as _json

    parsed = [_json.loads(d) if isinstance(d, str) else d for d in dumps]

    def walk(node, acc, d):
        # derive depth structurally — the "depth" field is not present in
        # every dump variant (leaves omit it)
        acc.append((node, d))
        for ch in node.get("children", ()):
            walk(ch, acc, d + 1)
        return acc

    t = len(parsed)
    all_nodes = [walk(p, [], 0) for p in parsed]
    n = max(max(nd["nodeid"] for nd, _ in nodes) + 1 for nodes in all_nodes)
    feat = np.zeros((t, n), dtype=np.int32)
    thresh = np.zeros((t, n), dtype=np.float32)
    left = np.zeros((t, n), dtype=np.int32)
    right = np.zeros((t, n), dtype=np.int32)
    prob = np.zeros((t, n), dtype=np.float32)
    depth = 1
    # default: every slot self-loops as a zero-valued leaf (unreferenced
    # ids in a sparse dump stay inert)
    idx = np.arange(n, dtype=np.int32)
    left[:] = idx[None, :]
    right[:] = idx[None, :]
    for ti, nodes in enumerate(all_nodes):
        for nd, d in nodes:
            i = int(nd["nodeid"])
            depth = max(depth, d)
            if "leaf" in nd:
                prob[ti, i] = np.float32(nd["leaf"])
                continue
            split = nd["split"]
            if not (isinstance(split, str) and split.startswith("f")
                    and split[1:].isdigit()):
                raise ValueError(
                    f"unsupported split name {split!r}: train on plain "
                    "arrays so xgboost emits f<index> feature names")
            fi = int(split[1:])
            if fi >= n_features:
                raise ValueError(
                    f"split on feature {fi} >= n_features {n_features}")
            feat[ti, i] = fi
            # strict-< emulation under the kernel's <= test
            thresh[ti, i] = np.nextafter(
                np.float32(nd["split_condition"]), np.float32(-np.inf),
                dtype=np.float32)
            left[ti, i] = int(nd["yes"])
            right[ti, i] = int(nd["no"])
    from real_time_fraud_detection_system_tpu.models.forest import (
        ftz_safe_thresholds,
    )

    # nextafter below a condition of exactly 0.0 yields a DENORMAL,
    # which XLA flushes to zero in comparisons — routing x == 0.0 to the
    # wrong side. Map denormal thresholds to their FTZ-exact stand-ins.
    thresh = ftz_safe_thresholds(thresh)
    return TreeEnsemble(
        feat=jnp.asarray(feat),
        thresh=jnp.asarray(thresh),
        left=jnp.asarray(left),
        right=jnp.asarray(right),
        prob=jnp.asarray(prob),
        max_depth=max(depth, 1),  # deepest leaf level = descent trip count
    )


def gbt_from_xgboost(model, n_features: int) -> GBTModel:
    """Serve a fitted ``xgboost.XGBClassifier`` through the TPU GBT path.

    The reference trains XGBoost as one of its 5 classifiers
    (``model_training.ipynb · cell 50``); this imports the fitted model
    into the same flat-table inference the first-party booster uses
    (``gbt_predict_proba`` — leaf-sum + base logit + sigmoid), so a
    reference user's existing model artifact serves unchanged. Binary
    logistic objectives only.
    """
    booster = model.get_booster()
    import json as _json

    cfg = _json.loads(booster.save_config())
    objective = (cfg.get("learner", {}).get("objective", {})
                 .get("name", "binary:logistic"))
    if not str(objective).startswith("binary:logistic"):
        raise ValueError(
            f"only binary:logistic models import cleanly, got {objective}")
    p0 = float(cfg["learner"]["learner_model_param"]["base_score"])
    base = float(np.log(p0 / (1.0 - p0))) if 0.0 < p0 < 1.0 else 0.0
    trees = _trees_from_xgb_dump(
        booster.get_dump(dump_format="json"), n_features)
    return GBTModel(trees=trees, base_score=jnp.float32(base))
