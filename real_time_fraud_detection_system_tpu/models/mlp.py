"""MLP classifier — successor to the reference's dormant deep-learning code.

The reference ships a commented-out PyTorch MLP/autoencoder section
(``shared_functions.py:1312-1707``) that was never invoked. This is its live
TPU-native equivalent: a plain pytree of (W, b) layers, bf16-friendly
matmuls on the MXU, trained with optax.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

MLPParams = List[Tuple[jnp.ndarray, jnp.ndarray]]


def init_mlp(
    n_features: int, hidden: Sequence[int] = (64, 32), seed: int = 0
) -> MLPParams:
    key = jax.random.PRNGKey(seed)
    dims = [n_features, *hidden, 1]
    params: MLPParams = []
    for i in range(len(dims) - 1):
        key, k = jax.random.split(key)
        scale = np.sqrt(2.0 / dims[i])
        params.append(
            (
                scale * jax.random.normal(k, (dims[i], dims[i + 1]), dtype=jnp.float32),
                jnp.zeros((dims[i + 1],), dtype=jnp.float32),
            )
        )
    return params


def mlp_logits(params: MLPParams, x: jnp.ndarray) -> jnp.ndarray:
    h = x
    for w, b in params[:-1]:
        h = jax.nn.relu(h @ w + b)
    w, b = params[-1]
    return (h @ w + b)[..., 0]


def mlp_predict_proba(params: MLPParams, x: jnp.ndarray) -> jnp.ndarray:
    return jax.nn.sigmoid(mlp_logits(params, x))


def mlp_loss(
    params: MLPParams,
    x: jnp.ndarray,
    y: jnp.ndarray,
    valid: jnp.ndarray | None = None,
    pos_weight: float = 1.0,
) -> jnp.ndarray:
    logits = mlp_logits(params, x)
    per = optax.sigmoid_binary_cross_entropy(logits, y.astype(jnp.float32))
    w = jnp.where(y > 0, pos_weight, 1.0)
    if valid is not None:
        w = w * valid.astype(jnp.float32)
    return jnp.sum(per * w) / jnp.maximum(jnp.sum(w), 1.0)


def train_mlp(
    x: np.ndarray,
    y: np.ndarray,
    hidden: Sequence[int] = (64, 32),
    learning_rate: float = 1e-3,
    batch_size: int = 4096,
    epochs: int = 5,
    pos_weight: float = 1.0,
    seed: int = 0,
) -> MLPParams:
    n, f = x.shape
    params = init_mlp(f, hidden, seed)
    opt = optax.adam(learning_rate)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, xb, yb):
        loss, g = jax.value_and_grad(mlp_loss)(params, xb, yb, None, pos_weight)
        updates, opt_state = opt.update(g, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    rng = np.random.default_rng(seed)
    xj = jnp.asarray(x, dtype=jnp.float32)
    yj = jnp.asarray(y, dtype=jnp.float32)
    for _ in range(epochs):
        perm = rng.permutation(n)
        for s in range(0, n - batch_size + 1, batch_size):
            idx = perm[s : s + batch_size]
            params, opt_state, _ = step(params, opt_state, xj[idx], yj[idx])
    return params
