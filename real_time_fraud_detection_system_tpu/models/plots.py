"""Evaluation plots — the reference's plotting toolkit, matplotlib-native.

Re-implements the visualization section of
``fraud_detection_model/shared_functions.py:925-1302``: ROC and
precision-recall curves, per-threshold metric curves, model-comparison bars
with train/predict execution times, and prequential model-selection
summaries. All functions draw on a provided/created Axes and return the
Figure, so they compose into dashboards or save straight to disk
(``save_plots`` writes a one-stop PNG report).

Matplotlib uses the Agg backend when no display is present; nothing here
requires a GUI.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from real_time_fraud_detection_system_tpu.models.metrics import (
    average_precision,
    roc_auc,
    threshold_based_metrics,
)


def _mpl():
    import matplotlib

    if matplotlib.get_backend().lower() not in ("agg",):
        try:
            matplotlib.use("Agg", force=False)
        except Exception:  # pragma: no cover - interactive sessions
            pass
    import matplotlib.pyplot as plt

    return plt


def roc_points(y_true: np.ndarray, y_score: np.ndarray):
    """(fpr, tpr) at every distinct threshold, descending score order."""
    y = np.asarray(y_true).astype(np.float64)
    s = np.asarray(y_score).astype(np.float64)
    if len(s) == 0:
        # Trivial curve: the plots degrade gracefully on an empty score set
        # (np.r_'s length-1 mask would otherwise IndexError a 0-row array).
        return np.array([0.0, 1.0]), np.array([0.0, 1.0])
    order = np.argsort(-s, kind="mergesort")
    y = y[order]
    tp = np.cumsum(y)
    fp = np.cumsum(1 - y)
    n_pos = max(tp[-1] if len(tp) else 0.0, 1e-12)
    n_neg = max(fp[-1] if len(fp) else 0.0, 1e-12)
    last = np.r_[s[order][1:] != s[order][:-1], True]
    return np.r_[0.0, fp[last] / n_neg], np.r_[0.0, tp[last] / n_pos]


def pr_points(y_true: np.ndarray, y_score: np.ndarray):
    """(recall, precision) curve points, descending score order."""
    y = np.asarray(y_true).astype(np.float64)
    s = np.asarray(y_score).astype(np.float64)
    if len(s) == 0:
        return np.array([0.0, 1.0]), np.array([1.0, 1.0])
    order = np.argsort(-s, kind="mergesort")
    y = y[order]
    tp = np.cumsum(y)
    fp = np.cumsum(1 - y)
    n_pos = max(tp[-1] if len(tp) else 0.0, 1e-12)
    last = np.r_[s[order][1:] != s[order][:-1], True]
    recall = np.r_[0.0, tp[last] / n_pos]
    precision = np.r_[1.0, tp[last] / np.maximum(tp[last] + fp[last], 1e-12)]
    return recall, precision


def plot_roc(y_true, y_score, label: Optional[str] = None, ax=None):
    """ROC curve with AUC in the legend (reference ``plot_roc_curve``)."""
    plt = _mpl()
    if ax is None:
        _, ax = plt.subplots(figsize=(5, 5))
    fpr, tpr = roc_points(y_true, y_score)
    auc = roc_auc(y_true, y_score)
    name = label or "model"
    ax.plot(fpr, tpr, label=f"{name} (AUC={auc:.3f})")
    ax.plot([0, 1], [0, 1], "k--", lw=0.8, label="chance")
    ax.set_xlabel("False positive rate")
    ax.set_ylabel("True positive rate")
    ax.set_title("ROC curve")
    ax.legend(loc="lower right")
    return ax.figure


def plot_precision_recall(y_true, y_score, label: Optional[str] = None,
                          ax=None):
    """PR curve with AP in the legend (reference ``plot_precision_recall``)."""
    plt = _mpl()
    if ax is None:
        _, ax = plt.subplots(figsize=(5, 5))
    recall, precision = pr_points(y_true, y_score)
    ap = average_precision(y_true, y_score)
    name = label or "model"
    ax.plot(recall, precision, label=f"{name} (AP={ap:.3f})")
    base = float(np.asarray(y_true).mean()) if len(np.asarray(y_true)) else 0
    ax.axhline(base, color="k", ls="--", lw=0.8, label="chance")
    ax.set_xlabel("Recall")
    ax.set_ylabel("Precision")
    ax.set_title("Precision-recall curve")
    ax.legend(loc="upper right")
    return ax.figure


def plot_threshold_metrics(
    y_true, y_score,
    metrics: Sequence[str] = ("TPR", "FPR", "precision", "F1", "G-mean"),
    ax=None,
):
    """Metric-vs-threshold curves (reference threshold exploration,
    ``shared_functions.py:538-581`` surfaced as plots)."""
    plt = _mpl()
    if ax is None:
        _, ax = plt.subplots(figsize=(6, 4))
    thresholds = np.linspace(0.05, 0.95, 19)
    table = threshold_based_metrics(y_true, y_score, thresholds)
    for m in metrics:
        ax.plot(thresholds, [table[float(t)][m] for t in thresholds],
                marker=".", label=m)
    ax.set_xlabel("Decision threshold")
    ax.set_ylabel("Metric value")
    ax.set_title("Threshold metrics")
    ax.legend()
    return ax.figure


def plot_model_comparison(
    results: Dict[str, Dict[str, float]],
    metrics: Sequence[str] = ("auc_roc", "average_precision",
                              "card_precision@100"),
    ax=None,
):
    """Grouped bars of headline metrics per model (reference
    ``get_performances_plots``)."""
    plt = _mpl()
    if ax is None:
        _, ax = plt.subplots(figsize=(1.8 * max(len(results), 2) + 2, 4))
    names = list(results)
    width = 0.8 / max(len(metrics), 1)
    xs = np.arange(len(names))
    for j, m in enumerate(metrics):
        vals = [results[n].get(m, np.nan) for n in names]
        ax.bar(xs + j * width, vals, width, label=m)
    ax.set_xticks(xs + width * (len(metrics) - 1) / 2)
    ax.set_xticklabels(names)
    ax.set_ylim(0, 1)
    ax.set_title("Model comparison")
    ax.legend()
    return ax.figure


def plot_execution_times(times: Dict[str, Dict[str, float]], ax=None):
    """Fit/predict wall-clock bars per model (reference
    ``execution_times_model_collection``, ``shared_functions.py:499-512``)."""
    plt = _mpl()
    if ax is None:
        _, ax = plt.subplots(figsize=(1.5 * max(len(times), 2) + 2, 4))
    names = list(times)
    xs = np.arange(len(names))
    ax.bar(xs - 0.2, [times[n].get("fit_seconds", 0) for n in names],
           0.4, label="fit")
    ax.bar(xs + 0.2, [times[n].get("predict_seconds", 0) for n in names],
           0.4, label="predict")
    ax.set_xticks(xs)
    ax.set_xticklabels(names, rotation=20, ha="right")
    ax.set_ylabel("seconds")
    ax.set_title("Execution times")
    ax.legend()
    return ax.figure


def plot_prequential_summary(rows: List, metric: str = "auc_roc", ax=None):
    """Candidate mean±std on validation vs test folds (reference
    ``get_summary_performances`` visualization)."""
    from real_time_fraud_detection_system_tpu.models.selection import (
        _mean_std,
        _param_key,
    )

    plt = _mpl()
    if ax is None:
        _, ax = plt.subplots(figsize=(6, 4))
    by_params: Dict[str, list] = {}
    for r in rows:
        by_params.setdefault(_param_key(r.params), []).append(r)
    labels, v_means, v_stds, t_means, t_stds = [], [], [], [], []
    for key, prs in sorted(by_params.items()):
        labels.append(", ".join(f"{k}={v}" for k, v in prs[0].params.items())
                      or "default")
        vm, vs = _mean_std([r for r in prs if r.expe_type == "validation"],
                           metric)
        tm, ts = _mean_std([r for r in prs if r.expe_type == "test"], metric)
        v_means.append(vm); v_stds.append(vs)
        t_means.append(tm); t_stds.append(ts)
    xs = np.arange(len(labels))
    ax.errorbar(xs - 0.05, v_means, yerr=v_stds, fmt="o-",
                label="validation", capsize=3)
    ax.errorbar(xs + 0.05, t_means, yerr=t_stds, fmt="s--",
                label="test", capsize=3)
    ax.set_xticks(xs)
    ax.set_xticklabels(labels, rotation=20, ha="right")
    ax.set_ylabel(metric)
    ax.set_title("Prequential model selection")
    ax.legend()
    return ax.figure


def plot_tx_stats(txs, ax=None):
    """Dataset statistics: transactions/day and fraudulent txs/day over
    the generated table (reference ``get_tx_stats`` +
    ``get_template_tx_stats``, ``shared_functions.py:925-988`` — the
    notebook's first look at the simulator output)."""
    plt = _mpl()
    if ax is None:
        _, ax = plt.subplots(figsize=(8, 4))
    days = np.asarray(txs.tx_time_days)
    # full calendar range: a day with zero transactions plots as 0, not
    # as an interpolated segment between its neighbors
    n_days = int(days.max()) + 1 if len(days) else 0
    n_tx = np.bincount(days, minlength=n_days)
    n_fraud = np.bincount(days, weights=np.asarray(txs.tx_fraud),
                          minlength=n_days)
    xs_days = np.arange(n_days)
    ax.plot(xs_days, n_tx, label="# transactions")
    ax.plot(xs_days, n_fraud, label="# fraudulent txs")
    ax.set_xlabel("day")
    ax.set_ylabel("count")
    rate = n_fraud.sum() / max(n_tx.sum(), 1)
    ax.set_title(f"Transaction stats (fraud rate {rate:.2%})")
    ax.legend()
    return ax.figure


def plot_decision_boundary(
    predict_proba,
    x: np.ndarray,
    y: np.ndarray,
    feature_idx: Sequence[int] = (0, 1),
    resolution: int = 100,
    ax=None,
):
    """2-feature decision surface of any scorer (reference
    ``plot_decision_boundary_classifier``, ``shared_functions.py:
    1231-1302`` — the notebook's classifier-intuition figure).

    ``predict_proba(features) -> probs`` is called on a grid over the
    two selected features with the remaining features held at their
    column means."""
    plt = _mpl()
    if ax is None:
        _, ax = plt.subplots(figsize=(5, 4))
    i, j = feature_idx
    x = np.asarray(x, dtype=np.float64)
    y = np.asarray(y)
    xi, xj = x[:, i], x[:, j]
    pad_i = 0.1 * max(float(np.ptp(xi)), 1e-6)
    pad_j = 0.1 * max(float(np.ptp(xj)), 1e-6)
    gi = np.linspace(xi.min() - pad_i, xi.max() + pad_i, resolution)
    gj = np.linspace(xj.min() - pad_j, xj.max() + pad_j, resolution)
    mi, mj = np.meshgrid(gi, gj)
    grid = np.tile(x.mean(axis=0), (resolution * resolution, 1))
    grid[:, i] = mi.ravel()
    grid[:, j] = mj.ravel()
    probs = np.asarray(predict_proba(grid.astype(np.float32)))
    ax.contourf(mi, mj, probs.reshape(resolution, resolution),
                levels=20, cmap="RdBu_r", alpha=0.7, vmin=0, vmax=1)
    ax.scatter(xi[y == 0], xj[y == 0], s=8, c="tab:blue", label="genuine",
               edgecolors="none")
    ax.scatter(xi[y == 1], xj[y == 1], s=12, c="tab:red", label="fraud",
               edgecolors="none")
    ax.set_xlabel(f"feature {i}")
    ax.set_ylabel(f"feature {j}")
    ax.set_title("Decision boundary")
    ax.legend()
    return ax.figure


def save_plots(
    path: str,
    y_true,
    y_score,
    label: str = "model",
) -> str:
    """One-stop PNG report: ROC + PR + threshold metrics side by side."""
    plt = _mpl()
    fig, axes = plt.subplots(1, 3, figsize=(16, 5))
    plot_roc(y_true, y_score, label, ax=axes[0])
    plot_precision_recall(y_true, y_score, label, ax=axes[1])
    plot_threshold_metrics(y_true, y_score, ax=axes[2])
    fig.tight_layout()
    fig.savefig(path, dpi=120)
    plt.close(fig)
    return path
