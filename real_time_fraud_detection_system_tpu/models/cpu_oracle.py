"""CPU parity oracle — the reference's exact serving pipeline, kept as truth.

Mirrors ``fraud_detection.py:183-195``: sklearn ``StandardScaler.transform``
followed by ``predict_proba(...)[:, 1]`` of a sklearn classifier. The
``--scorer cpu`` switch routes scoring here; parity tests assert the TPU
path matches (probability-level for logreg/forest, AUC-level for the
approximated features).
"""

from __future__ import annotations

import numpy as np


class CpuScorer:
    def __init__(self, scaler, model):
        self.scaler = scaler  # sklearn StandardScaler
        self.model = model  # sklearn classifier with predict_proba

    def predict_proba(self, features: np.ndarray) -> np.ndarray:
        scaled = self.scaler.transform(features)
        return self.model.predict_proba(scaled)[:, 1]


def fit_cpu_scorer(
    features: np.ndarray,
    labels: np.ndarray,
    kind: str = "forest",
    n_trees: int = 100,
    max_depth: int | None = 8,
    seed: int = 0,
) -> CpuScorer:
    """Train the reference-style sklearn pipeline on host."""
    from sklearn.ensemble import RandomForestClassifier
    from sklearn.linear_model import LogisticRegression
    from sklearn.preprocessing import StandardScaler
    from sklearn.tree import DecisionTreeClassifier

    scaler = StandardScaler().fit(features)
    scaled = scaler.transform(features)
    if kind == "logreg":
        model = LogisticRegression(max_iter=1000, random_state=seed)
    elif kind == "tree":
        model = DecisionTreeClassifier(max_depth=2, random_state=seed)
    else:
        model = RandomForestClassifier(
            n_estimators=n_trees, max_depth=max_depth, random_state=seed, n_jobs=-1
        )
    model.fit(scaled, labels)
    # Serial predict: with n_jobs=-1 sklearn's forest predict_proba
    # accumulates per-tree probabilities from parallel workers in
    # nondeterministic order, so two calls on the SAME model differ by
    # ~1 ULP on ~20% of rows (measured: 111/600 at 20 trees on 2 cores).
    # A parity ORACLE must be bit-stable call-to-call; fitting above
    # keeps the parallel speedup, prediction pins the summation order.
    if hasattr(model, "n_jobs"):
        model.n_jobs = 1
    return CpuScorer(scaler, model)
