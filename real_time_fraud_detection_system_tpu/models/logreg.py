"""Logistic-regression scorer — the first TPU model (BASELINE.json config 2).

Weights are a tiny pytree kept HBM-resident next to the feature state;
scoring is one fused matvec per batch under jit, and the same loss/grad pair
drives both offline training (optax minibatch Adam) and the online-SGD
update from the labeled-feedback stream (config 4).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax


class LogRegParams(NamedTuple):
    w: jnp.ndarray  # float32 [F]
    b: jnp.ndarray  # float32 []


def init_logreg(n_features: int, seed: int = 0) -> LogRegParams:
    k = jax.random.PRNGKey(seed)
    return LogRegParams(
        w=0.01 * jax.random.normal(k, (n_features,), dtype=jnp.float32),
        b=jnp.zeros((), dtype=jnp.float32),
    )


def logreg_logits(params: LogRegParams, x: jnp.ndarray) -> jnp.ndarray:
    return x @ params.w + params.b


def logreg_predict_proba(params: LogRegParams, x: jnp.ndarray) -> jnp.ndarray:
    return jax.nn.sigmoid(logreg_logits(params, x))


def logreg_loss(
    params: LogRegParams,
    x: jnp.ndarray,
    y: jnp.ndarray,
    valid: jnp.ndarray | None = None,
    pos_weight: float = 1.0,
) -> jnp.ndarray:
    """Weighted BCE-with-logits; padded rows masked out."""
    logits = logreg_logits(params, x)
    per = optax.sigmoid_binary_cross_entropy(logits, y.astype(jnp.float32))
    w = jnp.where(y > 0, pos_weight, 1.0)
    if valid is not None:
        w = w * valid.astype(jnp.float32)
    return jnp.sum(per * w) / jnp.maximum(jnp.sum(w), 1.0)


def sgd_update(
    params: LogRegParams,
    x: jnp.ndarray,
    y: jnp.ndarray,
    valid: jnp.ndarray,
    lr: float,
    pos_weight: float = 1.0,
) -> LogRegParams:
    """One plain-SGD step — the online-update path (runs inside the
    streaming step function; gradient is psum-reduced across the mesh by the
    caller when sharded)."""
    g = jax.grad(logreg_loss)(params, x, y, valid, pos_weight)
    return jax.tree.map(lambda p, gi: p - lr * gi, params, g)


def train_logreg(
    x: np.ndarray,
    y: np.ndarray,
    learning_rate: float = 1e-2,
    batch_size: int = 4096,
    epochs: int = 5,
    pos_weight: float = 1.0,
    seed: int = 0,
) -> LogRegParams:
    """Offline minibatch-Adam training on (already scaled) features."""
    n, f = x.shape
    params = init_logreg(f, seed)
    opt = optax.adam(learning_rate)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, xb, yb):
        loss, g = jax.value_and_grad(logreg_loss)(
            params, xb, yb, None, pos_weight
        )
        updates, opt_state = opt.update(g, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    rng = np.random.default_rng(seed)
    xj = jnp.asarray(x, dtype=jnp.float32)
    yj = jnp.asarray(y, dtype=jnp.float32)
    for _ in range(epochs):
        perm = rng.permutation(n)
        for s in range(0, n - batch_size + 1, batch_size):
            idx = perm[s : s + batch_size]
            params, opt_state, _ = step(params, opt_state, xj[idx], yj[idx])
    return params
