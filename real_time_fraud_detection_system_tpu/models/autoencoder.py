"""Autoencoder anomaly scorer — the reference's dormant unsupervised model.

The commented-out PyTorch section of the reference
(``shared_functions.py:1312-1707``) includes a ``SimpleAutoencoder``
(encoder/decoder MLP trained to reconstruct the scaled feature vector, MSE
loss) intended for unsupervised fraud scoring: frauds reconstruct poorly, so
reconstruction error is the anomaly score. This is its live TPU-native
equivalent:

- plain (W, b) pytree layers like :mod:`.mlp`, MXU-friendly matmuls;
- trained with optax Adam on **legitimate transactions only** (labels are
  used solely to exclude known frauds from the train set — the serving path
  never needs labels);
- ``autoencoder_predict_proba`` maps per-row reconstruction MSE through a
  calibrated squashing ``1 - exp(-err/scale)`` so the engine can treat it
  exactly like any classifier's fraud probability (monotone in error,
  in [0, 1)); ``scale`` is fit to the train-set median error.
"""

from __future__ import annotations

from typing import List, NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

Layers = List[Tuple[jnp.ndarray, jnp.ndarray]]


class AutoencoderParams(NamedTuple):
    layers: Layers  # encoder + decoder stacked; last layer linear
    err_scale: jnp.ndarray  # scalar calibration for proba squashing


def init_autoencoder(
    n_features: int,
    hidden: Sequence[int] = (32, 8),
    seed: int = 0,
) -> AutoencoderParams:
    """Symmetric hourglass: f → hidden… → bottleneck → …hidden → f."""
    key = jax.random.PRNGKey(seed)
    dims = [n_features, *hidden, *reversed(hidden[:-1]), n_features]
    layers: Layers = []
    for i in range(len(dims) - 1):
        key, k = jax.random.split(key)
        scale = np.sqrt(2.0 / dims[i])
        layers.append(
            (
                scale
                * jax.random.normal(k, (dims[i], dims[i + 1]), dtype=jnp.float32),
                jnp.zeros((dims[i + 1],), dtype=jnp.float32),
            )
        )
    return AutoencoderParams(layers=layers, err_scale=jnp.asarray(1.0))


def reconstruct(params: AutoencoderParams, x: jnp.ndarray) -> jnp.ndarray:
    h = x
    for w, b in params.layers[:-1]:
        h = jax.nn.relu(h @ w + b)
    w, b = params.layers[-1]
    return h @ w + b


def reconstruction_error(
    params: AutoencoderParams, x: jnp.ndarray
) -> jnp.ndarray:
    """Per-row mean squared reconstruction error."""
    r = reconstruct(params, x)
    return jnp.mean((r - x) ** 2, axis=-1)


def autoencoder_predict_proba(
    params: AutoencoderParams, x: jnp.ndarray
) -> jnp.ndarray:
    """Anomaly score in [0, 1): 1 - exp(-err / err_scale).

    err == median legit error → score ≈ 0.39; large errors → 1. Monotone in
    the reconstruction error, so ranking metrics (AUC/AP/CP@k) are identical
    to using the raw error.
    """
    err = reconstruction_error(params, x)
    return 1.0 - jnp.exp(-err / jnp.maximum(params.err_scale, 1e-12))


def autoencoder_loss(
    params: AutoencoderParams,
    x: jnp.ndarray,
    y: jnp.ndarray | None = None,
    valid: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Masked mean reconstruction MSE. ``y`` (labels, 1=fraud), when given,
    masks frauds out of the objective — online updates then only pull the
    manifold toward legitimate traffic."""
    per = reconstruction_error(params, x)
    w = jnp.ones_like(per)
    if y is not None:
        w = w * (1.0 - jnp.clip(y.astype(jnp.float32), 0.0, 1.0))
    if valid is not None:
        w = w * valid.astype(jnp.float32)
    return jnp.sum(per * w) / jnp.maximum(jnp.sum(w), 1.0)


def train_autoencoder(
    x: np.ndarray,
    y: np.ndarray | None = None,
    hidden: Sequence[int] = (32, 8),
    learning_rate: float = 1e-3,
    batch_size: int = 4096,
    epochs: int = 10,
    seed: int = 0,
) -> AutoencoderParams:
    """Fit on scaled features; rows with y==1 are excluded from training."""
    x = np.asarray(x, dtype=np.float32)
    if y is not None:
        x = x[np.asarray(y) == 0]
    n, f = x.shape
    if n == 0:
        raise ValueError(
            "train_autoencoder: no legitimate rows to train on "
            "(all rows filtered out by labels)"
        )
    params = init_autoencoder(f, hidden, seed)
    opt = optax.adam(learning_rate)
    opt_state = opt.init(params.layers)

    @jax.jit
    def step(layers, opt_state, xb):
        def loss_fn(ls):
            return autoencoder_loss(params._replace(layers=ls), xb)

        loss, g = jax.value_and_grad(loss_fn)(layers)
        updates, opt_state = opt.update(g, opt_state)
        return optax.apply_updates(layers, updates), opt_state, loss

    rng = np.random.default_rng(seed)
    xj = jnp.asarray(x)
    layers = params.layers
    bs = min(batch_size, n)
    for _ in range(epochs):
        perm = rng.permutation(n)
        for s in range(0, n - bs + 1, bs):
            idx = perm[s : s + bs]
            layers, opt_state, _ = step(layers, opt_state, xj[idx])
    params = params._replace(layers=layers)
    # Calibrate the probability squash to the train-set median error.
    errs = np.asarray(reconstruction_error(params, xj))
    med = float(np.median(errs)) if len(errs) else 1.0
    return params._replace(err_scale=jnp.asarray(max(med, 1e-6)))
