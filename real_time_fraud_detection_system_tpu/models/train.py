"""Offline training pipeline: split → scale → fit → assess.

Re-implements the reference's training protocol
(``model_training.ipynb · cells 8,26,50``; ``shared_functions.py:133-188``):
a time-based train/delay/test split (153/30/30 days by default) where test
days drop transactions of customers already known compromised — known =
defrauded in the train window, plus frauds discovered up to each test day
minus the delay. Features come from :func:`..features.offline
.compute_features_replay` so the model trains on exactly the serving
semantics.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np

from real_time_fraud_detection_system_tpu.utils.logging import get_logger

from real_time_fraud_detection_system_tpu.config import Config
from real_time_fraud_detection_system_tpu.data.generator import Transactions
from real_time_fraud_detection_system_tpu.features.offline import (
    compute_features_replay,
)
from real_time_fraud_detection_system_tpu.models.forest import (
    TreeEnsemble,
    fit_forest,
    for_device,
)
from real_time_fraud_detection_system_tpu.models.forest import (
    predict_proba as forest_predict_proba,
)
from real_time_fraud_detection_system_tpu.models.logreg import (
    LogRegParams,
    logreg_predict_proba,
    train_logreg,
)
from real_time_fraud_detection_system_tpu.models.mlp import (
    mlp_predict_proba,
    train_mlp,
)
from real_time_fraud_detection_system_tpu.models.metrics import (
    performance_assessment,
)
from real_time_fraud_detection_system_tpu.models.scaler import (
    Scaler,
    fit_scaler,
    transform,
)


def fit_split_to_days(
    n_days: int, delta_train: int, delta_delay: int, delta_test: int
) -> Tuple[int, int, int]:
    """Shrink a (train, delay, test) day split to fit an n_days dataset.

    The reference pins 153/30/30 for its 245-day dataset
    (``model_training.ipynb · cell 8``); smaller datasets (docs examples,
    tests, `make run-all DAYS=...`) would get an EMPTY test window and NaN
    metrics with those absolutes. When the spans don't fit, scale them
    proportionally (preserving the 153:30:30 shape), keeping train/test
    ≥ 1 day; leftover days go to train. A ≤1-day dataset cannot hold
    disjoint train and test windows at all — it gets (n_days, 0, 0), and
    the caller's metrics are honestly NaN."""
    need = delta_train + delta_delay + delta_test
    if n_days >= need or need <= 0:
        return delta_train, delta_delay, delta_test
    if n_days <= 1:
        return max(n_days, 0), 0, 0
    f = n_days / need
    test = max(1, int(delta_test * f))
    delay = int(delta_delay * f)
    train = max(1, n_days - delay - test)
    if train + delay + test > n_days:
        delay = max(0, n_days - train - test)
    return train, delay, test


def scale_split_to_txs(
    txs: Transactions,
    delta_train: int,
    delta_delay: int,
    delta_test: int,
    start_day: int = 0,
    logger_name: str = "train",
) -> Tuple[int, int, int]:
    """:func:`fit_split_to_days` against the span actually available to a
    split anchored at ``start_day`` (days [start_day, dataset end)), with
    the scale-down warning. Shared by :func:`train_model` and
    ``selection.prequential_split``."""
    n_days = int(txs.tx_time_days.max()) + 1 if txs.n else 0
    avail = max(0, n_days - start_day)
    scaled = fit_split_to_days(avail, delta_train, delta_delay, delta_test)
    if scaled != (delta_train, delta_delay, delta_test):
        get_logger(logger_name).warning(
            "%d days available from day %d < configured %d/%d/%d split; "
            "scaled to %d/%d/%d",
            avail, start_day, delta_train, delta_delay, delta_test, *scaled,
        )
    return scaled


def train_delay_test_split(
    txs: Transactions,
    start_day: int = 0,
    delta_train: int = 153,
    delta_delay: int = 30,
    delta_test: int = 30,
) -> Tuple[np.ndarray, np.ndarray]:
    """Returns (train_mask, test_mask) over txs rows.

    Test-day filtering matches ``shared_functions.py:146-171``: a customer
    enters the known-compromised pool if they have a fraud in the train
    window, or a fraud on day (test_day - delay) as days advance; their
    transactions are excluded from the test set.
    """
    days = txs.tx_time_days
    train_mask = (days >= start_day) & (days < start_day + delta_train)

    known = set(np.unique(txs.customer_id[train_mask & (txs.tx_fraud == 1)]).tolist())
    test_mask = np.zeros(txs.n, dtype=bool)
    test_start = start_day + delta_train + delta_delay
    for d in range(delta_test):
        # Frauds discovered by this test day (delay days after they happened).
        disc_day = start_day + delta_train + d - 1
        disc = (days == disc_day) & (txs.tx_fraud == 1)
        known.update(np.unique(txs.customer_id[disc]).tolist())
        day_mask = days == test_start + d
        if known:
            known_arr = np.fromiter(known, dtype=np.int64)
            day_mask &= ~np.isin(txs.customer_id, known_arr)
        test_mask |= day_mask
    return train_mask, test_mask


@dataclass
class TrainedModel:
    """Scaler + fitted classifier params, ready for the serving step."""

    kind: str
    scaler: Scaler
    params: object  # LogRegParams | MLPParams | TreeEnsemble

    def _device_params(self, convert):
        """Lazily convert params to the fast device form, once."""
        dev = getattr(self, "_dev_cache", None)
        if dev is None:
            dev = convert(self.params)
            object.__setattr__(self, "_dev_cache", dev)
        return dev

    def predict_proba(self, features: np.ndarray) -> np.ndarray:
        import jax.numpy as jnp

        x = transform(self.scaler, jnp.asarray(features, dtype=jnp.float32))
        if self.kind == "logreg":
            return np.asarray(logreg_predict_proba(self.params, x))
        if self.kind == "mlp":
            return np.asarray(mlp_predict_proba(self.params, x))
        if self.kind == "gbt":
            from real_time_fraud_detection_system_tpu.models.gbt import (
                gbt_for_device,
                gbt_predict_proba,
            )

            nf = int(x.shape[1])
            dev = self._device_params(lambda p: gbt_for_device(p, nf))
            return np.asarray(gbt_predict_proba(dev, x))
        if self.kind in ("tree", "forest"):
            nf = int(x.shape[1])
            dev = self._device_params(lambda p: for_device(p, nf))
            return np.asarray(forest_predict_proba(dev, x))
        if self.kind == "autoencoder":
            from real_time_fraud_detection_system_tpu.models.autoencoder import (
                autoencoder_predict_proba,
            )

            return np.asarray(autoencoder_predict_proba(self.params, x))
        raise ValueError(f"unknown model kind {self.kind}")

    def _np_params(self):
        """One-time device→host conversion of params for the NumPy path."""
        cached = getattr(self, "_np_cache", None)
        if cached is None:
            if self.kind == "logreg":
                cached = (np.asarray(self.params.w), float(self.params.b))
            elif self.kind == "mlp":
                cached = [(np.asarray(w), np.asarray(b)) for w, b in self.params]
            elif self.kind == "autoencoder":
                cached = (
                    [(np.asarray(w), np.asarray(b)) for w, b in self.params.layers],
                    float(self.params.err_scale),
                )
            elif self.kind in ("tree", "forest", "gbt"):
                trees = self.params.trees if self.kind == "gbt" else self.params
                cached = {
                    "feat": np.asarray(trees.feat),
                    "thresh": np.asarray(trees.thresh),
                    "left": np.asarray(trees.left),
                    "right": np.asarray(trees.right),
                    "prob": np.asarray(trees.prob),
                    "max_depth": int(trees.max_depth),
                    "base": float(self.params.base_score)
                    if self.kind == "gbt" else 0.0,
                }
            object.__setattr__(self, "_np_cache", cached)
        scaler = getattr(self, "_np_scaler", None)
        if scaler is None:
            scaler = (np.asarray(self.scaler.mean), np.asarray(self.scaler.scale))
            object.__setattr__(self, "_np_scaler", scaler)
        return cached, scaler

    def predict_proba_np(self, features: np.ndarray) -> np.ndarray:
        """Pure-NumPy host scoring — the ``--scorer cpu`` baseline path
        (reference semantics: scaler.transform + predict_proba on CPU,
        ``fraud_detection.py:183-195``), no accelerator involved. Params are
        converted device→host once and cached."""
        params, (mean, scale) = self._np_params()
        x = ((features.astype(np.float32) - mean) / scale).astype(np.float32)
        if self.kind == "logreg":
            w, b = params
            z = x @ w + b
            return 1.0 / (1.0 + np.exp(-z))
        if self.kind == "mlp":
            h = x
            for w, b in params[:-1]:
                h = np.maximum(h @ w + b, 0.0)
            w, b = params[-1]
            z = (h @ w + b)[:, 0]
            return 1.0 / (1.0 + np.exp(-z))
        if self.kind == "autoencoder":
            layers, err_scale = params
            h = x
            for w, b in layers[:-1]:
                h = np.maximum(h @ w + b, 0.0)
            w, b = layers[-1]
            err = np.mean((h @ w + b - x) ** 2, axis=1)
            return 1.0 - np.exp(-err / max(err_scale, 1e-12))
        if self.kind in ("tree", "forest", "gbt"):
            feat = params["feat"]
            thresh = params["thresh"]
            left = params["left"]
            right = params["right"]
            prob = params["prob"]
            t = feat.shape[0]
            b_ = x.shape[0]
            node = np.zeros((b_, t), dtype=np.int64)
            tree_idx = np.arange(t)[None, :]
            for _ in range(params["max_depth"]):
                f = feat[tree_idx, node]
                xv = np.take_along_axis(x, f.reshape(b_, -1), axis=1).reshape(b_, t)
                go_left = xv <= thresh[tree_idx, node]
                node = np.where(go_left, left[tree_idx, node],
                                right[tree_idx, node])
            leaves = prob[tree_idx, node]
            if self.kind == "gbt":
                z = params["base"] + leaves.sum(axis=1)
                return 1.0 / (1.0 + np.exp(-z))
            return leaves.mean(axis=1)
        raise ValueError(f"unknown model kind {self.kind}")


def fit_classifier(
    kind: str,
    xs: np.ndarray,
    y_train: np.ndarray,
    cfg: Config,
    pos_weight: Optional[float] = None,
):
    """Fit one classifier of the 5-model zoo on pre-scaled features.

    Dispatch shared by :func:`train_model` and the model-selection machinery
    (``models/selection.py``); reference equivalent is the classifier dict of
    ``model_training.ipynb · cell 50``.
    """
    if pos_weight is None:
        from real_time_fraud_detection_system_tpu.models.metrics import (
            rebalance_pos_weight,
        )

        pos_weight = rebalance_pos_weight(y_train)

    if kind == "logreg":
        params = train_logreg(
            xs, y_train,
            learning_rate=cfg.train.learning_rate,
            batch_size=cfg.train.batch_size,
            epochs=cfg.train.epochs,
            pos_weight=pos_weight,
            seed=cfg.model.seed,
        )
    elif kind == "mlp":
        params = train_mlp(
            xs, y_train,
            hidden=tuple(cfg.model.mlp_hidden),
            batch_size=cfg.train.batch_size,
            epochs=cfg.train.epochs,
            pos_weight=pos_weight,
            seed=cfg.model.seed,
        )
    elif kind in ("tree", "forest"):
        params = fit_forest(
            xs, y_train,
            n_trees=cfg.model.forest_n_trees,
            max_depth=(cfg.model.tree_max_depth if kind == "tree"
                       else cfg.model.forest_max_depth),
            seed=cfg.model.seed,
            kind=kind,
        )
    elif kind == "gbt":
        from real_time_fraud_detection_system_tpu.models.gbt import train_gbt

        params = train_gbt(
            xs, y_train,
            n_trees=cfg.model.forest_n_trees,
            max_depth=cfg.model.forest_max_depth,
        )
    elif kind == "autoencoder":
        from real_time_fraud_detection_system_tpu.models.autoencoder import (
            train_autoencoder,
        )

        params = train_autoencoder(
            xs, y_train,
            hidden=tuple(cfg.model.autoencoder_hidden),
            batch_size=cfg.train.batch_size,
            epochs=cfg.train.epochs,
            seed=cfg.model.seed,
        )
    else:
        raise ValueError(f"unknown model kind {kind}")
    return params


def fit_and_assess(
    txs: Transactions,
    features: np.ndarray,
    cfg: Config,
    kind: str,
    train_mask: np.ndarray,
    test_mask: np.ndarray,
) -> Tuple[TrainedModel, dict, float, float, np.ndarray]:
    """scale → fit → predict → assess on one (train, test) mask pair.

    Shared by :func:`train_model` and the model-selection sweeps; returns
    (model, test metrics, fit_seconds, predict_seconds, test_probs) — the
    timing pair is the reference's per-classifier execution-time hook
    (``shared_functions.py:312-320``); the probs let callers plot/report
    without re-running the (timed) inference pass.
    """
    import time

    import jax.numpy as jnp

    x_train = features[train_mask]
    y_train = txs.tx_fraud[train_mask].astype(np.float32)
    scaler = fit_scaler(x_train)
    xs = np.asarray(transform(scaler, jnp.asarray(x_train, dtype=jnp.float32)))
    t0 = time.perf_counter()
    params = fit_classifier(kind, xs, y_train, cfg)
    fit_s = time.perf_counter() - t0
    model = TrainedModel(kind=kind, scaler=scaler, params=params)
    t0 = time.perf_counter()
    probs = model.predict_proba(features[test_mask])
    predict_s = time.perf_counter() - t0
    metrics = performance_assessment(
        txs.tx_fraud[test_mask],
        probs,
        days=txs.tx_time_days[test_mask],
        customer_ids=txs.customer_id[test_mask],
    )
    return model, metrics, fit_s, predict_s, probs


def fit_and_assess_sequence(
    txs: Transactions,
    cfg: Config,
    train_mask: np.ndarray,
    test_mask: np.ndarray,
    start_date: Optional[str] = None,
) -> Tuple[TrainedModel, dict, float, float, np.ndarray]:
    """Sequence-family counterpart of :func:`fit_and_assess`: train on
    the train-window sequences, evaluate by streaming the table through
    the ONLINE history step (the exact serving path — train/serve skew
    shows up here, not in production). Returns (model, test metrics,
    fit_seconds, predict_seconds, test_probs)."""
    import time

    import jax
    import jax.numpy as jnp

    from real_time_fraud_detection_system_tpu.core.batch import make_batch
    from real_time_fraud_detection_system_tpu.features.history import (
        init_history_state,
        update_and_score,
    )
    from real_time_fraud_detection_system_tpu.models.sequence import (
        build_sequences,
        train_transformer,
    )
    from real_time_fraud_detection_system_tpu.utils.timing import (
        date_to_epoch_s,
    )

    epoch0 = date_to_epoch_s(start_date or cfg.data.start_date)
    m = cfg.model
    seqs = build_sequences(
        txs.slice(train_mask), max_len=cfg.features.history_len,
        start_epoch_s=epoch0)
    t0 = time.perf_counter()
    params = train_transformer(
        seqs,
        d_model=m.seq_d_model,
        n_heads=m.seq_n_heads,
        n_layers=m.seq_n_layers,
        d_ff=m.seq_d_ff,
        epochs=cfg.train.epochs,
        seed=cfg.data.seed,
    )
    fit_s = time.perf_counter() - t0

    # serving-path evaluation: stream the table through the online step
    t_us = txs.epoch_us(epoch0)
    state = init_history_state(cfg.features)
    step = jax.jit(update_and_score, static_argnums=(3,))
    probs = np.zeros(txs.n, dtype=np.float64)
    rows = 4096
    t0 = time.perf_counter()
    for s in range(0, txs.n, rows):
        e = min(s + rows, txs.n)
        batch = make_batch(
            customer_id=txs.customer_id[s:e],
            terminal_id=txs.terminal_id[s:e],
            tx_datetime_us=t_us[s:e],
            amount_cents=txs.amount_cents[s:e],
            pad_to=rows,
        )
        state, p = step(state, params, jax.tree.map(jnp.asarray, batch),
                        cfg.features)
        probs[s:e] = np.asarray(p)[: e - s]
    predict_s = time.perf_counter() - t0
    metrics = performance_assessment(
        txs.tx_fraud[test_mask],
        probs[test_mask],
        days=txs.tx_time_days[test_mask],
        customer_ids=txs.customer_id[test_mask],
    )
    scaler = Scaler(mean=jnp.zeros(15, jnp.float32),
                    scale=jnp.ones(15, jnp.float32))
    model = TrainedModel(kind="sequence", scaler=scaler, params=params)
    return model, metrics, fit_s, predict_s, probs[test_mask]


def train_sequence_model(
    txs: Transactions,
    cfg: Config,
    start_date: Optional[str] = None,
) -> Tuple[TrainedModel, dict]:
    """Offline training of the sequence (causal transformer) family —
    see :func:`fit_and_assess_sequence` for the train/eval contract."""
    dtr, dde, dte = scale_split_to_txs(
        txs,
        cfg.train.delta_train_days,
        cfg.train.delta_delay_days,
        cfg.train.delta_test_days,
    )
    train_mask, test_mask = train_delay_test_split(
        txs, delta_train=dtr, delta_delay=dde, delta_test=dte
    )
    model, metrics, _, _, _ = fit_and_assess_sequence(
        txs, cfg, train_mask, test_mask, start_date=start_date)
    return model, metrics


def train_model(
    txs: Transactions,
    cfg: Config,
    features: Optional[np.ndarray] = None,
    kind: Optional[str] = None,
) -> Tuple[TrainedModel, dict]:
    """End-to-end offline training; returns (model, test metrics)."""
    kind = kind or cfg.model.kind
    if kind == "sequence":
        # the sequence family trains on event histories, not the replayed
        # aggregate features — dispatch before any replay work
        return train_sequence_model(txs, cfg)
    if features is None:
        features = compute_features_replay(
            txs, cfg.features, start_date=cfg.data.start_date
        )
    dtr, dde, dte = scale_split_to_txs(
        txs,
        cfg.train.delta_train_days,
        cfg.train.delta_delay_days,
        cfg.train.delta_test_days,
    )
    train_mask, test_mask = train_delay_test_split(
        txs, delta_train=dtr, delta_delay=dde, delta_test=dte
    )
    model, metrics, _, _, _ = fit_and_assess(
        txs, features, cfg, kind, train_mask, test_mask
    )
    return model, metrics
