"""Offline training pipeline: split → scale → fit → assess.

Re-implements the reference's training protocol
(``model_training.ipynb · cells 8,26,50``; ``shared_functions.py:133-188``):
a time-based train/delay/test split (153/30/30 days by default) where test
days drop transactions of customers already known compromised — known =
defrauded in the train window, plus frauds discovered up to each test day
minus the delay. Features come from :func:`..features.offline
.compute_features_replay` so the model trains on exactly the serving
semantics.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np

from real_time_fraud_detection_system_tpu.config import Config
from real_time_fraud_detection_system_tpu.data.generator import Transactions
from real_time_fraud_detection_system_tpu.features.offline import (
    compute_features_replay,
)
from real_time_fraud_detection_system_tpu.models.forest import (
    TreeEnsemble,
    ensemble_predict_proba,
    fit_forest,
)
from real_time_fraud_detection_system_tpu.models.logreg import (
    LogRegParams,
    logreg_predict_proba,
    train_logreg,
)
from real_time_fraud_detection_system_tpu.models.mlp import (
    mlp_predict_proba,
    train_mlp,
)
from real_time_fraud_detection_system_tpu.models.metrics import (
    performance_assessment,
)
from real_time_fraud_detection_system_tpu.models.scaler import (
    Scaler,
    fit_scaler,
    transform,
)


def train_delay_test_split(
    txs: Transactions,
    start_day: int = 0,
    delta_train: int = 153,
    delta_delay: int = 30,
    delta_test: int = 30,
) -> Tuple[np.ndarray, np.ndarray]:
    """Returns (train_mask, test_mask) over txs rows.

    Test-day filtering matches ``shared_functions.py:146-171``: a customer
    enters the known-compromised pool if they have a fraud in the train
    window, or a fraud on day (test_day - delay) as days advance; their
    transactions are excluded from the test set.
    """
    days = txs.tx_time_days
    train_mask = (days >= start_day) & (days < start_day + delta_train)

    known = set(np.unique(txs.customer_id[train_mask & (txs.tx_fraud == 1)]).tolist())
    test_mask = np.zeros(txs.n, dtype=bool)
    test_start = start_day + delta_train + delta_delay
    for d in range(delta_test):
        # Frauds discovered by this test day (delay days after they happened).
        disc_day = start_day + delta_train + d - 1
        disc = (days == disc_day) & (txs.tx_fraud == 1)
        known.update(np.unique(txs.customer_id[disc]).tolist())
        day_mask = days == test_start + d
        if known:
            known_arr = np.fromiter(known, dtype=np.int64)
            day_mask &= ~np.isin(txs.customer_id, known_arr)
        test_mask |= day_mask
    return train_mask, test_mask


@dataclass
class TrainedModel:
    """Scaler + fitted classifier params, ready for the serving step."""

    kind: str
    scaler: Scaler
    params: object  # LogRegParams | MLPParams | TreeEnsemble

    def predict_proba(self, features: np.ndarray) -> np.ndarray:
        import jax.numpy as jnp

        x = transform(self.scaler, jnp.asarray(features, dtype=jnp.float32))
        if self.kind == "logreg":
            return np.asarray(logreg_predict_proba(self.params, x))
        if self.kind == "mlp":
            return np.asarray(mlp_predict_proba(self.params, x))
        if self.kind in ("tree", "forest"):
            return np.asarray(ensemble_predict_proba(self.params, x))
        raise ValueError(f"unknown model kind {self.kind}")


def train_model(
    txs: Transactions,
    cfg: Config,
    features: Optional[np.ndarray] = None,
    kind: Optional[str] = None,
) -> Tuple[TrainedModel, dict]:
    """End-to-end offline training; returns (model, test metrics)."""
    kind = kind or cfg.model.kind
    if features is None:
        features = compute_features_replay(
            txs, cfg.features, start_date=cfg.data.start_date
        )
    train_mask, test_mask = train_delay_test_split(
        txs,
        delta_train=cfg.train.delta_train_days,
        delta_delay=cfg.train.delta_delay_days,
        delta_test=cfg.train.delta_test_days,
    )
    x_train = features[train_mask]
    y_train = txs.tx_fraud[train_mask].astype(np.float32)
    scaler = fit_scaler(x_train)
    import jax.numpy as jnp

    xs = np.asarray(transform(scaler, jnp.asarray(x_train, dtype=jnp.float32)))

    n_pos = max(float(y_train.sum()), 1.0)
    pos_weight = float((len(y_train) - n_pos) / n_pos) ** 0.5  # soft rebalance

    if kind == "logreg":
        params = train_logreg(
            xs, y_train,
            learning_rate=cfg.train.learning_rate,
            batch_size=cfg.train.batch_size,
            epochs=cfg.train.epochs,
            pos_weight=pos_weight,
            seed=cfg.model.seed,
        )
    elif kind == "mlp":
        params = train_mlp(
            xs, y_train,
            hidden=tuple(cfg.model.mlp_hidden),
            batch_size=cfg.train.batch_size,
            epochs=cfg.train.epochs,
            pos_weight=pos_weight,
            seed=cfg.model.seed,
        )
    elif kind in ("tree", "forest"):
        params = fit_forest(
            xs, y_train,
            n_trees=cfg.model.forest_n_trees,
            max_depth=(cfg.model.tree_max_depth if kind == "tree"
                       else cfg.model.forest_max_depth),
            seed=cfg.model.seed,
            kind=kind,
        )
    else:
        raise ValueError(f"unknown model kind {kind}")

    model = TrainedModel(kind=kind, scaler=scaler, params=params)
    probs = model.predict_proba(features[test_mask])
    metrics = performance_assessment(
        txs.tx_fraud[test_mask],
        probs,
        days=txs.tx_time_days[test_mask],
        customer_ids=txs.customer_id[test_mask],
    )
    return model, metrics
