"""Evaluation metrics: AUC-ROC, Average Precision, Card Precision@k,
threshold matrix — the reference's metric suite
(``shared_functions.py:352-365,376-411,442-460,538-581``), re-implemented
vectorized (no sklearn dependency in the hot path; sklearn is used only in
tests as the oracle).
"""

from __future__ import annotations

from typing import Dict, Sequence

import numpy as np


def rebalance_pos_weight(y: np.ndarray) -> float:
    """Soft class-rebalance weight sqrt(neg/pos) shared by all trainers."""
    n_pos = max(float(np.asarray(y).sum()), 1.0)
    n_tot = float(len(np.asarray(y)))
    return float(np.sqrt(max((n_tot - n_pos) / n_pos, 1.0)))


def roc_auc(y_true: np.ndarray, y_score: np.ndarray) -> float:
    """Mann-Whitney U formulation with midrank tie handling."""
    y = np.asarray(y_true).astype(np.float64)
    s = np.asarray(y_score).astype(np.float64)
    n_pos = y.sum()
    n_neg = len(y) - n_pos
    if n_pos == 0 or n_neg == 0:
        return float("nan")
    order = np.argsort(s, kind="mergesort")
    ranks = _midranks(s[order])
    r_pos = ranks[y[order] == 1].sum()
    u = r_pos - n_pos * (n_pos + 1) / 2.0
    return float(u / (n_pos * n_neg))


def _midranks(sorted_vals: np.ndarray) -> np.ndarray:
    n = len(sorted_vals)
    ranks = np.arange(1, n + 1, dtype=np.float64)
    # average ranks over ties
    _, first, counts = np.unique(sorted_vals, return_index=True, return_counts=True)
    for f, c in zip(first, counts):
        if c > 1:
            ranks[f : f + c] = ranks[f : f + c].mean()
    return ranks


def average_precision(y_true: np.ndarray, y_score: np.ndarray) -> float:
    """AP = Σ (R_i - R_{i-1}) · P_i over descending-score prefix points,
    matching sklearn.metrics.average_precision_score."""
    y = np.asarray(y_true).astype(np.float64)
    s = np.asarray(y_score).astype(np.float64)
    n_pos = y.sum()
    if n_pos == 0:
        return float("nan")
    order = np.argsort(-s, kind="mergesort")
    y = y[order]
    s = s[order]
    tp = np.cumsum(y)
    fp = np.cumsum(1 - y)
    precision = tp / (tp + fp)
    recall = tp / n_pos
    # Collapse tied score groups to their last (cumulative) point.
    last_of_group = np.r_[s[1:] != s[:-1], True]
    precision = precision[last_of_group]
    recall = recall[last_of_group]
    return float(np.sum(np.diff(np.r_[0.0, recall]) * precision))


def card_precision_top_k(
    y_true: np.ndarray,
    y_score: np.ndarray,
    days: np.ndarray,
    customer_ids: np.ndarray,
    k: int = 100,
) -> float:
    """Mean daily precision of the top-k most suspicious *cards*.

    For each day: aggregate per customer (max score, any-fraud), take the k
    highest-scored customers, precision = compromised fraction. Mean over
    days — the reference's ``card_precision_top_k`` metric
    (``shared_functions.py:352-411``).
    """
    days = np.asarray(days)
    precisions = []
    for d in np.unique(days):
        m = days == d
        cust = np.asarray(customer_ids)[m]
        score = np.asarray(y_score)[m]
        fraud = np.asarray(y_true)[m]
        uniq, inv = np.unique(cust, return_inverse=True)
        agg_score = np.full(len(uniq), -np.inf)
        np.maximum.at(agg_score, inv, score)
        agg_fraud = np.zeros(len(uniq))
        np.maximum.at(agg_fraud, inv, fraud)
        top = np.argsort(-agg_score, kind="mergesort")[:k]
        precisions.append(agg_fraud[top].mean() if len(top) else 0.0)
    return float(np.mean(precisions))


def threshold_based_metrics(
    y_true: np.ndarray,
    y_score: np.ndarray,
    thresholds: Sequence[float] = (0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9),
) -> Dict[float, Dict[str, float]]:
    """Per-threshold confusion metrics (reference ``shared_functions.py:538-581``)."""
    y = np.asarray(y_true).astype(bool)
    s = np.asarray(y_score)
    out: Dict[float, Dict[str, float]] = {}
    p = y.sum()
    n = (~y).sum()
    for t in thresholds:
        pred = s >= t
        tp = float((pred & y).sum())
        fp = float((pred & ~y).sum())
        fn = float((~pred & y).sum())
        tn = float((~pred & ~y).sum())
        tpr = tp / p if p else 0.0
        fpr = fp / n if n else 0.0
        tnr = tn / n if n else 0.0
        precision = tp / (tp + fp) if tp + fp else 0.0
        f1 = 2 * precision * tpr / (precision + tpr) if precision + tpr else 0.0
        out[float(t)] = {
            "TPR": tpr,
            "FPR": fpr,
            "TNR": tnr,
            "precision": precision,
            "F1": f1,
            "BER": 0.5 * (fpr + (fn / p if p else 0.0)),
            "G-mean": float(np.sqrt(tpr * tnr)),
            "accuracy": (tp + tn) / len(y) if len(y) else 0.0,
        }
    return out


def performance_assessment(
    y_true: np.ndarray,
    y_score: np.ndarray,
    days: np.ndarray | None = None,
    customer_ids: np.ndarray | None = None,
    top_k: int = 100,
) -> Dict[str, float]:
    """The reference's headline metric triple (``shared_functions.py:442-460``):
    AUC-ROC, Average Precision, Card Precision@k."""
    out = {
        "auc_roc": roc_auc(y_true, y_score),
        "average_precision": average_precision(y_true, y_score),
    }
    if days is not None and customer_ids is not None:
        out[f"card_precision@{top_k}"] = card_precision_top_k(
            y_true, y_score, days, customer_ids, top_k
        )
    return out
