from real_time_fraud_detection_system_tpu.models.scaler import (  # noqa: F401
    Scaler,
    fit_scaler,
    transform,
)
from real_time_fraud_detection_system_tpu.models.logreg import (  # noqa: F401
    LogRegParams,
    init_logreg,
    logreg_predict_proba,
    train_logreg,
)
from real_time_fraud_detection_system_tpu.models.mlp import (  # noqa: F401
    init_mlp,
    mlp_predict_proba,
    train_mlp,
)
from real_time_fraud_detection_system_tpu.models.forest import (  # noqa: F401
    GemmEnsemble,
    TreeEnsemble,
    ensemble_from_sklearn,
    ensemble_predict_proba,
    fit_forest,
    for_device,
    gemm_predict_proba,
    to_gemm,
)
from real_time_fraud_detection_system_tpu.models.metrics import (  # noqa: F401
    average_precision,
    card_precision_top_k,
    performance_assessment,
    roc_auc,
    threshold_based_metrics,
)
from real_time_fraud_detection_system_tpu.models.train import (  # noqa: F401
    TrainedModel,
    fit_classifier,
    train_delay_test_split,
    train_model,
    train_sequence_model,
)
from real_time_fraud_detection_system_tpu.models.autoencoder import (  # noqa: F401
    AutoencoderParams,
    autoencoder_loss,
    autoencoder_predict_proba,
    init_autoencoder,
    reconstruction_error,
    train_autoencoder,
)
from real_time_fraud_detection_system_tpu.models.plots import (  # noqa: F401
    plot_execution_times,
    plot_model_comparison,
    plot_precision_recall,
    plot_prequential_summary,
    plot_roc,
    plot_threshold_metrics,
    save_plots,
)
from real_time_fraud_detection_system_tpu.models.selection import (  # noqa: F401
    FoldPerformance,
    SelectionSummary,
    execution_times,
    kfold_cv_with_classifier,
    model_selection_wrapper,
    prequential_grid_search,
    prequential_split,
    summarize_performances,
)
