"""Sequence model family: causal transformer over per-customer history.

The reference shipped (commented out) a seq2seq additive-attention fraud
model over each card's transaction history
(``fraud_detection_model/shared_functions.py:1649-1707``, with the
``FraudDataset`` sequence assembly at ``:1312-1400``). This module is the
live TPU-native successor:

- per-event features (amount, inter-arrival time, time-of-day/weekday
  phases) embedded into ``d_model``;
- pre-LN causal transformer blocks; every position emits a fraud logit, so
  scoring transaction t uses exactly the history [0, t] — the streaming
  causality the reference's train/serve split got from feature snapshots;
- attention is pluggable: ``naive`` (materialized, short histories),
  ``blockwise`` (flash recurrence, long histories on one chip), or **ring**
  (:func:`..parallel.ring_attention.ring_attention`) for sequence-parallel
  long-context over the mesh;
- params are plain pytrees (NamedTuple/lists) like every other model family
  here — jit/pjit/optax-ready, no framework dependency.

Weights use bf16-safe math: matmuls run in the input dtype (cast to bf16 on
TPU for MXU), softmax/layernorm statistics in f32.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Callable, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from real_time_fraud_detection_system_tpu.data.generator import Transactions
from real_time_fraud_detection_system_tpu.parallel.ring_attention import (
    blockwise_attention,
)

N_EVENT_FEATURES = 8


# ---------------------------------------------------------------------------
# host-side sequence assembly (the FraudDataset analogue)
# ---------------------------------------------------------------------------


def event_features(
    amount: np.ndarray, t_s: np.ndarray
) -> np.ndarray:
    """Per-event feature vector [T, 8] from (amount, epoch-seconds)."""
    dt = np.diff(t_s, prepend=t_s[:1]).astype(np.float64)
    tod = (t_s % 86400) / 86400.0
    weekday = ((t_s // 86400 + 3) % 7) / 7.0
    f = np.stack(
        [
            np.log1p(np.maximum(amount, 0.0)),
            amount / 100.0,
            np.log1p(np.maximum(dt, 0.0)) / 10.0,
            np.sin(2 * np.pi * tod),
            np.cos(2 * np.pi * tod),
            np.sin(2 * np.pi * weekday),
            np.cos(2 * np.pi * weekday),
            np.ones_like(tod),  # bias/presence channel
        ],
        axis=1,
    )
    return f.astype(np.float32)


class SequenceBatch(NamedTuple):
    """Padded per-customer histories ([N, T, F] x/[N, T] y, mask)."""

    x: np.ndarray  # float32 [N, T, N_EVENT_FEATURES]
    y: np.ndarray  # int32 [N, T] — fraud label per event (0 where padded)
    mask: np.ndarray  # bool [N, T] — real event?
    customer_id: np.ndarray  # int64 [N]
    tx_index: np.ndarray  # int64 [N, T] — row index into the source table, -1 pad


def build_sequences(
    txs: Transactions,
    max_len: int = 128,
    min_len: int = 2,
    features: Optional[np.ndarray] = None,
    start_epoch_s: int = 0,
) -> SequenceBatch:
    """Group transactions by customer, time-sorted, pad/truncate to max_len.

    Truncation keeps the LAST max_len events (most recent history).
    ``features`` ([txs.n, F], e.g. the standardized 15-feature matrix from
    the replay kernel) is concatenated onto the intrinsic event channels —
    the reference's FraudDataset fed engineered feature columns per event
    (``shared_functions.py:1312-1400``); terminal risk lives only there.

    ``start_epoch_s`` anchors the table's relative ``tx_time_seconds`` to
    absolute epoch time. Pass the real start epoch when the model will be
    SERVED (``features/history.py`` computes weekday/time-of-day from
    absolute timestamps — training on unanchored times rotates the
    weekday phase channels between train and serve).
    """
    n_in = N_EVENT_FEATURES + (features.shape[1] if features is not None else 0)
    order = np.lexsort((txs.tx_time_seconds, txs.customer_id))
    cust = txs.customer_id[order]
    uniq, starts = np.unique(cust, return_index=True)
    ends = np.r_[starts[1:], len(cust)]

    xs, ys, ms, cids, idxs = [], [], [], [], []
    for u, s, e in zip(uniq, starts, ends):
        if e - s < min_len:
            continue
        sel = order[s:e][-max_len:]
        n = len(sel)
        f = event_features(
            txs.amount_cents[sel] / 100.0,
            txs.tx_time_seconds[sel].astype(np.int64) + start_epoch_s,
        )
        if features is not None:
            f = np.concatenate([f, features[sel].astype(np.float32)], axis=1)
        x = np.zeros((max_len, n_in), dtype=np.float32)
        y = np.zeros(max_len, dtype=np.int32)
        m = np.zeros(max_len, dtype=bool)
        ix = np.full(max_len, -1, dtype=np.int64)
        x[:n] = f
        y[:n] = txs.tx_fraud[sel]
        m[:n] = True
        ix[:n] = sel
        xs.append(x)
        ys.append(y)
        ms.append(m)
        cids.append(u)
        idxs.append(ix)
    return SequenceBatch(
        x=np.stack(xs) if xs else np.zeros((0, max_len, n_in), np.float32),
        y=np.stack(ys) if ys else np.zeros((0, max_len), np.int32),
        mask=np.stack(ms) if ms else np.zeros((0, max_len), bool),
        customer_id=np.asarray(cids, dtype=np.int64),
        tx_index=np.stack(idxs) if idxs else np.zeros((0, max_len), np.int64),
    )


# ---------------------------------------------------------------------------
# model
# ---------------------------------------------------------------------------


class BlockParams(NamedTuple):
    ln1_g: jnp.ndarray
    ln1_b: jnp.ndarray
    wq: jnp.ndarray  # [D, H, Dh]
    wk: jnp.ndarray
    wv: jnp.ndarray
    wo: jnp.ndarray  # [H, Dh, D]
    ln2_g: jnp.ndarray
    ln2_b: jnp.ndarray
    w1: jnp.ndarray  # [D, F]
    b1: jnp.ndarray
    w2: jnp.ndarray  # [F, D]
    b2: jnp.ndarray


class TransformerParams(NamedTuple):
    embed_w: jnp.ndarray  # [N_EVENT_FEATURES, D]
    embed_b: jnp.ndarray
    blocks: Tuple[BlockParams, ...]
    lnf_g: jnp.ndarray
    lnf_b: jnp.ndarray
    head_w: jnp.ndarray  # [D, 1]
    head_b: jnp.ndarray


def init_transformer(
    d_model: int = 32,
    n_heads: int = 2,
    n_layers: int = 2,
    d_ff: int = 64,
    n_in: int = N_EVENT_FEATURES,
    seed: int = 0,
) -> TransformerParams:
    key = jax.random.PRNGKey(seed)
    dh = d_model // n_heads

    def dense(key, shape, scale=None):
        scale = scale or 1.0 / math.sqrt(shape[0])
        return jax.random.normal(key, shape, dtype=jnp.float32) * scale

    keys = jax.random.split(key, 2 + 6 * n_layers)
    blocks: List[BlockParams] = []
    ki = 2
    for _ in range(n_layers):
        blocks.append(
            BlockParams(
                ln1_g=jnp.ones(d_model),
                ln1_b=jnp.zeros(d_model),
                wq=dense(keys[ki], (d_model, n_heads, dh), 1 / math.sqrt(d_model)),
                wk=dense(keys[ki + 1], (d_model, n_heads, dh), 1 / math.sqrt(d_model)),
                wv=dense(keys[ki + 2], (d_model, n_heads, dh), 1 / math.sqrt(d_model)),
                wo=dense(keys[ki + 3], (n_heads, dh, d_model), 1 / math.sqrt(d_model)),
                ln2_g=jnp.ones(d_model),
                ln2_b=jnp.zeros(d_model),
                w1=dense(keys[ki + 4], (d_model, d_ff)),
                b1=jnp.zeros(d_ff),
                w2=dense(keys[ki + 5], (d_ff, d_model)),
                b2=jnp.zeros(d_model),
            )
        )
        ki += 6
    return TransformerParams(
        embed_w=dense(keys[0], (n_in, d_model), 1 / math.sqrt(n_in)),
        embed_b=jnp.zeros(d_model),
        blocks=tuple(blocks),
        lnf_g=jnp.ones(d_model),
        lnf_b=jnp.zeros(d_model),
        head_w=dense(keys[1], (d_model, 1)),
        head_b=jnp.zeros(1),
    )


def _ln(x, g, b):
    xf = x.astype(jnp.float32)
    mu = xf.mean(-1, keepdims=True)
    var = ((xf - mu) ** 2).mean(-1, keepdims=True)
    return ((xf - mu) * jax.lax.rsqrt(var + 1e-6) * g + b).astype(x.dtype)


def naive_attn(q, k, v, causal=True):
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / math.sqrt(d)
    if causal:
        t = q.shape[1]
        mask = jnp.tril(jnp.ones((t, t), dtype=bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(q.dtype)


AttnFn = Callable[[jnp.ndarray, jnp.ndarray, jnp.ndarray], jnp.ndarray]


def _block_forward(h, blk, attn, red, ent):
    """One transformer block at full width — shared by the per-position
    form (training/TP) and the prefix layers of the serving form."""
    hn = ent(_ln(h, blk.ln1_g, blk.ln1_b))
    q = jnp.einsum("btd,dhe->bthe", hn, blk.wq)
    k = jnp.einsum("btd,dhe->bthe", hn, blk.wk)
    v = jnp.einsum("btd,dhe->bthe", hn, blk.wv)
    o = attn(q, k, v)
    h = h + red(jnp.einsum("bthe,hed->btd", o, blk.wo))
    hn = ent(_ln(h, blk.ln2_g, blk.ln2_b))
    return h + red(jax.nn.gelu(hn @ blk.w1 + blk.b1) @ blk.w2) + blk.b2


def transformer_logits(
    params: TransformerParams,
    x: jnp.ndarray,  # [B, T, N_EVENT_FEATURES]
    attn_fn: Optional[AttnFn] = None,
    reduce_fn: Optional[Callable[[jnp.ndarray], jnp.ndarray]] = None,
    enter_fn: Optional[Callable[[jnp.ndarray], jnp.ndarray]] = None,
) -> jnp.ndarray:
    """Per-position fraud logits [B, T]. ``attn_fn(q,k,v) -> o`` defaults to
    causal naive attention; pass a blockwise/ring closure for long T.

    ``reduce_fn`` and ``enter_fn`` bracket the two column→row parallel
    regions per block (Q/K/V→attention-out, and the MLP): identity here;
    the tensor-parallel path passes Megatron's *g* (psum forward,
    identity backward) as ``reduce_fn`` at each region's EXIT and *f*
    (identity forward, psum backward) at each ENTRY — without *f*, the
    gradients of replicated upstream params (embeddings, layernorms)
    would only count the local shard's heads. The SAME forward thus
    serves sharded (``parallel.tensor_parallel.tp_transformer_logits``)."""
    attn = attn_fn or (lambda q, k, v: naive_attn(q, k, v, causal=True))
    red = reduce_fn or (lambda t: t)
    ent = enter_fn or (lambda t: t)
    # positional information comes from the inter-arrival/time-of-day event
    # channels (translation-invariant histories), not absolute embeddings.
    h = x @ params.embed_w + params.embed_b
    for blk in params.blocks:
        h = _block_forward(h, blk, attn, red, ent)
    h = _ln(h, params.lnf_g, params.lnf_b)
    return (h @ params.head_w + params.head_b)[..., 0]


def transformer_last_logit(
    params: TransformerParams,
    x: jnp.ndarray,  # [B, T, N_EVENT_FEATURES]
    qpos: jnp.ndarray,  # int32 [B] — the one position each row is scored at
    attn_fn: Optional[AttnFn] = None,
) -> jnp.ndarray:
    """Serving form: the fraud logit at ONE position per row ([B]).

    Exactly ``transformer_logits(params, x, attn_fn)[b, qpos[b]]`` — but
    the LAST block, final layernorm, and head run on the single query
    position only; layers before the last still run at every position
    (their outputs are the last block's keys/values). The last block's
    score tensor shrinks from [B, H, K, K] to [B, H, K] — the serving
    memory win at long K (the engine consumes only each row's own-event
    logit, ``features/history.py::update_and_score``). Wall-clock it
    measured ~neutral on v5e (0.97–1.05×): the defaults' d_model=32
    leaves the serving transformer bound by its full-width small-lane
    elementwise/projection chain, not by attention scores — the next
    real levers are a per-customer KV cache (O(K·d·L) per event) and a
    lane-friendly d_model. The single-query attention masks keys to
    ``j <= qpos`` — the same causal row the full form computes.
    """
    attn = attn_fn or (lambda q, k, v: naive_attn(q, k, v, causal=True))
    ident = lambda t: t  # noqa: E731
    h = x @ params.embed_w + params.embed_b
    for blk in params.blocks[:-1]:
        h = _block_forward(h, blk, attn, ident, ident)

    blk = params.blocks[-1]
    t = h.shape[1]
    dh = blk.wq.shape[-1]
    hn = _ln(h, blk.ln1_g, blk.ln1_b)
    sel = qpos[:, None, None]  # [B,1,1] take_along_axis index
    hq = jnp.take_along_axis(h, sel, axis=1)  # [B,1,D]
    hnq = jnp.take_along_axis(hn, sel, axis=1)
    q = jnp.einsum("bod,dhe->bohe", hnq, blk.wq)  # [B,1,H,dh]
    k = jnp.einsum("btd,dhe->bthe", hn, blk.wk)
    v = jnp.einsum("btd,dhe->bthe", hn, blk.wv)
    s = jnp.einsum("bohe,bkhe->bhok", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / math.sqrt(dh)  # [B,H,1,K]
    mask = (jnp.arange(t, dtype=jnp.int32)[None, :]
            <= qpos[:, None])[:, None, None, :]
    s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhok,bkhe->bohe", p,
                   v.astype(jnp.float32)).astype(h.dtype)
    hq = hq + jnp.einsum("bohe,hed->bod", o, blk.wo)
    hn2 = _ln(hq, blk.ln2_g, blk.ln2_b)
    hq = hq + jax.nn.gelu(hn2 @ blk.w1 + blk.b1) @ blk.w2 + blk.b2
    hf = _ln(hq, params.lnf_g, params.lnf_b)
    return (hf @ params.head_w + params.head_b)[:, 0, 0]


def transformer_loss(
    params: TransformerParams,
    x: jnp.ndarray,
    y: jnp.ndarray,
    mask: jnp.ndarray,
    pos_weight: float = 1.0,
    attn_fn: Optional[AttnFn] = None,
    reduce_fn=None,
    enter_fn=None,
) -> jnp.ndarray:
    logits = transformer_logits(
        params, x, attn_fn, reduce_fn=reduce_fn,
        enter_fn=enter_fn).astype(jnp.float32)
    yf = y.astype(jnp.float32)
    w = jnp.where(yf > 0, pos_weight, 1.0) * mask.astype(jnp.float32)
    ll = jax.nn.log_sigmoid(logits) * yf + jax.nn.log_sigmoid(-logits) * (1 - yf)
    return -(w * ll).sum() / jnp.maximum(w.sum(), 1.0)


def train_transformer(
    seqs: SequenceBatch,
    d_model: int = 32,
    n_heads: int = 2,
    n_layers: int = 2,
    d_ff: int = 64,
    batch_size: int = 64,
    epochs: int = 3,
    learning_rate: float = 1e-3,
    pos_weight: Optional[float] = None,
    seed: int = 0,
    attn: str = "naive",
) -> TransformerParams:
    """Adam training on padded sequence batches (masked BCE)."""
    import optax

    params = init_transformer(
        d_model, n_heads, n_layers, d_ff, n_in=seqs.x.shape[-1], seed=seed
    )
    if pos_weight is None:
        from real_time_fraud_detection_system_tpu.models.metrics import (
            rebalance_pos_weight,
        )

        pos_weight = rebalance_pos_weight(seqs.y[seqs.mask])
    if attn == "blockwise":
        attn_fn = lambda q, k, v: blockwise_attention(q, k, v, causal=True)  # noqa: E731
    elif attn == "naive":
        attn_fn = None
    else:
        raise ValueError(
            f"unknown attn {attn!r}: use 'naive' or 'blockwise' here; for "
            "ring (sequence-parallel) attention build the forward with "
            "make_sp_logits_fn and train under pjit"
        )

    tx = optax.adam(learning_rate)
    opt_state = tx.init(params)
    loss = partial(transformer_loss, pos_weight=pos_weight, attn_fn=attn_fn)

    @jax.jit
    def step(params, opt_state, x, y, m):
        g = jax.grad(loss)(params, x, y, m)
        updates, opt_state = tx.update(g, opt_state)
        return optax.apply_updates(params, updates), opt_state

    n = seqs.x.shape[0]
    rng = np.random.default_rng(seed)
    nb = max(1, n // batch_size)
    for _ in range(epochs):
        order = rng.permutation(n)
        for b in range(nb):
            sel = order[b * batch_size : (b + 1) * batch_size]
            if len(sel) < batch_size:  # pad the ragged tail (static shapes)
                sel = np.resize(np.r_[sel, order], batch_size)
            params, opt_state = step(
                params, opt_state,
                jnp.asarray(seqs.x[sel]), jnp.asarray(seqs.y[sel]),
                jnp.asarray(seqs.mask[sel]),
            )
    return params


def make_sp_logits_fn(mesh, axis: str = "data"):
    """Sequence-parallel forward: logits(params, x) with the history axis
    sharded over the mesh and attention running as a ring over ICI.

    Everything outside attention is positionwise, so under jit the T-sharded
    layout propagates through embeddings/LN/MLP with zero collectives; the
    ring in attention is the only cross-device traffic — this is the
    long-context serving path for histories too large for one chip's HBM.
    """
    from real_time_fraud_detection_system_tpu.parallel.ring_attention import (
        make_ring_attention_sharded,
    )

    ring = make_ring_attention_sharded(mesh, axis=axis, causal=True)
    return jax.jit(partial(transformer_logits, attn_fn=ring))


def sequence_scores(
    params: TransformerParams,
    seqs: SequenceBatch,
    batch_size: int = 256,
    attn_fn: Optional[AttnFn] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Score every real event; returns (tx_index [M], prob [M]) aligned to
    source-table rows, for AUC eval against ``txs.tx_fraud``."""
    fn = jax.jit(partial(transformer_logits, attn_fn=attn_fn))
    n, t = seqs.y.shape
    probs = np.zeros((n, t), dtype=np.float32)
    for s in range(0, n, batch_size):
        e = min(s + batch_size, n)
        logits = fn(params, jnp.asarray(seqs.x[s:e]))
        probs[s:e] = np.asarray(jax.nn.sigmoid(logits.astype(jnp.float32)))
    m = seqs.mask
    return seqs.tx_index[m], probs[m]
