// Native host-side micro-batch prep: dedup + pack in two linear passes.
//
// The serving loop's host stage (runtime/engine.py::_start_batch) does
// latest-wins dedup by tx_id, key folding, µs-epoch splitting, cents→f32
// amounts, and the single-array packing of core/batch.py::pack_batch.
// The NumPy pipeline for that runs ~3.2M rows/s on one core — fine over a
// remote tunnel (the wire is slower), but the bottleneck for a locally
// attached chip whose projected loop rate is >3.5M rows/s. This unit is
// the same math as the NumPy path, one pass each, allocation-free:
//
//   latest_wins_keep — reference ROW_NUMBER() PARTITION BY tx_id ORDER BY
//     ts DESC semantics (kafka_s3_sink_transactions.py:173-190): for each
//     key keep the row with the greatest (ts, position). Open-addressing
//     hash, O(n). Bit-identical masks to ops/dedup.latest_wins_mask_np
//     (differential-fuzz-pinned in tests/test_native.py).
//
//   pack_rows — the fused make_batch + pack_batch: fold_key xor-fold,
//     floor day/second-of-day split, (double)cents/100 → float amounts
//     (same IEEE ops as NumPy's float64-divide-then-float32-cast), label
//     or -1, valid flags; zeros in the padding tail. Output layout is
//     core/batch.pack_batch's [7, pad] int32.
//
// Build: g++ -O3 -shared -fPIC -o libhostprep.so hostprep.cc

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

inline uint64_t mix64(uint64_t x) {
  x ^= x >> 33;
  x *= 0xff51afd7ed558ccdULL;
  x ^= x >> 33;
  x *= 0xc4ceb9fe1a85ec53ULL;
  x ^= x >> 33;
  return x;
}

}  // namespace

extern "C" {

// keep[i]=1 where row i is the latest version of its key. Returns the
// number of kept rows. Ties on ts resolve to the later position (i > cur
// always holds when revisiting a key).
int64_t latest_wins_keep(const int64_t* key, const int64_t* ts, int64_t n,
                         uint8_t* keep) {
  if (n <= 0) return 0;
  uint64_t cap = 1;
  while (cap < (uint64_t)n * 2) cap <<= 1;
  std::vector<int64_t> slot(cap, -1);
  std::memset(keep, 0, (size_t)n);
  const uint64_t mask = cap - 1;
  const int64_t kSentinel = INT64_MIN;
  for (int64_t i = 0; i < n; ++i) {
    // parity with the NumPy mask: INT64_MIN doubles as its invalid-row
    // sentinel, so rows carrying that key are never kept there either
    if (key[i] == kSentinel) continue;
    uint64_t j = mix64((uint64_t)key[i]) & mask;
    for (;;) {
      int64_t cur = slot[j];
      if (cur < 0) {
        slot[j] = i;
        keep[i] = 1;
        break;
      }
      if (key[cur] == key[i]) {
        if (ts[i] >= ts[cur]) {
          keep[cur] = 0;
          keep[i] = 1;
          slot[j] = i;
        }
        break;
      }
      j = (j + 1) & mask;
    }
  }
  int64_t kept = 0;
  for (int64_t i = 0; i < n; ++i) kept += keep[i];
  return kept;
}

// packed: int32 [7, pad] C-order. label may be NULL (=> -1 everywhere).
void pack_rows(const int64_t* dt_us, const int64_t* cust,
               const int64_t* term, const int64_t* amount,
               const int64_t* label, int64_t n, int64_t pad,
               int32_t* packed) {
  const int64_t kUsPerDay = 86400000000LL;
  int32_t* ck = packed;
  int32_t* tk = packed + pad;
  int32_t* day = packed + 2 * pad;
  int32_t* tod = packed + 3 * pad;
  int32_t* amt = packed + 4 * pad;
  int32_t* lab = packed + 5 * pad;
  int32_t* val = packed + 6 * pad;
  std::memset(packed, 0, sizeof(int32_t) * 7 * (size_t)pad);
  for (int64_t i = 0; i < n; ++i) {
    uint64_t c = (uint64_t)cust[i];
    ck[i] = (int32_t)(uint32_t)((c ^ (c >> 32)) & 0xFFFFFFFFULL);
    uint64_t t = (uint64_t)term[i];
    tk[i] = (int32_t)(uint32_t)((t ^ (t >> 32)) & 0xFFFFFFFFULL);
    int64_t d = dt_us[i] / kUsPerDay;
    int64_t r = dt_us[i] % kUsPerDay;
    if (r < 0) {  // match NumPy floor-division semantics
      d -= 1;
      r += kUsPerDay;
    }
    day[i] = (int32_t)d;
    tod[i] = (int32_t)(r / 1000000LL);
    float a = (float)((double)amount[i] / 100.0);
    std::memcpy(&amt[i], &a, 4);
    lab[i] = label ? (int32_t)label[i] : -1;
    val[i] = 1;
  }
}

}  // extern "C"
