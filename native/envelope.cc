// Native Debezium transaction-envelope decoder.
//
// Host-side ingest at benchmark rates bottlenecks on JSON parsing long
// before the TPU (SURVEY §7 "hard parts": 1M txns/s of envelopes). This is
// the C++ drop-in behind the same columnar interface as the Python decoder
// (real_time_fraud_detection_system_tpu/core/envelope.py): a single-pass
// field scanner specialized to the Debezium envelope layout produced by
// Kafka's JSON converter (reference schema:
// pyspark/scripts/kafka_s3_sink_transactions.py:77-126), including the
// base64 big-endian signed DECIMAL(10,2) amounts
// (kafka_s3_sink_transactions.py:63-73).
//
// Contract (mirrors the Python decoder):
//   - take payload.after, falling back to payload.before (delete events);
//   - null payload / missing row image / malformed JSON => valid=0;
//   - op codes: c=0, u=1, d=2, r=3;
//   - amounts decoded to int64 cents (never floats).
//
// Build: g++ -O3 -march=native -shared -fPIC -o libenvelope.so envelope.cc

#include <cstdint>
#include <cstring>

namespace {

// base64 decode table: 0-63 valid, 255 invalid, 254 padding '='
const uint8_t kB64[256] = {
    255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,
    255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,
    255,255,255,255,255,255,255,255,255,255,255, 62,255,255,255, 63,
     52, 53, 54, 55, 56, 57, 58, 59, 60, 61,255,255,255,254,255,255,
    255,  0,  1,  2,  3,  4,  5,  6,  7,  8,  9, 10, 11, 12, 13, 14,
     15, 16, 17, 18, 19, 20, 21, 22, 23, 24, 25,255,255,255,255,255,
    255, 26, 27, 28, 29, 30, 31, 32, 33, 34, 35, 36, 37, 38, 39, 40,
     41, 42, 43, 44, 45, 46, 47, 48, 49, 50, 51,255,255,255,255,255,
    255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,
    255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,
    255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,
    255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,
    255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,
    255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,
    255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,
    255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,
};

// Decode base64 text [s, e) as big-endian signed integer. Returns false on
// invalid input or width > 8 bytes.
bool b64_to_cents(const char* s, const char* e, int64_t* out) {
  uint8_t raw[16];
  int nraw = 0;
  uint32_t acc = 0;
  int nbits = 0;
  for (const char* p = s; p < e; ++p) {
    uint8_t v = kB64[(uint8_t)*p];
    if (v == 254) break;  // padding
    if (v == 255) return false;
    acc = (acc << 6) | v;
    nbits += 6;
    if (nbits >= 8) {
      nbits -= 8;
      if (nraw >= 16) return false;
      raw[nraw++] = (uint8_t)(acc >> nbits);
    }
  }
  if (nraw == 0 || nraw > 8) return false;
  int64_t val = (raw[0] & 0x80) ? -1 : 0;  // sign-extend
  for (int i = 0; i < nraw; ++i) val = (val << 8) | raw[i];
  *out = val;
  return true;
}

// Skip whitespace.
inline const char* ws(const char* p, const char* e) {
  while (p < e && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) ++p;
  return p;
}

// Find `"key"` at object top level starting from p (shallow scan: tracks
// brace/bracket depth and strings). Returns pointer just past the ':' of
// the match, or nullptr.
const char* find_key(const char* p, const char* e, const char* key) {
  size_t klen = strlen(key);
  int depth = 0;
  bool in_str = false;
  const char* str_start = nullptr;
  while (p < e) {
    char c = *p;
    if (in_str) {
      if (c == '\\') { p += 2; continue; }
      if (c == '"') {
        in_str = false;
        // at depth 1 inside the target object: check key match + ':'
        if (depth == 1 && (size_t)(p - str_start) == klen &&
            memcmp(str_start, key, klen) == 0) {
          const char* q = ws(p + 1, e);
          if (q < e && *q == ':') return q + 1;
        }
      }
      ++p;
      continue;
    }
    switch (c) {
      case '"': in_str = true; str_start = p + 1; break;
      case '{': case '[': ++depth; break;
      case '}': case ']':
        --depth;
        if (depth <= 0) return nullptr;  // left the object
        break;
      default: break;
    }
    ++p;
  }
  return nullptr;
}

// Parse an integer (possibly negative) at p.
bool parse_int(const char* p, const char* e, int64_t* out) {
  p = ws(p, e);
  bool neg = false;
  if (p < e && *p == '-') { neg = true; ++p; }
  if (p >= e || *p < '0' || *p > '9') return false;
  int64_t v = 0;
  while (p < e && *p >= '0' && *p <= '9') v = v * 10 + (*p++ - '0');
  *out = neg ? -v : v;
  return true;
}

// If p points at `null`, return true.
bool is_null(const char* p, const char* e) {
  p = ws(p, e);
  return (e - p) >= 4 && memcmp(p, "null", 4) == 0;
}

// Parse a JSON string value at p; sets [s, e2) to content. No unescaping
// (base64/op strings never contain escapes).
bool parse_str(const char* p, const char* e, const char** s, const char** e2) {
  p = ws(p, e);
  if (p >= e || *p != '"') return false;
  ++p;
  *s = p;
  while (p < e && *p != '"') {
    if (*p == '\\') ++p;
    ++p;
  }
  if (p >= e) return false;
  *e2 = p;
  return true;
}

// Parse one envelope [m, e) into row i of the output columns.
// Returns 1 when the row is valid.
static int parse_envelope(
    const char* m, const char* e, int64_t i,
    int64_t* tx_id, int64_t* t_us, int64_t* cust, int64_t* term,
    int64_t* cents, int8_t* op, uint8_t* valid) {
  tx_id[i] = t_us[i] = cust[i] = term[i] = cents[i] = 0;
  op[i] = 0;
  valid[i] = 0;

  const char* p = ws(m, e);
  if (p >= e || *p != '{') return 0;
  const char* payload = find_key(p, e, "payload");
  if (!payload || is_null(payload, e)) return 0;
  payload = ws(payload, e);
  if (payload >= e || *payload != '{') return 0;

  // op code (optional; default 'c')
  const char* opv = find_key(payload, e, "op");
  if (opv) {
    const char *s, *se;
    if (parse_str(opv, e, &s, &se) && se > s) {
      switch (*s) {
        case 'c': op[i] = 0; break;
        case 'u': op[i] = 1; break;
        case 'd': op[i] = 2; break;
        case 'r': op[i] = 3; break;
        default: op[i] = 0; break;
      }
    }
  }

  const char* row = find_key(payload, e, "after");
  if (!row || is_null(row, e)) row = find_key(payload, e, "before");
  if (!row || is_null(row, e)) return 0;
  row = ws(row, e);
  if (row >= e || *row != '{') return 0;

  const char* v;
  if (!(v = find_key(row, e, "tx_id")) || !parse_int(v, e, &tx_id[i]))
    return 0;
  if (!(v = find_key(row, e, "tx_datetime")) || !parse_int(v, e, &t_us[i]))
    return 0;
  if (!(v = find_key(row, e, "customer_id")) || !parse_int(v, e, &cust[i]))
    return 0;
  if (!(v = find_key(row, e, "terminal_id")) || !parse_int(v, e, &term[i]))
    return 0;
  v = find_key(row, e, "tx_amount");
  if (v) {
    if (is_null(v, e)) {
      cents[i] = 0;
    } else {
      const char *s, *se;
      if (!parse_str(v, e, &s, &se) || !b64_to_cents(s, se, &cents[i]))
        return 0;
    }
  }
  valid[i] = 1;
  return 1;
}

}  // namespace

extern "C" {

// Decode n envelopes from a packed buffer. offsets has n+1 entries.
// Returns the number of valid rows.
int64_t decode_envelopes(
    const char* buf, const int64_t* offsets, int64_t n,
    int64_t* tx_id, int64_t* t_us, int64_t* cust, int64_t* term,
    int64_t* cents, int8_t* op, uint8_t* valid) {
  int64_t nvalid = 0;
  for (int64_t i = 0; i < n; ++i) {
    nvalid += parse_envelope(buf + offsets[i], buf + offsets[i + 1], i,
                             tx_id, t_us, cust, term, cents, op, valid);
  }
  return nvalid;
}

}  // extern "C"
