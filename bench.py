"""Benchmark harness — one JSON line for the driver.

Measures sustained scoring throughput (transactions/second) of the full
jitted hot path — feature-state update + window gather + scale + classify —
on the available accelerator, and compares against the CPU baseline
(the reference-equivalent sklearn pipeline on the same features).

    {"metric": "score_txns_per_sec", "value": N, "unit": "txns/s",
     "vs_baseline": speedup_over_cpu_sklearn}

Run directly: ``python bench.py`` (add ``--quick`` for a fast smoke run).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np


def _honor_platform_env() -> None:
    """Re-assert JAX_PLATFORMS from the environment.

    A TPU-proxy plugin's sitecustomize may force jax_platforms at interpreter
    start; an explicit JAX_PLATFORMS from the caller must win (e.g. CPU smoke
    runs in sandboxes where the TPU tunnel is unavailable)."""
    want = os.environ.get("JAX_PLATFORMS")
    if want:
        import jax

        jax.config.update("jax_platforms", want)


def _build(batch_rows: int, model_kind: str):
    import jax
    import jax.numpy as jnp

    from real_time_fraud_detection_system_tpu.config import Config, FeatureConfig
    from real_time_fraud_detection_system_tpu.core.batch import make_batch
    from real_time_fraud_detection_system_tpu.features.online import (
        init_feature_state,
        update_and_featurize,
    )
    from real_time_fraud_detection_system_tpu.models.scaler import Scaler, transform

    cfg = Config(
        features=FeatureConfig(customer_capacity=8192, terminal_capacity=16384)
    )
    fcfg = cfg.features
    rng = np.random.default_rng(0)

    if model_kind == "forest":
        from sklearn.ensemble import RandomForestClassifier

        from real_time_fraud_detection_system_tpu.models.forest import (
            ensemble_from_sklearn,
            for_device,
        )
        from real_time_fraud_detection_system_tpu.models.forest import (
            predict_proba as forest_predict_proba,
        )

        xtr = rng.normal(0, 1, (2048, 15))
        ytr = (xtr[:, 0] + 0.5 * xtr[:, 1] > 0.8).astype(np.int32)
        skl = RandomForestClassifier(n_estimators=100, max_depth=8,
                                     random_state=0, n_jobs=-1).fit(xtr, ytr)
        params = for_device(ensemble_from_sklearn(skl, 15), 15)
        predict = forest_predict_proba
    else:
        from real_time_fraud_detection_system_tpu.models.logreg import (
            init_logreg,
            logreg_predict_proba,
        )

        skl = None
        params = init_logreg(15)
        predict = logreg_predict_proba

    scaler = Scaler(mean=jnp.zeros(15), scale=jnp.ones(15))

    def step(fstate, params, batch):
        fstate, feats = update_and_featurize(fstate, batch, fcfg)
        probs = predict(params, transform(scaler, feats))
        return fstate, jnp.where(batch.valid, probs, 0.0)

    step = jax.jit(step, donate_argnums=(0,))

    n = batch_rows
    batch = make_batch(
        customer_id=rng.integers(0, 5000, n).astype(np.int64),
        terminal_id=rng.integers(0, 10000, n).astype(np.int64),
        tx_datetime_us=(20200 * 86400 + rng.integers(0, 86400, n)).astype(np.int64)
        * 1_000_000,
        amount_cents=rng.integers(100, 50000, n).astype(np.int64),
    )
    jbatch = jax.tree.map(jnp.asarray, batch)
    fstate = init_feature_state(fcfg)
    return step, fstate, params, jbatch, skl


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    # 256k rows ≈ 2× the per-row throughput of 64k on v5e (the feature
    # scatter and the GEMM both amortize better). Measured to fit on a
    # 16 GB v5e with the default depth-8/100-tree forest (XLA fuses the
    # [B,T,I] proj into the decision compute); much larger forests may
    # need a smaller batch.
    ap.add_argument("--batch-rows", type=int, default=262144)
    ap.add_argument("--model", default="forest", choices=["forest", "logreg"])
    ap.add_argument("--seconds", type=float, default=5.0)
    args = ap.parse_args()
    if args.quick:
        args.batch_rows = 4096
        args.seconds = 1.0

    _honor_platform_env()
    import jax

    step, fstate, params, jbatch, skl = _build(args.batch_rows, args.model)

    # warmup / compile
    fstate, probs = step(fstate, params, jbatch)
    jax.block_until_ready(probs)

    # timed loop — sync every `chunk` steps so the dispatch queue stays
    # bounded (an unbounded async backlog makes the final sync unbounded,
    # pathological over high-RTT device tunnels).
    chunk = 8
    t0 = time.perf_counter()
    iters = 0
    while time.perf_counter() - t0 < args.seconds:
        for _ in range(chunk):
            fstate, probs = step(fstate, params, jbatch)
        jax.block_until_ready(probs)
        iters += chunk
    wall = time.perf_counter() - t0
    tps = iters * args.batch_rows / wall
    per_batch_ms = wall / iters * 1e3

    # CPU baseline: the reference-equivalent sklearn predict_proba on the
    # same batch size (feature extraction excluded on both sides would be
    # unfair — here CPU gets features for free, so the TPU number is
    # conservative).
    vs = 0.0
    if skl is not None:
        rng = np.random.default_rng(1)
        feats = rng.normal(0, 1, (args.batch_rows, 15))
        t0 = time.perf_counter()
        cpu_iters = 0
        while time.perf_counter() - t0 < min(args.seconds, 2.0):
            skl.predict_proba(feats)
            cpu_iters += 1
        cpu_tps = cpu_iters * args.batch_rows / (time.perf_counter() - t0)
        vs = tps / cpu_tps if cpu_tps > 0 else 0.0

    print(
        json.dumps(
            {
                "metric": "score_txns_per_sec",
                "value": round(tps, 1),
                "unit": "txns/s",
                "vs_baseline": round(vs, 3),
                "detail": {
                    "model": args.model,
                    "batch_rows": args.batch_rows,
                    "per_batch_ms": round(per_batch_ms, 3),
                    "device": str(jax.devices()[0]),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
