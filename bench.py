"""Benchmark harness — full-detail JSON line, then a compact headline line.

Measures sustained scoring throughput (transactions/second) of the full
jitted hot path — feature-state update + window gather + scale + classify —
plus classify-latency percentiles and an MFU estimate, and compares against
the CPU baseline (the reference-equivalent sklearn pipeline).

    {"metric": "score_txns_per_sec", "value": N, "unit": "txns/s",
     "vs_baseline": speedup_over_cpu_sklearn, "detail": {...}}

Robustness (the driver runs this unattended over a TPU tunnel that can be
slow, hung, or down):

- the measurement runs in a supervised CHILD process whose stdout is
  STREAMED: the child prints ``BENCH_ALIVE`` the moment ``jax.devices()``
  returns and ``BENCH_PROGRESS`` lines as it works, so the parent can
  tell a live-but-slow child (extend the budget) from a truly hung one
  (kill it);
- the tunnel has been observed to hang for hours then recover suddenly
  (round-3 log in BASELINE.md), so the parent runs a LADDER of spaced
  TPU attempts across a ``BENCH_WINDOW_S`` wall clock (default 2700 s):
  one 600 s-liveness attempt, then — with the CPU fallback result
  banked as insurance — 300 s-liveness re-attempts every ~60 s. The
  first attempt that goes live wins; SIGTERM mid-ladder still emits the
  banked CPU line;
- after liveness, every progress line re-arms a settle timer; a child
  that stalls mid-measurement is killed, bounded by a hard cap;
- when every TPU attempt fails, the emitted headline is the CPU
  sklearn-oracle path (``--scorer cpu``, the reference-equivalent
  serving pipeline), NOT the MXU-shaped GEMM kernel on CPU, which is
  reported under ``detail.jax_cpu`` instead;
- batch size starts modest (16k) and scales up, keeping the best
  successful size — a failed 256k-row first allocation no longer kills
  the run;
- on unrecoverable failure the output is still ONE parseable JSON line
  (``value`` 0, ``error`` set) and rc=1;
- on success TWO lines are printed: the full-detail result JSON, then a
  compact headline line (same schema, detail reduced to backend/device) —
  the driver records only a tail window of stdout, and the full line
  outgrew it in round 4 (``BENCH_r04.json`` ``parsed: null``).

Run directly: ``python bench.py`` (add ``--quick`` for a fast smoke run).
An explicit ``JAX_PLATFORMS`` from the caller is honored and skips the
TPU retry ladder (e.g. CPU smoke runs in sandboxes).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np

ALIVE_LINE = "BENCH_ALIVE"
PROGRESS_LINE = "BENCH_PROGRESS"

# Live measurement children (parent side): the SIGTERM handler must kill
# these before exiting, or an orphaned child keeps measuring on the TPU
# for up to its hard cap after the parent is gone.
_LIVE_PROCS: list = []


def _progress(msg: str) -> None:
    """Child-side liveness breadcrumb (parent re-arms its settle timer)."""
    print(f"{PROGRESS_LINE} {msg}", flush=True)


def _run_cpu_mesh_tool(tool_name: str, tool_args: list,
                       timeout_s: float, label: str) -> dict:
    """Run a tools/ bench script on the virtual CPU mesh as a
    subprocess (this possibly-TPU-attached process cannot adopt the
    8-device CPU env itself) and parse its one-JSON-line result. Shared
    by the sharded scaling and sharded state-scale blocks so the
    poll/timeout/kill/parse discipline cannot diverge between them."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("BENCH_ROLE", None)
    tool = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tools", tool_name)
    p = subprocess.Popen([sys.executable, tool] + list(tool_args),
                         env=env, stdout=subprocess.PIPE,
                         stderr=subprocess.PIPE, text=True)
    t0 = time.monotonic()
    while p.poll() is None:
        if time.monotonic() - t0 > timeout_s:
            p.kill()
            p.wait()
            raise TimeoutError(f"{tool_name} subprocess > {timeout_s} s")
        _progress(label)
        time.sleep(20.0)
    out, err = p.communicate()
    lines = [ln for ln in out.splitlines() if ln.startswith("{")]
    if p.returncode != 0 or not lines:
        raise RuntimeError(f"rc={p.returncode}: {err.strip()[-200:]}")
    return json.loads(lines[-1])

# Peak dense bf16 matmul FLOP/s per chip, by device_kind substring
# (public spec sheets). MFU here is model-FLOPs / (wall · peak): a lower
# bound, since the f32-HIGHEST proj pass runs below bf16 peak.
_PEAK_FLOPS = (
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5 lite", 197e12),
    ("v5e", 197e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 61.5e12),
    ("v2", 22.5e12),
)
_DEFAULT_PEAK = 197e12  # assume v5e-class when the kind is unrecognized


def _peak_flops(device_kind: str) -> float:
    kind = device_kind.lower()
    for sub, peak in _PEAK_FLOPS:
        if sub in kind:
            return peak
    return _DEFAULT_PEAK


def _honor_platform_env() -> None:
    """Re-assert JAX_PLATFORMS from the environment.

    A TPU-proxy plugin's sitecustomize may force jax_platforms at
    interpreter start; an explicit JAX_PLATFORMS from the caller must win
    (e.g. the CPU fallback child, or smoke runs in sandboxes)."""
    want = os.environ.get("JAX_PLATFORMS")
    if want:
        import jax

        jax.config.update("jax_platforms", want)


def _build_model(model_kind: str, rng):
    """Returns (params, predict, skl_model_or_None)."""
    import jax.numpy as jnp  # noqa: F401  (keeps jax import localized)

    if model_kind == "forest":
        from sklearn.ensemble import RandomForestClassifier

        from real_time_fraud_detection_system_tpu.models.forest import (
            ensemble_from_sklearn,
            for_device,
        )
        from real_time_fraud_detection_system_tpu.models.forest import (
            predict_proba as forest_predict_proba,
        )

        xtr = rng.normal(0, 1, (2048, 15))
        ytr = (xtr[:, 0] + 0.5 * xtr[:, 1] > 0.8).astype(np.int32)
        skl = RandomForestClassifier(n_estimators=100, max_depth=8,
                                     random_state=0, n_jobs=-1).fit(xtr, ytr)
        params = for_device(ensemble_from_sklearn(skl, 15), 15)
        return params, forest_predict_proba, skl

    from real_time_fraud_detection_system_tpu.models.logreg import (
        init_logreg,
        logreg_predict_proba,
    )

    return init_logreg(15), logreg_predict_proba, None


def _model_flops_per_row(params) -> float:
    """Static model FLOPs per scored row (the classify kernel only; the
    feature scatter/gather contributes negligible FLOPs)."""
    from real_time_fraud_detection_system_tpu.models.forest import (
        GemmEnsemble,
    )

    if isinstance(params, GemmEnsemble):
        t, f, i = params.sel.shape
        l = params.path.shape[2]
        # proj [B,F]x[T,F,I] + z [B,T,I]x[T,I,L] + leaf [B,T,L]x[T,L]
        return 2.0 * t * i * (f + l) + 2.0 * t * l
    if hasattr(params, "w"):  # logreg
        return 2.0 * int(np.prod(np.shape(params.w)))
    return 0.0


def _make_batch_cols(rng, n: int) -> dict:
    return {
        "customer_id": rng.integers(0, 5000, n).astype(np.int64),
        "terminal_id": rng.integers(0, 10000, n).astype(np.int64),
        "tx_datetime_us": (
            (20200 * 86400 + rng.integers(0, 86400, n)).astype(np.int64)
            * 1_000_000
        ),
        "amount_cents": rng.integers(100, 50000, n).astype(np.int64),
    }


class _ProbsCap:
    """Sink stub that keeps only the served probabilities — the capture
    half of every engine-level exactness A/B."""

    def __init__(self):
        self.probs: list = []

    def append(self, res):
        self.probs.append(res.probs)

    def concat(self):
        return np.concatenate(self.probs)


class _RandSource:
    """Pre-generated random micro-batches for the engine-loop measurement
    (generation cost excluded from the measured loop)."""

    def __init__(self, n_batches: int, rows: int, seed: int = 2):
        rng = np.random.default_rng(seed)
        self._batches = []
        for b in range(n_batches):
            c = _make_batch_cols(rng, rows)
            self._batches.append({
                "tx_id": np.arange(b * rows, (b + 1) * rows, dtype=np.int64),
                "tx_datetime_us": c["tx_datetime_us"],
                "customer_id": c["customer_id"],
                "terminal_id": c["terminal_id"],
                "tx_amount_cents": c["amount_cents"],
                "kafka_ts_ms": c["tx_datetime_us"] // 1000,
            })
        self._i = 0

    def poll_batch(self):
        if self._i >= len(self._batches):
            return None
        b = self._batches[self._i]
        self._i += 1
        return b

    @property
    def offsets(self):
        return [self._i]

    def seek(self, offsets):
        self._i = int(offsets[0])


def _child_main(args) -> None:
    """The actual measurement (runs under a parent-enforced timeout)."""
    _honor_platform_env()
    import jax
    import jax.numpy as jnp

    from real_time_fraud_detection_system_tpu.utils import (
        enable_compilation_cache,
    )

    enable_compilation_cache()

    from real_time_fraud_detection_system_tpu.config import (
        Config,
        FeatureConfig,
        RuntimeConfig,
    )
    from real_time_fraud_detection_system_tpu.features.online import (
        init_feature_state,
        update_and_featurize,
    )
    from real_time_fraud_detection_system_tpu.models.scaler import (
        Scaler,
        transform,
    )

    dev = jax.devices()[0]
    # Liveness probe: backend bring-up (jax.devices()) is the step observed
    # to block >500 s over a sick tunnel. Announcing it completed lets the
    # parent distinguish slow-but-live from hung.
    print(
        f"{ALIVE_LINE} backend={jax.default_backend()} "
        f"device_kind={dev.device_kind}",
        flush=True,
    )
    on_cpu = jax.default_backend() == "cpu"
    # All measurement sections, scaled down (CI coverage of the TPU-only
    # code paths on CPU; never set by the driver).
    full = (not (on_cpu or args.quick)
            or os.environ.get("BENCH_FULL_SECTIONS") == "1")
    rng = np.random.default_rng(0)

    cfg = Config(
        features=FeatureConfig(customer_capacity=8192,
                               terminal_capacity=16384)
    )
    fcfg = cfg.features
    params, predict, skl = _build_model(args.model, rng)
    headline_z_mode = None
    if args.model == "forest":
        # The headline hot path measures the SERVING default arithmetic
        # (runtime.z_mode="auto" → int8 on TPU / f32 on CPU) — what
        # `rtfds score` actually runs since round 9, decision-identical
        # by the gemm_leaf_sum exactness contract.
        from real_time_fraud_detection_system_tpu.models.forest import (
            resolve_z_mode,
        )

        headline_z_mode = resolve_z_mode("auto")
        _forest_predict = predict

        def predict(p, x, _zm=headline_z_mode):  # noqa: F811
            return _forest_predict(p, x, _zm)
    scaler = Scaler(mean=jnp.zeros(15), scale=jnp.ones(15))

    def _step_body(fstate, params, batch):
        fstate, feats = update_and_featurize(fstate, batch, fcfg)
        probs = predict(params, transform(scaler, feats))
        return fstate, jnp.where(batch.valid, probs, 0.0)

    step = jax.jit(_step_body, donate_argnums=(0,))

    from real_time_fraud_detection_system_tpu.core.batch import make_batch

    def _measure(n_rows: int, seconds: float):
        """→ (txns_per_sec, per_batch_ms). Compiles on first call."""
        c = _make_batch_cols(rng, n_rows)
        batch = jax.tree.map(jnp.asarray, make_batch(**c))
        fstate = init_feature_state(fcfg)
        fstate, probs = step(fstate, params, batch)  # warmup/compile
        jax.block_until_ready(probs)
        # Sync every `chunk` steps so the dispatch queue stays bounded
        # (an unbounded async backlog makes the final sync unbounded,
        # pathological over high-RTT device tunnels).
        chunk = 8
        t0 = time.perf_counter()
        iters = 0
        while time.perf_counter() - t0 < seconds:
            for _ in range(chunk):
                fstate, probs = step(fstate, params, batch)
            jax.block_until_ready(probs)
            iters += chunk
        wall = time.perf_counter() - t0
        return iters * n_rows / wall, wall / iters * 1e3

    # ---- throughput: start modest, scale up, keep the best ----
    if args.quick or on_cpu:
        sizes = [4096]
    else:
        # 2M rows fails remote compile on the tunnel (HTTP 500); 1M is the
        # largest size observed to compile and is also the fastest.
        sizes = [16384, 262144, 1048576]
    seconds = min(args.seconds, 2.0) if on_cpu else args.seconds
    by_size = {}
    best_tps, best_rows, best_ms = 0.0, 0, 0.0
    size_error = None
    for n_rows in sizes:
        _progress(f"measuring size={n_rows}")
        try:
            tps, ms = _measure(n_rows, seconds)
        except Exception as e:  # alloc/compile failure: keep smaller sizes
            size_error = f"{n_rows}: {type(e).__name__}: {str(e)[:160]}"
            break
        _progress(f"size={n_rows} tps={tps:.0f}")
        by_size[str(n_rows)] = round(tps, 1)
        if tps > best_tps:
            best_tps, best_rows, best_ms = tps, n_rows, ms

    if best_rows == 0:
        raise RuntimeError(f"no batch size succeeded ({size_error})")

    # (The round-4 z-mode shootout — bf16 vs int8 gemm_leaf_sum microbench
    # — graduated: z_mode is now a serving knob (runtime.z_mode) and the
    # A/B moved to the engine-level detail.device_plane block below, which
    # measures the serving step rather than the isolated contraction.)

    # ---- classify latency: p50/p99 across serving batch sizes ----------
    _progress("latency percentiles")
    serve_rows = 4096
    # Engine-loop batch: on TPU, per-call overhead (tunnel RTT when
    # benched remotely; dispatch otherwise) swamps a 4k-row batch — serve
    # at a size where the device does real work per round trip, like the
    # throughput headline does.
    engine_rows = 65536 if not (args.quick or on_cpu) else serve_rows
    lat_iters = 10 if args.quick or on_cpu else 40
    lat_sizes = ([1024, 4096, 16384, 65536] if (full and not on_cpu)
                 else [1024, serve_rows] if full else [serve_rows])
    latency_by_batch = {}
    step_p50_ms = step_p99_ms = 0.0
    for n_rows in lat_sizes:
        c = _make_batch_cols(rng, n_rows)
        sbatch = jax.tree.map(jnp.asarray, make_batch(**c))
        sstate = init_feature_state(fcfg)
        sstate, probs = step(sstate, params, sbatch)  # warmup/compile
        jax.block_until_ready(probs)
        lats = []
        for _ in range(lat_iters):
            t0 = time.perf_counter()
            sstate, probs = step(sstate, params, sbatch)
            jax.block_until_ready(probs)
            lats.append(time.perf_counter() - t0)
        lats = np.asarray(lats)
        p50 = float(np.percentile(lats, 50) * 1e3)
        p99 = float(np.percentile(lats, 99) * 1e3)
        latency_by_batch[str(n_rows)] = {"p50_ms": round(p50, 3),
                                         "p99_ms": round(p99, 3)}
        if n_rows == serve_rows:
            step_p50_ms, step_p99_ms = p50, p99
        _progress(f"latency size={n_rows} p50={p50:.1f}ms")

    # ---- per-call overhead probe (tunnel RTT / dispatch floor) ---------
    # One trivial op round trip: upper-bounds the fixed cost every
    # dispatch pays. Over the axon tunnel this IS the serving-latency
    # floor; locally attached it is ~dispatch overhead. Separates "the
    # loop is slow" from "the wire is slow" in the engine numbers below.
    _progress("rtt probe")
    tiny = jnp.zeros((8, 128), jnp.float32)
    tiny_f = jax.jit(lambda a: a.sum())
    jax.block_until_ready(tiny_f(tiny))
    rtts = []
    for _ in range(20):
        t0 = time.perf_counter()
        jax.block_until_ready(tiny_f(tiny))
        rtts.append(time.perf_counter() - t0)
    rtt_p50_ms = float(np.percentile(np.asarray(rtts), 50) * 1e3)

    # ---- device-side step latency: chained dependent steps -------------
    # The per-call timings above are RTT-floored over a remote tunnel
    # (p50 flat ~66 ms from 1k→64k rows); naive dispatch loops lie under
    # async dispatch. Protocol: run the FULL hot-path step n times
    # back-to-back inside ONE jitted ``fori_loop`` — the feature state
    # carries through, so iterations are data-dependent and cannot
    # overlap — with n a TRACED trip count (one compile serves every n).
    # The two-point form (t(n2)-t(n1))/(n2-n1) cancels RTT, dispatch and
    # fetch cost exactly, leaving pure device step time.
    device_latency_by_batch = {}
    if full or os.environ.get("BENCH_FULL_SECTIONS") == "1":
        _progress("chained device latency")

        def _chained(fstate, params, batch, n):
            def body(i, carry):
                fs, acc = carry
                fs, p = _step_body(fs, params, batch)
                return (fs, acc + p.sum())

            _, acc = jax.lax.fori_loop(
                0, n, body, (fstate, jnp.float32(0)))
            return acc

        chained = jax.jit(_chained)
        n_lo, n_hi = 8, 72
        trials = 3 if (on_cpu or args.quick) else 5
        for n_rows in lat_sizes:
            try:
                c = _make_batch_cols(rng, n_rows)
                dbatch = jax.tree.map(jnp.asarray, make_batch(**c))
                dstate = init_feature_state(fcfg)
                np.asarray(chained(dstate, params, dbatch,
                                   jnp.int32(n_lo)))  # compile
                per_step = []
                for _ in range(trials):
                    t0 = time.perf_counter()
                    np.asarray(chained(dstate, params, dbatch,
                                       jnp.int32(n_lo)))
                    t_lo = time.perf_counter() - t0
                    t0 = time.perf_counter()
                    np.asarray(chained(dstate, params, dbatch,
                                       jnp.int32(n_hi)))
                    t_hi = time.perf_counter() - t0
                    per_step.append((t_hi - t_lo) / (n_hi - n_lo))
                ps = np.asarray(per_step) * 1e3
                p50_ms = float(np.percentile(ps, 50))
                device_latency_by_batch[str(n_rows)] = {
                    "step_ms_p50": round(p50_ms, 4),
                    "step_ms_max": round(float(ps.max()), 4),
                    # device-side throughput the chained steps imply —
                    # what a locally attached host would sustain at this
                    # batch size (no per-call wire costs). None when the
                    # differenced timing is jitter-dominated (<= 0).
                    "device_rows_per_s": (
                        round(n_rows / (p50_ms / 1e3), 1)
                        if p50_ms > 0 else None),
                    "chained_n": [n_lo, n_hi],
                    "trials": trials,
                }
                _progress(
                    f"device step size={n_rows} p50={p50_ms:.3f}ms")
            except Exception as e:
                device_latency_by_batch[str(n_rows)] = {
                    "error": f"{type(e).__name__}: {str(e)[:160]}"}

    # ---- engine-loop latency (host decode + device step per micro-batch)
    _progress("engine loop")
    engine_stats = None
    phase_p50 = None
    host_plane = None
    device_plane = None
    if args.model == "forest":
        from real_time_fraud_detection_system_tpu.runtime.engine import (
            ScoringEngine,
        )

        n_eng = 8 if args.quick or on_cpu else 50
        # Depth-8 pipelining on TPU: per-dispatch overhead (tunnel RTT
        # when benched remotely) overlaps across in-flight batches
        # instead of serializing the loop.
        depth = 2 if (args.quick or on_cpu) else 8
        ecfg = Config(
            features=FeatureConfig(customer_capacity=8192,
                                   terminal_capacity=16384),
            runtime=RuntimeConfig(batch_buckets=(engine_rows,),
                                  max_batch_rows=engine_rows,
                                  trigger_seconds=0.0,
                                  pipeline_depth=depth),
        )
        def _engine_stats(e, rows=None, n=None) -> dict:
            """Warmup run (jit compile outside the stats), measured run,
            rounded stats dict — shared by every engine-loop variant."""
            rows = rows or engine_rows
            n = n or n_eng
            e.run(_RandSource(1, rows, seed=3), trigger_seconds=0.0)
            s = e.run(_RandSource(n, rows), trigger_seconds=0.0)
            return {
                "batch_rows": rows,
                "rows_per_s": round(s["rows_per_s"], 1),
                "latency_p50_ms": round(s["latency_p50_ms"], 3),
                "latency_p99_ms": round(s["latency_p99_ms"], 3),
                "host_prep_p50_ms": round(s["host_prep_p50_ms"], 3),
                "dispatch_p50_ms": round(s["dispatch_p50_ms"], 3),
                "result_wait_p50_ms": round(s["result_wait_p50_ms"], 3),
                "pipeline_depth": s["pipeline_depth"],
            }

        import dataclasses as _dc

        def _alerts_cfg(base: Config) -> Config:
            """emit_features=False twin of an engine config: the [B, 15]
            feature matrix never leaves HBM — the dominant per-batch D2H
            when the chip is remote. Same scores, no feature columns."""
            return Config(
                features=base.features,
                runtime=_dc.replace(base.runtime, emit_features=False),
            )

        def _guarded(key: str, fn) -> None:
            """A failed variant records ITS OWN error key and never
            clobbers earlier successful measurements. Emits a progress
            line per variant: each costs a compile + 13 big batches over
            the tunnel, and three back-to-back variants with no output
            tripped the parent's 420 s settle timer on a slow link."""
            _progress(f"engine variant {key}")
            try:
                engine_stats[key] = fn()
            except Exception as e:
                engine_stats[key] = {
                    "error": f"{type(e).__name__}: {str(e)[:160]}"
                }

        engine_stats = _engine_stats(
            ScoringEngine(ecfg, kind="forest", params=params, scaler=scaler)
        )

        # ---- registry-backed before/after evidence (ROADMAP PR-1 note):
        # per-phase p50s for sync vs async sink and precompile off/on,
        # straight from the run-stats trackers + the engine's registry.
        _progress("engine loop phase p50 before/after")

        def _phase_p50_block():
            import dataclasses as _pdc
            import shutil
            import tempfile

            from real_time_fraud_detection_system_tpu.io.sink import (
                AsyncSink,
                ParquetSink,
            )
            from real_time_fraud_detection_system_tpu.utils.metrics import (
                MetricsRegistry,
            )

            def _phases(s):
                return {
                    k: round(s[f"{k}_p50_ms"], 4)
                    for k in ("host_prep", "dispatch", "result_wait",
                              "sink_write")
                }

            out = {}
            # sink_write: inline parquet write vs bounded-queue enqueue
            for label, asynk in (("sink_sync", False), ("sink_async", True)):
                d = tempfile.mkdtemp(prefix=f"rtfds_bench_{label}_")
                sink = ParquetSink(d)
                if asynk:
                    sink = AsyncSink(sink, max_queue=8)
                e = ScoringEngine(ecfg, kind="forest", params=params,
                                  scaler=scaler)
                e.run(_RandSource(1, engine_rows, seed=3), sink=sink,
                      trigger_seconds=0.0)
                s = e.run(_RandSource(n_eng, engine_rows), sink=sink,
                          trigger_seconds=0.0)
                if asynk:
                    sink.close()
                shutil.rmtree(d, ignore_errors=True)
                out[label] = {"rows_per_s": round(s["rows_per_s"], 1),
                              **_phases(s)}

            # precompile: the second bucket size first lands MID-STREAM
            # (after the recompile detector's warmup) — precompile off
            # pays that compile inside the loop, on dispatches a ready
            # executable and the counter stays 0
            small = max(256, engine_rows // 4)

            class _Scripted:
                def __init__(self, sizes, seed=2):
                    srng = np.random.default_rng(seed)
                    self._b = []
                    at = 0
                    for n in sizes:
                        c = _make_batch_cols(srng, n)
                        self._b.append({
                            "tx_id": np.arange(at, at + n, dtype=np.int64),
                            "tx_datetime_us": c["tx_datetime_us"],
                            "customer_id": c["customer_id"],
                            "terminal_id": c["terminal_id"],
                            "tx_amount_cents": c["amount_cents"],
                            "kafka_ts_ms": c["tx_datetime_us"] // 1000,
                        })
                        at += n
                    self._i = 0

                def poll_batch(self):
                    if self._i >= len(self._b):
                        return None
                    b = self._b[self._i]
                    self._i += 1
                    return b

                @property
                def offsets(self):
                    return [self._i]

                def seek(self, offsets):
                    self._i = int(offsets[0])

            sizes = [engine_rows] * 5 + [small, engine_rows, small]
            for label, pre in (("precompile_off", False),
                               ("precompile_on", True)):
                reg = MetricsRegistry()
                pcfg = Config(
                    features=ecfg.features,
                    runtime=_pdc.replace(
                        ecfg.runtime, batch_buckets=(small, engine_rows),
                        precompile=pre),
                )
                e = ScoringEngine(pcfg, kind="forest", params=params,
                                  scaler=scaler, metrics=reg)
                # warmup run triggers the precompile hook (when on), so
                # the measured stream never includes build-time compiles
                e.run(_RandSource(1, engine_rows, seed=3),
                      trigger_seconds=0.0)
                s = e.run(_Scripted(sizes), trigger_seconds=0.0)
                rc = reg.get("rtfds_xla_recompiles_total")
                out[label] = {
                    "rows_per_s": round(s["rows_per_s"], 1),
                    "latency_p99_ms": round(s["latency_p99_ms"], 3),
                    "mid_stream_recompiles": int(rc.value) if rc else 0,
                    **_phases(s),
                }
            return out

        try:
            phase_p50 = _phase_p50_block()
        except Exception as e:
            phase_p50 = {"error": f"{type(e).__name__}: {str(e)[:160]}"}

        # ---- host data plane off/on (registry-backed, same protocol):
        # the engine loop over a decode-heavy (envelope) source with the
        # host-plane features off (serial decode, synchronous polling,
        # blocking fetch) vs on (parallel slab decode + background
        # prefetch + overlapped result fetch). The r05 session measured
        # the device step at ~10 ms/batch while the loop delivered one
        # every ~280 ms — this block is the before/after for closing
        # that host gap.
        _progress("host data plane off/on")

        def _host_plane_block():
            import dataclasses as _hdc

            from real_time_fraud_detection_system_tpu.core import (
                native as _nat,
            )
            from real_time_fraud_detection_system_tpu.core.envelope import (
                decode_transaction_envelopes,
                encode_transaction_envelopes,
            )
            from real_time_fraud_detection_system_tpu.runtime import (
                PrefetchSource,
            )
            from real_time_fraud_detection_system_tpu.utils.metrics import (
                MetricsRegistry,
            )

            hp_rows = 4096 if (on_cpu or args.quick) else engine_rows
            hp_batches = 6 if (on_cpu or args.quick) else 12
            rng_hp = np.random.default_rng(5)
            corpus = []
            for b in range(hp_batches + 1):  # +1: the warmup batch
                c = _make_batch_cols(rng_hp, hp_rows)
                corpus.append(encode_transaction_envelopes(
                    np.arange(b * hp_rows, (b + 1) * hp_rows,
                              dtype=np.int64),
                    c["tx_datetime_us"], c["customer_id"],
                    c["terminal_id"], c["amount_cents"]))

            class _EnvSource:
                """Kafka-shaped source: each poll decodes one envelope
                byte-batch with an explicit worker count."""

                def __init__(self, msgs_list, workers):
                    self._b = msgs_list
                    self._i = 0
                    self._w = workers

                def poll_batch(self):
                    if self._i >= len(self._b):
                        return None
                    msgs = self._b[self._i]
                    self._i += 1
                    if _nat.native_available():
                        cols, invalid = \
                            _nat.decode_transaction_envelopes_native(
                                msgs, workers=self._w)
                    else:
                        cols, invalid = decode_transaction_envelopes(msgs)
                    if invalid.any():
                        keep = ~invalid
                        cols = {k: v[keep] for k, v in cols.items()}
                    return cols

                @property
                def offsets(self):
                    return [self._i]

                def seek(self, offsets):
                    self._i = int(offsets[0])

            def _variant(workers, prefetch, overlap):
                reg = MetricsRegistry()
                vcfg = Config(
                    features=ecfg.features,
                    runtime=_hdc.replace(ecfg.runtime,
                                         fetch_overlap=overlap))
                e = ScoringEngine(vcfg, kind="forest", params=params,
                                  scaler=scaler, metrics=reg)
                e.run(_EnvSource(corpus[:1], workers),
                      trigger_seconds=0.0)  # compile outside the stats
                src = _EnvSource(corpus[1:], workers)
                if prefetch:
                    src = PrefetchSource(src, max_batches=4, registry=reg)
                s = e.run(src, trigger_seconds=0.0)
                if prefetch:
                    src.close()
                poll = reg.get("rtfds_phase_seconds", phase="source_poll")
                out = {
                    "decode_workers": workers,
                    "prefetch_batches": 4 if prefetch else 0,
                    "fetch_overlap": overlap,
                    "rows_per_s": round(s["rows_per_s"], 1),
                    "source_poll_p50_ms": round(
                        poll.percentile(50) * 1e3, 3)
                    if poll is not None and poll.count else None,
                    "result_wait_p50_ms": round(
                        s["result_wait_p50_ms"], 3),
                }
                ov = reg.get("rtfds_fetch_overlap_seconds_total")
                if ov is not None and ov.value:
                    out["fetch_overlap_s_total"] = round(ov.value, 4)
                return out

            return {
                "batch_rows": hp_rows,
                "batches": hp_batches,
                "off": _variant(1, False, False),
                "on": _variant(max(2, _nat.get_decode_workers()), True,
                               True),
            }

        try:
            host_plane = _host_plane_block()
        except Exception as e:
            host_plane = {"error": f"{type(e).__name__}: {str(e)[:160]}"}

        # ---- device plane off/on (the round-9 A/B): the SERVING engine
        # step measured over z_mode {f32, int8} × fused Pallas step
        # {off, on} under precompile, with exactness asserted from the
        # served probabilities (the int8 arm must be decision-identical
        # — on CPU bit-identical — to the f32 control). Folds the old
        # gemm_leaf_sum z-mode microbench shootout into an engine-level
        # measurement; per-arm mfu/mfu_of_ceiling are annotated once the
        # roofline ceiling is computed below.
        _progress("device plane z_mode x fused")

        def _device_plane_block():
            import dataclasses as _zdc

            from real_time_fraud_detection_system_tpu.utils.metrics import (
                MetricsRegistry,
            )

            out = {"batch_rows": engine_rows, "batches": n_eng}
            probs_by = {}

            def _arm(label, z, fused):
                _progress(f"device plane {label}")
                reg = MetricsRegistry()
                acfg = Config(
                    features=ecfg.features,
                    runtime=_zdc.replace(ecfg.runtime, z_mode=z,
                                         use_pallas=fused,
                                         precompile=True))
                e = ScoringEngine(acfg, kind="forest", params=params,
                                  scaler=scaler, metrics=reg)
                cap = _ProbsCap()
                # warmup run triggers precompile: the measured stream
                # never includes build-time compiles
                e.run(_RandSource(1, engine_rows, seed=3),
                      trigger_seconds=0.0)
                s = e.run(_RandSource(n_eng, engine_rows), sink=cap,
                          trigger_seconds=0.0)
                rc = reg.get("rtfds_xla_recompiles_total")
                probs_by[label] = cap.concat()
                out[label] = {
                    "z_mode": e.z_mode,
                    "use_pallas": fused,
                    "rows_per_s": round(s["rows_per_s"], 1),
                    "latency_p50_ms": round(s["latency_p50_ms"], 3),
                    "mid_stream_recompiles": int(rc.value) if rc else 0,
                }

            _arm("z_f32_fused_off", "f32", False)
            _arm("z_int8_fused_off", "int8", False)
            if on_cpu and not os.environ.get("BENCH_FULL_SECTIONS"):
                # the fused kernel only interprets off-TPU — measuring it
                # there times the interpreter, not the device plane
                out["fused_arms_skipped"] = "cpu (interpret-only)"
            else:
                _arm("z_f32_fused_on", "f32", True)
                _arm("z_int8_fused_on", "int8", True)
            a, b = (probs_by["z_f32_fused_off"],
                    probs_by["z_int8_fused_off"])
            out["max_abs_delta_int8_vs_f32"] = float(np.abs(a - b).max())
            out["decision_flips_int8_vs_f32"] = int(
                ((a >= 0.5) != (b >= 0.5)).sum())
            if "z_int8_fused_on" in probs_by:
                f = probs_by["z_int8_fused_on"]
                out["max_abs_delta_fused_vs_unfused"] = float(
                    np.abs(f - b).max())
                out["decision_flips_fused_vs_unfused"] = int(
                    ((f >= 0.5) != (b >= 0.5)).sum())
            return out

        try:
            device_plane = _device_plane_block()
        except Exception as e:
            device_plane = {"error": f"{type(e).__name__}: {str(e)[:160]}"}

        if full:
            _progress("engine loop alerts-only")
            _guarded("alerts_only", lambda: _engine_stats(
                ScoringEngine(_alerts_cfg(ecfg), kind="forest",
                              params=params, scaler=scaler)))
        # RTT-vs-device-time decomposition (VERDICT r3 item 2): what the
        # loop would do with the per-call overhead removed — i.e. with a
        # locally attached chip instead of the tunnel.
        dev_ms = None
        lb = latency_by_batch.get(str(engine_rows))
        if lb is not None:
            dev_ms = max(lb["p50_ms"] - rtt_p50_ms, 1e-3)
        if dev_ms is not None:
            bound_ms = max(dev_ms, engine_stats["host_prep_p50_ms"])
            engine_stats["decomposition"] = {
                "rtt_per_call_ms": round(rtt_p50_ms, 3),
                "device_step_ms_est": round(dev_ms, 3),
                "loop_ms_per_batch": round(
                    engine_rows / max(engine_stats["rows_per_s"], 1e-9)
                    * 1e3, 3),
                "projected_local_rows_per_s": round(
                    engine_rows / (bound_ms / 1e3), 1),
            }
        if full:
            # Big-batch loop: amortize the per-batch fixed costs further
            # (the serving analogue of the 1M-row throughput headline).
            _progress("engine loop 262k")
            big = 262144 if not on_cpu else 8192
            bcfg = Config(
                features=FeatureConfig(customer_capacity=8192,
                                       terminal_capacity=16384),
                runtime=RuntimeConfig(batch_buckets=(big,),
                                      max_batch_rows=big,
                                      trigger_seconds=0.0,
                                      pipeline_depth=depth),
            )
            _guarded("big_batch", lambda: _engine_stats(
                ScoringEngine(bcfg, kind="forest", params=params,
                              scaler=scaler), rows=big, n=12))
            _guarded("big_batch_alerts", lambda: _engine_stats(
                ScoringEngine(_alerts_cfg(bcfg), kind="forest",
                              params=params, scaler=scaler),
                rows=big, n=12))
            # bf16 feature emission: halves the feature D2H (the
            # full-featured loop's bottleneck on a constrained link);
            # predictions stay f32-exact.
            _guarded("big_batch_bf16", lambda: _engine_stats(
                ScoringEngine(
                    bcfg.replace(runtime=_dc.replace(
                        bcfg.runtime, emit_dtype="bfloat16")),
                    kind="forest", params=params, scaler=scaler),
                rows=big, n=12))

            # Selective emission: probs for EVERY row, feature columns
            # only for rows clearing the alert threshold — the full
            # analyzed schema lands for flagged traffic while clean rows
            # skip the dominant D2H (one packed transfer per batch, same
            # round-trip count as alerts-only). Threshold = this random
            # stream's own q99, i.e. ~1% flagged — the reference's alert
            # regime (0.88% test-set fraud rate).
            def _selective():
                # Calibrate on the EVOLVED feature state: the probability
                # tail drifts as the window state accumulates, so a
                # fresh-state probe under-sets the threshold and every
                # batch overflows the compaction cap. Run a full-emission
                # probe engine over the exact stream the measurement will
                # see (same seeds, same batching) and take q99 of the
                # probabilities it actually serves.
                cal = _ProbsCap()
                probe = ScoringEngine(bcfg, kind="forest", params=params,
                                      scaler=scaler)
                probe.run(_RandSource(1, big, seed=3), trigger_seconds=0.0)
                probe.run(_RandSource(12, big), sink=cal,
                          trigger_seconds=0.0)
                allp = cal.concat()
                # The forest's probability mass is discrete (tree-vote
                # averages): the q99 VALUE can carry a fat atom, and the
                # engine flags with >=, so thresholding AT q99 can flag
                # far more than 1% (measured: 29% — every batch
                # overflowed). Step just above the atom instead.
                thr = float(np.nextafter(
                    np.float32(np.quantile(allp, 0.99)), np.float32(2.0)))
                thr = min(max(thr, 1e-6), 1.0)
                e = ScoringEngine(
                    bcfg.replace(runtime=_dc.replace(
                        bcfg.runtime, emit_threshold=thr,
                        # true flagged rate ~1% ⇒ 1/32 still 3× headroom,
                        # and the packed transfer shrinks toward the
                        # alerts-only floor (probs dominate it)
                        emit_cap_fraction=1 / 32)),
                    kind="forest", params=params, scaler=scaler)
                st = _engine_stats(e, rows=big, n=12)
                st["emit_threshold_q99"] = round(thr, 6)
                st["flagged_fraction"] = round(
                    float((allp >= thr).mean()), 5)
                st["overflow_batches"] = e.selective_overflows
                return st

            _progress("engine loop 262k selective emission")
            _guarded("big_batch_selective", _selective)
        if not (on_cpu or args.quick):
            # Sharded serving loop on a 1-chip mesh: the shard_map step +
            # partition/spill machinery running on real hardware (the
            # multi-chip path minus the extra chips — those are validated
            # on the driver's virtual-device dryrun). Guarded: a failed
            # remote compile of the wider shard_map step must not discard
            # the already-measured headline numbers.
            _progress("sharded engine loop (1-device mesh)")
            from real_time_fraud_detection_system_tpu.runtime import (
                ShardedScoringEngine,
            )

            try:
                engine_stats["sharded_1dev"] = _engine_stats(
                    ShardedScoringEngine(
                        ecfg, kind="forest", params=params, scaler=scaler,
                        n_devices=1, rows_per_shard=engine_rows,
                    )
                )
            except Exception as e:
                engine_stats["sharded_1dev"] = {
                    "error": f"{type(e).__name__}: {str(e)[:160]}"
                }
        if full:
            # Virtual-mesh scaling curve (subprocess: needs the 8-device
            # CPU mesh env, which this TPU-attached process cannot adopt).
            # On the sandbox's shared host cores the claim is FLAT rows/s
            # across widths (shard_map + partition/re-assemble overhead
            # amortizes, VERDICT r4 item 4), not wall-clock speedup.
            _progress("sharded scaling curve (virtual CPU mesh)")

            def _scaling():
                # 16k rows: big enough that per-shard-program
                # dispatch noise stops dominating (the 2k quick
                # size wobbles ±40%), ~15 s on one host core
                return _run_cpu_mesh_tool(
                    "sharded_scaling_bench.py",
                    ["--rows", "16384", "--batches", "3"],
                    timeout_s=1200.0, label="sharded scaling running")

            _guarded("sharded_scaling", _scaling)
        if on_cpu and skl is not None:
            # The CPU serving path users actually get (--scorer cpu):
            # framework feature engine + host-side sklearn classify. This
            # is the loop to compare with cpu_sklearn_txns_per_sec — the
            # GEMM loop above is a TPU kernel interpreted on CPU.
            _progress("cpu-oracle engine loop")

            class _SklOracle:
                def __init__(self, inner):
                    self._inner = inner

                def predict_proba(self, x):
                    return self._inner.predict_proba(x)[:, 1]

            engine_stats = {
                "gemm_on_cpu": engine_stats,
                "cpu_oracle": _engine_stats(
                    ScoringEngine(ecfg, kind="forest", params=params,
                                  scaler=scaler, scorer="cpu",
                                  cpu_model=_SklOracle(skl))
                ),
            }

    def _timed_rows_per_s(run_once, rows: int, seconds: float) -> float:
        """Chunked-dispatch timing shared by the kernel-comparison blocks:
        ``run_once()`` returns the value to sync on; the caller has already
        made one warmed call (compile excluded from the clock)."""
        t0 = time.perf_counter()
        iters = 0
        while time.perf_counter() - t0 < seconds:
            for _ in range(4):
                out = run_once()
            jax.block_until_ready(out)
            iters += 4
        return round(iters * rows / (time.perf_counter() - t0), 1)

    # ---- fused Pallas featurize+score vs plain-jnp composition ---------
    # The linear-scorer kernel (ops/pallas_kernels.py). On CPU it only
    # interprets (slow, exact) — measured on TPU only. Answers VERDICT r3
    # item 8: quantify the fused kernel against XLA's own fusion.
    pallas_stats = None
    if full:
        _progress("pallas fused vs unfused")
        try:
            from real_time_fraud_detection_system_tpu.features.online import (
                update_and_score_pallas,
            )
            from real_time_fraud_detection_system_tpu.models.logreg import (
                init_logreg,
                logreg_predict_proba,
            )

            lp = init_logreg(15)
            pl_rows = 65536 if not on_cpu else 1024
            c = _make_batch_cols(rng, pl_rows)
            pbatch = jax.tree.map(jnp.asarray, make_batch(**c))

            def unfused(fstate, batch):
                fstate, feats = update_and_featurize(fstate, batch, fcfg)
                pr = logreg_predict_proba(lp, transform(scaler, feats))
                return fstate, jnp.where(batch.valid, pr, 0.0)

            def fused(fstate, batch):
                fstate, pr, _ = update_and_score_pallas(
                    fstate, batch, fcfg, scaler.mean, scaler.scale,
                    lp.w, lp.b)
                return fstate, jnp.where(batch.valid, pr, 0.0)

            pallas_stats = {}
            outs = {}
            for name, fn in (("unfused", unfused), ("fused", fused)):
                jfn = jax.jit(fn, donate_argnums=(0,))
                fs = init_feature_state(fcfg)
                fs, pr = jfn(fs, pbatch)
                jax.block_until_ready(pr)
                outs[name] = np.asarray(pr)

                def once(jfn=jfn):
                    nonlocal fs
                    fs, pr = jfn(fs, pbatch)
                    return pr

                pallas_stats[f"{name}_rows_per_s"] = _timed_rows_per_s(
                    once, pl_rows, min(args.seconds, 3.0))
            pallas_stats["max_abs_delta"] = float(
                np.abs(outs["fused"] - outs["unfused"]).max())
        except Exception as e:
            pallas_stats = {"error": f"{type(e).__name__}: {str(e)[:160]}"}

    # ---- fused Pallas forest kernel vs XLA's GEMM fusion ---------------
    # The flagship classify chain (ops/pallas_forest.py): does a hand-tiled
    # VMEM-resident kernel beat XLA's automatic fusion of the three-GEMM
    # composition? Measured classify-only so the halves are isolated from
    # the featurize cost. (Round-4 measurement: XLA wins — its fusion of
    # this chain is already intermediate-free; the kernel stays an opt-in
    # proof of hand-fusibility, not the default.)
    pallas_forest_stats = None
    if args.model == "forest" and full and not on_cpu:
        _progress("pallas forest kernel vs xla gemm")
        try:
            from real_time_fraud_detection_system_tpu.models.forest import (
                gemm_predict_proba,
            )
            from real_time_fraud_detection_system_tpu.ops.pallas_forest import (
                pallas_predict_proba,
                to_pallas,
            )

            pfr = 262_144
            xq = jnp.asarray(
                rng.normal(0, 1, (pfr, 15)).astype(np.float32))
            pf = to_pallas(params)
            fns = {
                "xla_gemm": jax.jit(lambda x: gemm_predict_proba(params, x)),
                "pallas_kernel": jax.jit(
                    lambda x: pallas_predict_proba(pf, x, block_rows=2048,
                                                   interpret=False)),
            }
            pallas_forest_stats = {"rows": pfr}
            pouts = {}
            for name, fn in fns.items():
                pr = fn(xq)
                jax.block_until_ready(pr)
                pouts[name] = np.asarray(pr)
                pallas_forest_stats[f"{name}_rows_per_s"] = \
                    _timed_rows_per_s(lambda fn=fn: fn(xq), pfr,
                                      min(args.seconds, 3.0))
            pallas_forest_stats["max_abs_delta"] = float(
                np.abs(pouts["xla_gemm"] - pouts["pallas_kernel"]).max())

            # hot-path split: featurize-only throughput at the same size,
            # so headline = harmonic composition of the two halves is on
            # record (classify-only is the xla_gemm row above)
            def _feat_only(fstate, batch):
                fstate, feats = update_and_featurize(fstate, batch, fcfg)
                return fstate, feats.sum()

            jfeat = jax.jit(_feat_only, donate_argnums=(0,))
            fbatch = jax.tree.map(
                jnp.asarray, make_batch(**_make_batch_cols(rng, pfr)))
            fs = init_feature_state(fcfg)
            fs, s = jfeat(fs, fbatch)
            jax.block_until_ready(s)

            def _feat_once():
                nonlocal fs
                fs, s = jfeat(fs, fbatch)
                return s

            pallas_forest_stats["featurize_only_rows_per_s"] = \
                _timed_rows_per_s(_feat_once, pfr, min(args.seconds, 3.0))
        except Exception as e:
            pallas_forest_stats = {
                "error": f"{type(e).__name__}: {str(e)[:160]}"}

    # ---- training throughput on the device -----------------------------
    # The reference records per-classifier training_execution_time hooks
    # (shared_functions.py:312-320) but never publishes values; here the
    # jax training loops (logreg SGD + MLP) are timed on whatever backend
    # is live — the on-chip analogue of those hooks.
    train_stats = None
    if full:
        _progress("train throughput")
        try:
            from real_time_fraud_detection_system_tpu.models.logreg import (
                train_logreg,
            )
            from real_time_fraud_detection_system_tpu.models.mlp import (
                train_mlp,
            )

            tr_rows = 262_144 if not on_cpu else 16_384
            xtr2 = rng.normal(0, 1, (tr_rows, 15)).astype(np.float32)
            ytr2 = (xtr2[:, 0] - 0.3 * xtr2[:, 2] > 0.7).astype(np.int32)
            train_stats = {"rows": tr_rows, "batch_size": 16384}

            def _timed_fit(fit, epochs: int) -> float:
                t0 = time.perf_counter()
                params_out = fit(epochs)
                jax.block_until_ready(jax.tree.leaves(params_out))
                return time.perf_counter() - t0

            for name, fit in (
                ("logreg", lambda e: train_logreg(
                    xtr2, ytr2, batch_size=16384, epochs=e)),
                ("mlp", lambda e: train_mlp(
                    xtr2, ytr2, hidden=(64, 32), batch_size=16384,
                    epochs=e)),
            ):
                # train_* builds its jitted step per call, so any single
                # call includes one compile. Report the cold number (what
                # one call costs) AND a warm steady-state figure from
                # differencing a 1-epoch and an N-epoch call — the
                # compile cancels, leaving N-1 epochs of step time. The
                # epoch ladder grows until the delta clears the noise
                # floor (round 4 used a fixed 8-epoch delta, which on TPU
                # finished under the threshold and silently dropped the
                # warm number — the figure the training story owes).
                _progress(f"train {name} cold")
                w1 = _timed_fit(fit, 1)
                train_stats[f"{name}_cold_rows_per_s"] = round(
                    tr_rows / w1, 1)
                for hi in (41, 201):
                    # each rung is minutes of silent dispatches on a slow
                    # link — keep the supervisor's settle timer re-armed
                    _progress(f"train {name} warm x{hi}")
                    whi = _timed_fit(fit, hi)
                    if whi - w1 > 0.25:
                        train_stats[f"{name}_warm_rows_per_s"] = round(
                            (hi - 1) * tr_rows / (whi - w1), 1)
                        train_stats[f"{name}_warm_epochs"] = hi - 1
                        break

        except Exception as e:
            train_stats = {"error": f"{type(e).__name__}: {str(e)[:160]}"}

        # Tree-ensemble fit wall-clock (the reference's
        # training_execution_time hook for its RandomForest; full
        # reference-scale fits are recorded by `rtfds compare`, see
        # BASELINE.md). Own guard: a forest failure must not discard the
        # logreg/mlp warm figures measured above.
        _progress("train forest fit")
        try:
            from real_time_fraud_detection_system_tpu.models.forest import (
                fit_forest,
            )

            n_fit = 32_768 if not on_cpu else 8_192
            xtrf = rng.normal(0, 1, (n_fit, 15)).astype(np.float32)
            ytrf = (xtrf[:, 0] - 0.3 * xtrf[:, 2] > 0.7).astype(np.int32)
            t0 = time.perf_counter()
            fit_forest(xtrf, ytrf, n_trees=100, max_depth=8)
            w = time.perf_counter() - t0
            train_stats = train_stats if isinstance(train_stats, dict) \
                else {}
            train_stats["forest_fit"] = {
                "rows": n_fit, "n_trees": 100, "max_depth": 8,
                "wall_s": round(w, 2),
                "rows_per_s": round(n_fit / w, 1),
            }
        except Exception as e:
            if isinstance(train_stats, dict):
                train_stats["forest_fit"] = {
                    "error": f"{type(e).__name__}: {str(e)[:160]}"}

    # ---- long-context scorer: sequence serving throughput --------------
    # The fused history step (features/history.py): per-customer ring
    # update + causal-transformer score per row. Guarded — a failure here
    # must never discard the headline numbers.
    _progress("sequence scorer")
    seq_stats = None
    try:
        from real_time_fraud_detection_system_tpu.features.history import (
            init_history_state,
            update_and_score,
        )
        from real_time_fraud_detection_system_tpu.models.sequence import (
            init_transformer,
        )

        tparams = init_transformer(
            d_model=32, n_heads=2, n_layers=2, d_ff=64, seed=0)
        seq_step = jax.jit(update_and_score, static_argnums=(3,),
                           donate_argnums=(0,))

        def _measure_seq(history_len: int, rows: int, iters: int) -> dict:
            """One sequence-scorer measurement: build, warmup, timed
            loop, stats — shared by the K=32 base and long-K variants."""
            from real_time_fraud_detection_system_tpu.features.history import (
                _attn_fn_for,
            )

            cfg_k = FeatureConfig(
                customer_capacity=8192, terminal_capacity=1024,
                history_len=history_len)
            c = _make_batch_cols(rng, rows)
            b = jax.tree.map(jnp.asarray, make_batch(**c))
            st = init_history_state(cfg_k)
            st, p = seq_step(st, tparams, b, cfg_k)
            jax.block_until_ready(p)
            t0 = time.perf_counter()
            for _ in range(iters):
                st, p = seq_step(st, tparams, b, cfg_k)
            jax.block_until_ready(p)
            return {
                "txns_per_sec": round(
                    iters * rows / (time.perf_counter() - t0), 1),
                "batch_rows": rows,
                "history_len": history_len,
                # derived from the real dispatch, never hardcoded
                "attn": ("naive" if _attn_fn_for(cfg_k, history_len)
                         is None else "blockwise"),
            }

        seq_rows = 4096 if (args.quick or on_cpu) else 65536
        seq_stats = _measure_seq(
            32, seq_rows, iters=2 if (args.quick or on_cpu) else 20)
        seq_stats["d_model"] = 32
        seq_stats["backend"] = jax.default_backend()

        if full:
            # Long-context variant: K past seq_attn_block so the serving
            # transformer runs the blockwise (flash) attention — the
            # [B, H, K, K] naive form would OOM at production batch
            # sizes (137 GB at K=512/B=64k). Own guard: a failure here
            # records its own error key, never the base measurement's.
            _progress("sequence scorer long-history")
            try:
                lh_rows = 8192 if not on_cpu else 1024
                seq_stats["long_history"] = _measure_seq(
                    256, lh_rows, iters=2 if on_cpu else 10)
                # the point of this row is the flash path — refuse to
                # record a mislabeled naive measurement if the auto
                # threshold ever moves past 256
                assert seq_stats["long_history"]["attn"] == "blockwise"
                # Decomposition of the K=32 → K=256 gap (round-4 verdict:
                # the 11× drop mixed batch-size and attention cost).
                # K=32 at the SAME small batch isolates the batch-size
                # share; K=256 at the full batch (guarded — big
                # activations) isolates the attention share. Each row
                # guards itself so a failure never clobbers the
                # already-recorded long_history measurement.
                try:
                    seq_stats["k32_same_small_batch"] = _measure_seq(
                        32, lh_rows, iters=2 if on_cpu else 10)
                except Exception as e:
                    seq_stats["k32_same_small_batch"] = {
                        "error": f"{type(e).__name__}: {str(e)[:160]}"
                    }
                try:
                    seq_stats["long_history_full_batch"] = _measure_seq(
                        256, seq_rows, iters=2 if on_cpu else 5)
                except Exception as e:
                    seq_stats["long_history_full_batch"] = {
                        "error": f"{type(e).__name__}: {str(e)[:160]}"
                    }
            except Exception as e:
                seq_stats["long_history"] = {
                    "error": f"{type(e).__name__}: {str(e)[:160]}"
                }
    except Exception as e:
        seq_stats = {"error": f"{type(e).__name__}: {str(e)[:160]}"}

    # ---- host ingress: Debezium envelope decode rate --------------------
    # SURVEY's hard part: 1M txns/s of JSON envelopes bottlenecks on parse
    # before the TPU; the C++ scanner is the line-rate path.
    _progress("ingest decode rate")
    from real_time_fraud_detection_system_tpu.core import native
    from real_time_fraud_detection_system_tpu.core.envelope import (
        decode_transaction_envelopes_fast,
        encode_transaction_envelopes,
    )

    n_env = 20_000 if args.quick or on_cpu else 100_000
    env_cols = _make_batch_cols(rng, n_env)
    msgs = encode_transaction_envelopes(
        np.arange(n_env, dtype=np.int64), env_cols["tx_datetime_us"],
        env_cols["customer_id"], env_cols["terminal_id"],
        env_cols["amount_cents"],
    )
    decode_transaction_envelopes_fast(msgs[:256])  # warm (builds C++ lib)
    t0 = time.perf_counter()
    decode_transaction_envelopes_fast(msgs)
    ingest_rate = n_env / (time.perf_counter() - t0)

    # ---- MFU (model FLOPs only, bf16 peak denominator: a lower bound) ---
    flops_row = _model_flops_per_row(params)
    peak = _peak_flops(dev.device_kind)
    mfu = best_tps * flops_row / peak if peak > 0 else 0.0
    # Roofline ceiling: the hot path is bound by the featurize half —
    # scatter/gather passes over the window state in HBM (random access,
    # ~7 ms per 1M-row pass on v5e; ~20 passes for 3 windows × {count,
    # value} × {update, query} × {customer, terminal}) — NOT by the MXU.
    # The measured featurize-only rate IS that memory roofline, so the
    # achievable MFU ceiling for this op mix is featurize_rate ×
    # classify_flops / peak; mfu_of_ceiling says how much of the
    # achievable ceiling the headline captures (DESIGN.md §Roofline).
    # Measured UNCONDITIONALLY (round 9): the headline detail always
    # carries mfu/mfu_ceiling/mfu_of_ceiling, so every session's device-
    # plane claims have the same denominator on record (the pallas_forest
    # block's featurize figure is reused when it already measured one).
    mfu_ceiling = None
    mfu_of_ceiling = None
    featurize_rate = None
    if (isinstance(pallas_forest_stats, dict)
            and pallas_forest_stats.get("featurize_only_rows_per_s")):
        featurize_rate = float(
            pallas_forest_stats["featurize_only_rows_per_s"])
    else:
        _progress("featurize-only roofline")
        try:
            feat_rows = min(best_rows, 4096 if (on_cpu or args.quick)
                            else 262_144)

            def _feat_only(fstate, batch):
                fstate, feats = update_and_featurize(fstate, batch, fcfg)
                return fstate, feats.sum()

            jfeat = jax.jit(_feat_only, donate_argnums=(0,))
            fbatch = jax.tree.map(
                jnp.asarray, make_batch(**_make_batch_cols(rng, feat_rows)))
            ffs = init_feature_state(fcfg)
            ffs, fsum = jfeat(ffs, fbatch)
            jax.block_until_ready(fsum)

            def _feat_once():
                nonlocal ffs
                ffs, fsum = jfeat(ffs, fbatch)
                return fsum

            featurize_rate = _timed_rows_per_s(
                _feat_once, feat_rows, min(args.seconds, 2.0))
        except Exception as e:
            _progress(f"featurize-only failed: {type(e).__name__}: "
                      f"{str(e)[:120]}")
    if featurize_rate and peak > 0:
        mfu_ceiling = round(featurize_rate * flops_row / peak, 4)
        if mfu_ceiling > 0:
            mfu_of_ceiling = round(mfu / mfu_ceiling, 3)
    if isinstance(device_plane, dict) and peak > 0:
        # per-arm MFU annotation: the engine-level A/B reads as
        # mfu_of_ceiling before/after, not just rows/s
        device_plane["mfu_ceiling"] = mfu_ceiling
        for arm in device_plane.values():
            if isinstance(arm, dict) and "rows_per_s" in arm:
                arm_mfu = arm["rows_per_s"] * flops_row / peak
                arm["mfu"] = round(arm_mfu, 4)
                if mfu_ceiling:
                    arm["mfu_of_ceiling"] = round(arm_mfu / mfu_ceiling, 3)

    # ---- tiered feature-store scale curve (detail.state_scale) ----------
    # ROADMAP item 2's proof shape, extended to the host cold tier: key
    # universe 64k → 10M two-tier, then 100M with features.cold_store
    # (demote-don't-discard + async promote) × Zipf skew
    # with a BOUNDED hot tier (key_mode="exact") — loop rows/s must stay
    # flat (the state never grows past the working set), per-tier state
    # bytes must hold under --state-hbm-budget-mb (validated at engine
    # build), and the dense-tier hit rate quantifies what the sketch
    # tier absorbs. Also measures v2 delta-checkpoint bytes + restore
    # time of the bounded state against the dense-at-10M control's
    # static footprint.
    _progress("state scale")
    state_scale = None
    try:
        state_scale = _state_scale_block(args, on_cpu)
    except Exception as e:
        state_scale = {"error": f"{type(e).__name__}: {str(e)[:160]}"}

    # ---- sharded tiered-store scale matrix (detail.sharded_state_scale)
    # The scale-out half of the same proof: shards × {64k, 1M, 10M} Zipf
    # with per-shard directories — rows/s per shard count must stay flat
    # as the universe grows 1000×, per-shard dense hit rate and state
    # bytes reported from registry series, zero mid-stream recompiles
    # with per-shard compaction firing. Subprocess: needs the virtual
    # CPU mesh env this (possibly TPU-attached) process cannot adopt.
    _progress("sharded state scale (virtual CPU mesh)")
    sharded_state_scale = None
    try:
        sharded_state_scale = _run_cpu_mesh_tool(
            "sharded_state_scale_bench.py",
            ["--quick"] if (args.quick or on_cpu) else [],
            timeout_s=1800.0, label="sharded state scale running")
    except Exception as e:
        sharded_state_scale = {
            "error": f"{type(e).__name__}: {str(e)[:160]}"}

    # ---- multi-host scaling matrix (detail.multihost_scaling) ----------
    # ROADMAP item 1's proof: 1→2→4 REAL OS processes (launcher +
    # jax.distributed bootstrap + partition-affine ingest) over one
    # co-partitioned stream, per-process rate flat within 15% (rows per
    # process-CPU-second — wall rows/s on a shared-core box measures the
    # box, not the coordination cost), zero recompiles per worker from
    # each worker's own registry dump, no rows lost across the fleet.
    # Subprocess tool: the workers are independent interpreters anyway.
    _progress("multihost scaling (1/2/4 real processes)")
    multihost_scaling = None
    try:
        multihost_scaling = _run_cpu_mesh_tool(
            "multihost_scaling_bench.py",
            ["--quick"] if (args.quick or on_cpu) else [],
            timeout_s=2400.0, label="multihost scaling running")
    except Exception as e:
        multihost_scaling = {
            "error": f"{type(e).__name__}: {str(e)[:160]}"}

    # ---- elastic spike absorption (detail.elastic_absorb) --------------
    # ROADMAP item 4's proof: one 10x replay backlog driven into an
    # autoscaled fleet (--autoscale, real resize 1→2 mid-stream through
    # drain → merge → commit → relaunch) vs the identical fixed
    # 1-process control. Claims come from artifacts the fleets wrote:
    # rtfds_fleet_resizes_total{outcome=completed}==1 from the
    # launcher's registry snapshot, time-to-absorb from
    # rtfds_spike_absorb_seconds, exactly-once in both arms.
    _progress("elastic absorb (autoscaled vs fixed fleet)")
    elastic_absorb = None
    try:
        elastic_absorb = _run_cpu_mesh_tool(
            "elastic_absorb_bench.py",
            ["--quick"] if (args.quick or on_cpu) else [],
            timeout_s=1200.0, label="elastic absorb running")
    except Exception as e:
        elastic_absorb = {
            "error": f"{type(e).__name__}: {str(e)[:160]}"}

    # ---- CPU sklearn baseline (the reference-equivalent predict_proba) --
    # Measured at the headline batch size, capped at 65,536 rows per call
    # to bound a single predict_proba's cost; sklearn RF throughput is
    # batch-size-flat at that scale, so vs_baseline stays a fair
    # per-row-throughput comparison (cap recorded as cpu_baseline_rows).
    _progress("cpu baseline")
    vs = 0.0
    cpu_tps = None
    if skl is not None:
        base_rows = min(best_rows, 65536)  # bound a single call's cost
        feats = np.random.default_rng(1).normal(0, 1, (base_rows, 15))
        t0 = time.perf_counter()
        cpu_iters = 0
        while cpu_iters == 0 or time.perf_counter() - t0 < 2.0:
            skl.predict_proba(feats)
            cpu_iters += 1
        cpu_tps = cpu_iters * base_rows / (time.perf_counter() - t0)
        vs = best_tps / cpu_tps if cpu_tps > 0 else 0.0

    detail = {
        "model": args.model,
        "batch_rows": best_rows,
        "per_batch_ms": round(best_ms, 3),
        "txns_per_sec_by_batch": by_size,
        "p50_classify_ms": round(step_p50_ms, 3),
        "p99_classify_ms": round(step_p99_ms, 3),
        "latency_by_batch": latency_by_batch,
        "device_latency_by_batch": device_latency_by_batch,
        "rtt_per_call_ms": round(rtt_p50_ms, 3),
        "engine_loop": engine_stats,
        "mfu": round(mfu, 4),
        "mfu_ceiling": mfu_ceiling,
        "mfu_of_ceiling": mfu_of_ceiling,
        "headline_z_mode": headline_z_mode,
        "model_flops_per_row": flops_row,
        "peak_flops_assumed": peak,
        "device": str(dev),
        "device_kind": dev.device_kind,
        "backend": jax.default_backend(),
        "ingest_envelopes_per_sec": round(ingest_rate, 1),
        "ingest_decoder": "native" if native.native_available() else
        "python",
    }
    if phase_p50 is not None:
        # before/after per-phase p50 evidence: sync vs async sink,
        # precompile off vs on (mid_stream_recompiles is the proof)
        detail["phase_p50_ms"] = phase_p50
    if host_plane is not None:
        # engine-loop rows/s over a decode-heavy source with the host
        # data plane off vs on (parallel decode + prefetch + overlapped
        # fetch), same run protocol — the host-gap before/after
        detail["host_plane"] = host_plane
    if device_plane is not None:
        # serving-engine z_mode {f32,int8} × fused-step {off,on} A/B
        # under precompile, exactness asserted from served probs — the
        # engine-level successor of the round-4 z-mode microbench
        detail["device_plane"] = device_plane
    if train_stats is not None:
        detail["train"] = train_stats
    if pallas_stats is not None:
        detail["pallas_fused"] = pallas_stats
    if pallas_forest_stats is not None:
        detail["pallas_forest"] = pallas_forest_stats
    if seq_stats is not None:
        detail["sequence_scorer"] = seq_stats
    if cpu_tps is not None:
        detail["cpu_sklearn_txns_per_sec"] = round(cpu_tps, 1)
        detail["cpu_baseline_rows"] = base_rows
    if size_error:
        detail["size_scale_stopped"] = size_error
    if state_scale is not None:
        detail["state_scale"] = state_scale
    if sharded_state_scale is not None:
        detail["sharded_state_scale"] = sharded_state_scale
    if multihost_scaling is not None:
        detail["multihost_scaling"] = multihost_scaling
    if elastic_absorb is not None:
        detail["elastic_absorb"] = elastic_absorb

    # Registry snapshot beside the headline (ROADMAP PR-1 note): the
    # engine loops above populated rtfds_phase_seconds / rtfds_batch_
    # latency_seconds / rtfds_xla_* in the process registry — dump the
    # /metrics.json shape to a sidecar file so bench claims can cite
    # per-phase p50s instead of re-deriving them from RTT decomposition.
    snap_path = os.environ.get("BENCH_METRICS_OUT", "BENCH_METRICS.json")
    try:
        from real_time_fraud_detection_system_tpu.utils.metrics import (
            get_registry,
        )

        with open(snap_path, "w", encoding="utf-8") as f:
            json.dump(get_registry().snapshot(), f)
        detail["metrics_snapshot"] = snap_path
    except Exception as e:  # never let telemetry dumping kill the bench
        detail["metrics_snapshot_error"] = f"{type(e).__name__}: {e}"

    value = round(best_tps, 1)
    if on_cpu and cpu_tps:
        # On CPU the framework serves via the sklearn oracle
        # (``--scorer cpu`` — the reference-equivalent pipeline), so THAT
        # is the honest CPU headline. The MXU-shaped GEMM kernel run on
        # CPU is reported alongside, clearly labeled — it is a TPU kernel
        # being interpreted on the wrong hardware, not a regression.
        detail["cpu_headline"] = "sklearn_oracle (--scorer cpu path)"
        detail["jax_cpu_txns_per_sec"] = round(best_tps, 1)
        value = round(cpu_tps, 1)
        vs = 1.0
    print(json.dumps({
        "metric": "score_txns_per_sec",
        "value": value,
        "unit": "txns/s",
        "vs_baseline": round(vs, 3),
        "detail": detail,
    }))


class _ZipfSource:
    """Pre-generated Zipf-skewed micro-batches over an ``n_keys``
    universe with the day advancing every few batches (so recency
    compaction has dead history to reclaim). Generation cost stays
    outside the measured loop, like ``_RandSource``."""

    def __init__(self, n_batches: int, rows: int, sampler, day_every: int,
                 seed: int = 2):
        from real_time_fraud_detection_system_tpu.data.generator import (
            zipf_stream_cols,
        )

        rng = np.random.default_rng(seed)
        self._batches = [
            zipf_stream_cols(rng, rows, sampler,
                             n_terminals=max(sampler.n_keys // 8, 64),
                             day=20200 + b // day_every,
                             tx_id_start=b * rows)
            for b in range(n_batches)
        ]
        self._i = 0

    def poll_batch(self):
        if self._i >= len(self._batches):
            return None
        b = self._batches[self._i]
        self._i += 1
        return b

    @property
    def offsets(self):
        return [self._i]

    def seek(self, offsets):
        self._i = int(offsets[0])


def _state_scale_block(args, on_cpu: bool) -> dict:
    """The ``detail.state_scale`` measurement (see call-site comment)."""
    import dataclasses as _dc
    import tempfile

    from real_time_fraud_detection_system_tpu.config import (
        Config,
        FeatureConfig,
        RuntimeConfig,
    )
    from real_time_fraud_detection_system_tpu.data.generator import (
        ZipfKeySampler,
    )
    from real_time_fraud_detection_system_tpu.features.online import (
        state_bytes,
    )
    from real_time_fraud_detection_system_tpu.io.checkpoint import (
        Checkpointer,
    )
    from real_time_fraud_detection_system_tpu.models.logreg import (
        init_logreg,
    )
    from real_time_fraud_detection_system_tpu.models.scaler import Scaler
    from real_time_fraud_detection_system_tpu.runtime.engine import (
        ScoringEngine,
    )
    from real_time_fraud_detection_system_tpu.utils.metrics import (
        MetricsRegistry,
    )

    small = on_cpu or args.quick
    rows = 4096 if small else 65536
    n_batches = 8 if small else 24
    skew = 1.1
    budget_mb = args.state_hbm_budget_mb or 256.0
    fcfg = FeatureConfig(
        key_mode="exact",
        customer_capacity=1 << 15,
        terminal_capacity=1 << 15,
        cms_width=1 << 15,
        compact_every=4,
        state_hbm_budget_mb=budget_mb,
    )
    cfg = Config(
        features=fcfg,
        runtime=RuntimeConfig(batch_buckets=(rows,), max_batch_rows=rows,
                              precompile=True),
    )
    scaler = Scaler(mean=np.zeros(15, np.float32),
                    scale=np.ones(15, np.float32))
    sb = state_bytes(fcfg)
    out = {
        "skew": skew,
        "batch_rows": rows,
        "hot_tier_slots": fcfg.customer_capacity + fcfg.terminal_capacity,
        "hbm_budget_mb": budget_mb,
        "state_bytes": sb,
        "within_budget": sb["total"] <= budget_mb * 2 ** 20,
        "universes": {},
    }
    base_rate = None
    last_engine = None
    for n_keys in (65536, 1 << 20, 10_000_000):
        _progress(f"state scale universe {n_keys}")
        sampler = ZipfKeySampler(n_keys, skew)
        reg = MetricsRegistry()
        eng = ScoringEngine(cfg, kind="logreg", params=init_logreg(15),
                            scaler=scaler, metrics=reg)
        eng.run(_ZipfSource(2, rows, sampler, day_every=1, seed=7))  # warm
        stats = eng.run(_ZipfSource(n_batches, rows, sampler,
                                    day_every=max(n_batches // 6, 1)))
        dense = reg.get("rtfds_feature_tier_rows_total", tier="dense")
        cms = reg.get("rtfds_feature_tier_rows_total", tier="cms")
        d = dense.value if dense is not None else 0.0
        c = cms.value if cms is not None else 0.0
        rec = reg.family_total("rtfds_feature_slots_reclaimed_total") or 0
        recompiles = reg.get("rtfds_xla_recompiles_total")
        rate = stats["rows_per_s"]
        if base_rate is None:
            base_rate = rate
        out["universes"][str(n_keys)] = {
            "rows_per_s": round(rate, 1),
            "vs_64k": round(rate / base_rate, 3) if base_rate else None,
            "dense_hit_rate": round(d / (d + c), 4) if d + c else 1.0,
            "slots_reclaimed": int(rec),
            "mid_stream_recompiles": (recompiles.value
                                      if recompiles is not None else 0.0),
        }
        last_engine = eng
    # ---- 100M-key cold-tier cell ------------------------------------
    # The third tier's proof: same bounded 2×32k-slot hot tier, 10× the
    # 10M directory sweep — compaction DEMOTES evicted keys' exact rows
    # to host segments (features.cold_store) instead of discarding them,
    # and returning keys promote back asynchronously. rows/s must stay
    # within 15% of the 64k baseline, HBM stays the same static
    # state_bytes() (the cold tier is host memory/disk), and the
    # demotion/promotion counters + exactness_degraded_keys scope the
    # bit-identity claim honestly.
    n_cold = 100_000_000
    _progress(f"state scale universe {n_cold} (cold tier)")
    with tempfile.TemporaryDirectory() as td_cold:
        cold_fcfg = _dc.replace(fcfg, cold_store=td_cold,
                                cold_demote_slots=1024,
                                cold_promote_queue=256)
        cold_cfg = cfg.replace(features=cold_fcfg)
        sampler = ZipfKeySampler(n_cold, skew)
        reg = MetricsRegistry()
        eng = ScoringEngine(cold_cfg, kind="logreg",
                            params=init_logreg(15), scaler=scaler,
                            metrics=reg)
        eng.run(_ZipfSource(2, rows, sampler, day_every=1, seed=7))
        stats = eng.run(_ZipfSource(n_batches, rows, sampler,
                                    day_every=max(n_batches // 6, 1)))
        eng.drain_promotions()
        dense = reg.get("rtfds_feature_tier_rows_total", tier="dense")
        cms = reg.get("rtfds_feature_tier_rows_total", tier="cms")
        d = dense.value if dense is not None else 0.0
        c = cms.value if cms is not None else 0.0
        recompiles = reg.get("rtfds_xla_recompiles_total")

        def _mval(name):
            m = reg.get(name)
            return m.value if m is not None else 0.0

        rate = stats["rows_per_s"]
        out["universes"][str(n_cold)] = {
            "rows_per_s": round(rate, 1),
            "vs_64k": round(rate / base_rate, 3) if base_rate else None,
            "dense_hit_rate": round(d / (d + c), 4) if d + c else 1.0,
            "mid_stream_recompiles": (recompiles.value
                                      if recompiles is not None else 0.0),
            "exactness_degraded_keys": int(
                stats.get("exactness_degraded_keys", 0)),
            "cold": {
                "keys": int(_mval("rtfds_feature_cold_keys")),
                "bytes": int(_mval("rtfds_feature_cold_bytes")),
                "demotions": int(
                    _mval("rtfds_feature_cold_demotions_total")),
                "promotions": int(
                    _mval("rtfds_feature_cold_promotions_total")),
                "promote_wait_s": round(_mval(
                    "rtfds_feature_cold_promote_wait_seconds_total"), 3),
            },
        }
        out["flat_100m_within_15pct"] = (
            bool(rate >= 0.85 * base_rate) if base_rate else None)
    # delta-checkpoint cost of the bounded state vs the dense-at-10M
    # control (static accounting: direct mode needs capacity >= universe)
    dense_cap = 1 << 24  # next pow2 >= 10M
    dense_fcfg = _dc.replace(fcfg, key_mode="direct",
                             customer_capacity=dense_cap,
                             compact_every=0, state_hbm_budget_mb=0.0)
    out["dense_control_state_bytes"] = state_bytes(dense_fcfg)
    with tempfile.TemporaryDirectory() as td:
        ck = Checkpointer(td, full_every=4)
        ck.save(last_engine.state)  # full
        sizes0 = {f: os.path.getsize(os.path.join(td, f))
                  for f in os.listdir(td) if f.endswith(".npz")}
        sampler = ZipfKeySampler(10_000_000, skew)
        last_engine.run(_ZipfSource(2, rows, sampler, day_every=1,
                                    seed=11))
        ck.save(last_engine.state)  # delta vs the full above
        sizes1 = {f: os.path.getsize(os.path.join(td, f))
                  for f in os.listdir(td) if f.endswith(".npz")}
        delta_files = sorted(set(sizes1) - set(sizes0))
        t0 = time.perf_counter()
        ck.restore(last_engine.state)
        restore_s = time.perf_counter() - t0
        out["checkpoint"] = {
            "full_bytes": max(sizes0.values()),
            "delta_bytes": (sizes1[delta_files[0]] if delta_files
                            else None),
            "restore_s": round(restore_s, 3),
        }
    return out


def _parse_args(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--model", default="forest",
                    choices=["forest", "logreg"])
    ap.add_argument("--seconds", type=float, default=5.0)
    ap.add_argument("--state-hbm-budget-mb", type=float, default=0.0,
                    help="HBM budget for the detail.state_scale curve's "
                         "tiered feature state, validated at engine "
                         "build (0 = the block's 256 MB default)")
    ap.add_argument("--probe-timeout", type=float, default=0.0,
                    help="liveness budget (s) for the FIRST TPU attempt "
                         "— how long backend bring-up may take before "
                         "the probe is declared dead (0 = auto: 600, or "
                         "300 with --quick). A dead probe is CACHED: "
                         "the ladder stops re-attempting and falls back "
                         "to CPU immediately instead of burning the "
                         "bench window 300 s at a time")
    return ap.parse_args(argv)


def _emit_final(result: dict) -> None:
    """Print the full result JSON, then a compact headline line LAST.

    The driver records only a tail window of stdout; the full detail dict
    grew long enough that the leading ``"metric"/"value"`` keys fell out
    of that window (round-4 `BENCH_r04.json` has ``parsed: null``). The
    compact line — same schema, ``detail`` reduced to backend/device —
    is printed last so the tail window always contains one complete,
    parseable result line. The full line directly above it carries the
    complete detail for humans and for session artifacts.
    """
    print(json.dumps(result), flush=True)
    detail = result.get("detail", {}) or {}
    compact = {
        "metric": result.get("metric", "score_txns_per_sec"),
        "value": result.get("value", 0.0),
        "unit": result.get("unit", "txns/s"),
        "vs_baseline": result.get("vs_baseline", 0.0),
        "detail": {
            "backend": detail.get("backend"),
            "device_kind": detail.get("device_kind"),
            "tpu_attempts": detail.get("tpu_attempts"),
            "fallback": detail.get("fallback"),
            "full_detail": "see the full JSON line above",
        },
    }
    print(json.dumps(compact), flush=True)


def _run_child(args, platform, liveness_s, settle_s, hard_cap_s):
    """Run the measurement child with streamed-stdout supervision.

    Timeline: the child must print ``BENCH_ALIVE`` (emitted the moment
    ``jax.devices()`` returns) within ``liveness_s``; after that, every
    further stdout line re-arms a ``settle_s`` timer (compile + measure
    per size each end with a ``BENCH_PROGRESS`` line). ``hard_cap_s``
    bounds the whole attempt regardless of chattiness.

    → (parsed_json_or_None, error_string_or_None).
    """
    env = dict(os.environ)
    env["BENCH_ROLE"] = "child"
    env["PYTHONUNBUFFERED"] = "1"
    if platform is not None:
        env["JAX_PLATFORMS"] = platform
    cmd = [sys.executable, os.path.abspath(__file__),
           "--model", args.model, "--seconds", str(args.seconds),
           "--state-hbm-budget-mb", str(args.state_hbm_budget_mb)]
    if args.quick:
        cmd.append("--quick")

    proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True, bufsize=1)
    _LIVE_PROCS.append(proc)
    lines: list = []
    last_line_t = [time.monotonic()]
    alive_t: list = []
    stderr_buf: list = []

    def _pump_out():
        for ln in proc.stdout:
            ln = ln.rstrip("\n")
            if not ln.strip():
                continue
            lines.append(ln)
            last_line_t[0] = time.monotonic()
            if ln.startswith(ALIVE_LINE) and not alive_t:
                alive_t.append(time.monotonic())

    def _pump_err():
        for ln in proc.stderr:
            stderr_buf.append(ln.rstrip("\n"))

    t_out = threading.Thread(target=_pump_out, daemon=True)
    t_err = threading.Thread(target=_pump_err, daemon=True)
    t_out.start()
    t_err.start()

    t0 = time.monotonic()
    killed_why = None
    while proc.poll() is None:
        now = time.monotonic()
        if now - t0 > hard_cap_s:
            killed_why = f"hard cap {hard_cap_s:.0f}s exceeded"
        elif not alive_t and now - t0 > liveness_s:
            killed_why = (
                f"no liveness within {liveness_s:.0f}s "
                "(backend bring-up hung)"
            )
        elif alive_t and now - last_line_t[0] > settle_s:
            killed_why = (
                f"live child stalled: no output for {settle_s:.0f}s "
                f"(last: {lines[-1][:80] if lines else '<none>'})"
            )
        if killed_why:
            proc.kill()
            proc.wait()
            break
        time.sleep(1.0)
    t_out.join(timeout=10.0)
    t_err.join(timeout=10.0)
    if proc in _LIVE_PROCS:
        _LIVE_PROCS.remove(proc)

    if killed_why:
        return None, killed_why
    if proc.returncode == 0 and lines:
        for ln in reversed(lines):
            if ln.startswith("{"):
                try:
                    return json.loads(ln), None
                except json.JSONDecodeError:
                    break
    tail = stderr_buf or lines
    return None, (
        f"rc={proc.returncode}: " + " | ".join(tail[-3:])[-400:]
    )


def main() -> None:
    args = _parse_args()
    if os.environ.get("BENCH_ROLE") == "child":
        _child_main(args)
        return

    ambient = os.environ.get("JAX_PLATFORMS", "")
    if ambient and "cpu" in ambient and "axon" not in ambient \
            and "tpu" not in ambient:
        # Caller pinned a CPU-only platform (sandbox smoke run): one
        # attempt. An ambient TPU platform (the driver's tunnel env sets
        # JAX_PLATFORMS=axon) still gets the TPU attempt ladder.
        result, err = _run_child(args, ambient, 300.0, 300.0, 900.0)
        if result is not None:
            _emit_final(result)
            return
        print(json.dumps({
            "metric": "score_txns_per_sec", "value": 0.0,
            "unit": "txns/s", "vs_baseline": 0.0, "error": str(err)[-600:],
        }))
        sys.exit(1)

    # The tunnel's observed behavior (rounds 1-3): when healthy,
    # jax.devices() returns in <1 s (occasionally ~500 s while warming);
    # when sick, it hangs forever — and can come back at ANY point in a
    # multi-hour window. One patient attempt therefore loses whenever the
    # tunnel recovers after its liveness budget expires. The ladder:
    #
    #   1. one TPU attempt with a 600 s liveness budget (covers the
    #      slow-but-live bring-up);
    #   2. bank the CPU fallback measurement (the honest sklearn-oracle
    #      headline) — an answer now exists no matter what;
    #   3. keep re-attempting TPU with 300 s budgets, 60 s apart, until
    #      the BENCH_WINDOW_S wall clock (default 2700 s) runs out;
    #   4. emit the TPU result the moment an attempt lands; else the
    #      banked CPU result with the attempt log.
    #
    # SIGTERM/SIGINT mid-ladder prints the banked result before dying so
    # an impatient caller still gets a parseable line.
    import signal

    try:
        window_s = float(os.environ.get("BENCH_WINDOW_S",
                                        "600" if args.quick else "2700"))
    except ValueError:
        window_s = 2700.0
    t_start = time.monotonic()

    def _remaining() -> float:
        return window_s - (time.monotonic() - t_start)

    errors: list = []
    banked: list = []  # [result] once the CPU fallback lands

    def _emit_banked_and_exit(signum=None, frame=None):
        for p in list(_LIVE_PROCS):  # no orphans holding the TPU
            try:
                p.kill()
            except OSError:
                pass
        if banked:
            banked[0].setdefault("detail", {})["fallback"] = "cpu"
            banked[0]["detail"]["tpu_errors"] = errors[-3:]
            _emit_final(banked[0])
            sys.exit(0)
        sys.exit(1)

    signal.signal(signal.SIGTERM, _emit_banked_and_exit)
    signal.signal(signal.SIGINT, _emit_banked_and_exit)

    def _tpu_attempt(liveness_s: float):
        # Hard cap: a full measurement pass is ~25 min warm, ~30+ cold
        # (every section recompiles over the tunnel) — the cap must
        # outlast a COLD pass or the driver's run dies mid-measurement.
        # Returns the attempt's error string (None only on the success
        # path, which exits) so the caller can classify dead probes.
        result, err = _run_child(args, None, liveness_s, 420.0,
                                 liveness_s + 2700.0)
        if result is not None:
            d = result.setdefault("detail", {})
            d["tpu_attempts"] = len(errors) + 1
            if errors:
                d["tpu_errors"] = errors[-3:]
            _emit_final(result)
            sys.exit(0)
        errors.append(err)
        print(f"# tpu attempt {len(errors)} failed: {err}",
              file=sys.stderr, flush=True)
        return err

    def _probe_dead(err) -> bool:
        # the no-liveness kill means jax.devices() never returned —
        # nothing was listening behind the tunnel (vs a child that came
        # up and then crashed/stalled mid-measurement, which is worth
        # re-attempting: the backend exists)
        return err is not None and "no liveness" in str(err)

    err = _tpu_attempt(args.probe_timeout
                       or (300.0 if args.quick else 600.0))
    # Cache the liveness verdict: BENCH_r05 burned 3 × 300 s of its
    # window re-probing a tunnel that never answered once. A dead first
    # probe means dead backend for this run — bank the CPU fallback and
    # emit it immediately; only a child that PROVED the backend alive
    # (printed BENCH_ALIVE, then failed later) earns re-attempts.
    backend_dead = _probe_dead(err)
    if backend_dead:
        print("# tpu probe dead (no liveness): caching the verdict, "
              "falling back to cpu without re-attempts",
              file=sys.stderr, flush=True)

    cpu_result, cpu_err = _run_child(args, "cpu", 300.0, 300.0, 1200.0)
    cpu_errors: list = []
    if cpu_result is not None:
        if backend_dead:
            cpu_result.setdefault("detail", {})["tpu_liveness"] = "dead"
        banked.append(cpu_result)
    else:
        # kept OUT of `errors`: that list counts TPU attempts and feeds
        # detail.tpu_errors; a CPU failure would misreport both
        cpu_errors.append(f"cpu fallback: {cpu_err}")

    while not backend_dead and _remaining() > 300.0:
        time.sleep(min(60.0, max(0.0, _remaining() - 300.0)))
        err = _tpu_attempt(min(300.0, _remaining() - 60.0))
        if _probe_dead(err):
            backend_dead = True  # the tunnel died mid-window: stop here
            if banked:
                banked[0].setdefault("detail", {})["tpu_liveness"] = "dead"

    if banked:
        _emit_banked_and_exit()
    print(json.dumps({
        "metric": "score_txns_per_sec",
        "value": 0.0,
        "unit": "txns/s",
        "vs_baseline": 0.0,
        "error": " || ".join(str(e) for e in errors + cpu_errors)[-600:],
    }))
    sys.exit(1)


if __name__ == "__main__":
    main()
