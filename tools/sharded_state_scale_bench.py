"""Sharded tiered-feature-store scale matrix: shards × key universe.

The sharded half of ROADMAP item 2's proof shape (``bench.py`` records
it as ``detail.sharded_state_scale``): drive the SHARDED exact engine
(per-shard key directories + sketch replicas, ``key_mode="exact"``)
over a Zipf-skewed stream while the key universe grows 64k → 1M → 10M
with the hot tier FIXED, at 2 and 4 virtual devices, under
``--precompile``. The claims this matrix substantiates:

- rows/s at a 10M-key universe stays within ~10% of the SAME shard
  count's 64k baseline (state work is bounded by the working set, not
  the universe — the coordination cost stays flat as keys grow 1000×);
- zero mid-stream recompiles with per-shard compaction firing
  (``rtfds_xla_recompiles_total`` from the registry, not prints);
- per-shard dense hit rate and per-shard state bytes come from the
  REGISTRY series (``rtfds_feature_tier_rows_total{tier,shard}``,
  ``rtfds_feature_state_bytes{tier}``), the same numbers ``/healthz``
  serves.

All widths run on the same host cores (virtual CPU mesh), so the claim
is flat rows/s per width across universes — not wall-clock speedup.

Prints ONE JSON line. Run standalone
(``python tools/sharded_state_scale_bench.py [--quick]``) or let
``bench.py`` spawn it.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


class _ZipfSource:
    """Pre-generated Zipf micro-batches with the day advancing every few
    batches (so per-shard recency compaction has dead history to
    reclaim). Generation cost stays outside the measured loop."""

    def __init__(self, n_batches: int, rows: int, sampler, day_every: int,
                 seed: int = 2):
        from real_time_fraud_detection_system_tpu.data.generator import (
            zipf_stream_cols,
        )

        rng = np.random.default_rng(seed)
        self._batches = [
            zipf_stream_cols(rng, rows, sampler,
                             n_terminals=max(sampler.n_keys // 8, 64),
                             day=20200 + b // day_every,
                             tx_id_start=b * rows)
            for b in range(n_batches)
        ]
        self._i = 0

    def poll_batch(self):
        if self._i >= len(self._batches):
            return None
        b = self._batches[self._i]
        self._i += 1
        return b

    @property
    def offsets(self):
        return [self._i]

    def seek(self, offsets):
        self._i = int(offsets[0])


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--rows", type=int, default=16384)
    ap.add_argument("--batches", type=int, default=6)
    ap.add_argument("--shards", type=int, nargs="*", default=[2, 4])
    args = ap.parse_args()

    from real_time_fraud_detection_system_tpu.config import (
        Config,
        FeatureConfig,
        RuntimeConfig,
    )
    from real_time_fraud_detection_system_tpu.data.generator import (
        ZipfKeySampler,
    )
    from real_time_fraud_detection_system_tpu.models.logreg import (
        init_logreg,
    )
    from real_time_fraud_detection_system_tpu.models.scaler import Scaler
    from real_time_fraud_detection_system_tpu.runtime import (
        ShardedScoringEngine,
    )
    from real_time_fraud_detection_system_tpu.utils.metrics import (
        MetricsRegistry,
    )

    rows = 4096 if args.quick else args.rows
    n_meas = 3 if args.quick else args.batches
    skew = 1.1
    fcfg = FeatureConfig(
        key_mode="exact",
        customer_capacity=1 << 15,
        terminal_capacity=1 << 15,
        cms_width=1 << 14,
        compact_every=2,
    )
    cfg = Config(
        features=fcfg,
        runtime=RuntimeConfig(batch_buckets=(rows,), max_batch_rows=rows,
                              precompile=True),
    )
    params = init_logreg(15)
    scaler = Scaler(mean=np.zeros(15, np.float32),
                    scale=np.ones(15, np.float32))

    result = {
        "skew": skew,
        "batch_rows": rows,
        "batches": n_meas,
        "hot_tier_slots": fcfg.customer_capacity + fcfg.terminal_capacity,
        "host_cores": os.cpu_count(),
        "note": ("virtual CPU mesh on shared host cores: the claim is "
                 "flat rows/s per shard count as the universe grows "
                 "1000x (vs_64k within ~0.9), with per-shard hit rate "
                 "and state bytes from the registry"),
        "by_shards": {},
    }
    for n_dev in args.shards:
        if n_dev > jax.device_count():
            result["by_shards"][str(n_dev)] = {
                "skipped": f"needs {n_dev} devices, "
                           f"{jax.device_count()} visible"}
            continue
        cell: dict = {}
        base_rate = None
        for n_keys in (65536, 1 << 20, 10_000_000):
            sampler = ZipfKeySampler(n_keys, skew)
            reg = MetricsRegistry()
            eng = ShardedScoringEngine(
                cfg, kind="logreg", params=params, scaler=scaler,
                n_devices=n_dev, metrics=reg)
            eng.run(_ZipfSource(2, rows, sampler, day_every=1, seed=7))
            stats = eng.run(_ZipfSource(
                n_meas, rows, sampler,
                day_every=max(n_meas // 3, 1)))
            rate = stats["rows_per_s"]
            if base_rate is None:
                base_rate = rate
            per_shard_hit = {}
            for s in range(n_dev):
                d = reg.get("rtfds_feature_tier_rows_total",
                            tier="dense", shard=str(s))
                c = reg.get("rtfds_feature_tier_rows_total",
                            tier="cms", shard=str(s))
                dv = d.value if d is not None else 0.0
                cv = c.value if c is not None else 0.0
                per_shard_hit[str(s)] = (
                    round(dv / (dv + cv), 4) if dv + cv else 1.0)
            sb = {
                tier: reg.get("rtfds_feature_state_bytes",
                              tier=tier).value
                for tier in ("dense", "directory", "cms", "total")
            }
            rc = reg.get("rtfds_xla_recompiles_total")
            rec_rows = [
                v for labels, v in reg.family_series(
                    "rtfds_feature_slots_reclaimed_total")
                if "shard" in labels and labels.get("table") == "terminal"]
            cell[str(n_keys)] = {
                "rows_per_s": round(rate, 1),
                "vs_64k": (round(rate / base_rate, 3)
                           if base_rate else None),
                "dense_hit_rate_per_shard": per_shard_hit,
                "state_bytes_per_shard": {
                    k: int(v) // n_dev for k, v in sb.items()},
                "shards_reclaiming": sum(1 for v in rec_rows if v > 0),
                "mid_stream_recompiles": (rc.value if rc is not None
                                          else 0.0),
            }
            print(f"# shards={n_dev} universe={n_keys}: "
                  f"{cell[str(n_keys)]['rows_per_s']} rows/s "
                  f"(vs_64k {cell[str(n_keys)]['vs_64k']})",
                  file=sys.stderr, flush=True)
        cell["flat_within_10pct"] = all(
            u.get("vs_64k", 1.0) is None or u["vs_64k"] >= 0.9
            for u in cell.values() if isinstance(u, dict))
        result["by_shards"][str(n_dev)] = cell
    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
