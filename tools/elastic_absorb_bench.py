"""Elastic-fleet spike absorption: autoscaled fleet vs fixed control.

ROADMAP item 4's proof shape (``bench.py`` records it as
``detail.elastic_absorb``): drive a 10x ingest spike (a replay backlog
ten times the overload ladder's lag high-water mark) into

- an ELASTIC fleet: ``tools/multihost_launcher.py --autoscale`` starts
  at 1 process, observes the worst-process rung through real worker
  registries, and resizes 1 -> 2 mid-stream through the full
  drain -> merge -> commit -> relaunch window;
- a FIXED control: the identical worker, same ladder, same stream, no
  autoscaler — it rides the spike alone.

Reported, all from artifacts the fleets themselves wrote (report JSON,
worker registry dumps, the launcher's own metric snapshot):

- ``rtfds_fleet_resizes_total{outcome=completed}`` == 1 in the elastic
  arm (the resize actually happened, from the registry counter);
- time-to-absorb (``rtfds_spike_absorb_seconds``: first grow-rung
  observation until the fleet is back at rung <= 1);
- wall time to drain the identical backlog, elastic vs fixed — the
  capacity claim (the second generation pays its own jax startup, so
  the win must survive that);
- rows deferred by the admission ladder per arm (``rtfds_shed_rows_
  total`` — rung-3 deferrals, all replayed; exactly-once holds in BOTH
  arms: fleet rows_total == stream rows).

Exactness across the resize is pinned in ``tests/test_elastic_smoke.py``;
this bench measures absorption. Prints ONE JSON line. Run standalone
(``python tools/elastic_absorb_bench.py [--quick]``) or let ``bench.py``
spawn it.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import socket
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _make_dataset(path: str, n_rows: int, seed: int = 11) -> None:
    """Co-partitioned stream (terminal residues track customer residues
    for fleets up to 2) — the partitioned deployment's exactness
    contract, same recipe as the multihost scaling matrix."""
    import numpy as np

    from real_time_fraud_detection_system_tpu.data.generator import (
        Transactions,
    )
    from real_time_fraud_detection_system_tpu.io.artifacts import (
        save_transactions,
    )

    rng = np.random.default_rng(seed)
    cust = rng.integers(0, 2048, n_rows).astype(np.int64)
    term = (rng.integers(0, 512, n_rows) * 2
            + (cust % 2)).astype(np.int64)
    t_s = np.sort(rng.integers(0, 30 * 86400, n_rows)).astype(np.int64)
    save_transactions(path, Transactions(
        tx_id=np.arange(n_rows, dtype=np.int64),
        tx_time_seconds=t_s,
        tx_time_days=(t_s // 86400).astype(np.int32),
        customer_id=cust,
        terminal_id=term,
        amount_cents=(rng.integers(1, 500, n_rows) * 100
                      ).astype(np.int64),
        tx_fraud=np.zeros(n_rows, np.int8),
        tx_fraud_scenario=np.zeros(n_rows, np.int8)))


def _make_model(path: str) -> None:
    import numpy as np

    from real_time_fraud_detection_system_tpu.io.artifacts import (
        save_model,
    )
    from real_time_fraud_detection_system_tpu.models.logreg import (
        init_logreg,
    )
    from real_time_fraud_detection_system_tpu.models.scaler import Scaler
    from real_time_fraud_detection_system_tpu.models.train import (
        TrainedModel,
    )

    save_model(path, TrainedModel(
        kind="logreg",
        scaler=Scaler(mean=np.zeros(15, np.float32),
                      scale=np.ones(15, np.float32)),
        params=init_logreg(15)))


def _port_base() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _shed_total(dumps_dir: str) -> float:
    import glob

    total = 0.0
    for path in glob.glob(os.path.join(dumps_dir, "*.json")):
        with open(path, "r", encoding="utf-8") as f:
            snap = json.load(f)
        total += sum(float(r.get("value", 0.0) or 0.0) for r in
                     snap.get("rtfds_shed_rows_total",
                              {}).get("series", []))
    return total


def _score_args(data: str, model: str, out: str, ckpt: str,
                dumps: str, lag_high: int, batch_rows: int) -> list:
    return ["--", "score", "--source", "replay", "--data", data,
            "--model-file", model, "--scorer", "tpu", "--precompile",
            "--devices", "1", "--batch-rows", str(batch_rows),
            "--max-batch-rows", str(batch_rows),
            "--out", out, "--checkpoint-dir", ckpt,
            "--overload", "--overload-lag-high", str(lag_high),
            "--overload-climb-dwell", "1",
            "--overload-spill", os.path.join(dumps, "spill-{proc}"),
            "--metrics-dump", os.path.join(dumps, "{proc}.json")]


def _run(cmd: list, timeout_s: float, label: str) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    t0 = time.monotonic()
    p = subprocess.run(cmd, env=env, stdout=subprocess.PIPE,
                       stderr=subprocess.PIPE, text=True,
                       timeout=timeout_s)
    wall = time.monotonic() - t0
    lines = [ln for ln in p.stdout.splitlines() if ln.startswith("{")]
    if p.returncode != 0 or not lines:
        raise RuntimeError(f"{label} rc={p.returncode}: "
                           f"{p.stderr.strip()[-300:]}")
    return {"report": json.loads(lines[-1]), "wall_s": round(wall, 2)}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--rows", type=int, default=163840)
    ap.add_argument("--batch-rows", type=int, default=128)
    ap.add_argument("--timeout", type=float, default=420.0)
    args = ap.parse_args()

    n_rows = 81920 if args.quick else args.rows
    lag_high = n_rows // 10  # the backlog IS a 10x spike by construction
    work = tempfile.mkdtemp(prefix="rtfds-elastic-")
    launcher = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "multihost_launcher.py")
    result = {
        "rows": n_rows,
        "overload_lag_high": lag_high,
        "spike_ratio": round(n_rows / lag_high, 1),
        "batch_rows": args.batch_rows,
        "host_cores": os.cpu_count(),
        "note": ("One 10x replay backlog into an autoscaled 1->2 fleet "
                 "vs the identical fixed 1-process control. Elastic "
                 "wall time includes the resize window AND the second "
                 "generation's own jax startup — the absorb win must "
                 "pay for the machinery that produced it. Deferred "
                 "rows are rung-3 admission holds, all replayed; "
                 "exactly-once holds in both arms."),
    }
    try:
        data = os.path.join(work, "txs.npz")
        model = os.path.join(work, "model.npz")
        _make_dataset(data, n_rows)
        _make_model(model)

        # ---- elastic arm: autoscaled 1 -> 2 --------------------------
        el = os.path.join(work, "elastic")
        el_dumps = os.path.join(el, "dumps")
        os.makedirs(el_dumps, exist_ok=True)
        el_run = _run(
            [sys.executable, launcher, "--processes", "1",
             "--no-coordinator", "--autoscale",
             "--autoscale-min", "1", "--autoscale-max", "2",
             "--autoscale-grow-rung", "2",
             "--autoscale-grow-dwell", "1.0",
             "--autoscale-shrink-dwell", "600",
             "--autoscale-cooldown", "3",
             "--autoscale-interval", "0.2", "--max-resizes", "1",
             "--worker-metrics-base", str(_port_base()),
             "--workdir", os.path.join(el, "wd"),
             "--timeout", str(args.timeout)]
            + _score_args(data, model,
                          os.path.join(el, "out", "{gen}"),
                          os.path.join(el, "ckpt", "{gen}"),
                          el_dumps, lag_high, args.batch_rows),
            args.timeout + 120, "elastic arm")
        with open(os.path.join(el, "wd", "launcher-metrics.json"),
                  encoding="utf-8") as f:
            lm = json.load(f)
        completed = sum(
            float(r.get("value", 0.0) or 0.0)
            for r in lm.get("rtfds_fleet_resizes_total",
                            {}).get("series", [])
            if (r.get("labels") or {}).get("outcome") == "completed")
        auto = el_run["report"]["autoscale"]
        result["elastic"] = {
            "wall_s": el_run["wall_s"],
            "rows_total": el_run["report"]["rows_total"],
            "resizes_completed": completed,
            "spike_absorb_s": auto["spike_absorb_s"],
            "resize_window_s": (auto.get("last_resize") or {}
                                ).get("seconds"),
            "final_processes": auto["current"],
            "deferred_rows": _shed_total(el_dumps),
        }
        print(f"# elastic: {el_run['wall_s']}s wall, absorb "
              f"{auto['spike_absorb_s']}s, {completed:.0f} resize(s)",
              file=sys.stderr, flush=True)

        # ---- fixed control: same worker, no autoscaler ---------------
        fx = os.path.join(work, "fixed")
        fx_dumps = os.path.join(fx, "dumps")
        os.makedirs(fx_dumps, exist_ok=True)
        fx_run = _run(
            [sys.executable, launcher, "--processes", "1",
             "--no-coordinator",
             "--workdir", os.path.join(fx, "wd"),
             "--timeout", str(args.timeout)]
            + _score_args(data, model, os.path.join(fx, "out"),
                          os.path.join(fx, "ckpt"), fx_dumps,
                          lag_high, args.batch_rows),
            args.timeout + 120, "fixed arm")
        result["fixed"] = {
            "wall_s": fx_run["wall_s"],
            "rows_total": fx_run["report"]["rows_total"],
            "deferred_rows": _shed_total(fx_dumps),
        }
        print(f"# fixed: {fx_run['wall_s']}s wall",
              file=sys.stderr, flush=True)

        result["drain_speedup_vs_fixed"] = (
            round(result["fixed"]["wall_s"]
                  / result["elastic"]["wall_s"], 3)
            if result["elastic"]["wall_s"] > 0 else None)
        result["claims"] = {
            "resize_completed": completed == 1,
            "spike_absorbed": (auto["spike_absorb_s"] is not None
                               and auto["spike_absorb_s"] > 0),
            "exactly_once_both_arms": (
                result["elastic"]["rows_total"] == n_rows
                and result["fixed"]["rows_total"] == n_rows),
            "fewer_deferred_than_fixed": (
                result["elastic"]["deferred_rows"]
                < result["fixed"]["deferred_rows"]),
            # a second process only adds capacity when there is a
            # second core to run it on — on a 1-core host the elastic
            # arm pays the resize for nothing, so the speedup claim is
            # N/A there (recorded as null, not a false failure)
            "elastic_drains_faster": (
                result["elastic"]["wall_s"] < result["fixed"]["wall_s"]
                if (os.cpu_count() or 1) >= 2 else None),
        }
    finally:
        shutil.rmtree(work, ignore_errors=True)
    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
