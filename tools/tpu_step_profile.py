"""Forest-kernel variant race + hot-path stage timing on the live backend.

The GEMM forest (forest.py:226-256) measures ~5% MFU on v5e. Its three
stages have very different hardware shapes:

  proj  einsum bf,tfi->bti  f32 HIGHEST  (K=15: thin, 6-pass)
  z     einsum bti,til->btl bf16->f32    (the FLOPs; K=I~100)
  leaf  einsum btl,tl->b    f32 HIGHEST  (reduction)

This script times (a) each stage in isolation, (b) whole-kernel variants
that keep decision-exactness, on whatever backend is live:

  current   — the shipping kernel
  projHIGH  — proj at HIGH (3-pass) [exactness check reported; known to
              flip decisions for threshold-sitting inputs — measured here]
  gatherD   — d via constant-index take_along_axis instead of the sel
              matmul (static feat indices; no precision question)
  flatproj  — proj as ONE [B,15]x[15,T*I] matmul (reshape of sel) at
              HIGHEST; same math, different tiling
  int8z     — the z contraction in int8×int8→int32 (d is 0/1, path is
              ±1/0, z counts ≤ depth: all exactly representable; v5e
              MXU int8 peak is 2× bf16)

Prints one JSON line; run under the tunnel watcher when the TPU is up.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    import jax
    import jax.numpy as jnp

    want = os.environ.get("JAX_PLATFORMS")
    if want:
        jax.config.update("jax_platforms", want)
    from real_time_fraud_detection_system_tpu.utils import (
        enable_compilation_cache,
    )

    enable_compilation_cache()

    from sklearn.ensemble import RandomForestClassifier

    from real_time_fraud_detection_system_tpu.models.forest import (
        ensemble_from_sklearn,
        gemm_predict_proba,
        to_gemm,
    )

    rng = np.random.default_rng(0)
    xtr = rng.normal(0, 1, (2048, 15))
    ytr = (xtr[:, 0] + 0.5 * xtr[:, 1] > 0.8).astype(np.int32)
    skl = RandomForestClassifier(n_estimators=100, max_depth=8,
                                 random_state=0, n_jobs=-1).fit(xtr, ytr)
    ens = ensemble_from_sklearn(skl, 15)
    g = to_gemm(ens, 15)
    T, F, I = (int(s) for s in g.sel.shape)
    L = int(g.path.shape[2])

    # 262144 RESOURCE_EXHAUSTs a v5e when all five raced variants hold
    # their buffers at once (observed 2026-07-30); 65536 fits.
    B = int(os.environ.get("PROFILE_ROWS", "65536"))
    x = jnp.asarray(rng.normal(0, 1, (B, 15)).astype(np.float32))
    xh = np.asarray(x)
    oracle = skl.predict_proba(xh)[:, 1]

    dev = jax.devices()[0]
    hi = jax.lax.Precision.HIGHEST
    on_tpu = jax.default_backend() == "tpu"
    zdt = jnp.bfloat16 if on_tpu else jnp.float32

    feat_flat = jnp.asarray(
        np.argmax(np.asarray(g.sel), axis=1).astype(np.int32))  # [T, I]
    # nodes whose sel column is all-zero are padding; mark with feature 0
    # (their thresh is +inf so the decision is always True — same as the
    # matmul form where proj=0 <= inf).

    def stage_proj(x):
        return jnp.einsum("bf,tfi->bti", x, g.sel, precision=hi)

    def stage_z(d):
        return jnp.einsum("bti,til->btl", d, g.path.astype(zdt),
                          preferred_element_type=jnp.float32)

    def stage_leaf(onehot):
        return jnp.einsum("btl,tl->b", onehot, g.leaf_val, precision=hi)

    def kernel_current(x):
        return gemm_predict_proba(g, x)

    def kernel_projHIGH(x):
        proj = jnp.einsum("bf,tfi->bti", x, g.sel,
                          precision=jax.lax.Precision.HIGH)
        d = (proj <= g.thresh[None]).astype(zdt)
        z = stage_z(d)
        onehot = (jnp.abs(z - g.target[None]) < 0.5).astype(jnp.float32)
        return stage_leaf(onehot) / T

    def kernel_gatherD(x):
        # x[:, feat[t,i]] via one gather with STATIC indices
        xg = x[:, feat_flat.reshape(-1)].reshape(x.shape[0], T, I)
        d = (xg <= g.thresh[None]).astype(zdt)
        z = stage_z(d)
        onehot = (jnp.abs(z - g.target[None]) < 0.5).astype(jnp.float32)
        return stage_leaf(onehot) / T

    sel_flat = jnp.transpose(g.sel, (1, 0, 2)).reshape(F, T * I)

    def kernel_flatproj(x):
        proj = jnp.einsum("bf,fj->bj", x, sel_flat,
                          precision=hi).reshape(x.shape[0], T, I)
        d = (proj <= g.thresh[None]).astype(zdt)
        z = stage_z(d)
        onehot = (jnp.abs(z - g.target[None]) < 0.5).astype(jnp.float32)
        return stage_leaf(onehot) / T

    def kernel_int8z(x):
        # the SHIPPED int8 kernel (forest.gemm_leaf_sum z_mode="int8"),
        # not a hand-rolled copy — the race must time what serving runs
        return gemm_predict_proba(g, x, "int8")

    def bench(fn, *args, iters=20):
        if not on_tpu:
            iters = max(1, iters // 10)  # GEMM-on-CPU is ~1000x slower
        f = jax.jit(fn)
        out = f(*args)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = f(*args)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / iters, out

    results = {"device_kind": dev.device_kind, "B": B,
               "T": T, "I": I, "L": L}

    # stage timings (proj output is big — stage timing includes HBM
    # round-trip the fused kernel avoids; still ranks relative cost)
    t_proj, proj = bench(stage_proj, x, iters=5)
    d = (proj <= g.thresh[None]).astype(zdt)
    t_z, z = bench(stage_z, d, iters=5)
    onehot = (jnp.abs(z - g.target[None]) < 0.5).astype(jnp.float32)
    t_leaf, _ = bench(stage_leaf, onehot, iters=5)
    results["stage_ms"] = {"proj": round(t_proj * 1e3, 2),
                           "z": round(t_z * 1e3, 2),
                           "leaf": round(t_leaf * 1e3, 2)}
    del proj, d, z, onehot

    for name, fn in [("current", kernel_current),
                     ("projHIGH", kernel_projHIGH),
                     ("gatherD", kernel_gatherD),
                     ("flatproj", kernel_flatproj),
                     ("int8z", kernel_int8z)]:
        try:
            t, out = bench(fn, x)
            p = np.asarray(out)
            results[name] = {
                "ms": round(t * 1e3, 2),
                "rows_per_s": round(B / t, 0),
                "max_abs_diff_vs_sklearn": float(np.max(np.abs(p - oracle))),
            }
        except Exception as e:
            results[name] = {"error": f"{type(e).__name__}: {str(e)[:160]}"}

    print(json.dumps(results))


if __name__ == "__main__":
    main()
