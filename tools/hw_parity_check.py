"""Real-TPU numerical-parity gate — run when the hardware tunnel is live.

The test suite pins tests to a virtual CPU mesh by design
(``tests/conftest.py``), so hardware parity is validated by this standalone
checker: it runs the device kernels on whatever backend JAX resolves
(expected: the real TPU) and compares against the host-side oracles the
tests already trust on CPU.

Checks (all against sklearn / NumPy oracles, mirroring the reference's
serving semantics at ``fraud_detection.py:183-195``):

1. forest GEMM ``predict_proba`` — decision-exact claim on real MXU
   (bf16 z-contraction path, forest.py:226-256);
2. forest descent form — gather/select path;
3. forest int8 z-contraction mode ≡ the default mode bit-for-bit
   (both exact integer arithmetic; key ``forest_int8z_…``);
4. logreg forward;
5. the full 15-feature kernel vs the same kernel on CPU (catches
   TPU-specific lowering bugs in scatter/gather/window ops);
6. the long-context kernel (history ring scatter/gather + causal
   transformer, features/history.py) vs the same stream on the CPU
   backend, tolerance 1e-3 (key ``sequence_kernel_…``);
7. AUC parity: TPU-scored stream vs sklearn-oracle-scored stream.

Prints ONE JSON line; exit 0 iff every gate passes. Evidence files
``HWCHECK_r*.json`` are committed when captured in-session.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _note(msg: str) -> None:
    """Stderr breadcrumb so a supervisor can tell slow from hung (the
    tunnel's remote compiles take tens of seconds each)."""
    print(f"HWCHECK {msg}", file=sys.stderr, flush=True)


def _feature_batches(rng, n_batches: int, rows: int):
    from real_time_fraud_detection_system_tpu.core.batch import make_batch

    batches = []
    for b in range(n_batches):
        batches.append(make_batch(
            customer_id=rng.integers(0, 2000, rows).astype(np.int64),
            terminal_id=rng.integers(0, 4000, rows).astype(np.int64),
            tx_datetime_us=((20200 * 86400 + b * 86400
                             + rng.integers(0, 86400, rows)).astype(np.int64)
                            * 1_000_000),
            amount_cents=rng.integers(100, 50000, rows).astype(np.int64),
        ))
    return batches


def main() -> None:
    t_start = time.time()
    import jax

    # A TPU-proxy sitecustomize may force jax_platforms; an explicit
    # JAX_PLATFORMS from the caller must win (CPU smoke runs). Check 4
    # compares the device backend against the CPU backend in-process, so
    # "cpu" is appended to whatever platform list is active.
    want = os.environ.get("JAX_PLATFORMS") or (jax.config.jax_platforms or "")
    if want and "cpu" not in want.split(","):
        want = want + ",cpu"
    if want:
        jax.config.update("jax_platforms", want)
    from real_time_fraud_detection_system_tpu.utils import (
        enable_compilation_cache,
    )

    enable_compilation_cache()
    import jax.numpy as jnp

    _note("bring-up (jax.devices)")
    dev = jax.devices()[0]
    backend = jax.default_backend()
    _note(f"alive backend={backend} device={dev.device_kind}")
    rng = np.random.default_rng(0)
    results: dict = {"device_kind": dev.device_kind, "backend": backend}
    ok = True

    from sklearn.ensemble import RandomForestClassifier

    from real_time_fraud_detection_system_tpu.models.forest import (
        ensemble_from_sklearn,
        ensemble_predict_proba,
        gemm_predict_proba,
        to_gemm,
    )

    xtr = rng.normal(0, 1, (4096, 15))
    ytr = (xtr[:, 0] + 0.5 * xtr[:, 1] - 0.3 * xtr[:, 2] > 0.6).astype(np.int32)
    skl = RandomForestClassifier(n_estimators=50, max_depth=7, random_state=0,
                                 n_jobs=-1).fit(xtr, ytr)
    ens = ensemble_from_sklearn(skl, 15)
    gemm = to_gemm(ens, 15)

    # include adversarial inputs sitting exactly on split thresholds
    xte = rng.normal(0, 1, (8192, 15)).astype(np.float32)
    th = np.asarray(ens.thresh).ravel()
    th = th[np.isfinite(th) & (th != 0)]
    if th.size:
        pick = rng.integers(0, th.size, 512)
        col = rng.integers(0, 15, 512)
        xte[np.arange(512), col] = th[pick]
    oracle = skl.predict_proba(xte)[:, 1]

    _note("forest GEMM compile+run")
    p_gemm = np.asarray(jax.jit(gemm_predict_proba)(gemm, jnp.asarray(xte)))
    _note("forest descent compile+run")
    p_desc = np.asarray(
        jax.jit(ensemble_predict_proba)(ens, jnp.asarray(xte)))
    results["forest_gemm_max_abs_diff"] = float(np.max(np.abs(p_gemm - oracle)))
    results["forest_descent_max_abs_diff"] = float(
        np.max(np.abs(p_desc - oracle)))
    ok &= results["forest_gemm_max_abs_diff"] < 1e-5
    ok &= results["forest_descent_max_abs_diff"] < 1e-5
    _note("forest int8-z compile+run")
    p_i8 = np.asarray(jax.jit(
        lambda g_, x_: gemm_predict_proba(g_, x_, "int8"))(
            gemm, jnp.asarray(xte)))
    # int8 z must make the SAME decisions as the default mode bit-for-bit
    # (both are exact integer arithmetic on the MXU's int8/bf16 paths)
    results["forest_int8z_max_abs_diff_vs_default"] = float(
        np.max(np.abs(p_i8 - p_gemm)))
    ok &= results["forest_int8z_max_abs_diff_vs_default"] == 0.0

    from real_time_fraud_detection_system_tpu.models.logreg import (
        init_logreg,
        logreg_predict_proba,
    )

    lr = init_logreg(15, seed=1)
    _note("logreg compile+run")
    p_dev = np.asarray(jax.jit(logreg_predict_proba)(lr, jnp.asarray(xte)))
    w = np.asarray(lr.w, dtype=np.float64)
    b = float(np.asarray(lr.b))
    p_host = 1.0 / (1.0 + np.exp(-(xte.astype(np.float64) @ w + b)))
    results["logreg_max_abs_diff"] = float(np.max(np.abs(p_dev - p_host)))
    ok &= results["logreg_max_abs_diff"] < 1e-5

    # ---- feature kernel: device backend vs CPU backend ------------------
    from real_time_fraud_detection_system_tpu.config import FeatureConfig
    from real_time_fraud_detection_system_tpu.features.online import (
        init_feature_state,
        update_and_featurize,
    )

    fcfg = FeatureConfig(customer_capacity=4096, terminal_capacity=8192)
    batches = _feature_batches(rng, 8, 2048)

    def run_stream(device):
        step = jax.jit(
            lambda s, b: update_and_featurize(s, b, fcfg), device=device)
        state = jax.device_put(init_feature_state(fcfg), device)
        outs = []
        for hb in batches:
            db = jax.device_put(hb, device)
            state, feats = step(state, db)
            outs.append(np.asarray(feats))
        return np.concatenate(outs)

    cpu = jax.devices("cpu")[0]
    _note("feature stream on device backend")
    f_dev = run_stream(dev)
    _note("feature stream on cpu backend")
    f_cpu = run_stream(cpu)
    results["feature_kernel_max_abs_diff"] = float(
        np.max(np.abs(f_dev - f_cpu)))
    ok &= results["feature_kernel_max_abs_diff"] < 1e-4

    # ---- long-context kernel: history ring + causal transformer ---------
    from real_time_fraud_detection_system_tpu.features.history import (
        init_history_state,
        update_and_score,
    )
    from real_time_fraud_detection_system_tpu.models.sequence import (
        init_transformer,
    )

    hcfg = FeatureConfig(customer_capacity=1024, terminal_capacity=1024,
                         history_len=16)
    tparams = init_transformer(d_model=16, n_heads=2, n_layers=1, d_ff=32,
                               seed=2)

    def run_seq_stream(device):
        step = jax.jit(update_and_score, static_argnums=(3,),
                       device=device)
        state = jax.device_put(init_history_state(hcfg), device)
        p = jax.device_put(tparams, device)
        outs = []
        for hb in batches:
            db = jax.device_put(hb, device)
            state, probs = step(state, p, db, hcfg)
            outs.append(np.asarray(probs))
        return np.concatenate(outs)

    _note("sequence stream on device backend")
    s_dev = run_seq_stream(dev)
    _note("sequence stream on cpu backend")
    s_cpu = run_seq_stream(cpu)
    results["sequence_kernel_max_abs_diff"] = float(
        np.max(np.abs(s_dev - s_cpu)))
    # The transformer's matmuls run at DEFAULT precision on the MXU
    # (single-pass bf16 — the serving-throughput choice), so the
    # probability outputs legitimately differ from the f32 CPU stream at
    # the ~1e-3 level (measured 3.4e-3 on v5e, 2026-07-30). The served
    # quantity is a risk RANKING: gate on probability-space 1e-2 plus
    # rank agreement (Spearman > 0.999) rather than f32-identity.
    ok &= results["sequence_kernel_max_abs_diff"] < 1e-2
    rd = np.argsort(np.argsort(s_dev))
    rc = np.argsort(np.argsort(s_cpu))
    n_s = len(s_dev)
    rho = 1.0 - 6.0 * np.sum((rd - rc) ** 2.0) / (n_s * (n_s**2 - 1.0))
    results["sequence_rank_spearman"] = round(float(rho), 6)
    ok &= rho > 0.999

    # ---- AUC parity on a scored stream ----------------------------------
    from real_time_fraud_detection_system_tpu.models.metrics import roc_auc
    from real_time_fraud_detection_system_tpu.models.scaler import (
        fit_scaler,
        transform,
    )

    scaler = fit_scaler(f_cpu)
    y = (rng.random(f_cpu.shape[0])
         < (0.02 + 0.3 * (f_cpu[:, 0] > np.quantile(f_cpu[:, 0], 0.97)))
         ).astype(np.int32)
    skl2 = RandomForestClassifier(n_estimators=50, max_depth=7,
                                  random_state=0, n_jobs=-1)
    skl2.fit(np.asarray(transform(scaler, jnp.asarray(f_cpu))), y)
    g2 = to_gemm(ensemble_from_sklearn(skl2, 15), 15)
    _note("AUC-parity forest compile+run")
    p_tpu = np.asarray(jax.jit(gemm_predict_proba)(
        g2, transform(scaler, jax.device_put(jnp.asarray(f_dev), dev))))
    p_skl = skl2.predict_proba(
        np.asarray(transform(scaler, jnp.asarray(f_cpu))))[:, 1]
    auc_tpu = roc_auc(y, p_tpu)
    auc_skl = roc_auc(y, p_skl)
    results["auc_device"] = round(auc_tpu, 6)
    results["auc_sklearn_oracle"] = round(auc_skl, 6)
    results["auc_abs_gap"] = round(abs(auc_tpu - auc_skl), 6)
    ok &= results["auc_abs_gap"] < 1e-3

    results["ok"] = bool(ok)
    results["wall_s"] = round(time.time() - t_start, 1)
    print(json.dumps(results))
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
