#!/bin/sh
# Watch the TPU tunnel; when it comes alive, run the hardware parity gate
# and save the evidence file. Exits after first success or when the overall
# window (arg 1, seconds, default 4h) expires.
#
# Usage: sh tools/hw_watch.sh [window_s] [outfile]
set -u
WINDOW=${1:-14400}
OUT=${2:-HWCHECK_r03.json}
START=$(date +%s)
cd "$(dirname "$0")/.."

while :; do
  NOW=$(date +%s)
  [ $((NOW - START)) -ge "$WINDOW" ] && { echo "hw_watch: window expired"; exit 2; }
  if timeout 90 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    echo "hw_watch: tunnel alive at $(date -u +%H:%M:%S), running parity gate"
    if timeout 1800 python tools/hw_parity_check.py > "$OUT.tmp" 2> "$OUT.log"; then
      mv "$OUT.tmp" "$OUT"
      echo "hw_watch: parity gate PASSED -> $OUT"
      cat "$OUT"
      echo "hw_watch: racing forest-kernel variants (tools/tpu_step_profile.py)"
      timeout 1800 env PROFILE_ROWS=${PROFILE_ROWS:-65536} python tools/tpu_step_profile.py \
        > PROFILE_r03.json 2>> "$OUT.log" \
        && { echo "hw_watch: profile -> PROFILE_r03.json"; cat PROFILE_r03.json; } \
        || echo "hw_watch: profile attempt failed (rc=$?)"
      echo "hw_watch: fresh bench while the window is open (bench.py)"
      # bench.py prints the full-detail JSON line first, then a compact
      # headline line LAST (driver tail-window contract); the session
      # artifact keeps only the full line so it stays one json.load()-able
      # document like every prior BENCH_SESSION_*.json.
      BENCH_OUT="BENCH_SESSION_${BENCH_TAG:-r03b}_tpu.json"
      timeout 2400 python bench.py > "$BENCH_OUT.raw" 2>> "$OUT.log" \
        && { grep '^{' "$BENCH_OUT.raw" | head -1 > "$BENCH_OUT"; \
             rm -f "$BENCH_OUT.raw"; \
             echo "hw_watch: bench -> $BENCH_OUT"; cat "$BENCH_OUT"; } \
        || echo "hw_watch: bench attempt failed (rc=$?)"
      exit 0
    fi
    echo "hw_watch: parity attempt failed (rc=$?), tail of log:"
    tail -3 "$OUT.log"
  fi
  sleep 240
done
