"""Inline suppression pragmas.

Grammar (one per line)::

    # rtfdslint: disable=rule-a,rule-b (why this is deliberate)
    # rtfdslint: disable-file=rule-a (why the whole file opts out)

A trailing pragma (after code) suppresses findings on its OWN line; a
pragma on a comment-only line suppresses findings on the NEXT line —
the usual spelling above a flagged ``except``/``with``/call statement,
where the reason won't fit in the margin.

The parenthesised reason is REQUIRED: a pragma without one does not
suppress anything and instead surfaces as a ``pragma-missing-reason``
P1 finding — the workflow the acceptance gate enforces ("every pragma
carries a reason"). ``disable=all`` is deliberately not supported;
suppressions are per-rule so a pragma can never hide a future rule's
finding for free.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set  # noqa: F401 (Dict in hints)

from .finding import Finding

_PRAGMA_RE = re.compile(
    r"#\s*rtfdslint:\s*(?P<kind>disable(?:-file)?)\s*=\s*"
    r"(?P<rules>[a-z0-9_,\- ]+?)\s*"
    r"(?:\((?P<reason>.*)\))?\s*$"  # greedy: reasons may nest parens
)
_PRAGMA_HINT_RE = re.compile(r"#\s*rtfdslint\s*:")


@dataclass
class Pragma:
    line: int
    kind: str            # "disable" | "disable-file"
    rules: List[str]
    reason: str


@dataclass
class FilePragmas:
    """All pragmas of one file + the line→rules suppression index."""

    pragmas: List[Pragma] = field(default_factory=list)
    by_line: Dict[int, Set[str]] = field(default_factory=dict)
    file_wide: Set[str] = field(default_factory=set)

    def suppresses(self, rule: str, line: int) -> bool:
        if rule in self.file_wide:
            return True
        return rule in self.by_line.get(line, set())


def parse_pragmas(relpath: str, text: str, known_rules: Set[str],
                  stmt_cover: "Dict[int, int] | None" = None,
                  ) -> "tuple[FilePragmas, list]":
    """Scan a file's raw text for pragmas.

    Returns the suppression index plus meta-findings (missing reason,
    unknown rule name). A reason-less pragma is parsed but NOT entered
    into the suppression index.

    ``stmt_cover`` (start line → last line of the innermost statement
    starting there, from the file's AST) expands each pragma to cover
    its annotated statement's FULL physical span, so a wrapped
    statement whose flagged expression lands on a later line is still
    suppressed.
    """
    fp = FilePragmas()
    meta: List[Finding] = []
    for i, raw in enumerate(text.splitlines(), start=1):
        if "rtfdslint" not in raw:
            continue
        m = _PRAGMA_RE.search(raw)
        if not m:
            if _PRAGMA_HINT_RE.search(raw):
                meta.append(Finding(
                    rule="pragma-malformed", severity="P1",
                    path=relpath, line=i,
                    message=("line looks like an rtfdslint pragma but "
                             "does not parse — it suppresses NOTHING; "
                             "expected comment form rtfdslint"
                             ": disable=<rules> (<reason>)")))
            continue
        rules = [r.strip() for r in m.group("rules").split(",") if r.strip()]
        reason = (m.group("reason") or "").strip()
        fp.pragmas.append(Pragma(i, m.group("kind"), rules, reason))
        if not reason:
            meta.append(Finding(
                rule="pragma-missing-reason", severity="P1",
                path=relpath, line=i,
                message=("rtfdslint pragma without a (reason); the "
                         "suppression is ignored until one is given"),
                context=",".join(rules)))
            continue
        unknown = [r for r in rules if known_rules and r not in known_rules]
        for r in unknown:
            meta.append(Finding(
                rule="pragma-unknown-rule", severity="P2",
                path=relpath, line=i,
                message=f"pragma names unknown rule {r!r}", context=r))
        live = [r for r in rules if r not in unknown]
        if m.group("kind") == "disable-file":
            fp.file_wide.update(live)
            continue
        # comment-only line: the pragma governs the NEXT line's
        # statement; trailing form governs its own line's statement
        anchor = i + 1 if raw.lstrip().startswith("#") else i
        last = anchor
        if stmt_cover:
            last = stmt_cover.get(anchor, anchor)
        for line in range(anchor, last + 1):
            fp.by_line.setdefault(line, set()).update(live)
    return fp, meta
