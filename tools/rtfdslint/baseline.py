"""Checked-in baseline of accepted pre-existing findings.

The baseline is the second suppression channel (the first is inline
pragmas). Pragmas are preferred — they live next to the code and
self-document — but some findings have no single good line to annotate
(e.g. a cross-file metric-drift verdict) or belong to code that is
deliberately left as-is; those go here, each with a REQUIRED reason.

Entries match by fingerprint (rule + path + context + message — no
line numbers, so edits elsewhere in the file don't invalidate them)
with an occurrence ``count`` so N identical findings need one entry.
A reason-less entry is a configuration error: the runner refuses it
loudly rather than silently suppressing (acceptance rule: "every
baseline entry carries a reason").
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Dict, List

from .finding import Finding

FORMAT_VERSION = 1


class BaselineError(ValueError):
    """Malformed baseline file — refuse to lint rather than mis-suppress."""


@dataclass
class Baseline:
    path: str = ""
    entries: Dict[str, dict] = field(default_factory=dict)  # fp -> entry
    #: fingerprints consumed during this run (for stale-entry reporting)
    _used: Dict[str, int] = field(default_factory=dict)

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not path or not os.path.exists(path):
            return cls(path=path)
        with open(path, encoding="utf-8") as f:
            try:
                data = json.load(f)
            except ValueError as e:
                raise BaselineError(f"{path}: not valid JSON ({e})") from e
        if not isinstance(data, dict) \
                or not isinstance(data.get("entries"), list):
            raise BaselineError(
                f"{path}: expected an object with an 'entries' list")
        entries: Dict[str, dict] = {}
        for ent in data["entries"]:
            if not isinstance(ent, dict):
                raise BaselineError(
                    f"{path}: entry {ent!r} is not an object")
            fp = ent.get("fingerprint", "")
            reason = str(ent.get("reason", "")).strip()
            if not fp:
                raise BaselineError(f"{path}: entry without fingerprint")
            if not reason:
                raise BaselineError(
                    f"{path}: entry {fp} ({ent.get('rule', '?')} at "
                    f"{ent.get('path', '?')}) has no reason — every "
                    "baseline entry must say why it is accepted")
            ent.setdefault("count", 1)
            entries[fp] = ent
        return cls(path=path, entries=entries)

    def absorb(self, finding: Finding) -> bool:
        """True (and consume one occurrence) if the finding is baselined."""
        ent = self.entries.get(finding.fingerprint)
        if ent is None:
            return False
        used = self._used.get(finding.fingerprint, 0)
        if used >= int(ent.get("count", 1)):
            return False
        self._used[finding.fingerprint] = used + 1
        return True

    def stale_entries(self) -> List[dict]:
        """Entries that matched nothing (candidates for deletion)."""
        out = []
        for fp, ent in self.entries.items():
            if self._used.get(fp, 0) == 0:
                out.append(ent)
        return out

    @staticmethod
    def write(path: str, findings: List[Finding],
              prior: "Baseline", default_reason: str) -> int:
        """``--update-baseline``: write the current P0/P1 finding set.

        Reasons survive from the prior baseline where the fingerprint
        persists; new entries take ``default_reason`` (the CLI's
        ``--reason``, which update mode requires — a baseline entry can
        never be born reason-less).
        """
        by_fp: Dict[str, dict] = {}
        for f in findings:
            ent = by_fp.get(f.fingerprint)
            if ent is not None:
                ent["count"] += 1
                continue
            old = prior.entries.get(f.fingerprint)
            by_fp[f.fingerprint] = {
                "fingerprint": f.fingerprint,
                "rule": f.rule,
                "severity": f.severity,
                "path": f.path,
                "context": f.context,
                "message": f.message,
                "count": 1,
                "reason": (old or {}).get("reason") or default_reason,
            }
        data = {
            "format": FORMAT_VERSION,
            "comment": ("accepted pre-existing rtfdslint findings; every "
                        "entry needs a reason. Regenerate with "
                        "`rtfds lint --update-baseline --reason '...'`."),
            "entries": sorted(by_fp.values(),
                              key=lambda e: (e["path"], e["rule"],
                                             e["message"])),
        }
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(data, f, indent=2, sort_keys=False)
            f.write("\n")
        os.replace(tmp, path)
        return len(by_fp)
