"""Lint orchestration: build project → run rules → pragma/baseline → verdict."""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .baseline import Baseline
from .finding import Finding, RuleStats
from .pragmas import parse_pragmas
from .registry import all_rules, known_rule_names
from .project import PACKAGE_NAME, Project

DEFAULT_BASELINE = "tools/rtfdslint/baseline.json"


@dataclass
class LintResult:
    findings: List[Finding] = field(default_factory=list)   # active only
    suppressed: List[Finding] = field(default_factory=list)  # pragma'd
    baselined: List[Finding] = field(default_factory=list)
    stale_baseline: List[dict] = field(default_factory=list)
    stats: Dict[str, RuleStats] = field(default_factory=dict)
    files_scanned: int = 0
    #: optional rtfdsverify.VerifyResult attached by --verify-device;
    #: its gate failures fold into this result's verdict
    verifier: object = None

    def gate_failures(self, strict: bool = False) -> List[Finding]:
        bad = ("P0", "P1") if not strict else ("P0", "P1", "P2")
        out = [f for f in self.findings if f.severity in bad]
        if self.verifier is not None:
            out += self.verifier.gate_failures(strict=strict)
        return out

    def to_json(self, strict: bool = False) -> dict:
        return {
            "version": 2,
            "files_scanned": self.files_scanned,
            "strict": strict,
            "findings": [f.to_json() for f in self.findings],
            "suppressed": [f.to_json() for f in self.suppressed],
            "baselined": [f.to_json() for f in self.baselined],
            "stale_baseline_entries": self.stale_baseline,
            "rules": {k: v.to_json() for k, v in sorted(self.stats.items())},
            # Device-contract verifier block (tools/rtfdsverify): None
            # unless the caller ran it (`rtfds lint --verify-device`) —
            # the key is always present so JSON consumers can detect
            # "not run" vs "ran clean" without schema sniffing.
            "verifier": (self.verifier.to_json(strict=strict)
                         if self.verifier is not None else None),
            "summary": {
                "active": len(self.findings),
                "gate_failures": len(self.gate_failures(strict=strict)),
                "suppressed": len(self.suppressed),
                "baselined": len(self.baselined),
            },
        }


def run_lint(root: str,
             targets: Optional[List[str]] = None,
             baseline_path: Optional[str] = DEFAULT_BASELINE,
             rules: Optional[List[str]] = None,
             report_stale: Optional[bool] = None) -> LintResult:
    """Run the analyzer. ``targets`` defaults to the serving package.

    ``baseline_path`` is repo-root-relative (or absolute); pass None to
    lint without a baseline (the self-check test does).
    ``report_stale`` controls the stale-baseline-entry report; the
    default (None) enables it only on unfocused runs — a ``rules``
    filter or an explicit ``targets`` list narrows the finding set, so
    live out-of-scope entries would be reported as stale and the
    "delete them" advice would be wrong.
    """
    focused = bool(rules) or targets is not None
    targets = targets or [PACKAGE_NAME]
    project = Project(root, targets)
    selected = all_rules()
    if rules:
        wanted = set(rules)
        unknown = wanted - {r.name for r in selected}
        if unknown:
            # same contract as a typo'd target: never a vacuous pass
            raise ValueError(
                f"unknown rule name(s) {sorted(unknown)} — see "
                "--list-rules for the catalog")
        # placeholder rules (lock-order-cycle, undocumented-metric) are
        # produced by another rule's analysis: pull the producer in so
        # a focused run is never a vacuous pass…
        producers = {getattr(r, "produced_by", "") for r in selected
                     if r.name in wanted}
        selected = [r for r in selected
                    if r.name in wanted or r.name in producers]

    raw: List[Finding] = list(project.parse_findings)
    for rule_cls in selected:
        raw.extend(rule_cls().run(project))

    # pragma suppression (reason-required; meta-findings join the pool)
    known = known_rule_names()
    pragma_idx = {}
    for rel, pf in project.files.items():
        fp, meta = parse_pragmas(rel, pf.text, known,
                                 stmt_cover=_stmt_cover(pf))
        pragma_idx[rel] = fp
        raw.extend(meta)
    if project.readme_text:
        fp, meta = parse_pragmas(project.readme_rel, project.readme_text,
                                 known)
        pragma_idx[project.readme_rel] = fp
        raw.extend(meta)
    if rules:
        # findings narrow back to exactly what was asked for — a
        # focused run must not fail on unrelated pragma hygiene — but
        # parse-error P0s survive: a file the analyzer cannot read
        # invalidates ANY focused run over it
        keep = set(rules) | {"parse-error"}
        raw = [f for f in raw if f.rule in keep]

    baseline = Baseline(path="")
    if baseline_path:
        bp = baseline_path if os.path.isabs(baseline_path) \
            else os.path.join(root, baseline_path)
        baseline = Baseline.load(bp)

    result = LintResult(files_scanned=len(project.files))
    raw.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    deduped: List[Finding] = []
    seen = set()
    for f in raw:
        key = (f.rule, f.path, f.line, f.col, f.message)
        if key in seen:
            continue  # same site reached via two analysis paths
        seen.add(key)
        deduped.append(f)
    for f in deduped:
        stats = result.stats.setdefault(f.rule, RuleStats())
        fp = pragma_idx.get(f.path)
        if fp is not None and fp.suppresses(f.rule, f.line):
            f.suppressed = "pragma"
            result.suppressed.append(f)
            stats.suppressed += 1
        elif baseline.absorb(f):  # P2s absorb too (output hygiene);
            # only P0/P1 ever gate, baselined or not
            f.suppressed = "baseline"
            result.baselined.append(f)
            stats.baselined += 1
        else:
            result.findings.append(f)
            stats.active += 1
    if report_stale if report_stale is not None else not focused:
        result.stale_baseline = baseline.stale_entries()
    return result


def _stmt_cover(pf) -> Dict[int, int]:
    """start line → last covered line, for pragma span expansion.

    A pragma annotates a STATEMENT; if that statement wraps across
    physical lines (Black-style reformat, parenthesized expressions),
    the finding may anchor below the pragma line. Simple statements
    cover their full span; compound statements (if/with/try/def…)
    cover only their header (through the line before the first body
    statement) so a pragma above an `if` never blankets the body.
    """
    import ast

    cover: Dict[int, int] = {}
    if pf.tree is None:
        return cover
    compound = (ast.If, ast.While, ast.For, ast.AsyncFor, ast.With,
                ast.AsyncWith, ast.Try, ast.FunctionDef,
                ast.AsyncFunctionDef, ast.ClassDef)
    match_t = getattr(ast, "Match", ())
    for node in ast.walk(pf.tree):
        if not isinstance(node, ast.stmt):
            continue
        start = node.lineno
        if isinstance(node, compound) or (match_t
                                          and isinstance(node, match_t)):
            body = getattr(node, "body", None)
            end = (body[0].lineno - 1 if body
                   else getattr(node, "end_lineno", start))
        else:
            end = getattr(node, "end_lineno", start)
        end = max(start, end)
        prev = cover.get(start)
        if prev is None or end < prev:  # innermost statement wins
            cover[start] = end
    return cover


def update_baseline(root: str, result: LintResult,
                    baseline_path: str, reason: str) -> int:
    """Absorb the current gate failures into the baseline file.

    Entries that are still matching (``result.baselined`` — whatever
    their severity) are REWRITTEN with their existing reasons, not
    dropped: regenerating must never resurface a previously-accepted
    finding on the next run. Only stale entries (matched nothing this
    run) fall out.
    """
    bp = baseline_path if os.path.isabs(baseline_path) \
        else os.path.join(root, baseline_path)
    prior = Baseline.load(bp)
    keep = result.gate_failures() + result.baselined
    return Baseline.write(bp, keep, prior, reason)
