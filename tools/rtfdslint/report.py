"""Human and JSON reporters for a LintResult."""

from __future__ import annotations

import json
from typing import List

from .finding import severity_rank
from .runner import LintResult


def render_json(result: LintResult, strict: bool = False) -> str:
    return json.dumps(result.to_json(strict=strict), indent=2,
                      sort_keys=False)


def render_human(result: LintResult, verbose: bool = False,
                 strict: bool = False) -> str:
    out: List[str] = []
    findings = sorted(result.findings,
                      key=lambda f: (severity_rank(f.severity), f.path,
                                     f.line))
    for f in findings:
        out.append(f.render())
    if verbose and result.suppressed:
        out.append("")
        out.append(f"-- suppressed by pragma ({len(result.suppressed)}):")
        out.extend("   " + f.render() for f in result.suppressed)
    if verbose and result.baselined:
        out.append("")
        out.append(f"-- baselined ({len(result.baselined)}):")
        out.extend("   " + f.render() for f in result.baselined)
    if result.stale_baseline:
        out.append("")
        out.append("-- stale baseline entries (matched nothing; delete "
                   "or re-run --update-baseline):")
        for ent in result.stale_baseline:
            out.append(f"   {ent.get('rule')} {ent.get('path')}: "
                       f"{ent.get('message', '')[:80]}")
    counts = {"P0": 0, "P1": 0, "P2": 0}
    for f in result.findings:
        counts[f.severity] += 1
    # the gate line MUST agree with the process exit code, so it is
    # computed under the same strictness
    gate = result.gate_failures(strict=strict)
    bar = "P0/P1/P2" if strict else "P0/P1"
    out.append("")
    out.append(
        f"rtfdslint: {result.files_scanned} files, "
        f"{len(result.findings)} active finding(s) "
        f"[P0={counts['P0']} P1={counts['P1']} P2={counts['P2']}], "
        f"{len(result.suppressed)} pragma-suppressed, "
        f"{len(result.baselined)} baselined")
    out.append("gate: " + (f"FAIL — unbaselined {bar} present"
                           if gate else f"clean (no unbaselined {bar})"))
    return "\n".join(out)
