"""Finding: one rule violation at one source location.

Fingerprints deliberately exclude line numbers so a baseline entry
survives unrelated edits above the finding; they include the enclosing
definition's qualname so two identical messages in different functions
stay distinct.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

#: Severity policy (README "Static analysis" section is the prose copy):
#: P0 — would break a paid-for runtime invariant (e.g. a concretization
#:      inside a jit-reachable function: trace-time crash or silent
#:      per-batch recompile). Gates; severity signals urgency, not
#:      unwaivability — the analysis is approximate, so a reasoned
#:      pragma/baseline entry remains the escape hatch (and is itself
#:      auditable: suppressions are listed under --verbose).
#: P1 — likely bug or taxonomy erosion (unguarded cross-thread
#:      read-modify-write, swallowed crash signal, wall-clock duration).
#:      Gates unless baselined/pragma'd with a reason.
#: P2 — advisory / documentation drift. Reported, never gates unless
#:      ``--strict``.
SEVERITIES = ("P0", "P1", "P2")


@dataclass
class Finding:
    rule: str
    severity: str  # one of SEVERITIES
    path: str      # repo-relative posix path
    line: int
    message: str
    context: str = ""   # enclosing qualname ("module:Class.method")
    col: int = 0
    suppressed: str = ""  # "", "pragma", or "baseline"

    def __post_init__(self) -> None:
        if self.severity not in SEVERITIES:
            raise ValueError(f"bad severity {self.severity!r}")

    @property
    def fingerprint(self) -> str:
        blob = "|".join((self.rule, self.path, self.context, self.message))
        return hashlib.sha1(blob.encode()).hexdigest()[:16]

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "severity": self.severity,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "context": self.context,
            "message": self.message,
            "fingerprint": self.fingerprint,
            "suppressed": self.suppressed,
        }

    def render(self) -> str:
        where = f"{self.path}:{self.line}"
        ctx = f" [{self.context}]" if self.context else ""
        return f"{self.severity} {self.rule} {where}{ctx}: {self.message}"


@dataclass
class RuleStats:
    """Per-rule counts for the summary block."""

    active: int = 0
    suppressed: int = 0
    baselined: int = 0

    def to_json(self) -> dict:
        return {"active": self.active, "suppressed": self.suppressed,
                "baselined": self.baselined}


def severity_rank(sev: str) -> int:
    return SEVERITIES.index(sev)
