"""Shared project model: parsed files, symbol index, call resolution.

Built once per lint run and handed to every rule. The index is
deliberately best-effort — pure-``ast`` name resolution cannot follow
dynamic dispatch — but it is *conservative in the right direction* for
each rule that uses it (rules document their own approximations).
"""

from __future__ import annotations

import ast
import os
import posixpath
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .finding import Finding

PACKAGE_NAME = "real_time_fraud_detection_system_tpu"


@dataclass
class ClassInfo:
    name: str
    node: ast.ClassDef
    file: "PyFile"
    methods: Dict[str, "FuncDef"] = field(default_factory=dict)


@dataclass
class FuncDef:
    node: ast.AST            # FunctionDef | AsyncFunctionDef | Lambda
    file: "PyFile"
    qualname: str            # "Class.method" / "outer.inner" / "fn"
    class_info: Optional[ClassInfo] = None
    parent: Optional["FuncDef"] = None
    children: Dict[str, "FuncDef"] = field(default_factory=dict)

    @property
    def key(self) -> Tuple[str, str]:
        return (self.file.relpath, self.qualname)

    @property
    def name(self) -> str:
        return self.qualname.rsplit(".", 1)[-1]

    def param_names(self) -> List[str]:
        a = self.node.args
        names = [p.arg for p in
                 list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)]
        if a.vararg:
            names.append(a.vararg.arg)
        if a.kwarg:
            names.append(a.kwarg.arg)
        return names


@dataclass
class PyFile:
    relpath: str             # repo-relative posix path
    path: str
    text: str
    tree: Optional[ast.Module]
    error: str = ""
    # symbol index (filled by _index_file)
    functions: List[FuncDef] = field(default_factory=list)
    top_functions: Dict[str, FuncDef] = field(default_factory=dict)
    classes: Dict[str, ClassInfo] = field(default_factory=dict)
    imports: Dict[str, str] = field(default_factory=dict)  # local -> dotted

    @property
    def module(self) -> str:
        mod = self.relpath[:-3].replace("/", ".")
        if mod.endswith(".__init__"):
            mod = mod[: -len(".__init__")]
        return mod


class Project:
    """All parsed sources + the cross-file symbol index."""

    def __init__(self, root: str, targets: List[str],
                 readme: str = "README.md",
                 tests_dir: str = "tests") -> None:
        self.root = os.path.abspath(root)
        self.files: Dict[str, PyFile] = {}
        self.target_paths: List[str] = []
        self.target_specs: List[str] = [self._norm_spec(t)
                                        for t in targets]
        self.parse_findings: List[Finding] = []
        self._modules: Dict[str, PyFile] = {}

        for t in targets:
            matched = False
            for rel in self._expand(t):
                matched = True
                self.target_paths.append(rel)
                self._load(rel)
            if not matched:
                # a typo'd target must be a hard error, never a
                # permanently-green lint over nothing
                raise FileNotFoundError(
                    f"lint target {t!r} matched no .py files under "
                    f"{self.root}")
        # aux sources: tests participate in the metric two-way diff and
        # may carry pragmas, but rules do not target them by default
        self.tests_rel: List[str] = []
        tdir = os.path.join(self.root, tests_dir)
        if os.path.isdir(tdir):
            for rel in sorted(self._expand(tests_dir)):
                self.tests_rel.append(rel)
                self._load(rel)
        self.readme_rel = readme
        rp = os.path.join(self.root, readme)
        self.readme_text = ""
        if os.path.exists(rp):
            with open(rp, encoding="utf-8") as f:
                self.readme_text = f.read()
        for pf in self.files.values():
            self._index_file(pf)

    # -- loading -----------------------------------------------------------

    def _norm_spec(self, target: str) -> str:
        """Root-relative normalized spelling of a target spec, so
        ``./pkg``, ``pkg/`` and an absolute path all compare equal
        (rules that key on "is the whole package targeted" depend on
        this)."""
        spec = target.replace(os.sep, "/")
        if os.path.isabs(target):
            spec = os.path.relpath(target, self.root).replace(os.sep, "/")
        return posixpath.normpath(spec).strip("/")

    def _expand(self, target: str) -> Iterable[str]:
        abspath = os.path.join(self.root, target)
        if os.path.isfile(abspath):
            yield target.replace(os.sep, "/")
            return
        for dirpath, dirnames, filenames in os.walk(abspath):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in ("__pycache__", ".git"))
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    rel = os.path.relpath(os.path.join(dirpath, fn),
                                          self.root)
                    yield rel.replace(os.sep, "/")

    def _load(self, rel: str) -> None:
        if rel in self.files:
            return
        path = os.path.join(self.root, rel)
        try:
            with open(path, encoding="utf-8") as f:
                text = f.read()
        except OSError as e:
            self.parse_findings.append(Finding(
                rule="parse-error", severity="P0", path=rel, line=1,
                message=f"unreadable: {e}"))
            return
        try:
            tree = ast.parse(text, filename=rel)
            pf = PyFile(rel, path, text, tree)
        except SyntaxError as e:
            pf = PyFile(rel, path, text, None, error=str(e))
            self.parse_findings.append(Finding(
                rule="parse-error", severity="P0", path=rel,
                line=int(e.lineno or 1),
                message=f"syntax error: {e.msg}"))
        self.files[rel] = pf
        self._modules[pf.module] = pf

    # -- indexing ----------------------------------------------------------

    def _index_file(self, pf: PyFile) -> None:
        if pf.tree is None:
            return

        def visit(node: ast.AST, parent_fn: Optional[FuncDef],
                  cls: Optional[ClassInfo], prefix: str) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.Import, ast.ImportFrom)):
                    self._index_import(pf, child)
                if isinstance(child, ast.ClassDef):
                    ci = ClassInfo(child.name, child, pf)
                    pf.classes[child.name] = ci
                    visit(child, None, ci, child.name + ".")
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    fd = FuncDef(child, pf, prefix + child.name,
                                 class_info=cls, parent=parent_fn)
                    pf.functions.append(fd)
                    if cls is not None and parent_fn is None:
                        cls.methods[child.name] = fd
                    elif parent_fn is None:
                        pf.top_functions[child.name] = fd
                    else:
                        parent_fn.children[child.name] = fd
                    visit(child, fd, cls, fd.qualname + ".")
                else:
                    visit(child, parent_fn, cls, prefix)

        visit(pf.tree, None, None, "")

    def _index_import(self, pf: PyFile, node: ast.AST) -> None:
        if isinstance(node, ast.Import):
            for alias in node.names:
                pf.imports[alias.asname or alias.name.split(".")[0]] = \
                    alias.name
        elif isinstance(node, ast.ImportFrom):
            base = node.module or ""
            if node.level:  # relative: resolve against this module
                parts = pf.module.split(".")
                parts = parts[: len(parts) - node.level]
                base = ".".join(parts + ([node.module]
                                         if node.module else []))
            for alias in node.names:
                if alias.name == "*":
                    continue
                pf.imports[alias.asname or alias.name] = \
                    f"{base}.{alias.name}" if base else alias.name

    # -- queries -----------------------------------------------------------

    def target_files(self) -> List[PyFile]:
        return [self.files[r] for r in self.target_paths
                if r in self.files]

    def test_files(self) -> List[PyFile]:
        return [self.files[r] for r in self.tests_rel if r in self.files]

    def module_file(self, dotted: str) -> Optional[PyFile]:
        return self._modules.get(dotted)

    def qualname_at(self, pf: PyFile, line: int) -> str:
        """Innermost definition enclosing ``line`` (finding context)."""
        best = ""
        best_span = None
        for fd in pf.functions:
            n = fd.node
            end = getattr(n, "end_lineno", n.lineno)
            if n.lineno <= line <= end:
                span = end - n.lineno
                if best_span is None or span <= best_span:
                    best, best_span = fd.qualname, span
        return best

    # -- call resolution ---------------------------------------------------

    def resolve_call(self, pf: PyFile, scope: Optional[FuncDef],
                     call: ast.Call) -> Optional[FuncDef]:
        """Best-effort static resolution of a call to a FuncDef.

        Handles: lexical nested functions, module top-level functions,
        ``self.method(...)`` within a class, imported package symbols
        (``from ..ops.windows import f`` / ``from . import mod``) and
        one-level module attribute calls (``windows.f(...)``). Returns
        None for anything dynamic.
        """
        fn = call.func
        if isinstance(fn, ast.Name):
            cur = scope
            while cur is not None:
                if fn.id in cur.children:
                    return cur.children[fn.id]
                cur = cur.parent
            if scope is not None and scope.class_info is not None \
                    and fn.id in scope.class_info.methods:
                return scope.class_info.methods[fn.id]
            if fn.id in pf.top_functions:
                return pf.top_functions[fn.id]
            dotted = pf.imports.get(fn.id)
            if dotted and "." in dotted:
                mod, _, sym = dotted.rpartition(".")
                mf = self.module_file(mod)
                if mf is not None:
                    return mf.top_functions.get(sym)
            return None
        if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
            base, attr = fn.value.id, fn.attr
            if base in ("self", "cls") and scope is not None \
                    and scope.class_info is not None:
                return scope.class_info.methods.get(attr)
            dotted = pf.imports.get(base)
            if dotted:
                mf = self.module_file(dotted)
                if mf is not None:
                    return mf.top_functions.get(attr)
        return None

    def reachable(self, roots: Iterable[FuncDef]) -> Set[Tuple[str, str]]:
        """BFS closure of statically-resolvable calls from ``roots``."""
        seen: Dict[Tuple[str, str], FuncDef] = {}
        work = list(roots)
        while work:
            fd = work.pop()
            if fd.key in seen:
                continue
            seen[fd.key] = fd
            for call in walk_calls(fd.node):
                tgt = self.resolve_call(fd.file, fd, call)
                if tgt is not None and tgt.key not in seen:
                    work.append(tgt)
        self._reach_cache = seen
        return set(seen)

    def reachable_funcs(self, roots: Iterable[FuncDef]) -> List[FuncDef]:
        keys = self.reachable(roots)
        return [self._reach_cache[k] for k in sorted(keys)]


def walk_calls(fn_node: ast.AST) -> Iterable[ast.Call]:
    """Calls lexically inside a def, not descending into nested defs."""
    for node in iter_own_nodes(fn_node):
        if isinstance(node, ast.Call):
            yield node


def iter_own_nodes(fn_node: ast.AST) -> Iterable[ast.AST]:
    """All nodes of a def excluding nested function/class bodies."""
    body = fn_node.body if isinstance(fn_node.body, list) else [fn_node.body]
    stack: List[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            stack.append(child)


def dotted_name(node: ast.AST) -> str:
    """'jnp.zeros' for Attribute chains rooted at a Name, else ''."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""
