"""Rule plugin registry.

A rule is a class with ``name``, ``doc`` (one-line catalog entry) and a
``run(project) -> Iterable[Finding]``; registration is the decorator::

    @register
    class MyRule:
        name = "my-rule"
        doc = "what invariant this protects"
        def run(self, project): ...

Rules are discovered by importing :mod:`rtfdslint.rules` (its
``__init__`` imports every rule module); anything registered after that
— e.g. a repo-local plugin imported by a wrapper script — participates
identically. Names must be unique and kebab-case (they are the pragma
and baseline vocabulary).
"""

from __future__ import annotations

import re
from typing import Dict, List, Type

_RULES: Dict[str, type] = {}
_loaded = False
_NAME_RE = re.compile(r"^[a-z][a-z0-9]*(-[a-z0-9]+)*$")

#: meta-rule names emitted by the framework itself (pragma hygiene);
#: they have no plugin class but are valid pragma/baseline targets.
META_RULES = ("pragma-missing-reason", "pragma-unknown-rule",
              "pragma-malformed", "parse-error")


def register(cls: Type) -> Type:
    name = getattr(cls, "name", "")
    if not _NAME_RE.match(name or ""):
        raise ValueError(f"rule name {name!r} must be kebab-case")
    if name in _RULES or name in META_RULES:
        raise ValueError(f"duplicate rule name {name!r}")
    if not getattr(cls, "doc", ""):
        raise ValueError(f"rule {name!r} needs a one-line doc")
    _RULES[name] = cls
    return cls


def all_rules() -> List[type]:
    _ensure_loaded()
    return [_RULES[k] for k in sorted(_RULES)]


def get_rule(name: str) -> type:
    _ensure_loaded()
    return _RULES[name]


def known_rule_names() -> set:
    _ensure_loaded()
    return set(_RULES) | set(META_RULES)


def _ensure_loaded() -> None:
    # a dedicated flag, NOT `if not _RULES`: a repo-local plugin may
    # register itself before the first all_rules() call, and the
    # built-ins must still load alongside it
    global _loaded
    if not _loaded:
        from . import rules  # noqa: F401  (side effect: registration)

        # only after the import SUCCEEDS: a failed first load must be
        # retried, never remembered as "loaded" with a partial rule set
        _loaded = True
