"""config-flag-drift: CLI flags, config fields and README knob docs agree.

The metric-name-drift rule's sibling for the CONFIG surface. The
operator-facing knob path is README example → argparse flag →
``dataclasses.replace(cfg.<plane>, field=…)`` → frozen config field;
a break anywhere on it is silent at review time and embarrassing at
runtime:

* a ``--flag`` shown on an ``rtfds`` command line in the README that no
  ``add_argument`` defines → the documented invocation exits 2 (P1);
* a flag ``add_argument`` parses whose dest no code ever reads
  (``args.<dest>`` / ``getattr(args, "<dest>")``) → a silent no-op knob
  the operator believes they set (P1);
* a ``replace(cfg.<plane>, keyword=…)`` keyword that is not a field of
  that plane's dataclass → TypeError on a path that may only run in
  production (P1);
* a ``RuntimeConfig`` field the README never mentions (literally or as
  its ``--dashed-flag`` spelling) → an operator-invisible serving knob,
  the config twin of ``undocumented-metric`` (P2, reported as
  ``undocumented-config-knob``).

Approximations (deliberate): dest-read detection accepts a matching
string constant inside a tuple/list literal (the CLI's forwarding
loops iterate such tuples over ``getattr``); README flag extraction
only looks at ``rtfds``-bearing command lines inside fenced code
blocks, so prose mentions and other tools' flags never false-positive.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Set, Tuple

from ..finding import Finding
from ..project import PACKAGE_NAME, Project, PyFile
from ..registry import register

_FLAG_RE = re.compile(r"--[a-z][a-z0-9-]*")
_FENCE_RE = re.compile(r"^```")


def _collect_flags(pf: PyFile) -> Dict[str, Tuple[str, int]]:
    """long flag → (dest, line) over every ``add_argument`` call."""
    out: Dict[str, Tuple[str, int]] = {}
    for n in ast.walk(pf.tree):
        if not (isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
                and n.func.attr == "add_argument"):
            continue
        longs = [a.value for a in n.args
                 if isinstance(a, ast.Constant) and isinstance(a.value, str)
                 and a.value.startswith("--")]
        if not longs:
            continue  # positional argument: not a knob surface
        dest = None
        for kw in n.keywords:
            if kw.arg == "dest" and isinstance(kw.value, ast.Constant):
                dest = kw.value.value
        if dest is None:
            dest = longs[0].lstrip("-").replace("-", "_")
        for f in longs:
            out.setdefault(f, (dest, n.lineno))
    return out


def _collect_dest_reads(pf: PyFile) -> Set[str]:
    """Names provably read off an ``args`` namespace."""
    reads: Set[str] = set()
    for n in ast.walk(pf.tree):
        if isinstance(n, ast.Attribute) and isinstance(n.value, ast.Name) \
                and n.value.id == "args":
            reads.add(n.attr)
        elif isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                and n.func.id == "getattr" and n.args \
                and isinstance(n.args[0], ast.Name) \
                and n.args[0].id == "args":
            if len(n.args) > 1 and isinstance(n.args[1], ast.Constant):
                reads.add(str(n.args[1].value))
        elif isinstance(n, (ast.Tuple, ast.List)):
            # forwarding-loop idiom: `for flag in ("json", ...):
            # getattr(args, flag)` — accept tuple/list string literals
            for el in n.elts:
                if isinstance(el, ast.Constant) and isinstance(el.value,
                                                               str):
                    reads.add(el.value)
    return reads


def _config_fields(pf: PyFile) -> Dict[str, Dict[str, int]]:
    """dataclass name → {field name: line} for every class in config.py."""
    out: Dict[str, Dict[str, int]] = {}
    for n in ast.walk(pf.tree):
        if not isinstance(n, ast.ClassDef):
            continue
        fields: Dict[str, int] = {}
        for stmt in n.body:
            if isinstance(stmt, ast.AnnAssign) \
                    and isinstance(stmt.target, ast.Name):
                fields[stmt.target.id] = stmt.lineno
        out[n.name] = fields
    return out


def _readme_rtfds_flags(text: str) -> Dict[str, int]:
    """--flags used on rtfds command lines in fenced blocks → first line."""
    out: Dict[str, int] = {}
    in_fence = False
    carry = ""
    carry_line = 0
    for i, line in enumerate(text.splitlines(), start=1):
        if _FENCE_RE.match(line.strip()):
            in_fence = not in_fence
            carry = ""
            continue
        if not in_fence:
            continue
        if carry:
            line, lineno = carry + " " + line.strip(), carry_line
        else:
            lineno = i
        if line.rstrip().endswith("\\"):
            carry, carry_line = line.rstrip()[:-1], lineno
            continue
        carry = ""
        # strip comments: a '# ... --flag' remark is prose, not a knob
        code = line.split("#", 1)[0]
        if "rtfds" not in code:
            continue
        for m in _FLAG_RE.finditer(code):
            out.setdefault(m.group(0), lineno)
    return out


#: cfg attribute → config.py dataclass holding its fields
_PLANES = {
    "data": "DataConfig", "features": "FeatureConfig",
    "model": "ModelConfig", "train": "TrainConfig",
    "runtime": "RuntimeConfig", "learn": "LearnConfig",
    "mesh": "MeshConfig",
}


def _replace_calls(pf: PyFile) -> Iterable[Tuple[str, List[str], int]]:
    """(plane attr, keyword names, line) per ``*.replace(cfg.<plane>, …)``
    and ``cfg.replace(<plane>=…)`` call."""
    for n in ast.walk(pf.tree):
        if not (isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == "replace"):
            continue
        # dataclasses.replace(cfg.runtime, kw=...) — any module alias
        if n.args and isinstance(n.args[0], ast.Attribute) \
                and n.args[0].attr in _PLANES:
            kws = [kw.arg for kw in n.keywords if kw.arg]
            yield n.args[0].attr, kws, n.lineno
        # cfg.replace(runtime=..., learn=...) carries plane OBJECTS, not
        # field keywords — nothing to check there
    return


@register
class ConfigFlagDriftRule:
    name = "config-flag-drift"
    doc = ("CLI flags ↔ config fields ↔ README knob docs: a documented "
           "rtfds flag must exist, a parsed flag must be read, and "
           "replace() keywords must be real config fields")

    def run(self, project: Project) -> Iterable[Finding]:
        if PACKAGE_NAME not in project.target_specs:
            # whole-surface contract, same gating as metric-name-drift:
            # a partial run sees a partial flag/field set and every
            # verdict would be noise
            return []
        cli = project.files.get(f"{PACKAGE_NAME}/cli.py")
        cfg = project.files.get(f"{PACKAGE_NAME}/config.py")
        if cli is None or cli.tree is None or cfg is None \
                or cfg.tree is None:
            return []
        out: List[Finding] = []
        flags = _collect_flags(cli)
        reads = _collect_dest_reads(cli)
        classes = _config_fields(cfg)

        # 1) README rtfds command lines name only real flags
        for flag, line in sorted(_readme_rtfds_flags(
                project.readme_text).items()):
            if flag not in flags:
                out.append(Finding(
                    rule=self.name, severity="P1",
                    path=project.readme_rel, line=line,
                    message=(f"{flag} appears on an rtfds command line "
                             "but no add_argument defines it — the "
                             "documented invocation exits 2"),
                    context=flag))

        # 2) every parsed flag's dest is read somewhere
        dests_seen: Set[str] = set()
        for flag, (dest, line) in sorted(flags.items()):
            if dest in dests_seen:
                continue
            dests_seen.add(dest)
            if dest not in reads:
                out.append(Finding(
                    rule=self.name, severity="P1", path=cli.relpath,
                    line=line,
                    message=(f"{flag} is parsed into args.{dest} but "
                             "nothing ever reads it — the knob is a "
                             "silent no-op"),
                    context=flag))

        # 3) replace(cfg.<plane>, keyword=…) keywords are real fields
        for plane, kws, line in _replace_calls(cli):
            fields = classes.get(_PLANES[plane], {})
            for kw in kws:
                if fields and kw not in fields:
                    out.append(Finding(
                        rule=self.name, severity="P1", path=cli.relpath,
                        line=line,
                        message=(f"replace(cfg.{plane}, {kw}=…) names no "
                                 f"{_PLANES[plane]} field — TypeError on "
                                 "a path that may only run in "
                                 "production"),
                        context=f"{plane}.{kw}"))

        # 4) every RuntimeConfig serving knob is documented in README
        readme = project.readme_text
        for field, line in sorted(classes.get("RuntimeConfig",
                                              {}).items()):
            dashed = "--" + field.replace("_", "-")
            if field in readme or dashed in readme:
                continue
            out.append(Finding(
                rule="undocumented-config-knob", severity="P2",
                path=cfg.relpath, line=line,
                message=(f"RuntimeConfig.{field} is a serving knob the "
                         "README never mentions (document it, or its "
                         f"{dashed} flag spelling)"),
                context=field))
        return out


@register
class UndocumentedConfigKnobRule:
    """Catalog/pragma name holder; produced by ConfigFlagDriftRule
    (the runner follows ``produced_by`` for focused ``--rule`` runs)."""

    produced_by = "config-flag-drift"
    name = "undocumented-config-knob"
    doc = ("RuntimeConfig field absent from the README (an "
           "operator-invisible serving knob)")

    def run(self, project: Project) -> Iterable[Finding]:
        return []
