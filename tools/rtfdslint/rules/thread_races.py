"""cross-thread-race + lock-order-cycle: the single-writer discipline.

Six modules spawn threads (async sink writer, prefetch producer,
streaming learner, metrics HTTP server, checkpoint op-timeout, fault
injectors); each one's contract is "loop thread owns X, worker owns Y,
hand-offs go through a Queue/Event/lock or an atomic whole-object
swap". This rule derives that contract per class and flags where the
code breaks it.

Thread inventory: every ``threading.Thread(target=self.X)`` /
``executor.submit(self.X)`` inside a class marks ``X`` as a worker
entry point. The worker side is the self-call closure of those entry
points; the loop side is the closure of every other method
(``__init__`` is excluded — it runs before the thread exists).

An attribute shared by both sides is SAFE when every access is one of:
* inside ``with self.<lock>`` (a lock/RLock/Condition attr, by
  constructor or by name), including methods only ever called from
  inside such a block;
* an operation on a synchronization object itself (Queue/Event/
  deque/Lock constructed in ``__init__``);
* a plain whole-object rebind (``self.x = v``) or plain read — the
  sanctioned GIL-atomic swap idiom.

What's flagged (P1) is the remainder: read-modify-write (``+=``) or
in-place mutation (``.append``/``[k] = v``/``del``/``.update``…) of a
plain shared attribute with no guard on either side — exactly the
shape of bug PR 7's review-pass hardening list kept finding at runtime.

lock-order-cycle (P1): nested ``with self._a: … with self._b:``
acquisitions (lexically, plus one level of intra-class calls) build a
per-class acquisition graph; any cycle is a potential deadlock.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..finding import Finding
from ..project import (ClassInfo, FuncDef, Project, PyFile, dotted_name,
                       iter_own_nodes, walk_calls)
from ..registry import register

SYNC_CONSTRUCTORS = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Event", "threading.Semaphore", "threading.BoundedSemaphore",
    "threading.Barrier", "Lock", "RLock", "Condition", "Event",
    "queue.Queue", "queue.LifoQueue", "queue.PriorityQueue",
    "queue.SimpleQueue", "Queue", "SimpleQueue",
    "collections.deque", "deque",
}
LOCK_CONSTRUCTORS = {"threading.Lock", "threading.RLock",
                     "threading.Condition", "Lock", "RLock", "Condition"}
MUTATORS = {"append", "appendleft", "extend", "extendleft", "insert",
            "add", "update", "setdefault", "pop", "popitem", "popleft",
            "remove", "discard", "clear", "sort", "reverse", "write"}
THREAD_NAMES = {"threading.Thread", "Thread"}


@dataclass
class Access:
    attr: str
    kind: str      # "read" | "swap" | "rmw" | "mutate"
    guarded: bool
    method: str
    line: int


@dataclass
class MethodFacts:
    accesses: List[Access] = field(default_factory=list)
    #: (callee-name, guarded) intra-class call sites
    calls: List[Tuple[str, bool]] = field(default_factory=list)
    #: lock-acquisition nesting edges (outer, inner) + held-at-call map
    lock_edges: List[Tuple[str, str, int]] = field(default_factory=list)
    calls_under_lock: List[Tuple[str, str, int]] = field(
        default_factory=list)  # (callee, held lock, line)
    acquires: List[str] = field(default_factory=list)


@register
class CrossThreadRaceRule:
    name = "cross-thread-race"
    doc = ("mutable attribute written in one thread's reachable set and "
           "read in another's with no lock/queue/atomic-swap guard")

    def run(self, project: Project) -> Iterable[Finding]:
        out: List[Finding] = []
        for pf in project.target_files():
            if pf.tree is None:
                continue
            for ci in pf.classes.values():
                out.extend(self._check_class(project, pf, ci))
        return out

    # -- per-class ---------------------------------------------------------

    def _check_class(self, project: Project, pf: PyFile,
                     ci: ClassInfo) -> List[Finding]:
        targets = self._thread_targets(ci)
        if not targets:
            return []
        sync_attrs, lock_attrs = _sync_attrs(ci)
        facts = {name: self._method_facts(fd, sync_attrs, lock_attrs)
                 for name, fd in ci.methods.items()}
        self._propagate_lock_context(facts, targets)

        worker_methods = self._closure(ci, targets)
        # Loop-side roots: everything externally invocable. A PRIVATE
        # method that only exists inside the worker closure is not an
        # independent loop entry point — rooting it would count its
        # accesses on both sides and report single-thread-owned code as
        # racing with itself. (If loop-side code really calls it, it
        # enters the loop closure through that caller's public root.)
        loop_roots = [m for m in ci.methods
                      if m not in ("__init__",) and m not in targets
                      and (not m.startswith("_")
                           or m not in worker_methods)]
        loop_methods = self._closure(ci, loop_roots)

        findings = self._race_findings(pf, ci, facts, sync_attrs,
                                       worker_methods, loop_methods,
                                       targets)
        findings.extend(self._lock_cycles(pf, ci, facts))
        return findings

    def _thread_targets(self, ci: ClassInfo) -> Set[str]:
        """Worker entry points spawned by this class."""
        targets: Set[str] = set()
        for fd in ci.methods.values():
            for call in walk_calls(fd.node):
                dn = dotted_name(call.func)
                if dn in THREAD_NAMES:
                    for kw in call.keywords:
                        if kw.arg == "target":
                            m = _self_attr(kw.value)
                            if m:
                                targets.add(m)
                elif isinstance(call.func, ast.Attribute) \
                        and call.func.attr == "submit" and call.args:
                    m = _self_attr(call.args[0])
                    if m:
                        targets.add(m)
        return {t for t in targets if t in ci.methods}

    def _closure(self, ci: ClassInfo, roots: Iterable[str]) -> Set[str]:
        seen: Set[str] = set()
        work = [r for r in roots if r in ci.methods]
        while work:
            m = work.pop()
            if m in seen:
                continue
            seen.add(m)
            for call in walk_calls(ci.methods[m].node):
                callee = _self_call(call)
                if callee and callee in ci.methods and callee not in seen:
                    work.append(callee)
        return seen

    # -- per-method fact extraction ---------------------------------------

    def _method_facts(self, fd: FuncDef, sync_attrs: Set[str],
                      lock_attrs: Set[str]) -> MethodFacts:
        mf = MethodFacts()

        def walk(stmts: List[ast.stmt], held: List[str]) -> None:
            for s in stmts:
                if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                    continue
                if isinstance(s, ast.With):
                    locks_here = []
                    for item in s.items:
                        a = _self_attr(item.context_expr)
                        if a and (a in lock_attrs or _lockish(a)):
                            # items acquire left-to-right: earlier
                            # items of the SAME with are already held
                            # (`with self._a, self._b:` is an a->b edge)
                            for outer in held + locks_here:
                                if outer != a:
                                    mf.lock_edges.append(
                                        (outer, a, s.lineno))
                            locks_here.append(a)
                            mf.acquires.append(a)
                        else:
                            self._exprs(item.context_expr, held, mf, fd)
                    walk(s.body, held + locks_here)
                    continue
                if isinstance(s, ast.Try):
                    walk(s.body, held)
                    for h in s.handlers:
                        walk(h.body, held)
                    walk(s.orelse, held)
                    walk(s.finalbody, held)
                    continue
                if isinstance(s, (ast.If, ast.While)):
                    self._exprs(s.test, held, mf, fd)
                    walk(s.body, held)
                    walk(s.orelse, held)
                    continue
                if isinstance(s, ast.For):
                    self._exprs(s.iter, held, mf, fd)
                    self._store_targets(s.target, held, mf, fd)
                    walk(s.body, held)
                    walk(s.orelse, held)
                    continue
                if isinstance(s, ast.Match):
                    self._exprs(s.subject, held, mf, fd)
                    for case in s.cases:
                        if case.guard is not None:
                            self._exprs(case.guard, held, mf, fd)
                        walk(case.body, held)
                    continue
                if isinstance(s, ast.Assign):
                    self._exprs(s.value, held, mf, fd)
                    for t in s.targets:
                        self._store_targets(t, held, mf, fd)
                    continue
                if isinstance(s, ast.AnnAssign):
                    if s.value is not None:
                        self._exprs(s.value, held, mf, fd)
                    self._store_targets(s.target, held, mf, fd)
                    continue
                if isinstance(s, ast.AugAssign):
                    self._exprs(s.value, held, mf, fd)
                    a = _self_attr(s.target)
                    if a:
                        mf.accesses.append(Access(a, "rmw", bool(held),
                                                  fd.name, s.lineno))
                    elif isinstance(s.target, ast.Subscript):
                        base = _self_attr(s.target.value)
                        if base:
                            mf.accesses.append(Access(
                                base, "mutate", bool(held), fd.name,
                                s.lineno))
                    continue
                if isinstance(s, ast.Delete):
                    for t in s.targets:
                        a = _self_attr(t)
                        if a:
                            mf.accesses.append(Access(
                                a, "mutate", bool(held), fd.name,
                                s.lineno))
                        elif isinstance(t, ast.Subscript):
                            base = _self_attr(t.value)
                            if base:
                                mf.accesses.append(Access(
                                    base, "mutate", bool(held), fd.name,
                                    s.lineno))
                    continue
                # everything else: scan expressions
                for child in ast.iter_child_nodes(s):
                    if isinstance(child, ast.expr):
                        self._exprs(child, held, mf, fd)

        if isinstance(fd.node.body, list):
            walk(fd.node.body, [])
        return mf

    def _store_targets(self, node: ast.AST, held: List[str],
                       mf: MethodFacts, fd: FuncDef) -> None:
        a = _self_attr(node)
        if a:
            mf.accesses.append(Access(a, "swap", bool(held), fd.name,
                                      node.lineno))
            return
        if isinstance(node, ast.Subscript):
            base = _self_attr(node.value)
            if base:
                mf.accesses.append(Access(base, "mutate", bool(held),
                                          fd.name, node.lineno))
            self._exprs(node.slice, held, mf, fd)
            return
        if isinstance(node, (ast.Tuple, ast.List)):
            for elt in node.elts:
                self._store_targets(elt, held, mf, fd)

    def _exprs(self, expr: ast.AST, held: List[str], mf: MethodFacts,
               fd: FuncDef) -> None:
        """Reads, mutating calls and intra-class calls in an expression.

        Accesses inside a nested lambda/def are still recorded on the
        defining method's side (the common queue-callback idiom runs
        them near their definition) but ALWAYS as unguarded: the body
        executes later, when any lock held at definition time has long
        been released.
        """
        stack: List[tuple] = [(expr, False)]
        while stack:
            n, deferred = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                for child in ast.iter_child_nodes(n):
                    stack.append((child, True))
                continue
            for child in ast.iter_child_nodes(n):
                stack.append((child, deferred))
            guarded = bool(held) and not deferred
            if isinstance(n, ast.Call):
                callee = _self_call(n)
                if callee:
                    mf.calls.append((callee, guarded))
                    if guarded:
                        mf.calls_under_lock.append((callee, held[-1],
                                                    n.lineno))
                if isinstance(n.func, ast.Attribute):
                    base = _self_attr(n.func.value)
                    if base:
                        kind = ("mutate" if n.func.attr in MUTATORS
                                else "read")
                        mf.accesses.append(Access(base, kind, guarded,
                                                  fd.name, n.lineno))
            elif isinstance(n, ast.Attribute) and isinstance(n.ctx,
                                                             ast.Load):
                a = _self_attr(n)
                if a:
                    mf.accesses.append(Access(a, "read", guarded,
                                              fd.name, n.lineno))

    def _propagate_lock_context(self, facts: Dict[str, MethodFacts],
                                targets: Set[str]) -> None:
        """A private method only ever called under a lock is guarded.

        Thread ENTRY POINTS are excluded: ``Thread(target=self._work)``
        invokes ``_work`` with no lock held, so even if every in-code
        call site is guarded, the thread's own invocation is not.
        """
        for _ in range(3):  # tiny fixpoint (call chains are shallow)
            changed = False
            for name, mf in facts.items():
                if not name.startswith("_") or name == "__init__" \
                        or name in targets:
                    continue
                sites = [g for callee, g in _all_calls(facts)
                         if callee == name]
                if sites and all(sites):
                    for acc in mf.accesses:
                        if not acc.guarded:
                            acc.guarded = True
                            changed = True
                    for i, (callee, g) in enumerate(mf.calls):
                        if not g:
                            mf.calls[i] = (callee, True)
                            changed = True
            if not changed:
                break

    # -- verdicts ----------------------------------------------------------

    def _race_findings(self, pf: PyFile, ci: ClassInfo,
                       facts: Dict[str, MethodFacts],
                       sync_attrs: Set[str], worker: Set[str],
                       loop: Set[str], targets: Set[str]) -> List[Finding]:
        by_attr: Dict[str, Dict[str, List[Access]]] = {}
        for side, methods in (("worker", worker), ("loop", loop)):
            for m in sorted(methods):  # deterministic finding messages
                for acc in facts[m].accesses:
                    if acc.attr in sync_attrs or _lockish(acc.attr):
                        continue
                    by_attr.setdefault(acc.attr, {}).setdefault(
                        side, []).append(acc)
        out: List[Finding] = []
        for attr, sides in sorted(by_attr.items()):
            w, l = sides.get("worker", []), sides.get("loop", [])
            if not w or not l:
                continue
            for side_name, accs, other in (("worker", w, l),
                                           ("loop", l, w)):
                bad = [a for a in accs if not a.guarded
                       and a.kind in ("rmw", "mutate")]
                # ANY access on the other side races with an unguarded
                # RMW/mutation — a lock only excludes other lock
                # holders, so a fully-guarded far side does not make
                # this side's bare `+=` safe (lost update)
                if bad and other:
                    a, o = bad[0], other[0]
                    out.append(Finding(
                        rule=self.name, severity="P1", path=pf.relpath,
                        line=a.line,
                        message=(
                            f"self.{attr} is {_verb(a.kind)} WITHOUT a "
                            f"guard in {side_name}-side "
                            f"{ci.name}.{a.method} and "
                            f"{_verb(o.kind)}"
                            f"{'' if not o.guarded else ' (guarded)'} in "
                            f"{_other(side_name)}-side "
                            f"{ci.name}.{o.method} — a lock only "
                            "excludes other lock holders (threads "
                            f"spawned with target={sorted(targets)})"),
                        context=f"{pf.module}:{ci.name}.{a.method}"))
                    break  # one finding per attribute
        return out

    def _lock_cycles(self, pf: PyFile, ci: ClassInfo,
                     facts: Dict[str, MethodFacts]) -> List[Finding]:
        edges: Dict[str, Set[str]] = {}
        lines: Dict[Tuple[str, str], int] = {}
        for mf in facts.values():
            for outer, inner, line in mf.lock_edges:
                edges.setdefault(outer, set()).add(inner)
                lines.setdefault((outer, inner), line)
            # one level of call-aware nesting: with self._a: self.m()
            # where m acquires self._b
            for callee, lock, line in mf.calls_under_lock:
                cmf = facts.get(callee)
                if cmf is None:
                    continue
                for inner in cmf.acquires:
                    if inner != lock:
                        edges.setdefault(lock, set()).add(inner)
                        lines.setdefault((lock, inner), line)
        out: List[Finding] = []
        seen_cycles: Set[frozenset] = set()
        for start in sorted(edges):
            cyc = _find_cycle(edges, start)
            if cyc and frozenset(cyc) not in seen_cycles:
                seen_cycles.add(frozenset(cyc))
                line = lines.get((cyc[0], cyc[1]), ci.node.lineno)
                out.append(Finding(
                    rule="lock-order-cycle", severity="P1",
                    path=pf.relpath, line=line,
                    message=(f"{ci.name} acquires locks in a cycle: "
                             + " -> ".join(f"self.{a}" for a in cyc)
                             + " -> self." + cyc[0]
                             + " (potential deadlock under concurrent "
                               "entry)"),
                    context=f"{pf.module}:{ci.name}"))
        return out


@register
class LockOrderCycleRule:
    """Registered for catalog/pragma purposes; findings are produced by
    CrossThreadRaceRule (which owns the shared per-class facts) — the
    runner follows ``produced_by`` so ``--rule lock-order-cycle`` runs
    the producing analysis instead of passing vacuously."""

    name = "lock-order-cycle"
    doc = ("nested `with self._lock` acquisitions form a cycle across "
           "a class's methods (potential deadlock)")
    produced_by = "cross-thread-race"

    def run(self, project: Project) -> Iterable[Finding]:
        return []


def _verb(kind: str) -> str:
    return {"rmw": "read-modify-written (augmented assign)",
            "mutate": "mutated in place",
            "swap": "rebound", "read": "read"}[kind]


def _other(side: str) -> str:
    return "loop" if side == "worker" else "worker"


_LOCK_TOKENS = {"lock", "rlock", "mutex", "cond", "condition", "cv"}


def _lockish(attr: str) -> bool:
    """Name-convention lock detection, TOKEN-anchored: `_lock`,
    `state_lock`, `cond` — but never `seconds` or `clock` ('cond'/'lock'
    as substrings must not exclude plain attributes from analysis)."""
    return bool(_LOCK_TOKENS
                & set(attr.lower().lstrip("_").split("_")))


def _self_attr(node: ast.AST) -> str:
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return ""


def _self_call(call: ast.Call) -> str:
    if isinstance(call.func, ast.Attribute) \
            and isinstance(call.func.value, ast.Name) \
            and call.func.value.id == "self":
        return call.func.attr
    return ""


def _all_calls(facts: Dict[str, MethodFacts]):
    for mf in facts.values():
        for c in mf.calls:
            yield c


def _sync_attrs(ci: ClassInfo) -> Tuple[Set[str], Set[str]]:
    """Attrs assigned from sync-primitive constructors in __init__."""
    sync: Set[str] = set()
    locks: Set[str] = set()
    init = ci.methods.get("__init__")
    if init is None:
        return sync, locks
    for n in iter_own_nodes(init.node):
        if not isinstance(n, ast.Assign):
            continue
        if not isinstance(n.value, ast.Call):
            continue
        dn = dotted_name(n.value.func)
        if dn in SYNC_CONSTRUCTORS:
            for t in n.targets:
                a = _self_attr(t)
                if a:
                    sync.add(a)
                    if dn in LOCK_CONSTRUCTORS:
                        locks.add(a)
    return sync, locks


def _find_cycle(edges: Dict[str, Set[str]],
                start: str) -> Optional[List[str]]:
    path: List[str] = []
    on_path: Set[str] = set()

    def dfs(node: str) -> Optional[List[str]]:
        if node in on_path:
            return path[path.index(node):]
        if node not in edges:
            return None
        path.append(node)
        on_path.add(node)
        for nxt in sorted(edges[node]):
            got = dfs(nxt)
            if got:
                return got
        path.pop()
        on_path.discard(node)
        return None

    return dfs(start)
