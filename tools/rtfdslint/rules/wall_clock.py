"""wall-clock-duration: `time.time()` deltas measured as latency.

Every latency/duration claim in this repo is registry-grounded
(standing ROADMAP rule), and wall clock is not a duration clock: NTP
slews it, the operator can step it, and a negative "latency" poisons
histograms silently. Durations use ``time.perf_counter()`` (or
``monotonic``); ``time.time()`` is for *timestamps* — manifest stamps,
part-file names, cross-process ages.

Flagged (P1): a ``time.time()`` value appearing in a ``-`` expression —
directly (``time.time() - t0``) or through a local variable assigned
from it in the same function. Cross-process age checks (healthz batch
age vs a wall-clock gauge, probe-cache TTL vs a persisted stamp) are
wall-clock *on purpose*: those carry a pragma naming that reason.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set

from ..finding import Finding
from ..project import Project, PyFile, dotted_name, iter_own_nodes
from ..registry import register


def _time_aliases(pf: PyFile) -> Set[str]:
    """Dotted spellings of wall-clock time() in this file."""
    out = {"time.time"}
    for local, target in pf.imports.items():
        if target == "time":
            out.add(f"{local}.time")
        elif target == "time.time":
            out.add(local)
    return out


def _is_wall_call(node: ast.AST, aliases: Set[str]) -> bool:
    return isinstance(node, ast.Call) and dotted_name(node.func) in aliases


@register
class WallClockDurationRule:
    name = "wall-clock-duration"
    doc = ("time.time() delta used as a duration — use perf_counter/"
           "monotonic; wall clock is for timestamps only")

    def run(self, project: Project) -> Iterable[Finding]:
        out: List[Finding] = []
        for pf in project.target_files():
            if pf.tree is None:
                continue
            aliases = _time_aliases(pf)
            for fd in pf.functions:
                out.extend(self._scan_scope(
                    pf, iter_own_nodes(fd.node), aliases,
                    f"{pf.module}:{fd.qualname}"))
            # module level (rare but possible)
            out.extend(self._scan_scope(
                pf, _module_level(pf.tree), aliases, pf.module))
        return out

    def _scan_scope(self, pf: PyFile, nodes, aliases: Set[str],
                    context: str) -> List[Finding]:
        # single source-ordered pass: a rebind to anything else KILLS a
        # name's wall-clock status, so `t = time.time(); ...;
        # t = time.perf_counter(); d = perf_counter() - t` never flags
        all_nodes = sorted(
            (n for n in nodes
             if isinstance(n, (ast.Assign, ast.AnnAssign, ast.BinOp))),
            key=lambda n: (n.lineno, n.col_offset))
        wall_names: Set[str] = set()
        out: List[Finding] = []
        for n in all_nodes:
            if isinstance(n, ast.AnnAssign):
                if isinstance(n.target, ast.Name) and n.value is not None:
                    (wall_names.add(n.target.id)
                     if _is_wall_call(n.value, aliases)
                     else wall_names.discard(n.target.id))
                continue
            if isinstance(n, ast.Assign):
                for t in n.targets:
                    _bind_wall(t, n.value, aliases, wall_names)
                continue
            if not isinstance(n.op, ast.Sub):
                continue
            for side in (n.left, n.right):
                if self._wallish(side, aliases, wall_names):
                    out.append(Finding(
                        rule=self.name, severity="P1", path=pf.relpath,
                        line=n.lineno,
                        message=("duration computed from time.time(); "
                                 "use time.perf_counter() — or pragma "
                                 "with the reason a cross-process wall-"
                                 "clock age is really meant"),
                        context=context))
                    break
        return out

    def _wallish(self, node: ast.AST, aliases: Set[str],
                 wall_names: Set[str]) -> bool:
        """The operand IS (or directly wraps) a wall-clock value."""
        if _is_wall_call(node, aliases):
            return True
        if isinstance(node, ast.Name) and node.id in wall_names:
            return True
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id in ("float", "int") and node.args:
            return self._wallish(node.args[0], aliases, wall_names)
        return False


def _bind_wall(target: ast.AST, value: ast.AST, aliases: Set[str],
               wall_names: Set[str]) -> None:
    """Per-name wall status for one assignment target, including the
    ``t0, t1 = time.time(), time.time()`` tuple form; any non-wall
    rebind kills the name's status."""
    if isinstance(target, ast.Name):
        (wall_names.add(target.id) if _is_wall_call(value, aliases)
         else wall_names.discard(target.id))
        return
    if isinstance(target, (ast.Tuple, ast.List)):
        elts_v = (value.elts if isinstance(value, (ast.Tuple, ast.List))
                  and len(value.elts) == len(target.elts) else None)
        for i, t in enumerate(target.elts):
            _bind_wall(t, elts_v[i] if elts_v is not None
                       else ast.Constant(value=None), aliases, wall_names)


def _module_level(tree: ast.Module):
    stack: List[ast.AST] = list(tree.body)
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef, ast.Lambda)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))
