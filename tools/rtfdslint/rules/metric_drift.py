"""metric-name-drift: the registry, the docs and the dashboards agree.

The standing ROADMAP rule is that perf/robustness claims cite
``rtfds_*`` registry metrics. That only works if the names line up:
a dashboard tile or test asserting on a metric that nothing registers
reads forever-zero (silently green), and a registered metric the
README never mentions is an operator trap. Two-way diff:

* every ``rtfds_*`` token referenced in ``io/dashboard.py``, README
  and ``tests/`` must be registered by a
  ``.counter/.gauge/.histogram("rtfds_…")`` call somewhere in the
  package (or, for tests, in the tests themselves — fixtures register
  scratch metrics); histogram ``_bucket``/``_sum``/``_count`` suffixes
  normalize to the base name. Unregistered reference → P1.
* every name registered in the package must appear in the README —
  literally or via a documented ``rtfds_family_*`` wildcard prefix.
  Undocumented metric → P2 at the registration site.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Set, Tuple

from ..finding import Finding
from ..project import Project, PyFile
from ..registry import register

REGISTER_METHODS = {"counter", "gauge", "histogram"}
HIST_SUFFIXES = ("_bucket", "_sum", "_count")
#: token not followed by more name chars or a literal ``*`` (wildcards
#: are documentation prefixes, not names)
_REF_RE = re.compile(r"rtfds_[a-z0-9_]+(?![\w*])")
_WILD_RE = re.compile(r"(rtfds_[a-z0-9_]*)\*")


def _registrations(pf: PyFile) -> Iterable[Tuple[str, int]]:
    if pf.tree is None:
        return
    for n in ast.walk(pf.tree):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr in REGISTER_METHODS and n.args:
            a0 = n.args[0]
            if isinstance(a0, ast.Constant) and isinstance(a0.value, str) \
                    and a0.value.startswith("rtfds_"):
                yield a0.value, n.lineno


def _refs_in_text(text: str) -> Dict[str, int]:
    """name -> first line referenced."""
    out: Dict[str, int] = {}
    for i, line in enumerate(text.splitlines(), start=1):
        for m in _REF_RE.finditer(line):
            out.setdefault(m.group(0), i)
    return out


@register
class MetricNameDriftRule:
    name = "metric-name-drift"
    doc = ("rtfds_* name referenced in dashboard/README/tests but "
           "registered nowhere (reads forever-zero)")

    def run(self, project: Project) -> Iterable[Finding]:
        from ..project import PACKAGE_NAME

        if PACKAGE_NAME not in project.target_specs:
            # the two-way diff is a WHOLE-package contract: on a partial
            # run (one subdir, one file, a self-check over tools/) the
            # registration set is incomplete and every verdict would be
            # noise — skip rather than flood false P1s
            return []
        pkg_reg: Dict[str, Tuple[str, int]] = {}   # name -> first site
        for pf in project.target_files():
            for name, line in _registrations(pf):
                pkg_reg.setdefault(name, (pf.relpath, line))
        test_reg: Set[str] = set()
        for pf in project.test_files():
            test_reg.update(n for n, _ in _registrations(pf))

        def covered(name: str, registered: Set[str]) -> bool:
            if name in registered:
                return True
            for suf in HIST_SUFFIXES:
                if name.endswith(suf) and name[: -len(suf)] in registered:
                    return True
            return False

        out: List[Finding] = []
        # dashboard/README references must resolve against PACKAGE
        # registrations — a tests-only fixture metric must not satisfy a
        # production tile (it would still read forever-zero in serving).
        # References inside tests/ may additionally use the tests' own
        # scratch registrations.
        pkg_names = set(pkg_reg)
        ref_sources: List[Tuple[str, str, Set[str]]] = []
        dash = project.files.get(
            "real_time_fraud_detection_system_tpu/io/dashboard.py")
        if dash is not None:
            ref_sources.append((dash.relpath, dash.text, pkg_names))
        if project.readme_text:
            ref_sources.append((project.readme_rel, project.readme_text,
                                pkg_names))
        for pf in project.test_files():
            ref_sources.append((pf.relpath, pf.text,
                                pkg_names | test_reg))
        for rel, text, registered in ref_sources:
            for name, line in sorted(_refs_in_text(text).items()):
                if not covered(name, registered):
                    out.append(Finding(
                        rule=self.name, severity="P1", path=rel,
                        line=line,
                        message=(f"{name} is referenced here but no "
                                 ".counter/.gauge/.histogram call "
                                 "registers it — the reference reads "
                                 "forever-zero"),
                        context=name))
        # direction 2: registered but undocumented
        doc_names = set(_refs_in_text(project.readme_text))
        doc_prefixes = {m.group(1)
                        for m in _WILD_RE.finditer(project.readme_text)}
        for name in sorted(pkg_reg):
            if name in doc_names:
                continue
            if any(name.startswith(p) for p in doc_prefixes):
                continue
            rel, line = pkg_reg[name]
            out.append(Finding(
                rule="undocumented-metric", severity="P2", path=rel,
                line=line,
                message=(f"{name} is registered but the README "
                         "observability catalog never mentions it "
                         "(document it or fold it into a documented "
                         "rtfds_family_* wildcard)"),
                context=name))
        return out


@register
class UndocumentedMetricRule:
    """Catalog/pragma name holder; produced by MetricNameDriftRule
    (the runner follows ``produced_by`` for focused ``--rule`` runs)."""

    produced_by = "metric-name-drift"
    name = "undocumented-metric"
    doc = ("metric registered in the package but absent from the README "
           "observability catalog")

    def run(self, project: Project) -> Iterable[Finding]:
        return []
