"""unbounded-queue: every runtime/io buffer carries an explicit bound.

The overload work (PR 12) makes unbounded buffering a CORRECTNESS bug,
not a style nit: the whole ladder exists because "just queue it" turns
sustained traffic above capacity into silent memory growth and an OOM
death far from the cause. Every queue the serving path owns is bounded
and backpressured (async sink, prefetch, learner, overload spill) — a
new ``Queue()``/``deque()`` constructed WITHOUT a bound in ``runtime/``
or ``io/`` either gets one or carries a pragma saying why its growth is
bounded by construction.

Flagged (P1, in ``runtime/``+``io/`` only — the thread-shared serving
planes; models/ops/tools build host-side data structures where list
growth is the algorithm):

* ``queue.Queue()`` / ``LifoQueue`` / ``PriorityQueue`` with no
  ``maxsize`` (or a constant ``maxsize=0`` — stdlib spelling for
  unbounded), resolved through the file's import table;
* ``collections.deque()`` with no ``maxlen`` (positional form
  ``deque(it, maxlen)`` counts as bounded);
* ``multiprocessing.Queue()`` with no ``maxsize``;
* the list-as-queue idiom: an ``x = []``/``list()`` attribute whose
  owner also calls BOTH ``x.append(...)`` and ``x.pop(0)`` /
  ``x.pop()``-at-head somewhere in the same file (a FIFO grown on one
  side and drained on the other — the shape a bounded ``deque`` or
  ``Queue`` should own).

A non-constant bound expression counts as bounded (someone chose one);
this rule only hunts the *absence* of a choice.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from ..finding import Finding
from ..project import Project, dotted_name
from ..registry import register

SCOPED_SUBDIRS = ("/runtime/", "/io/")
QUEUE_DOTTED = {
    "queue.Queue", "queue.LifoQueue", "queue.PriorityQueue",
    "queue.SimpleQueue", "multiprocessing.Queue",
}
DEQUE_DOTTED = {"collections.deque"}


def _scoped(relpath: str) -> bool:
    return any(s in "/" + relpath for s in SCOPED_SUBDIRS)


def _resolve(pf, dn: str) -> str:
    """Normalize 'Queue'/'q.Queue' to the canonical dotted path via the
    file's import table (same approximation as blocking-calls)."""
    if not dn:
        return ""
    head, _, rest = dn.partition(".")
    target = pf.imports.get(head)
    if target:
        return target + ("." + rest if rest else "")
    return dn


def _const_zero_or_none(node) -> bool:
    return isinstance(node, ast.Constant) and node.value in (0, None)


def _queue_unbounded(call: ast.Call) -> bool:
    """queue.Queue(...): bounded iff a maxsize arg exists and is not a
    constant 0/None."""
    if call.args:
        return _const_zero_or_none(call.args[0])
    for kw in call.keywords:
        if kw.arg == "maxsize":
            return _const_zero_or_none(kw.value)
    return True


def _deque_unbounded(call: ast.Call) -> bool:
    """deque(iterable, maxlen): bounded iff the 2nd positional or the
    maxlen kw exists and is not a constant None."""
    if len(call.args) >= 2:
        return _const_zero_or_none(call.args[1])
    for kw in call.keywords:
        if kw.arg == "maxlen":
            return _const_zero_or_none(kw.value)
    return True


def _attr_key(node) -> str:
    """'self.q' / 'q' for the mutation-site heuristic, '' otherwise."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
        return f"{node.value.id}.{node.attr}"
    if isinstance(node, ast.Name):
        return node.id
    return ""


@register
class UnboundedQueueRule:
    name = "unbounded-queue"
    doc = ("Queue()/deque()/list-as-queue without a bound in runtime/ "
           "or io/ — unbounded buffering turns overload into silent "
           "memory growth (the failure mode the overload ladder exists "
           "to prevent)")

    def run(self, project: Project) -> Iterable[Finding]:
        out: List[Finding] = []
        for pf in project.target_files():
            if pf.tree is None or not _scoped(pf.relpath):
                continue
            # pass 1: explicit queue/deque constructions
            for n in ast.walk(pf.tree):
                if not isinstance(n, ast.Call):
                    continue
                dn = _resolve(pf, dotted_name(n.func))
                if dn in QUEUE_DOTTED and _queue_unbounded(n):
                    what = dn.rsplit(".", 1)[-1]
                    if dn == "queue.SimpleQueue":
                        hint = ("SimpleQueue cannot be bounded — use "
                                "queue.Queue(maxsize=…)")
                    else:
                        hint = "pass maxsize=…"
                    out.append(Finding(
                        rule=self.name, severity="P1", path=pf.relpath,
                        line=n.lineno,
                        message=(f"{what}() constructed without a bound "
                                 "in a serving-plane module — a stalled "
                                 "consumer grows it until OOM; "
                                 f"{hint}, or pragma why growth is "
                                 "bounded by construction"),
                        context=f"{pf.module}:"
                                f"{project.qualname_at(pf, n.lineno)}"))
                elif dn in DEQUE_DOTTED and _deque_unbounded(n):
                    out.append(Finding(
                        rule=self.name, severity="P1", path=pf.relpath,
                        line=n.lineno,
                        message=("deque() constructed without maxlen in "
                                 "a serving-plane module — pass "
                                 "maxlen=… (only where drop-oldest is "
                                 "correct), or pragma why growth is "
                                 "bounded by construction"),
                        context=f"{pf.module}:"
                                f"{project.qualname_at(pf, n.lineno)}"))
            # pass 2: list-as-queue — [] attrs both appended and
            # head-popped in this file
            empties = {}   # key -> first assignment line
            appends = set()
            pops = set()
            for n in ast.walk(pf.tree):
                if isinstance(n, (ast.Assign, ast.AnnAssign)):
                    val = n.value
                    is_empty = (isinstance(val, ast.List)
                                and not val.elts) or (
                        isinstance(val, ast.Call)
                        and isinstance(val.func, ast.Name)
                        and val.func.id == "list" and not val.args)
                    if not is_empty:
                        continue
                    targets = (n.targets if isinstance(n, ast.Assign)
                               else [n.target])
                    for t in targets:
                        key = _attr_key(t)
                        if key:
                            empties.setdefault(key, n.lineno)
                elif isinstance(n, ast.Call) and isinstance(
                        n.func, ast.Attribute):
                    key = _attr_key(n.func.value)
                    if not key:
                        continue
                    if n.func.attr == "append":
                        appends.add(key)
                    elif n.func.attr == "pop" and (
                            not n.args
                            or (isinstance(n.args[0], ast.Constant)
                                and n.args[0].value == 0)):
                        pops.add(key)
            for key, line in sorted(empties.items()):
                if key in appends and key in pops:
                    out.append(Finding(
                        rule=self.name, severity="P1", path=pf.relpath,
                        line=line,
                        message=(f"{key} is a list used as a queue "
                                 "(append + pop at an end) in a "
                                 "serving-plane module — use a bounded "
                                 "Queue/deque, or pragma why growth is "
                                 "bounded by construction"),
                        context=f"{pf.module}:{key}"))
        return out
