"""blocking-call-on-loop-thread: keep the serving loop non-blocking.

PRs 3/5 moved every stall off the loop thread (async sink, prefetch,
overlapped fetch) and pinned the wins in perf-smoke; a stray
``time.sleep`` or subprocess call in engine-step-reachable code undoes
them invisibly until a p99 regression lands. Entry points are the
``run``/``process_batch``/``step`` methods of the ``*Engine`` classes
in ``runtime/``; reachability follows the statically-resolvable call
graph (same approximation as the jit rule). Sanctioned wait points —
the autobatch trigger pacing credited as wait time — carry pragmas.

Flagged (P1): ``time.sleep``, ``subprocess.*``, ``os.system``,
``urllib.request.urlopen``, ``socket.create_connection``,
``input`` in that reachable set.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from ..finding import Finding
from ..project import FuncDef, Project, dotted_name, iter_own_nodes
from ..registry import register

ENTRY_METHODS = {"run", "process_batch", "step"}
BLOCKING_DOTTED = {
    "time.sleep", "os.system", "subprocess.run", "subprocess.call",
    "subprocess.check_call", "subprocess.check_output",
    "subprocess.Popen", "urllib.request.urlopen",
    "socket.create_connection",
}
BLOCKING_BARE = {"input"}


@register
class BlockingCallOnLoopThreadRule:
    name = "blocking-call-on-loop-thread"
    doc = ("time.sleep / sync I/O reachable from the engine step path "
           "(stalls the serving loop) outside sanctioned wait points")

    def run(self, project: Project) -> Iterable[Finding]:
        roots: List[FuncDef] = []
        for rel in ("real_time_fraud_detection_system_tpu/runtime/"
                    "engine.py",
                    "real_time_fraud_detection_system_tpu/runtime/"
                    "sharded_engine.py"):
            pf = project.files.get(rel)
            if pf is None or pf.tree is None:
                continue
            for ci in pf.classes.values():
                if not ci.name.endswith("Engine"):
                    continue
                for m in ENTRY_METHODS:
                    fd = ci.methods.get(m)
                    if fd is not None:
                        roots.append(fd)
        if not roots:
            return []
        out: List[Finding] = []
        for fd in project.reachable_funcs(roots):
            pf = fd.file
            for n in iter_own_nodes(fd.node):
                if not isinstance(n, ast.Call):
                    continue
                dn = _resolve_through_imports(pf, dotted_name(n.func))
                bare = n.func.id if isinstance(n.func, ast.Name) else ""
                if dn in BLOCKING_DOTTED or bare in BLOCKING_BARE:
                    out.append(Finding(
                        rule=self.name, severity="P1", path=pf.relpath,
                        line=n.lineno,
                        message=(f"{dn or bare}() is reachable from the "
                                 "engine step path and blocks the "
                                 "serving loop thread — move it off-"
                                 "loop, or pragma the sanctioned wait "
                                 "point with its reason"),
                        context=f"{pf.module}:{fd.qualname}"))
        return out


def _resolve_through_imports(pf, dn: str) -> str:
    """'sleep' / 'tm.sleep' → 'time.sleep' via the file's import table
    (`from time import sleep`, `import time as tm`, plain `import
    time` all normalize to the canonical dotted path)."""
    if not dn:
        return ""
    head, _, rest = dn.partition(".")
    target = pf.imports.get(head)
    if target:
        return target + ("." + rest if rest else "")
    return dn
