"""Exception-taxonomy rules: keep the supervisor's crash classes legible.

``run_with_recovery`` recovers on a *typed* tuple (``TransientError``,
``OSError``, …) and its crash-loop breaker keys on the exception type
at a progress point (PR 4); the checkpoint/registry planes re-raise
original types after retry exhaustion (PR 6/7). Two code shapes erode
that taxonomy:

* ``raise RuntimeError(...)`` / ``raise Exception(...)`` in `runtime/`
  or `io/` — the supervisor cannot tell it from a jax-internal error
  (``TransientError`` deliberately subclasses ``RuntimeError``; a raw
  ``RuntimeError`` is an unclassified crash). P1 there, P2 elsewhere.
* broad catches. ``except Exception: pass`` (P1 anywhere) erases the
  crash signal entirely — the breaker never sees the type, the flight
  recorder never sees the event. A broad catch that does real handling
  is P1 in `runtime/`/`io/` and P2 elsewhere, UNLESS the handler
  re-raises via a bare ``raise`` (metering/translation wrappers keep
  the original type — that's the taxonomy-preserving shape).

``except recover_on`` / other name-typed catches are never flagged:
the tuple is typed at its definition site.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from ..finding import Finding
from ..project import Project, PyFile, dotted_name
from ..registry import register

GENERIC_RAISES = {"Exception", "RuntimeError", "BaseException"}
BROAD_CATCHES = {"Exception", "BaseException"}
#: paths whose exceptions the supervisor/recovery plane classifies
CLASSIFIED_SUBDIRS = ("/runtime/", "/io/")


def _classified(relpath: str) -> bool:
    return any(s in "/" + relpath for s in CLASSIFIED_SUBDIRS)


def _handler_types(h: ast.ExceptHandler) -> List[str]:
    if h.type is None:
        return ["<bare>"]
    nodes = h.type.elts if isinstance(h.type, ast.Tuple) else [h.type]
    return [dotted_name(n) or "<expr>" for n in nodes]


def _swallows(h: ast.ExceptHandler) -> bool:
    for s in h.body:
        if isinstance(s, (ast.Pass, ast.Continue)):
            continue
        if isinstance(s, ast.Expr) and isinstance(s.value, ast.Constant):
            continue  # docstring / ...
        return False
    return True


def _reraises(h: ast.ExceptHandler) -> bool:
    """Does the handler ITSELF re-raise the caught exception?

    A bare ``raise`` or ``raise e`` (the handler's own caught name)
    belonging to this handler counts: both preserve the original type.
    One inside a nested function (runs later, if ever) or inside a
    nested ``try``'s own except block (re-raises the INNER exception)
    does not preserve this handler's taxonomy.
    """
    stack: list = list(h.body)
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda, ast.ClassDef)):
            continue
        if isinstance(n, ast.Raise):
            if n.exc is None:
                return True
            if h.name and isinstance(n.exc, ast.Name) \
                    and n.exc.id == h.name:
                return True
        if isinstance(n, ast.Try):
            # body/else/finally still see this handler's exception
            # context; the nested handlers have their own
            stack.extend(n.body)
            stack.extend(n.orelse)
            stack.extend(n.finalbody)
            continue
        stack.extend(ast.iter_child_nodes(n))
    return False


@register
class RaiseGenericRule:
    name = "raise-generic-exception"
    doc = ("raise of bare Exception/RuntimeError in supervisor-classified "
           "paths (runtime/, io/) — the crash taxonomy can't see it")

    def run(self, project: Project) -> Iterable[Finding]:
        out: List[Finding] = []
        for pf in project.target_files():
            if pf.tree is None:
                continue
            for n in ast.walk(pf.tree):
                if not isinstance(n, ast.Raise) or n.exc is None:
                    continue
                exc = n.exc
                name = dotted_name(exc.func) if isinstance(exc, ast.Call) \
                    else dotted_name(exc)
                if name in GENERIC_RAISES:
                    sev = "P1" if _classified(pf.relpath) else "P2"
                    out.append(Finding(
                        rule=self.name, severity=sev, path=pf.relpath,
                        line=n.lineno,
                        message=(f"raise {name} — use a typed exception "
                                 "(TransientError subclass or a domain "
                                 "error) so the supervisor taxonomy can "
                                 "classify it"),
                        context=(f"{pf.module}:"
                                 f"{project.qualname_at(pf, n.lineno)}")))
        return out


@register
class ExceptionSwallowRule:
    name = "exception-swallow"
    doc = ("`except Exception: pass` — erases the crash-loop breaker's "
           "signal and the flight-record event")

    def run(self, project: Project) -> Iterable[Finding]:
        out: List[Finding] = []
        for pf in project.target_files():
            if pf.tree is None:
                continue
            for h in _handlers(pf):
                types = _handler_types(h)
                if not (set(types) & BROAD_CATCHES) and "<bare>" not in types:
                    continue
                if _swallows(h):
                    out.append(Finding(
                        rule=self.name, severity="P1", path=pf.relpath,
                        line=h.lineno,
                        message=("broad except silently swallows "
                                 f"({'/'.join(types)}) — at minimum log "
                                 "the type so crash classification and "
                                 "triage keep their signal"),
                        context=(f"{pf.module}:"
                                 f"{project.qualname_at(pf, h.lineno)}")))
        return out


@register
class BroadCatchRule:
    name = "broad-exception-catch"
    doc = ("`except Exception` without a bare re-raise in supervisor-"
           "classified paths — narrows the taxonomy to mush")

    def run(self, project: Project) -> Iterable[Finding]:
        out: List[Finding] = []
        for pf in project.target_files():
            if pf.tree is None:
                continue
            for h in _handlers(pf):
                types = _handler_types(h)
                if not (set(types) & BROAD_CATCHES) and "<bare>" not in types:
                    continue
                if _swallows(h) or _reraises(h):
                    continue  # swallow has its own rule; re-raise is fine
                sev = "P1" if _classified(pf.relpath) else "P2"
                out.append(Finding(
                    rule=self.name, severity=sev, path=pf.relpath,
                    line=h.lineno,
                    message=(f"broad catch ({'/'.join(types)}) handles "
                             "without re-raising — narrow to the types "
                             "this site really expects, or pragma with "
                             "the reason the broad net is intentional"),
                    context=(f"{pf.module}:"
                             f"{project.qualname_at(pf, h.lineno)}")))
        return out


def _handlers(pf: PyFile) -> Iterable[ast.ExceptHandler]:
    for n in ast.walk(pf.tree):
        if isinstance(n, ast.ExceptHandler):
            yield n
