"""Rule plugins — importing this package registers every rule."""

from . import blocking_calls  # noqa: F401
from . import config_drift  # noqa: F401
from . import exceptions  # noqa: F401
from . import jit_hazards  # noqa: F401
from . import metric_drift  # noqa: F401
from . import thread_races  # noqa: F401
from . import unbounded_queue  # noqa: F401
from . import wall_clock  # noqa: F401
