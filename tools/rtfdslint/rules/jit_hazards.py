"""jit-recompile-hazard: concretizations and value-branching in traced code.

The PR 3 invariant is ZERO mid-stream recompiles; the runtime proves it
after the fact with ``rtfds_xla_recompiles_total``. This rule proves it
before runtime: starting from every ``jax.jit``/``pjit`` call site and
decorator, it walks the statically-resolvable call graph and runs a
small taint analysis — parameters of a jitted function are traced
values (minus ``static_argnums``/``static_argnames``), assignments
propagate taint, ``.shape``/``.ndim``/``.dtype``/``.size``/``len()``
launder it (shapes are static under trace). Inside that reachable set
it flags, at P0:

* ``.item()`` / ``.tolist()`` on a tainted value — host sync; under
  trace a ConcretizationTypeError, as a closure a silent per-value
  recompile;
* ``int()/float()/bool()/complex()`` of a tainted value — same;
* ``np.*`` calls with a tainted argument — numpy forces concretization;
* ``if``/``while``/``assert`` tests on a tainted value — Python-value
  branching retraces per distinct value;
* ``jnp.zeros/ones/full/empty/arange/linspace/eye`` whose shape/bound
  argument is tainted — non-static shape construction.

Approximation notes: resolution is lexical + one-level imports, so a
dynamically-chosen step function is invisible (the runtime recompile
detector stays the backstop); taint does not flow through containers.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..finding import Finding
from ..project import (FuncDef, Project, PyFile, dotted_name,
                       iter_own_nodes)
from ..registry import register

SHAPE_ATTRS = {"shape", "ndim", "dtype", "size", "weak_type", "sharding",
               "aval", "itemsize"}
CASTS = {"int", "float", "bool", "complex"}
SHAPE_BUILDERS = {"zeros", "ones", "full", "empty", "arange", "linspace",
                  "eye", "tri"}
JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit"}
_MAX_DEPTH = 24


def _numpy_aliases(pf: PyFile) -> Set[str]:
    return {local for local, dotted in pf.imports.items()
            if dotted == "numpy"}


def _jnp_aliases(pf: PyFile) -> Set[str]:
    return {local for local, dotted in pf.imports.items()
            if dotted in ("jax.numpy", "jax.experimental.numpy")}


class _Taint:
    """Per-function forward taint over simple assignments."""

    def __init__(self, tainted: Set[str],
                 static_attrs: Optional[Set[str]] = None) -> None:
        self.names = set(tainted)
        self.static_attrs = static_attrs or set()

    def expr(self, node: ast.AST) -> bool:
        """Does this expression (transitively) carry a traced value?"""
        stack = [node]
        while stack:
            n = stack.pop()
            if isinstance(n, ast.Attribute) \
                    and (n.attr in SHAPE_ATTRS
                         or n.attr in self.static_attrs):
                continue  # static under trace: launders taint
            if isinstance(n, ast.Call):
                fn = n.func
                if isinstance(fn, ast.Name) and fn.id == "len":
                    continue  # len() of a traced array is static
            if isinstance(n, ast.Name) and n.id in self.names:
                return True
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
                continue
            stack.extend(ast.iter_child_nodes(n))
        return False


@register
class JitRecompileHazardRule:
    name = "jit-recompile-hazard"
    doc = ("tracer leaks / value-branching / non-static shapes in "
           "jit-reachable code (PR 3 zero-recompile invariant)")

    def run(self, project: Project) -> Iterable[Finding]:
        self.project = project
        self.findings: List[Finding] = []
        self._memo: Set[Tuple[str, str, frozenset]] = set()
        self._alias_cache: Dict[str, Tuple[Set[str], Set[str]]] = {}
        self._static_attrs = _static_property_names(project)
        for pf in project.target_files():
            if pf.tree is None:
                continue
            for fd, call in self._jit_sites(pf):
                root, static = self._jit_target(pf, fd, call)
                if root is None:
                    continue
                params = [p for p in _params_of(root.node)
                          if p not in static]
                self._analyze(root, frozenset(params), 0)
            for fd in pf.functions:
                static = self._decorator_static(fd.node)
                if static is None:
                    continue
                params = [p for p in fd.param_names()
                          if p not in static and p not in ("self", "cls")]
                self._analyze(fd, frozenset(params), 0)
        return self.findings

    # -- root discovery ----------------------------------------------------

    def _jit_sites(self, pf: PyFile):
        """(enclosing FuncDef|None, jit Call) pairs in one file."""
        seen_calls = set()
        for fd in pf.functions:
            for node in iter_own_nodes(fd.node):
                if isinstance(node, ast.Call) \
                        and dotted_name(node.func) in JIT_NAMES:
                    seen_calls.add(id(node))
                    yield fd, node
        for node in ast.walk(pf.tree):
            if isinstance(node, ast.Call) and id(node) not in seen_calls \
                    and dotted_name(node.func) in JIT_NAMES:
                yield None, node

    def _jit_target(self, pf: PyFile, scope: Optional[FuncDef],
                    call: ast.Call):
        """Resolve jax.jit(<target>, ...) → (FuncDef-ish, static names)."""
        if not call.args:
            return None, set()
        target = call.args[0]
        fd: Optional[FuncDef] = None
        if isinstance(target, ast.Lambda):
            fd = FuncDef(target, pf, f"<lambda@{target.lineno}>",
                         class_info=scope.class_info if scope else None,
                         parent=scope)
        elif isinstance(target, (ast.Name, ast.Attribute)):
            fake_call = ast.Call(func=target, args=[], keywords=[])
            fd = self.project.resolve_call(pf, scope, fake_call)
        if fd is None:
            return None, set()
        # static names resolve against the *resolved* def's parameter
        # list (static_argnums on a bare name needs the target's
        # params). jax.jit(self.step, …) receives a BOUND method: self
        # is already applied, so indices start at the first real param.
        bound = (isinstance(target, ast.Attribute)
                 and isinstance(target.value, ast.Name)
                 and target.value.id in ("self", "cls"))
        return fd, self._static_names(call, fd.node, bound=bound)

    def _static_names(self, call: ast.Call, target: ast.AST,
                      bound: bool = False) -> Set[str]:
        """static_argnums/static_argnames → parameter-name set.

        For an UNBOUND def (``jax.jit(step)``, decorator on a method),
        jax's static_argnums counts ``self`` as position 0, so indexing
        uses the full parameter list; for a BOUND target
        (``jax.jit(self.step)``), self is already applied and indices
        start at the first real parameter."""
        params: List[str] = _params_full(target) if isinstance(
            target, (ast.Lambda, ast.FunctionDef,
                     ast.AsyncFunctionDef)) else []
        if bound and params and params[0] in ("self", "cls"):
            params = params[1:]
        out: Set[str] = set()
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                out.update(_const_strs(kw.value))
            elif kw.arg == "static_argnums":
                for i in _const_ints(kw.value):
                    if 0 <= i < len(params):
                        out.add(params[i])
                    elif not params:
                        out.add(f"<pos{i}>")
        return out

    def _decorator_static(self, node: ast.AST) -> Optional[Set[str]]:
        """static-name set when decorated @jax.jit / @partial(jax.jit,…)."""
        for dec in getattr(node, "decorator_list", []):
            if dotted_name(dec) in JIT_NAMES:
                return set()
            if isinstance(dec, ast.Call):
                dn = dotted_name(dec.func)
                if dn in JIT_NAMES:
                    return self._static_names(dec, node)
                if dn in ("partial", "functools.partial") and dec.args \
                        and dotted_name(dec.args[0]) in JIT_NAMES:
                    return self._static_names(dec, node)
        return None

    # -- taint walk --------------------------------------------------------

    def _analyze(self, fd: FuncDef, tainted_params: frozenset,
                 depth: int) -> None:
        key = (fd.file.relpath, fd.qualname, tainted_params)
        if key in self._memo or depth > _MAX_DEPTH or not tainted_params:
            return
        self._memo.add(key)
        taint = _Taint(set(tainted_params), self._static_attrs)
        pf = fd.file
        body = fd.node.body
        if not isinstance(body, list):  # Lambda
            self._check_expr(pf, fd, body, taint, depth)
            return
        self._stmts(pf, fd, body, taint, depth)

    def _stmts(self, pf: PyFile, fd: FuncDef, stmts: List[ast.stmt],
               taint: _Taint, depth: int) -> None:
        for s in stmts:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue
            if isinstance(s, ast.Assign):
                self._check_expr(pf, fd, s.value, taint, depth)
                is_t = taint.expr(s.value)
                for tgt in s.targets:
                    _retaint_target(tgt, is_t, taint)
            elif isinstance(s, ast.AnnAssign) and s.value is not None:
                self._check_expr(pf, fd, s.value, taint, depth)
                if isinstance(s.target, ast.Name):
                    (taint.names.add(s.target.id) if taint.expr(s.value)
                     else taint.names.discard(s.target.id))
            elif isinstance(s, ast.AugAssign):
                self._check_expr(pf, fd, s.value, taint, depth)
                if isinstance(s.target, ast.Name) and taint.expr(s.value):
                    taint.names.add(s.target.id)
            elif isinstance(s, (ast.If, ast.While)):
                self._check_expr(pf, fd, s.test, taint, depth)
                if not _identity_test(s.test) and taint.expr(s.test):
                    self._emit(pf, s.test,
                               "Python-value branching on a traced value "
                               "(retrace per distinct value, or "
                               "ConcretizationTypeError)", fd)
                self._stmts(pf, fd, s.body, taint, depth)
                self._stmts(pf, fd, s.orelse, taint, depth)
            elif isinstance(s, ast.Assert):
                self._check_expr(pf, fd, s.test, taint, depth)
                if taint.expr(s.test):
                    self._emit(pf, s.test,
                               "assert on a traced value (concretizes "
                               "under trace)", fd)
            elif isinstance(s, ast.For):
                self._check_expr(pf, fd, s.iter, taint, depth)
                if taint.expr(s.iter):
                    for n in ast.walk(s.target):
                        if isinstance(n, ast.Name):
                            taint.names.add(n.id)
                self._stmts(pf, fd, s.body, taint, depth)
                self._stmts(pf, fd, s.orelse, taint, depth)
            elif isinstance(s, ast.With):
                for item in s.items:
                    self._check_expr(pf, fd, item.context_expr, taint,
                                     depth)
                self._stmts(pf, fd, s.body, taint, depth)
            elif isinstance(s, ast.Try):
                self._stmts(pf, fd, s.body, taint, depth)
                for h in s.handlers:
                    self._stmts(pf, fd, h.body, taint, depth)
                self._stmts(pf, fd, s.orelse, taint, depth)
                self._stmts(pf, fd, s.finalbody, taint, depth)
            elif isinstance(s, ast.Match):
                self._check_expr(pf, fd, s.subject, taint, depth)
                if taint.expr(s.subject):
                    self._emit(pf, s.subject,
                               "match on a traced value (structural "
                               "patterns concretize under trace)", fd)
                for case in s.cases:
                    if case.guard is not None:
                        self._check_expr(pf, fd, case.guard, taint,
                                         depth)
                        if taint.expr(case.guard):
                            self._emit(pf, case.guard,
                                       "Python-value branching on a "
                                       "traced value (retrace per "
                                       "distinct value, or "
                                       "ConcretizationTypeError)", fd)
                    self._stmts(pf, fd, case.body, taint, depth)
            else:
                for child in ast.iter_child_nodes(s):
                    if isinstance(child, ast.expr):
                        self._check_expr(pf, fd, child, taint, depth)

    def _check_expr(self, pf: PyFile, fd: FuncDef, expr: ast.AST,
                    taint: _Taint, depth: int) -> None:
        # manual stack so nested lambda/def bodies are PRUNED (their
        # params shadow outer names; ast.walk would still visit them
        # and report false positives against the outer taint env)
        stack = [expr]
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            if isinstance(n, ast.Call):
                self._check_call(pf, fd, n, taint, depth)
            elif isinstance(n, ast.IfExp) \
                    and not _identity_test(n.test) \
                    and taint.expr(n.test):
                # `a if cond else b` branches exactly like an if stmt
                self._emit(pf, n.test,
                           "Python-value branching on a traced value "
                           "(retrace per distinct value, or "
                           "ConcretizationTypeError)", fd)
            stack.extend(ast.iter_child_nodes(n))

    def _check_call(self, pf: PyFile, fd: FuncDef, call: ast.Call,
                    taint: _Taint, depth: int) -> None:
        fn = call.func
        if isinstance(fn, ast.Attribute):
            if fn.attr in ("item", "tolist") and not call.args \
                    and taint.expr(fn.value):
                self._emit(pf, call,
                           f".{fn.attr}() on a traced value (host "
                           "concretization — trace-time crash or "
                           "silent per-value recompile)", fd)
                return
            dn = dotted_name(fn)
            root = dn.split(".", 1)[0] if dn else ""
            np_al, jnp_al = self._aliases(pf)
            if root in np_al and (
                    any(taint.expr(a) for a in call.args)
                    or any(taint.expr(kw.value) for kw in call.keywords)):
                self._emit(pf, call,
                           f"{dn}() on a traced value (numpy forces "
                           "concretization/device sync)", fd)
                return
            if root in jnp_al and fn.attr in SHAPE_BUILDERS:
                shape_args = call.args[:1] + [
                    kw.value for kw in call.keywords
                    if kw.arg in ("shape", "stop", "N")]
                if any(taint.expr(a) for a in shape_args):
                    self._emit(pf, call,
                               f"{dn}() with a traced shape/bound "
                               "argument (non-static shape "
                               "construction)", fd)
                    return
        elif isinstance(fn, ast.Name):
            if fn.id in CASTS and len(call.args) == 1 \
                    and taint.expr(call.args[0]):
                self._emit(pf, call,
                           f"{fn.id}() of a traced value (host "
                           "concretization)", fd)
                return
        # interprocedural: taint flows into resolvable callees
        tgt = self.project.resolve_call(pf, fd, call)
        if tgt is None or not isinstance(tgt.node, (ast.FunctionDef,
                                                    ast.AsyncFunctionDef)):
            return
        params = tgt.param_names()
        if params and tgt.class_info is not None and params[0] in ("self",
                                                                   "cls"):
            params = params[1:]
        flowed: Set[str] = set()
        for i, a in enumerate(call.args):
            if isinstance(a, ast.Starred):
                continue
            if i < len(params) and taint.expr(a):
                flowed.add(params[i])
        for kw in call.keywords:
            if kw.arg and kw.arg in params and taint.expr(kw.value):
                flowed.add(kw.arg)
        if flowed:
            self._analyze(tgt, frozenset(flowed), depth + 1)

    def _aliases(self, pf: PyFile) -> Tuple[Set[str], Set[str]]:
        got = self._alias_cache.get(pf.relpath)
        if got is None:
            got = (_numpy_aliases(pf), _jnp_aliases(pf))
            self._alias_cache[pf.relpath] = got
        return got

    def _emit(self, pf: PyFile, node: ast.AST, msg: str,
              fd: FuncDef) -> None:
        self.findings.append(Finding(
            rule=self.name, severity="P0", path=pf.relpath,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0),
            message=msg,
            context=f"{pf.module}:{fd.qualname}"))


def _params_full(node: ast.AST) -> List[str]:
    """Positional parameter names INCLUDING self/cls (index-accurate)."""
    a = getattr(node, "args", None)
    if a is None:
        return []
    return [p.arg for p in list(a.posonlyargs) + list(a.args)
            + list(a.kwonlyargs)]


def _params_of(node: ast.AST) -> List[str]:
    return [n for n in _params_full(node) if n not in ("self", "cls")]


def _const_strs(node: ast.AST) -> List[str]:
    out = []
    for n in ast.walk(node):
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            out.append(n.value)
    return out


def _retaint_target(tgt: ast.AST, is_tainted: bool,
                    taint: _Taint) -> None:
    """Apply an assignment's taint to its target.

    Only plain-Name bindings change a name's taint; an attribute or
    subscript store (``obj.y = v`` / ``d[k] = v``) rebinds NOTHING —
    walking it would wrongly taint/launder the base object name.
    """
    if isinstance(tgt, ast.Name):
        (taint.names.add(tgt.id) if is_tainted
         else taint.names.discard(tgt.id))
    elif isinstance(tgt, (ast.Tuple, ast.List)):
        for elt in tgt.elts:
            _retaint_target(elt, is_tainted, taint)
    elif isinstance(tgt, ast.Starred):
        _retaint_target(tgt.value, is_tainted, taint)


def _identity_test(test: ast.AST) -> bool:
    """``x is None`` / ``x is not None`` — identity never concretizes."""
    if isinstance(test, ast.Compare) \
            and all(isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops):
        return True
    if isinstance(test, ast.BoolOp):
        return all(_identity_test(v) for v in test.values)
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return _identity_test(test.operand)
    return False


def _static_property_names(project: Project) -> Set[str]:
    """Names of @property methods whose body derives only from shapes.

    ``WindowState.capacity`` → ``self.bucket_day.shape[0]`` is static
    under trace; accessing ``.capacity`` on a traced state launders
    taint. Name-based across the package (documented approximation):
    a name qualifies only if EVERY property of that name in the
    package is shape-derived.
    """
    shapey: Set[str] = set()
    traced: Set[str] = set()
    probe = _Taint({"self"})
    for pf in project.target_files():
        for fd in pf.functions:
            if fd.class_info is None or not isinstance(
                    fd.node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not any(dotted_name(d) in ("property", "functools."
                       "cached_property", "cached_property")
                       for d in fd.node.decorator_list):
                continue
            ann = fd.node.returns
            if isinstance(ann, ast.Name) and ann.id in ("int", "float",
                                                        "bool", "str"):
                shapey.add(fd.name)  # annotated Python scalar: static
                continue
            rets = [s for s in ast.walk(fd.node)
                    if isinstance(s, ast.Return) and s.value is not None]
            if rets and all(not probe.expr(r.value) for r in rets):
                shapey.add(fd.name)
            else:
                traced.add(fd.name)
    return shapey - traced


def _const_ints(node: ast.AST) -> List[int]:
    out = []
    for n in ast.walk(node):
        if isinstance(n, ast.Constant) and isinstance(n.value, int) \
                and not isinstance(n.value, bool):
            out.append(n.value)
    return out
