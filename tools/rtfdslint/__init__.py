"""rtfdslint — project-native static analysis for the rtfds serving loop.

The test suite can only spot-check the invariants PRs 1-7 paid for
(zero mid-stream recompiles, typed crash classification, registry-
grounded metric claims, single-writer thread discipline) at runtime;
this package enforces them at review time, before the code ever runs.

Pure stdlib (``ast``), no new dependencies. Entry points:

* ``rtfds lint`` (CLI subcommand) / ``make lint-static``
* ``python -m rtfdslint`` with ``tools/`` on ``sys.path``
* :func:`run_lint` for in-process use (the tier-1 gate test).

Known approximations (deliberate — the runtime detectors stay the
backstop; see each rule module's docstring for its own list):

* name resolution is lexical + one-level imports: dynamically chosen
  step functions, ``getattr`` dispatch and containers of callables are
  invisible to the jit/blocking reachability walks;
* taint does not flow through containers or object attributes
  (``state[0]``/``box.value`` holding a tracer), and hazards inside
  lambdas defined in jit code are skipped entirely (their params
  shadow; the pruning trades false positives for misses);
* the race detector reasons per class over ``self`` attributes only:
  module-global state, closures handed to ``Thread(target=…)`` and
  cross-object aliasing are out of scope, and check-then-act races on
  atomically-swapped references cannot be seen statically;
* lock-order analysis is lexical plus ONE level of intra-class calls —
  deeper call-chain acquisitions don't edge into the graph.
"""

from .finding import Finding, SEVERITIES  # noqa: F401
from .registry import all_rules, get_rule, register  # noqa: F401
from .runner import LintResult, run_lint  # noqa: F401

__version__ = "1.0.0"
