"""Argparse front-end: ``python -m rtfdslint`` and ``rtfds lint``."""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from .baseline import BaselineError
from .registry import all_rules
from .report import render_human, render_json
from .runner import DEFAULT_BASELINE, run_lint, update_baseline


def _find_root(start: str) -> str:
    """Walk up to the repo root (the dir holding the serving package)."""
    cur = os.path.abspath(start)
    from .project import PACKAGE_NAME
    while True:
        if os.path.isdir(os.path.join(cur, PACKAGE_NAME)):
            return cur
        nxt = os.path.dirname(cur)
        if nxt == cur:
            return os.path.abspath(start)
        cur = nxt


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="rtfds lint",
        description=("project-native static analyzer: recompile hazards, "
                     "cross-thread races, exception taxonomy, wall-clock "
                     "durations, metric drift, loop-thread blocking"))
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the serving package)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: discovered from cwd)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help=f"baseline file (default {DEFAULT_BASELINE})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (show everything)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="absorb current P0/P1 findings into the baseline")
    ap.add_argument("--reason", default="",
                    help="reason recorded on NEW baseline entries "
                         "(required with --update-baseline)")
    ap.add_argument("--rule", action="append", default=None,
                    help="run only this rule (repeatable)")
    ap.add_argument("--strict", action="store_true",
                    help="P2 findings also fail the gate")
    ap.add_argument("--verbose", action="store_true",
                    help="also list pragma-suppressed/baselined findings")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--verify-device", action="store_true",
                    help="also run the jaxpr-level device-contract "
                         "verifier (tools/rtfdsverify — needs jax, "
                         "CPU-only) and fold its findings into the "
                         "report and gate; --json carries them under "
                         "\"verifier\"")
    return ap


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for cls in all_rules():
            print(f"{cls.name:32s} {cls.doc}")
        return 0
    root = args.root or _find_root(os.getcwd())
    baseline = None if args.no_baseline else args.baseline
    try:
        result = run_lint(root, targets=args.paths or None,
                          baseline_path=baseline, rules=args.rule,
                          # explicit paths also narrow the finding set:
                          # never advise deleting out-of-scope entries
                          report_stale=not (args.rule or args.paths))
    except (BaselineError, FileNotFoundError, ValueError) as e:
        print(f"rtfdslint: {e}", file=sys.stderr)
        return 2
    if args.verify_device:
        if args.update_baseline:
            # each tool owns its baseline file; folding verifier
            # findings into the LINT baseline would mis-file them
            print("rtfdslint: --update-baseline does not combine with "
                  "--verify-device (use `rtfds verify-device "
                  "--update-baseline` for verifier findings)",
                  file=sys.stderr)
            return 2
        # lazy sibling import: the verifier needs jax; plain lint runs
        # stay stdlib-only
        tools_dir = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        if tools_dir not in sys.path:
            sys.path.insert(0, tools_dir)
        try:
            from rtfdsverify.runner import run_verify
        except ImportError as e:
            print(f"rtfdslint: --verify-device needs tools/rtfdsverify "
                  f"and a working jax ({e})", file=sys.stderr)
            return 2
        vb = (None if args.no_baseline
              else "tools/rtfdsverify/baseline.json")
        try:
            result.verifier = run_verify(root, baseline_path=vb)
        except (BaselineError, ValueError) as e:
            print(f"rtfdslint: verify-device: {e}", file=sys.stderr)
            return 2
    if args.update_baseline:
        if args.no_baseline:
            # the prior baseline would not load, so its still-matching
            # entries (any severity) could not be carried forward —
            # the rewrite would silently drop them
            print("rtfdslint: --update-baseline cannot be combined "
                  "with --no-baseline (prior entries must be loaded "
                  "to be preserved)", file=sys.stderr)
            return 2
        if args.rule or args.paths:
            # a focused run matches only its own scope's findings —
            # regenerating from it would silently delete every
            # out-of-scope entry. Baseline updates are whole-gate only.
            print("rtfdslint: --update-baseline must run over the full "
                  "default gate (no --rule, no path arguments) — a "
                  "focused run would drop every baseline entry outside "
                  "its scope", file=sys.stderr)
            return 2
        if not args.reason.strip():
            print("rtfdslint: --update-baseline requires --reason "
                  "'why these findings are accepted' (a baseline entry "
                  "can never be born reason-less)", file=sys.stderr)
            return 2
        n = update_baseline(root, result, args.baseline, args.reason.strip())
        print(f"rtfdslint: baseline now holds {n} entr"
              f"{'y' if n == 1 else 'ies'} at {args.baseline}")
        return 0
    if args.json:
        print(render_json(result, strict=args.strict))
    else:
        print(render_human(result, verbose=args.verbose,
                           strict=args.strict))
        if result.verifier is not None:
            from rtfdsverify.runner import render_human as verify_render

            print()
            print(verify_render(result.verifier, verbose=args.verbose,
                                strict=args.strict))
    failures = result.gate_failures(strict=args.strict)
    return 1 if failures else 0
