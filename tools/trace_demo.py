"""Produce a sample span trace on CPU — the `make trace-demo` target.

Runs a small synthetic stream through the single-chip engine with the
process tracer enabled, exports the Chrome-trace JSON, and prints the
`rtfds trace`-style summary plus the slowest batch's ASCII waterfall.
The exported file loads directly in ui.perfetto.dev / chrome://tracing.

Usage::

    JAX_PLATFORMS=cpu python tools/trace_demo.py --out out/trace_demo.json
"""

from __future__ import annotations

import argparse
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="trace_demo.json")
    ap.add_argument("--batches", type=int, default=12)
    ap.add_argument("--batch-rows", type=int, default=1024)
    args = ap.parse_args()

    from real_time_fraud_detection_system_tpu.config import (
        Config,
        DataConfig,
        FeatureConfig,
        RuntimeConfig,
        TrainConfig,
    )
    from real_time_fraud_detection_system_tpu.data import generate_dataset
    from real_time_fraud_detection_system_tpu.io import MemorySink
    from real_time_fraud_detection_system_tpu.io.dashboard import (
        render_trace_waterfall,
    )
    from real_time_fraud_detection_system_tpu.models import train_model
    from real_time_fraud_detection_system_tpu.runtime import (
        ReplaySource,
        ScoringEngine,
    )
    from real_time_fraud_detection_system_tpu.utils.timing import (
        date_to_epoch_s,
    )
    from real_time_fraud_detection_system_tpu.utils.trace import (
        get_tracer,
        summarize_chrome,
    )

    cfg = Config(
        data=DataConfig(n_customers=200, n_terminals=400, n_days=40,
                        seed=0, start_date="2025-04-01"),
        features=FeatureConfig(customer_capacity=512,
                               terminal_capacity=1024),
        train=TrainConfig(delta_train_days=20, delta_delay_days=5,
                          delta_test_days=10, epochs=2),
        runtime=RuntimeConfig(batch_buckets=(256, 1024, 4096)),
    )
    _, _, txs = generate_dataset(cfg.data)
    model, _ = train_model(txs, cfg, kind="logreg")

    tracer = get_tracer().configure(enabled=True)
    engine = ScoringEngine(cfg, model.kind, model.params, model.scaler)
    source = ReplaySource(txs, date_to_epoch_s(cfg.data.start_date),
                          batch_rows=args.batch_rows)
    stats = engine.run(source, sink=MemorySink(),
                       max_batches=args.batches)

    out_dir = os.path.dirname(os.path.abspath(args.out))
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
    manifest = tracer.export(args.out)
    trace = tracer.export_chrome()
    summary = summarize_chrome(trace, top_k=5)

    print(f"scored {stats['rows']} rows in {stats['batches']} batches "
          f"({stats['rows_per_s']:.0f} rows/s)")
    print(f"trace: {manifest['trace']} ({manifest['events']} events) — "
          "load in ui.perfetto.dev, or run "
          f"`python -m real_time_fraud_detection_system_tpu.cli trace "
          f"--trace {args.out}`")
    print(f"compile events on the timeline: "
          f"{len(summary['compile_events'])}")
    print()
    print(render_trace_waterfall(trace))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
