"""Multi-host fleet launcher: spawn, monitor and aggregate N serving
processes on this machine — CI's stand-in for a TPU pod's per-host
process manager, and the bench/smoke driver.

Each worker is a real ``rtfds score`` process: its own interpreter, its
own jax runtime, its own registry, its own residue block of the global
shard space. The launcher

- picks a coordinator port and injects ``--coordinator /
  --num-processes / --process-id`` (so the workers run the REAL
  ``jax.distributed.initialize`` barrier; ``--no-coordinator`` runs an
  uncoordinated fleet — no cross-process jax state at all);
- substitutes ``{proc}`` in worker args (per-process paths) — the
  score CLI itself already per-process-suffixes ``--out`` /
  ``--checkpoint-dir`` / ``--raw-table`` under proc-NN/;
- monitors the fleet with pod semantics: in coordinated mode a worker
  death is a HOST LOSS — the coordination service dies with process 0
  and heartbeats poison the rest — so the launcher drains the fleet and
  relaunches ALL workers with ``--resume`` (per-process checkpoints +
  sink ``truncate_after`` fencing give exactly-once across the
  restart, the PR 4/6 supervisor machinery per process). In
  uncoordinated mode only the dead worker respawns.
- optionally serves the coordinator-side ``/metrics`` aggregation view
  (``--metrics-port``): every worker's ``/metrics.json`` fetched,
  merged with a ``process`` label, rendered as one Prometheus page —
  plus ``/cluster`` (liveness + restart counts as JSON);
- optionally appends cluster events (worker exits, fleet restarts) to a
  flight record the ops dashboard renders as the Cluster tile.

Prints ONE JSON line: per-worker stats (parsed from each worker's own
stats line) plus fleet totals. Exit 0 iff every worker of the final
generation exited 0.

Usage::

    python tools/multihost_launcher.py --processes 2 -- \\
        score --source replay --data txs.npz --model-file m.npz \\
        --precompile --devices 1 --out out --checkpoint-dir ckpt \\
        --metrics-dump dumps/{proc}.json
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from real_time_fraud_detection_system_tpu.utils.metrics import (  # noqa: E402
    FlightRecorder,
    merge_process_snapshots,
    render_snapshot_prometheus,
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _worker_snapshots(ports: Dict[int, int]) -> Dict[str, dict]:
    """Fetch each live worker's ``/metrics.json`` registry snapshot;
    a dead/not-up-yet worker is simply absent."""
    import urllib.request

    out: Dict[str, dict] = {}
    for pid, port in ports.items():
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics.json",
                    timeout=2.0) as r:
                out[str(pid)] = json.loads(r.read().decode())
        except (OSError, ValueError):
            continue
    return out


def _arg_value(worker_args: List[str], flag: str) -> Optional[str]:
    for i, a in enumerate(worker_args):
        if a == flag and i + 1 < len(worker_args):
            return worker_args[i + 1]
        if a.startswith(flag + "="):
            return a.split("=", 1)[1]
    return None


def _gen_sub(tmpl: str, gen: int) -> str:
    return tmpl.replace("{gen}", f"gen-{gen:03d}")


def _last_json_line(path: str) -> Optional[dict]:
    """Last ``{...}`` line of a worker log — its stats line."""
    try:
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            out = None
            for ln in f:
                ln = ln.strip()
                if ln.startswith("{") and ln.endswith("}"):
                    try:
                        out = json.loads(ln)
                    except ValueError:
                        continue
            return out
    except OSError:
        return None


class _Worker:
    """One fleet member: the spawned process + its log + restart count."""

    def __init__(self, pid: int, cmd: List[str], env: dict,
                 log_path: str):
        self.process_id = pid
        self.cmd = cmd
        self.env = env
        self.log_path = log_path
        self.restarts = 0
        self.proc: Optional[subprocess.Popen] = None

    def spawn(self, extra_args: Optional[List[str]] = None) -> None:
        cmd = self.cmd + list(extra_args or [])
        log = open(self.log_path, "ab")
        try:
            self.proc = subprocess.Popen(
                cmd, stdout=log, stderr=subprocess.STDOUT, env=self.env)
        finally:
            log.close()  # the child holds its own fd

    def poll(self) -> Optional[int]:
        return self.proc.poll() if self.proc is not None else None

    def kill(self) -> None:
        if self.proc is not None and self.proc.poll() is None:
            self.proc.send_signal(signal.SIGTERM)
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait()

    def stats(self) -> Optional[dict]:
        return _last_json_line(self.log_path)


class _ClusterMetricsServer:
    """Coordinator-side aggregation view: ``/metrics`` (merged
    Prometheus text), ``/metrics.json`` (merged snapshot), ``/cluster``
    (liveness). Worker registries are scraped on demand from their
    ``--metrics-port`` endpoints; a dead worker simply drops out of the
    merge (its absence IS the signal, mirrored in /cluster)."""

    def __init__(self, port: int, worker_ports: Dict[int, int],
                 cluster_fn, include_launcher: bool = False):
        self.port = port
        self.worker_ports = worker_ports
        self.cluster_fn = cluster_fn
        # autoscale mode: merge the LAUNCHER's own registry (fleet
        # size, resize counters/durations) into the aggregation view
        # as the "launcher" process
        self.include_launcher = include_launcher
        self._httpd = None
        self._thread = None

    def _fetch_snapshots(self) -> Dict[str, dict]:
        out = _worker_snapshots(self.worker_ports)
        if self.include_launcher:
            from real_time_fraud_detection_system_tpu.utils.metrics \
                import get_registry

            out["launcher"] = get_registry().snapshot()
        return out

    def start(self) -> None:
        from http.server import BaseHTTPRequestHandler, HTTPServer

        outer = self

        class Handler(BaseHTTPRequestHandler):
            def _send(self, code, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 — stdlib handler API
                if self.path.startswith("/metrics.json"):
                    merged = merge_process_snapshots(
                        outer._fetch_snapshots())
                    self._send(200, json.dumps(merged).encode(),
                               "application/json")
                elif self.path.startswith("/metrics"):
                    merged = merge_process_snapshots(
                        outer._fetch_snapshots())
                    self._send(200,
                               render_snapshot_prometheus(merged).encode(),
                               "text/plain; version=0.0.4")
                elif self.path.startswith("/cluster"):
                    self._send(200, json.dumps(outer.cluster_fn()).encode(),
                               "application/json")
                else:
                    self._send(404, b"not found", "text/plain")

            def log_message(self, *a):
                pass  # endpoint scrapes are not log news

        self._httpd = HTTPServer(("0.0.0.0", self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="cluster-metrics",
            daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()


def build_workers(args, worker_args: List[str], coordinator: str,
                  n_processes: Optional[int] = None,
                  gen: Optional[int] = None) -> List[_Worker]:
    """``n_processes``/``gen`` override the fixed fleet shape for the
    autoscale path: ``{gen}`` in worker args substitutes per-generation
    paths (gen-NNN), the same way ``{proc}`` substitutes per-process
    ones, so every topology generation owns disjoint durable roots."""
    n = args.processes if n_processes is None else n_processes
    workers = []
    for pid in range(n):
        sub = [a.replace("{proc}", f"{pid:02d}") for a in worker_args]
        if gen is not None:
            sub = [_gen_sub(a, gen) for a in sub]
        cmd = [sys.executable, "-m",
               "real_time_fraud_detection_system_tpu.cli"] + sub
        cmd += ["--num-processes", str(n),
                "--process-id", str(pid)]
        if coordinator:
            cmd += ["--coordinator", coordinator]
        if args.worker_metrics_base:
            cmd += ["--metrics-port",
                    str(args.worker_metrics_base + pid)]
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        # the launcher OWNS each worker's virtual device count: strip
        # any inherited force flag (e.g. a test harness's 8-device
        # mesh), then set ours when more than one local device is asked
        flags = " ".join(
            f for f in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f)
        if args.local_devices > 1:
            flags = (flags + " --xla_force_host_platform_device_count="
                     f"{args.local_devices}").strip()
        if flags:
            env["XLA_FLAGS"] = flags
        else:
            env.pop("XLA_FLAGS", None)
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        prefix = f"gen-{gen:03d}-" if gen is not None else ""
        log_path = os.path.join(args.workdir,
                                f"{prefix}proc-{pid:02d}.log")
        workers.append(_Worker(pid, cmd, env, log_path))
    return workers


def _run_autoscale(args, worker_args: List[str], recorder) -> int:
    """Elastic fleet: pressure-driven resize loop around the worker set.

    The policy brain and FSM spine live in ``runtime.elastic`` (unit-
    tested without processes); this loop is their I/O shell. Steady
    state polls every worker's registry snapshot, distills the fleet
    signals (worst overload rung, lag trend, shed backlog) and, when a
    dwell completes, walks one resize through the chaos-survivable
    phases:

    - DRAINING: SIGTERM every worker (they run ``--drain-on-sigterm``),
      wait for ALL to exit 0 with a final checkpoint at their exact
      sink frontier. Any non-zero exit / timeout → rollback.
    - RETOPOLOGIZING: assemble the new generation's worker set with
      ``--resume --resume-merge OLD:P:L:REASON`` (the merge itself runs
      worker-side, idempotently, into each new worker's own lineage).
    - COMMITTING: atomically replace the topology manifest
      (tmp+fsync+rename+read-back); a torn manifest → rollback.
    - RELAUNCHING: spawn the new fleet; → STEADY.

    Rollback (any fault in the window) relaunches the PRE-resize fleet
    with ``--resume``: drained workers continue from their final
    checkpoints, a SIGKILLed worker replays from its last cadence
    checkpoint behind its sink ``truncate_after`` fence — exactly-once
    either way, counted in
    ``rtfds_fleet_resizes_total{outcome=rolled_back}``.
    """
    from real_time_fraud_detection_system_tpu.runtime.elastic import (
        COMMITTING,
        DRAINING,
        RELAUNCHING,
        RETOPOLOGIZING,
        STEADY,
        ElasticConfig,
        ElasticPolicy,
        ResizeFsm,
        fleet_metrics,
        load_topology,
        signals_from_snapshots,
        store_topology,
    )

    ckpt_tmpl = _arg_value(worker_args, "--checkpoint-dir")
    if not ckpt_tmpl or "{gen}" not in ckpt_tmpl:
        print("# --autoscale needs --checkpoint-dir containing {gen} "
              "in the worker args (per-generation lineage roots)",
              file=sys.stderr, flush=True)
        return 2
    out_tmpl = _arg_value(worker_args, "--out")
    if out_tmpl and "{gen}" not in out_tmpl:
        print("# --autoscale needs {gen} in --out (per-generation sink "
              "parts keep batch_index lineages disjoint)",
              file=sys.stderr, flush=True)
        return 2
    cold_tmpl = _arg_value(worker_args, "--cold-store")
    if "--drain-on-sigterm" not in worker_args:
        worker_args = worker_args + ["--drain-on-sigterm"]

    policy = ElasticPolicy(ElasticConfig(
        min_processes=args.autoscale_min,
        max_processes=args.autoscale_max,
        grow_rung=args.autoscale_grow_rung,
        grow_dwell_s=args.autoscale_grow_dwell,
        shrink_dwell_s=args.autoscale_shrink_dwell,
        cooldown_s=args.autoscale_cooldown))
    fm = fleet_metrics()
    auto: dict = {"current": args.processes, "target": None,
                  "generation": 0, "completed": 0, "rolled_back": 0,
                  "last_resize": None, "spike_absorb_s": None}

    def _journal(rec: dict) -> None:
        if recorder is not None:
            recorder.record_event("resize_phase", **rec)

    fsm = ResizeFsm(journal=_journal)
    topo_path = os.path.join(args.workdir, "topology.json")
    cur_p = args.processes
    gen = 0
    chaos = args.chaos_resize or None
    resize_attempts = 0
    topo_man = {"generation": 0, "processes": cur_p,
                "local_devices": args.local_devices,
                "checkpoint_root": _gen_sub(ckpt_tmpl, 0),
                "reason": "bootstrap"}
    store_topology(topo_path, topo_man)
    fm.fleet_size.set(cur_p)
    fm.resize_pending.set(0)

    workers = build_workers(args, worker_args, "", n_processes=cur_p,
                            gen=gen)
    ports = {w.process_id: args.worker_metrics_base + w.process_id
             for w in workers}
    retired: List[_Worker] = []  # every pre-resize generation's workers

    def cluster_state() -> dict:
        return {
            "processes": cur_p,
            "coordinated": False,
            "fleet_restarts": 0,
            "autoscale": {
                **auto, "phase": fsm.phase,
                "min": policy.cfg.min_processes,
                "max": policy.cfg.max_processes,
            },
            "workers": [
                {"process": w.process_id, "alive": w.poll() is None,
                 "restarts": w.restarts, "rc": w.poll()}
                for w in workers
            ],
        }

    server = None
    if args.metrics_port:
        server = _ClusterMetricsServer(args.metrics_port, ports,
                                       cluster_state,
                                       include_launcher=True)
        server.start()
        print(f"# cluster metrics on :{server.port} "
              "(/metrics /metrics.json /cluster + autoscale)",
              file=sys.stderr, flush=True)

    resume_args = ["--resume"] if "--resume" not in worker_args else []

    def relaunch(n: int, g: int, extra: List[str]) -> None:
        nonlocal workers
        retired.extend(workers)
        workers = build_workers(args, worker_args, "", n_processes=n,
                                gen=g)
        ports.clear()
        ports.update({w.process_id: args.worker_metrics_base
                      + w.process_id for w in workers})
        for w in workers:
            w.spawn(extra)

    def do_resize(dec) -> None:
        nonlocal cur_p, gen, chaos, topo_man
        t_r = time.monotonic()
        auto["target"] = dec.target
        fm.resize_pending.set(1)
        if recorder is not None:
            recorder.record_event("resize_begin", direction=dec.direction,
                                  current=cur_p, target=dec.target,
                                  reason=dec.reason)
        print(f"# resize {dec.direction} {cur_p} -> {dec.target}: "
              f"{dec.reason}", file=sys.stderr, flush=True)
        fsm.to(DRAINING, direction=dec.direction, target=dec.target)

        def fail(stage: str, why: str) -> None:
            fsm.rollback(stage=stage, why=why)
            if recorder is not None:
                recorder.record_event("resize_rollback", stage=stage,
                                      why=why, direction=dec.direction)
            for w in workers:
                w.kill()
            try:
                # the torn-manifest fault quarantined the committed
                # topology; restore the pre-resize manifest so readers
                # keep seeing the fleet that is actually serving
                store_topology(topo_path, topo_man)
            except (OSError, ValueError):
                pass
            relaunch(cur_p, gen, resume_args)
            fm.resizes_total(dec.direction, "rolled_back").inc()
            fm.resize_pending.set(0)
            fm.resize_seconds.observe(time.monotonic() - t_r)
            auto["rolled_back"] += 1
            auto["target"] = None
            auto["last_resize"] = {
                "direction": dec.direction, "outcome": "rolled_back",
                "stage": stage, "why": why, "epoch": time.time()}
            fsm.to(STEADY, outcome="rolled_back", stage=stage)
            print(f"# resize rolled back at {stage}: {why} — "
                  f"pre-resize fleet of {cur_p} relaunched",
                  file=sys.stderr, flush=True)

        # -- DRAINING: coordinated drain to final checkpoints ----------
        if chaos == "kill-mid-drain":
            chaos = None
            victim = workers[-1]
            if victim.proc is not None and victim.proc.poll() is None:
                victim.proc.kill()  # SIGKILL: no final checkpoint lands
        for w in workers:
            if w.proc is not None and w.proc.poll() is None:
                w.proc.send_signal(signal.SIGTERM)
        deadline = time.monotonic() + args.drain_timeout
        while (time.monotonic() < deadline
               and any(w.poll() is None for w in workers)):
            time.sleep(0.1)
        rcs = {w.process_id: w.poll() for w in workers}
        if any(r is None or r != 0 for r in rcs.values()):
            fail("drain", f"worker exits {rcs} (want all 0: a final "
                 "checkpoint at the sink frontier)")
            return

        # -- RETOPOLOGIZING: new generation's worker set ---------------
        fsm.to(RETOPOLOGIZING, target=dec.target)
        old_ckpt = _gen_sub(ckpt_tmpl, gen)
        new_gen = gen + 1
        extra = list(resume_args) + [
            "--resume-merge",
            f"{old_ckpt}:{cur_p}:{args.local_devices}:{dec.reason}"]
        if cold_tmpl:
            old_cold = _gen_sub(cold_tmpl, gen)
            srcs = ([old_cold] if cur_p == 1 else
                    [os.path.join(old_cold, f"proc-{p:02d}")
                     for p in range(cur_p)])
            srcs = [s for s in srcs if os.path.isdir(s)]
            if srcs:
                extra += ["--resume-merge-cold", ",".join(srcs)]
        if chaos == "crash-pre-relaunch":
            chaos = None
            fail("retopologize", "injected crash between the final "
                 "checkpoints and the new fleet's launch")
            return

        # -- COMMITTING: atomically replace the topology manifest ------
        fsm.to(COMMITTING, generation=new_gen)
        new_man = {"generation": new_gen, "processes": dec.target,
                   "local_devices": args.local_devices,
                   "checkpoint_root": _gen_sub(ckpt_tmpl, new_gen),
                   "merged_from": old_ckpt, "direction": dec.direction,
                   "reason": dec.reason, "epoch": time.time()}
        committed = None
        if chaos == "torn-manifest":
            chaos = None
            with open(topo_path, "wb") as f:
                # a torn write: half a JSON object, no rename discipline
                f.write(json.dumps(new_man)[:17].encode())
            committed = load_topology(topo_path)  # quarantines the tear
        else:
            try:
                store_topology(topo_path, new_man)
                committed = new_man
            except (OSError, ValueError) as e:
                print(f"# topology commit failed: {e}", file=sys.stderr,
                      flush=True)
        if committed != new_man:
            fail("commit", "topology manifest failed read-back "
                 "(torn write)")
            return

        # -- RELAUNCHING: the new fleet adopts the merged lineage ------
        fsm.to(RELAUNCHING, generation=new_gen, processes=dec.target)
        from_p = cur_p
        relaunch(dec.target, new_gen, extra)
        gen, cur_p, topo_man = new_gen, dec.target, new_man
        fm.fleet_size.set(cur_p)
        fm.resizes_total(dec.direction, "completed").inc()
        fm.resize_pending.set(0)
        dt = time.monotonic() - t_r
        fm.resize_seconds.observe(dt)
        auto.update(current=cur_p, target=None, generation=gen)
        auto["completed"] += 1
        auto["last_resize"] = {
            "direction": dec.direction, "outcome": "completed",
            "from": from_p, "to": cur_p, "reason": dec.reason,
            "seconds": round(dt, 3), "epoch": time.time()}
        if recorder is not None:
            recorder.record_event("resize_complete",
                                  direction=dec.direction, processes=cur_p,
                                  generation=gen, seconds=round(dt, 3))
        fsm.to(STEADY, outcome="completed", generation=gen)
        print(f"# resize complete: {from_p} -> {cur_p} in {dt:.1f}s "
              f"(generation {gen})", file=sys.stderr, flush=True)

    for w in workers:
        w.spawn()
        if recorder is not None:
            recorder.record_event("cluster_worker_start",
                                  process=w.process_id, generation=gen)
    t0 = time.monotonic()
    rc = 0
    absorb_t0 = None
    try:
        while True:
            states = {w.process_id: w.poll() for w in workers}
            if all(s is not None for s in states.values()):
                rc = 0 if all(s == 0 for s in states.values()) else 1
                break
            if args.timeout and time.monotonic() - t0 > args.timeout:
                print("# fleet timeout — killing workers",
                      file=sys.stderr, flush=True)
                for w in workers:
                    w.kill()
                rc = 1
                break
            dead_bad = [w for w in workers
                        if states[w.process_id] not in (None, 0)]
            if dead_bad:
                # steady-state worker death (outside any resize window):
                # uncoordinated fleets respawn just the dead worker on
                # its own lineage
                stop = False
                for w in dead_bad:
                    if w.restarts >= args.max_worker_restarts:
                        for v in workers:
                            v.kill()
                        rc = 1
                        stop = True
                        break
                    w.restarts += 1
                    if recorder is not None:
                        recorder.record_event("cluster_worker_restart",
                                              process=w.process_id,
                                              attempt=w.restarts,
                                              generation=gen)
                    w.spawn(resume_args)
                if stop:
                    break
                time.sleep(args.autoscale_interval)
                continue
            sig = signals_from_snapshots(_worker_snapshots(ports))
            now = time.monotonic()
            if absorb_t0 is None and sig.worst_rung >= \
                    policy.cfg.grow_rung:
                absorb_t0 = now
            elif absorb_t0 is not None and sig.worst_rung <= 1:
                # spike absorbed: pressure first crossed the grow rung
                # absorb_t0 ago, and the (possibly resized) fleet is
                # back under control
                fm.spike_absorb.set(now - absorb_t0)
                auto["spike_absorb_s"] = round(now - absorb_t0, 3)
                absorb_t0 = None
            dec = policy.observe(sig, cur_p, now)
            if dec is not None and (args.max_resizes <= 0
                                    or resize_attempts < args.max_resizes):
                resize_attempts += 1
                do_resize(dec)
                policy.note_resized(time.monotonic())
            time.sleep(args.autoscale_interval)
    finally:
        for w in workers:
            w.kill()
        if server is not None:
            server.stop()
        try:
            # the fleet counters (resizes by outcome, fleet size, spike
            # absorb) live in THIS process's registry — persist them so
            # the smoke/bench can assert from artifacts, not stdout
            from real_time_fraud_detection_system_tpu.utils.metrics \
                import get_registry

            with open(os.path.join(args.workdir,
                                   "launcher-metrics.json"), "w",
                      encoding="utf-8") as f:
                json.dump(get_registry().snapshot(), f)
        except (OSError, ValueError):
            pass

    # dedupe by log path (a respawned worker reuses its log; the last
    # stats line is the authoritative one for that lineage)
    by_log: Dict[str, _Worker] = {}
    for w in retired + workers:
        by_log[w.log_path] = w
    worker_rows = []
    rows_total = 0
    for path in sorted(by_log):
        w = by_log[path]
        st = w.stats() or {}
        rows = int(st.get("rows", 0) or 0)
        rows_total += rows
        worker_rows.append({
            "process": w.process_id,
            "rc": w.poll(),
            "restarts": w.restarts,
            "rows": rows,
            "rows_per_s": round(float(st.get("rows_per_s", 0.0)
                                      or 0.0), 1),
            "batches": int(st.get("batches", 0) or 0),
            "log": w.log_path,
        })
    if recorder is not None:
        recorder.close()
    print(json.dumps({
        "processes": cur_p,
        "coordinated": False,
        "serialized": False,
        "fleet_restarts": 0,
        "autoscale": {**auto, "phase": fsm.phase,
                      "attempts": resize_attempts,
                      "generations": gen + 1},
        "rows_total": rows_total,
        "workers": worker_rows,
    }), flush=True)
    return rc


def main() -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--processes", type=int, required=True,
                    help="fleet size (one rtfds score process each)")
    ap.add_argument("--local-devices", type=int, default=1,
                    help="virtual devices per worker (sets XLA_FLAGS "
                         "force_host_platform_device_count for CPU "
                         "fleets; pass the matching --devices in the "
                         "score args)")
    ap.add_argument("--no-coordinator", action="store_true",
                    help="uncoordinated fleet: skip jax.distributed "
                         "(no spanning mesh possible; per-worker "
                         "restart becomes safe)")
    ap.add_argument("--coordinator-port", type=int, default=0,
                    help="port for process 0's coordination service "
                         "(0 = pick a free one)")
    ap.add_argument("--workdir", default=".multihost",
                    help="per-worker logs land here (proc-NN.log)")
    ap.add_argument("--max-fleet-restarts", type=int, default=0,
                    help="coordinated mode: a worker death is a host "
                         "loss — drain the fleet and relaunch ALL "
                         "workers with --resume, at most this many "
                         "times")
    ap.add_argument("--max-worker-restarts", type=int, default=0,
                    help="uncoordinated mode: respawn just the dead "
                         "worker with --resume, at most this many "
                         "times per worker")
    ap.add_argument("--worker-metrics-base", type=int, default=0,
                    help="give worker i --metrics-port base+i "
                         "(0 = workers serve no ports)")
    ap.add_argument("--metrics-port", type=int, default=0,
                    help="serve the coordinator-side aggregation view "
                         "(/metrics, /metrics.json, /cluster) on this "
                         "port; needs --worker-metrics-base")
    ap.add_argument("--flight-record", default="",
                    help="append cluster events (worker exits, fleet "
                         "restarts) to this JSONL — the dashboard's "
                         "Cluster tile reads it")
    ap.add_argument("--serialize", action="store_true",
                    help="run the workers ONE AT A TIME instead of "
                         "concurrently (requires --no-coordinator: a "
                         "barrier would deadlock staggered workers). "
                         "Residue blocks are disjoint, so the fleet's "
                         "output is identical; each worker gets the "
                         "host to itself — the bench uses this to "
                         "measure per-process rates as a pod (one "
                         "host per process) would deliver them, "
                         "uncontended by the shared-core CI box")
    ap.add_argument("--timeout", type=float, default=0.0,
                    help="kill the fleet after this many seconds "
                         "(0 = wait forever)")
    ap.add_argument("--autoscale", action="store_true",
                    help="elastic fleet: watch the aggregated worker "
                         "signals (worst overload rung, lag trend, shed "
                         "backlog) and resize the fleet under sustained "
                         "pressure/idle via coordinated drain -> "
                         "checkpoint merge -> relaunch, exactly-once "
                         "across every resize. Requires "
                         "--no-coordinator, --worker-metrics-base, and "
                         "{gen} in the worker --checkpoint-dir/--out "
                         "(README 'Elastic fleet playbook')")
    ap.add_argument("--autoscale-min", type=int, default=1,
                    help="never shrink below this many processes")
    ap.add_argument("--autoscale-max", type=int, default=4,
                    help="never grow beyond this many processes")
    ap.add_argument("--autoscale-grow-rung", type=int, default=2,
                    help="grow once the worst process holds this "
                         "overload rung for --autoscale-grow-dwell")
    ap.add_argument("--autoscale-grow-dwell", type=float, default=2.0,
                    help="seconds the grow condition must hold")
    ap.add_argument("--autoscale-shrink-dwell", type=float, default=10.0,
                    help="seconds of full fleet idle (rung 0, flat lag, "
                         "no shed backlog) before shrinking")
    ap.add_argument("--autoscale-cooldown", type=float, default=5.0,
                    help="seconds after any resize (completed or rolled "
                         "back) before either direction re-arms")
    ap.add_argument("--autoscale-interval", type=float, default=0.25,
                    help="seconds between fleet signal polls")
    ap.add_argument("--drain-timeout", type=float, default=90.0,
                    help="seconds to wait for every worker's "
                         "coordinated drain before rolling back")
    ap.add_argument("--max-resizes", type=int, default=0,
                    help="bound on resize ATTEMPTS, completed or rolled "
                         "back (0 = policy-limited only)")
    ap.add_argument("--chaos-resize", default="",
                    choices=["", "kill-mid-drain", "crash-pre-relaunch",
                             "torn-manifest"],
                    help="inject ONE fault into the first resize "
                         "window (the chaos smoke asserts it lands in "
                         "rtfds_fleet_resizes_total{outcome="
                         "rolled_back} with the pre-resize fleet "
                         "serving)")
    ap.add_argument("worker_args", nargs=argparse.REMAINDER,
                    help="-- score <args>  ({proc} substitutes the "
                         "2-digit process id)")
    args = ap.parse_args()

    worker_args = args.worker_args
    if worker_args and worker_args[0] == "--":
        worker_args = worker_args[1:]
    if not worker_args or worker_args[0] != "score":
        ap.error("worker args must start with the 'score' subcommand "
                 "(usage: ... -- score --source replay ...)")
    if args.processes < 1:
        ap.error("--processes must be >= 1")
    if args.metrics_port and not args.worker_metrics_base:
        ap.error("--metrics-port needs --worker-metrics-base (the "
                 "aggregator scrapes the workers' own endpoints)")
    if args.serialize and not args.no_coordinator:
        ap.error("--serialize requires --no-coordinator (the "
                 "jax.distributed barrier would deadlock workers that "
                 "are not all running)")
    if args.autoscale:
        if not args.no_coordinator:
            ap.error("--autoscale requires --no-coordinator (a resize "
                     "changes the process count; a spanning "
                     "jax.distributed mesh cannot survive that)")
        if not args.worker_metrics_base:
            ap.error("--autoscale needs --worker-metrics-base (the "
                     "policy reads each worker's registry snapshot)")
        if args.serialize:
            ap.error("--autoscale does not compose with --serialize "
                     "(pressure signals need the fleet running "
                     "concurrently)")

    os.makedirs(args.workdir, exist_ok=True)
    coordinator = ""
    if not args.no_coordinator:
        port = args.coordinator_port or _free_port()
        coordinator = f"127.0.0.1:{port}"

    recorder = None
    if args.flight_record:
        recorder = FlightRecorder(args.flight_record, manifest={
            "multihost": {"processes": args.processes,
                          "coordinated": bool(coordinator),
                          "autoscale": bool(args.autoscale)}})

    if args.autoscale:
        return _run_autoscale(args, worker_args, recorder)

    workers = build_workers(args, worker_args, coordinator)
    fleet_restarts = 0
    results: Dict[int, int] = {}

    def cluster_state() -> dict:
        return {
            "processes": args.processes,
            "coordinated": bool(coordinator),
            "fleet_restarts": fleet_restarts,
            "workers": [
                {"process": w.process_id,
                 "alive": w.poll() is None,
                 "restarts": w.restarts,
                 "rc": w.poll()}
                for w in workers
            ],
        }

    server = None
    if args.metrics_port:
        server = _ClusterMetricsServer(
            args.metrics_port,
            {w.process_id: args.worker_metrics_base + w.process_id
             for w in workers},
            cluster_state)
        server.start()
        print(f"# cluster metrics on :{server.port} "
              "(/metrics /metrics.json /cluster)", file=sys.stderr,
              flush=True)

    has_ckpt = "--checkpoint-dir" in worker_args
    resume_args = (["--resume"]
                   if has_ckpt and "--resume" not in worker_args else [])

    t0 = time.monotonic()
    rc = 0
    if args.serialize:
        # One worker at a time (disjoint residue blocks: the fleet's
        # output is identical to the concurrent run's) — each gets the
        # host alone, so its stats measure per-process capacity, not
        # shared-core time-slicing. Per-worker restart budget applies.
        try:
            for w in workers:
                while True:
                    w.spawn(resume_args if w.restarts else None)
                    if recorder is not None:
                        recorder.record_event("cluster_worker_start",
                                              process=w.process_id,
                                              attempt=w.restarts)
                    while w.poll() is None:
                        if args.timeout and \
                                time.monotonic() - t0 > args.timeout:
                            w.kill()
                            break
                        time.sleep(0.1)
                    if w.poll() == 0 or \
                            w.restarts >= args.max_worker_restarts:
                        break
                    w.restarts += 1
                results[w.process_id] = w.poll()
                if results[w.process_id] != 0:
                    rc = 1
        finally:
            for w in workers:
                w.kill()
            if server is not None:
                server.stop()
        return _report(args, workers, results, fleet_restarts,
                       coordinator, recorder, rc)

    for w in workers:
        w.spawn()
        if recorder is not None:
            recorder.record_event("cluster_worker_start",
                                  process=w.process_id)
    try:
        while True:
            states = {w.process_id: w.poll() for w in workers}
            if all(s is not None for s in states.values()):
                results = states
                break
            if args.timeout and time.monotonic() - t0 > args.timeout:
                print("# fleet timeout — killing workers",
                      file=sys.stderr, flush=True)
                for w in workers:
                    w.kill()
                results = {w.process_id: (w.poll() if w.poll() is not None
                                          else -9) for w in workers}
                rc = 1
                break
            dead_bad = [w for w in workers
                        if states[w.process_id] not in (None, 0)]
            if dead_bad and coordinator:
                # Host loss, pod semantics: the coordination service
                # (process 0) or a heartbeat-fenced peer is gone — the
                # fleet cannot continue half-alive. Drain and relaunch
                # everyone with --resume: each worker's own
                # checkpoint + sink truncate_after fencing (the PR 4/6
                # supervisor plane) makes the restart exactly-once per
                # residue block.
                if fleet_restarts >= args.max_fleet_restarts:
                    for w in workers:
                        w.kill()
                    # a worker that finished rc 0 before the fatal peer
                    # death keeps its honest exit code in the report
                    results = {w.process_id: (w.poll()
                                              if w.poll() is not None
                                              else 1)
                               for w in workers}
                    rc = 1
                    break
                fleet_restarts += 1
                for w in workers:
                    w.kill()
                if recorder is not None:
                    recorder.record_event(
                        "fleet_restart", generation=fleet_restarts,
                        died=[w.process_id for w in dead_bad])
                port = _free_port()
                coordinator = f"127.0.0.1:{port}"
                workers = build_workers(args, worker_args, coordinator)
                for w in workers:
                    w.restarts = fleet_restarts
                    w.spawn(resume_args)
                time.sleep(0.5)
                continue
            if dead_bad:
                # Uncoordinated fleet: a dead worker affects only its
                # own residue block — respawn just it, resuming its own
                # checkpoint lineage.
                for w in dead_bad:
                    if w.restarts >= args.max_worker_restarts:
                        for v in workers:
                            v.kill()
                        results = {v.process_id: v.poll()
                                   if v.poll() is not None else 1
                                   for v in workers}
                        rc = 1
                        break
                    w.restarts += 1
                    if recorder is not None:
                        recorder.record_event(
                            "cluster_worker_restart",
                            process=w.process_id, attempt=w.restarts)
                    w.spawn(resume_args)
                else:
                    time.sleep(0.2)
                    continue
                break
            time.sleep(0.2)
    finally:
        for w in workers:
            w.kill()
        if server is not None:
            server.stop()

    return _report(args, workers, results, fleet_restarts, coordinator,
                   recorder, rc)


def _report(args, workers, results, fleet_restarts, coordinator,
            recorder, rc) -> int:
    worker_rows = []
    rows_total = 0
    for w in workers:
        st = w.stats() or {}
        rows = int(st.get("rows", 0) or 0)
        rows_total += rows
        row = {
            "process": w.process_id,
            "rc": results.get(w.process_id, w.poll()),
            "restarts": w.restarts,
            "rows": rows,
            "rows_per_s": round(float(st.get("rows_per_s", 0.0) or 0.0),
                                1),
            "cpu_s": round(float(st.get("cpu_s", 0.0) or 0.0), 3),
            "batches": int(st.get("batches", 0) or 0),
            "log": w.log_path,
        }
        worker_rows.append(row)
        if recorder is not None:
            recorder.record_event(
                "cluster_worker", process=w.process_id, rc=row["rc"],
                rows=rows, rows_per_s=row["rows_per_s"],
                restarts=w.restarts)
        if row["rc"] != 0:
            rc = rc or 1
    if recorder is not None:
        recorder.close()
    print(json.dumps({
        "processes": args.processes,
        "coordinated": bool(coordinator),
        "serialized": bool(args.serialize),
        "fleet_restarts": fleet_restarts,
        "rows_total": rows_total,
        "workers": worker_rows,
    }), flush=True)
    return rc


if __name__ == "__main__":
    sys.exit(main())
