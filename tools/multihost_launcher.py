"""Multi-host fleet launcher: spawn, monitor and aggregate N serving
processes on this machine — CI's stand-in for a TPU pod's per-host
process manager, and the bench/smoke driver.

Each worker is a real ``rtfds score`` process: its own interpreter, its
own jax runtime, its own registry, its own residue block of the global
shard space. The launcher

- picks a coordinator port and injects ``--coordinator /
  --num-processes / --process-id`` (so the workers run the REAL
  ``jax.distributed.initialize`` barrier; ``--no-coordinator`` runs an
  uncoordinated fleet — no cross-process jax state at all);
- substitutes ``{proc}`` in worker args (per-process paths) — the
  score CLI itself already per-process-suffixes ``--out`` /
  ``--checkpoint-dir`` / ``--raw-table`` under proc-NN/;
- monitors the fleet with pod semantics: in coordinated mode a worker
  death is a HOST LOSS — the coordination service dies with process 0
  and heartbeats poison the rest — so the launcher drains the fleet and
  relaunches ALL workers with ``--resume`` (per-process checkpoints +
  sink ``truncate_after`` fencing give exactly-once across the
  restart, the PR 4/6 supervisor machinery per process). In
  uncoordinated mode only the dead worker respawns.
- optionally serves the coordinator-side ``/metrics`` aggregation view
  (``--metrics-port``): every worker's ``/metrics.json`` fetched,
  merged with a ``process`` label, rendered as one Prometheus page —
  plus ``/cluster`` (liveness + restart counts as JSON);
- optionally appends cluster events (worker exits, fleet restarts) to a
  flight record the ops dashboard renders as the Cluster tile.

Prints ONE JSON line: per-worker stats (parsed from each worker's own
stats line) plus fleet totals. Exit 0 iff every worker of the final
generation exited 0.

Usage::

    python tools/multihost_launcher.py --processes 2 -- \\
        score --source replay --data txs.npz --model-file m.npz \\
        --precompile --devices 1 --out out --checkpoint-dir ckpt \\
        --metrics-dump dumps/{proc}.json
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from real_time_fraud_detection_system_tpu.utils.metrics import (  # noqa: E402
    FlightRecorder,
    merge_process_snapshots,
    render_snapshot_prometheus,
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _last_json_line(path: str) -> Optional[dict]:
    """Last ``{...}`` line of a worker log — its stats line."""
    try:
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            out = None
            for ln in f:
                ln = ln.strip()
                if ln.startswith("{") and ln.endswith("}"):
                    try:
                        out = json.loads(ln)
                    except ValueError:
                        continue
            return out
    except OSError:
        return None


class _Worker:
    """One fleet member: the spawned process + its log + restart count."""

    def __init__(self, pid: int, cmd: List[str], env: dict,
                 log_path: str):
        self.process_id = pid
        self.cmd = cmd
        self.env = env
        self.log_path = log_path
        self.restarts = 0
        self.proc: Optional[subprocess.Popen] = None

    def spawn(self, extra_args: Optional[List[str]] = None) -> None:
        cmd = self.cmd + list(extra_args or [])
        log = open(self.log_path, "ab")
        try:
            self.proc = subprocess.Popen(
                cmd, stdout=log, stderr=subprocess.STDOUT, env=self.env)
        finally:
            log.close()  # the child holds its own fd

    def poll(self) -> Optional[int]:
        return self.proc.poll() if self.proc is not None else None

    def kill(self) -> None:
        if self.proc is not None and self.proc.poll() is None:
            self.proc.send_signal(signal.SIGTERM)
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait()

    def stats(self) -> Optional[dict]:
        return _last_json_line(self.log_path)


class _ClusterMetricsServer:
    """Coordinator-side aggregation view: ``/metrics`` (merged
    Prometheus text), ``/metrics.json`` (merged snapshot), ``/cluster``
    (liveness). Worker registries are scraped on demand from their
    ``--metrics-port`` endpoints; a dead worker simply drops out of the
    merge (its absence IS the signal, mirrored in /cluster)."""

    def __init__(self, port: int, worker_ports: Dict[int, int],
                 cluster_fn):
        self.port = port
        self.worker_ports = worker_ports
        self.cluster_fn = cluster_fn
        self._httpd = None
        self._thread = None

    def _fetch_snapshots(self) -> Dict[str, dict]:
        import urllib.request

        out: Dict[str, dict] = {}
        for pid, port in self.worker_ports.items():
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/metrics.json",
                        timeout=2.0) as r:
                    out[str(pid)] = json.loads(r.read().decode())
            except (OSError, ValueError):
                continue  # dead/not-up-yet worker: absent from the merge
        return out

    def start(self) -> None:
        from http.server import BaseHTTPRequestHandler, HTTPServer

        outer = self

        class Handler(BaseHTTPRequestHandler):
            def _send(self, code, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 — stdlib handler API
                if self.path.startswith("/metrics.json"):
                    merged = merge_process_snapshots(
                        outer._fetch_snapshots())
                    self._send(200, json.dumps(merged).encode(),
                               "application/json")
                elif self.path.startswith("/metrics"):
                    merged = merge_process_snapshots(
                        outer._fetch_snapshots())
                    self._send(200,
                               render_snapshot_prometheus(merged).encode(),
                               "text/plain; version=0.0.4")
                elif self.path.startswith("/cluster"):
                    self._send(200, json.dumps(outer.cluster_fn()).encode(),
                               "application/json")
                else:
                    self._send(404, b"not found", "text/plain")

            def log_message(self, *a):
                pass  # endpoint scrapes are not log news

        self._httpd = HTTPServer(("0.0.0.0", self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="cluster-metrics",
            daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()


def build_workers(args, worker_args: List[str],
                  coordinator: str) -> List[_Worker]:
    workers = []
    for pid in range(args.processes):
        sub = [a.replace("{proc}", f"{pid:02d}") for a in worker_args]
        cmd = [sys.executable, "-m",
               "real_time_fraud_detection_system_tpu.cli"] + sub
        cmd += ["--num-processes", str(args.processes),
                "--process-id", str(pid)]
        if coordinator:
            cmd += ["--coordinator", coordinator]
        if args.worker_metrics_base:
            cmd += ["--metrics-port",
                    str(args.worker_metrics_base + pid)]
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        # the launcher OWNS each worker's virtual device count: strip
        # any inherited force flag (e.g. a test harness's 8-device
        # mesh), then set ours when more than one local device is asked
        flags = " ".join(
            f for f in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f)
        if args.local_devices > 1:
            flags = (flags + " --xla_force_host_platform_device_count="
                     f"{args.local_devices}").strip()
        if flags:
            env["XLA_FLAGS"] = flags
        else:
            env.pop("XLA_FLAGS", None)
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        log_path = os.path.join(args.workdir, f"proc-{pid:02d}.log")
        workers.append(_Worker(pid, cmd, env, log_path))
    return workers


def main() -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--processes", type=int, required=True,
                    help="fleet size (one rtfds score process each)")
    ap.add_argument("--local-devices", type=int, default=1,
                    help="virtual devices per worker (sets XLA_FLAGS "
                         "force_host_platform_device_count for CPU "
                         "fleets; pass the matching --devices in the "
                         "score args)")
    ap.add_argument("--no-coordinator", action="store_true",
                    help="uncoordinated fleet: skip jax.distributed "
                         "(no spanning mesh possible; per-worker "
                         "restart becomes safe)")
    ap.add_argument("--coordinator-port", type=int, default=0,
                    help="port for process 0's coordination service "
                         "(0 = pick a free one)")
    ap.add_argument("--workdir", default=".multihost",
                    help="per-worker logs land here (proc-NN.log)")
    ap.add_argument("--max-fleet-restarts", type=int, default=0,
                    help="coordinated mode: a worker death is a host "
                         "loss — drain the fleet and relaunch ALL "
                         "workers with --resume, at most this many "
                         "times")
    ap.add_argument("--max-worker-restarts", type=int, default=0,
                    help="uncoordinated mode: respawn just the dead "
                         "worker with --resume, at most this many "
                         "times per worker")
    ap.add_argument("--worker-metrics-base", type=int, default=0,
                    help="give worker i --metrics-port base+i "
                         "(0 = workers serve no ports)")
    ap.add_argument("--metrics-port", type=int, default=0,
                    help="serve the coordinator-side aggregation view "
                         "(/metrics, /metrics.json, /cluster) on this "
                         "port; needs --worker-metrics-base")
    ap.add_argument("--flight-record", default="",
                    help="append cluster events (worker exits, fleet "
                         "restarts) to this JSONL — the dashboard's "
                         "Cluster tile reads it")
    ap.add_argument("--serialize", action="store_true",
                    help="run the workers ONE AT A TIME instead of "
                         "concurrently (requires --no-coordinator: a "
                         "barrier would deadlock staggered workers). "
                         "Residue blocks are disjoint, so the fleet's "
                         "output is identical; each worker gets the "
                         "host to itself — the bench uses this to "
                         "measure per-process rates as a pod (one "
                         "host per process) would deliver them, "
                         "uncontended by the shared-core CI box")
    ap.add_argument("--timeout", type=float, default=0.0,
                    help="kill the fleet after this many seconds "
                         "(0 = wait forever)")
    ap.add_argument("worker_args", nargs=argparse.REMAINDER,
                    help="-- score <args>  ({proc} substitutes the "
                         "2-digit process id)")
    args = ap.parse_args()

    worker_args = args.worker_args
    if worker_args and worker_args[0] == "--":
        worker_args = worker_args[1:]
    if not worker_args or worker_args[0] != "score":
        ap.error("worker args must start with the 'score' subcommand "
                 "(usage: ... -- score --source replay ...)")
    if args.processes < 1:
        ap.error("--processes must be >= 1")
    if args.metrics_port and not args.worker_metrics_base:
        ap.error("--metrics-port needs --worker-metrics-base (the "
                 "aggregator scrapes the workers' own endpoints)")
    if args.serialize and not args.no_coordinator:
        ap.error("--serialize requires --no-coordinator (the "
                 "jax.distributed barrier would deadlock workers that "
                 "are not all running)")

    os.makedirs(args.workdir, exist_ok=True)
    coordinator = ""
    if not args.no_coordinator:
        port = args.coordinator_port or _free_port()
        coordinator = f"127.0.0.1:{port}"

    recorder = None
    if args.flight_record:
        recorder = FlightRecorder(args.flight_record, manifest={
            "multihost": {"processes": args.processes,
                          "coordinated": bool(coordinator)}})

    workers = build_workers(args, worker_args, coordinator)
    fleet_restarts = 0
    results: Dict[int, int] = {}

    def cluster_state() -> dict:
        return {
            "processes": args.processes,
            "coordinated": bool(coordinator),
            "fleet_restarts": fleet_restarts,
            "workers": [
                {"process": w.process_id,
                 "alive": w.poll() is None,
                 "restarts": w.restarts,
                 "rc": w.poll()}
                for w in workers
            ],
        }

    server = None
    if args.metrics_port:
        server = _ClusterMetricsServer(
            args.metrics_port,
            {w.process_id: args.worker_metrics_base + w.process_id
             for w in workers},
            cluster_state)
        server.start()
        print(f"# cluster metrics on :{server.port} "
              "(/metrics /metrics.json /cluster)", file=sys.stderr,
              flush=True)

    has_ckpt = "--checkpoint-dir" in worker_args
    resume_args = (["--resume"]
                   if has_ckpt and "--resume" not in worker_args else [])

    t0 = time.monotonic()
    rc = 0
    if args.serialize:
        # One worker at a time (disjoint residue blocks: the fleet's
        # output is identical to the concurrent run's) — each gets the
        # host alone, so its stats measure per-process capacity, not
        # shared-core time-slicing. Per-worker restart budget applies.
        try:
            for w in workers:
                while True:
                    w.spawn(resume_args if w.restarts else None)
                    if recorder is not None:
                        recorder.record_event("cluster_worker_start",
                                              process=w.process_id,
                                              attempt=w.restarts)
                    while w.poll() is None:
                        if args.timeout and \
                                time.monotonic() - t0 > args.timeout:
                            w.kill()
                            break
                        time.sleep(0.1)
                    if w.poll() == 0 or \
                            w.restarts >= args.max_worker_restarts:
                        break
                    w.restarts += 1
                results[w.process_id] = w.poll()
                if results[w.process_id] != 0:
                    rc = 1
        finally:
            for w in workers:
                w.kill()
            if server is not None:
                server.stop()
        return _report(args, workers, results, fleet_restarts,
                       coordinator, recorder, rc)

    for w in workers:
        w.spawn()
        if recorder is not None:
            recorder.record_event("cluster_worker_start",
                                  process=w.process_id)
    try:
        while True:
            states = {w.process_id: w.poll() for w in workers}
            if all(s is not None for s in states.values()):
                results = states
                break
            if args.timeout and time.monotonic() - t0 > args.timeout:
                print("# fleet timeout — killing workers",
                      file=sys.stderr, flush=True)
                for w in workers:
                    w.kill()
                results = {w.process_id: (w.poll() if w.poll() is not None
                                          else -9) for w in workers}
                rc = 1
                break
            dead_bad = [w for w in workers
                        if states[w.process_id] not in (None, 0)]
            if dead_bad and coordinator:
                # Host loss, pod semantics: the coordination service
                # (process 0) or a heartbeat-fenced peer is gone — the
                # fleet cannot continue half-alive. Drain and relaunch
                # everyone with --resume: each worker's own
                # checkpoint + sink truncate_after fencing (the PR 4/6
                # supervisor plane) makes the restart exactly-once per
                # residue block.
                if fleet_restarts >= args.max_fleet_restarts:
                    for w in workers:
                        w.kill()
                    # a worker that finished rc 0 before the fatal peer
                    # death keeps its honest exit code in the report
                    results = {w.process_id: (w.poll()
                                              if w.poll() is not None
                                              else 1)
                               for w in workers}
                    rc = 1
                    break
                fleet_restarts += 1
                for w in workers:
                    w.kill()
                if recorder is not None:
                    recorder.record_event(
                        "fleet_restart", generation=fleet_restarts,
                        died=[w.process_id for w in dead_bad])
                port = _free_port()
                coordinator = f"127.0.0.1:{port}"
                workers = build_workers(args, worker_args, coordinator)
                for w in workers:
                    w.restarts = fleet_restarts
                    w.spawn(resume_args)
                time.sleep(0.5)
                continue
            if dead_bad:
                # Uncoordinated fleet: a dead worker affects only its
                # own residue block — respawn just it, resuming its own
                # checkpoint lineage.
                for w in dead_bad:
                    if w.restarts >= args.max_worker_restarts:
                        for v in workers:
                            v.kill()
                        results = {v.process_id: v.poll()
                                   if v.poll() is not None else 1
                                   for v in workers}
                        rc = 1
                        break
                    w.restarts += 1
                    if recorder is not None:
                        recorder.record_event(
                            "cluster_worker_restart",
                            process=w.process_id, attempt=w.restarts)
                    w.spawn(resume_args)
                else:
                    time.sleep(0.2)
                    continue
                break
            time.sleep(0.2)
    finally:
        for w in workers:
            w.kill()
        if server is not None:
            server.stop()

    return _report(args, workers, results, fleet_restarts, coordinator,
                   recorder, rc)


def _report(args, workers, results, fleet_restarts, coordinator,
            recorder, rc) -> int:
    worker_rows = []
    rows_total = 0
    for w in workers:
        st = w.stats() or {}
        rows = int(st.get("rows", 0) or 0)
        rows_total += rows
        row = {
            "process": w.process_id,
            "rc": results.get(w.process_id, w.poll()),
            "restarts": w.restarts,
            "rows": rows,
            "rows_per_s": round(float(st.get("rows_per_s", 0.0) or 0.0),
                                1),
            "cpu_s": round(float(st.get("cpu_s", 0.0) or 0.0), 3),
            "batches": int(st.get("batches", 0) or 0),
            "log": w.log_path,
        }
        worker_rows.append(row)
        if recorder is not None:
            recorder.record_event(
                "cluster_worker", process=w.process_id, rc=row["rc"],
                rows=rows, rows_per_s=row["rows_per_s"],
                restarts=w.restarts)
        if row["rc"] != 0:
            rc = rc or 1
    if recorder is not None:
        recorder.close()
    print(json.dumps({
        "processes": args.processes,
        "coordinated": bool(coordinator),
        "serialized": bool(args.serialize),
        "fleet_restarts": fleet_restarts,
        "rows_total": rows_total,
        "workers": worker_rows,
    }), flush=True)
    return rc


if __name__ == "__main__":
    sys.exit(main())
