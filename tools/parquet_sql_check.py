"""Prove the ParquetSink output is standard-SQL-servable.

The reference wires Superset → Trino → Iceberg so analysts query the
``analyzed_transactions`` table with plain SQL (``superset/entrypoint.sh:19``,
``trino-config/catalog/nessie.properties:1-14``). This framework's claim is
that :class:`io.sink.ParquetSink` output is byte-compatible Parquet that any
such engine can mount. This script demonstrates it end to end, no container
stack required:

1. score a synthetic stream into a ParquetSink directory (or use
   ``--dir`` for an existing one);
2. mount the part files with a third-party SQL engine — DuckDB when
   installed (the engine that shares Trino's Parquet scan architecture),
   else pyarrow.dataset → an in-memory sqlite3 database (both ship with
   CPython/pyarrow, so this path is exercisable on any host);
3. run the dashboard's queries as REAL SQL (summary tiles, top-risky
   terminals, alert feed, per-day volumes — the io/query.py surface);
4. cross-check every number against io/query.py's own numpy answers and
   exit non-zero on any mismatch.

Prints one JSON line: ``{"ok": true, "engine": "duckdb"|"sqlite", ...}``.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import tempfile

import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# SQL texts shared by both engines (ANSI subset both speak). The table
# name `analyzed` is bound to the mounted Parquet data.
SQL_SUMMARY = """
SELECT COUNT(*)                                   AS transactions,
       COUNT(DISTINCT customer_id)                AS customers,
       COUNT(DISTINCT terminal_id)                AS terminals,
       SUM(tx_amount)                             AS total_amount,
       SUM(CASE WHEN prediction >= :thr THEN 1 ELSE 0 END) AS flagged,
       SUM(CASE WHEN prediction >= :thr THEN tx_amount ELSE 0 END)
                                                  AS flagged_amount,
       AVG(prediction)                            AS score_mean
FROM analyzed
"""

SQL_TOP_TERMINALS = """
SELECT terminal_id,
       COUNT(*)        AS transactions,
       AVG(prediction) AS mean_score
FROM analyzed
GROUP BY terminal_id
HAVING COUNT(*) >= :min_tx
ORDER BY mean_score DESC, terminal_id ASC
LIMIT :k
"""

SQL_ALERTS = """
SELECT tx_id, prediction
FROM analyzed
WHERE prediction >= :thr
ORDER BY tx_datetime_us DESC, tx_id DESC
LIMIT :k
"""

SQL_DAILY = """
SELECT CAST((tx_datetime_us - tx_datetime_us % 86400000000)
            / 86400000000 AS BIGINT)                AS day,
       COUNT(*)                                     AS transactions,
       SUM(tx_amount)                               AS amount
FROM analyzed
GROUP BY 1
ORDER BY 1
"""



def _bind(sql: str, params: dict) -> str:
    """Inline the (numeric-only) named parameters — one text for both
    engines without driver-specific placeholder styles."""
    for k, v in params.items():
        assert isinstance(v, (int, float))
        sql = sql.replace(f":{k}", repr(v))
    return sql




def _close(a, b, tol=1e-6) -> bool:
    if isinstance(a, float) or isinstance(b, float):
        return math.isfinite(float(a)) and math.isfinite(float(b)) \
            and abs(float(a) - float(b)) <= tol * max(1.0, abs(float(a)))
    return int(a) == int(b)


def _make_demo_dir(directory: str) -> None:
    """Tiny datagen → train → score → ParquetSink run (CPU-sized)."""
    from real_time_fraud_detection_system_tpu.config import (
        Config,
        DataConfig,
        FeatureConfig,
        TrainConfig,
    )
    from real_time_fraud_detection_system_tpu.data import generate_dataset
    from real_time_fraud_detection_system_tpu.io import ParquetSink
    from real_time_fraud_detection_system_tpu.models import train_model
    from real_time_fraud_detection_system_tpu.runtime import (
        ReplaySource,
        ScoringEngine,
    )
    from real_time_fraud_detection_system_tpu.utils.timing import (
        date_to_epoch_s,
    )

    cfg = Config(
        data=DataConfig(n_customers=80, n_terminals=160, n_days=40, seed=5),
        features=FeatureConfig(customer_capacity=128,
                               terminal_capacity=256),
        train=TrainConfig(delta_train_days=20, delta_delay_days=5,
                          delta_test_days=10, epochs=2),
    )
    _, _, txs = generate_dataset(cfg.data)
    model, _ = train_model(txs, cfg, kind="logreg")
    eng = ScoringEngine(cfg, kind="logreg", params=model.params,
                        scaler=model.scaler)
    eng.run(
        ReplaySource(txs, date_to_epoch_s(cfg.data.start_date),
                     batch_rows=2048),
        sink=ParquetSink(directory),
    )


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--dir", default=None,
                    help="existing ParquetSink directory (default: "
                         "generate a demo one)")
    ap.add_argument("--threshold", type=float, default=0.5)
    ap.add_argument("--k", type=int, default=10)
    ap.add_argument("--min-tx", type=int, default=3)
    args = ap.parse_args()

    tmp = None
    directory = args.dir
    if directory is None:
        tmp = tempfile.TemporaryDirectory(prefix="rtfds_sqlcheck_")
        directory = tmp.name
        _make_demo_dir(directory)

    queries = {
        "summary": _bind(SQL_SUMMARY, {"thr": args.threshold}),
        "top_terminals": _bind(SQL_TOP_TERMINALS,
                               {"min_tx": args.min_tx, "k": args.k}),
        # alert limit far above the flagged count: a LIMIT cutting inside
        # a timestamp tie would make row membership engine-dependent
        "alerts": _bind(SQL_ALERTS, {"thr": args.threshold, "k": 100000}),
        "daily": SQL_DAILY,
    }
    from real_time_fraud_detection_system_tpu.io.sqlquery import (
        run_queries,
    )

    engine, rows = run_queries(directory, queries)

    # ---- oracle: io/query.py over the same files --------------------
    from real_time_fraud_detection_system_tpu.io.query import (
        load_analyzed,
        recent_alerts,
        summary_stats,
        top_risky_terminals,
    )

    cols = load_analyzed(directory)
    mism = []

    s = summary_stats(cols, threshold=args.threshold)
    (got,) = rows["summary"]
    for i, key in enumerate(("transactions", "customers", "terminals",
                             "total_amount", "flagged", "flagged_amount",
                             "score_mean")):
        if not _close(got[i], s[key]):
            mism.append(f"summary.{key}: sql={got[i]} np={s[key]}")

    t = top_risky_terminals(cols, k=args.k, threshold=args.threshold,
                            min_transactions=args.min_tx)
    sql_terms = [r[0] for r in rows["top_terminals"]]
    # mean-score ties can order differently between engines — compare the
    # score sequence (must be identical) and the id SET
    sql_scores = [r[2] for r in rows["top_terminals"]]
    if not all(_close(a, b) for a, b in
               zip(sql_scores, t["mean_score"].tolist())):
        mism.append(f"top_terminals.scores: sql={sql_scores[:5]} "
                    f"np={t['mean_score'][:5]}")
    if len(sql_terms) != len(t["terminal_id"]):
        mism.append("top_terminals.len")

    a = recent_alerts(cols, threshold=args.threshold, limit=100000)
    sql_alert_ids = [r[0] for r in rows["alerts"]]
    if sorted(sql_alert_ids) != sorted(np.asarray(a["tx_id"]).tolist()):
        mism.append(f"alerts: sql={sql_alert_ids} np={a['tx_id']}")

    days = rows["daily"]
    np_days = cols["tx_datetime_us"] // 86_400_000_000
    uniq, cnt = np.unique(np_days, return_counts=True)
    if [int(r[0]) for r in days] != uniq.tolist() or \
            [int(r[1]) for r in days] != cnt.tolist():
        mism.append("daily volumes")

    out = {
        "ok": not mism,
        "engine": engine,
        "directory": directory if tmp is None else "<demo>",
        "rows": int(s["transactions"]),
        "queries": sorted(queries),
        "mismatches": mism,
    }
    print(json.dumps(out))
    if tmp is not None:
        tmp.cleanup()
    return 0 if not mism else 1


if __name__ == "__main__":
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    sys.exit(main())
