"""The four device-contract checks.

Each check is a class with ``name``/``doc``/``severity-policy`` and a
``run(target, inventory, traced) -> findings`` where ``traced`` maps
``sig.key`` to the ``jax.jit(...).trace`` result for that signature
(or the exception tracing raised). Findings use the rtfdslint chassis
(fingerprint = rule + anchor path + context + message; context is the
signature's stable ``describe()`` label, so a baseline entry pins one
signature's verdict without line numbers).
"""

from __future__ import annotations

from typing import Iterable, List

from rtfdslint.finding import Finding

from . import jaxpr_walk as jw
from .targets import VerifyTarget

#: check registry (mirrors rtfdslint.registry, scoped to this package)
_CHECKS: List[type] = []


def register(cls: type) -> type:
    _CHECKS.append(cls)
    return cls


def all_checks() -> List[type]:
    return list(_CHECKS)


def known_check_names() -> set:
    return {c.name for c in _CHECKS}


def _f(check: str, severity: str, target: VerifyTarget, message: str,
       context: str = "") -> Finding:
    return Finding(rule=check, severity=severity, path=target.anchor,
                   line=target.line, message=message,
                   context=context or target.name)


def _jaxpr_of(traced):
    return traced.jaxpr  # jax.stages.Traced


@register
class AotCoverageCheck:
    """Prove warmup coverage: no reachable dispatch key outside the
    inventory, every inventory signature traces, no dead executables."""

    name = "aot-coverage"
    doc = ("every runtime-reachable dispatch signature is in the "
           "inventory precompile() compiles, and traces to a lowerable "
           "program — a mid-stream recompile is impossible by "
           "construction")

    def run(self, target: VerifyTarget, inventory, traced
            ) -> Iterable[Finding]:
        out: List[Finding] = []
        eng = target.engine
        keys = [sig.key for sig in inventory]
        if len(set(keys)) != len(keys):
            out.append(_f(self.name, "P0", target,
                          "duplicate dispatch keys in the inventory — "
                          "precompile() would silently skip one variant"))
        # Reachable keys, derived INDEPENDENTLY from the dispatch-site
        # contract (engine.py::_start_batch keys on ("step", 7, pad)
        # with pad from core.batch.bucket_size; the sharded engine on
        # ("sharded", routed)) — the inventory must cover them, and the
        # derivation deliberately does NOT call dispatch_inventory(), so
        # a drifted enumeration cannot vacuously agree with itself.
        sharded = hasattr(eng, "rows_per_shard")
        if sharded:
            expected = {("sharded", False), ("sharded", True)} \
                if eng.kind != "sequence" else set()
        else:
            expected = {
                ("step", 7, int(b))
                for b in sorted(set(eng.cfg.runtime.batch_buckets))
            }
        fcfg = eng.cfg.features
        if (eng.kind != "sequence"
                and getattr(fcfg, "key_mode", "") == "exact"
                and getattr(fcfg, "compact_every", 0) > 0):
            # engine.py::_maybe_compact dispatches the recency-
            # compaction pass under this key on its batch cadence —
            # single-chip AND sharded (the mesh engine swaps in the
            # shard_map'd per-shard pass under the same key)
            expected.add(("compact",))
            if getattr(fcfg, "cold_store", ""):
                # engine.py::_maybe_promote lands resolved cold-tier
                # promotions under this key between device steps (same
                # single-chip/sharded split as compact) — a returning
                # key must never pay a mid-stream compile
                expected.add(("promote",))
        for key in sorted(expected - set(keys), key=str):
            out.append(_f(
                self.name, "P0", target,
                f"uncovered dispatch signature {key}: the runtime can "
                "dispatch this key but dispatch_inventory() does not "
                "enumerate it — precompile() will never compile it and "
                "the first touch pays a mid-stream XLA compile"))
        for key in sorted(set(keys) - expected, key=str):
            out.append(_f(
                self.name, "P2", target,
                f"inventory signature {key} is not reachable from any "
                "dispatch site — precompile() compiles a dead "
                "executable (wasted warmup time and cache space)"))
        for sig in inventory:
            tr = traced.get(sig.key)
            if isinstance(tr, Exception):
                out.append(_f(
                    self.name, "P0", target,
                    f"signature fails to trace: {type(tr).__name__}: "
                    f"{str(tr)[:200]} — the warmup path would crash (or "
                    "skip) and serving would pay the failure mid-stream",
                    context=sig.describe()))
        return out


@register
class ZModeExactnessCheck:
    """The PR-9 exactness contract, structurally: walk every
    ``dot_general``/``convert_element_type`` in the traced scoring
    program and prove the dtype lattice."""

    name = "zmode-exactness"
    doc = ("int8/bf16 z arithmetic stays exact by construction: integer "
           "z contraction survives, decision/leaf contractions stay "
           "f32-HIGHEST, and no laundered downcast enters the scoring "
           "program")

    #: dots whose operands are provably tiny integers (bool-derived
    #: lhs) are exact in ANY precision/dtype — everything else must be
    #: f32 pinned to HIGHEST.
    def run(self, target: VerifyTarget, inventory, traced
            ) -> Iterable[Finding]:
        out: List[Finding] = []
        for sig in inventory:
            if sig.z_mode is None:
                continue  # non-ensemble kinds carry no z contraction
            tr = traced.get(sig.key)
            if tr is None or isinstance(tr, Exception):
                continue  # aot-coverage already flagged it
            jaxpr = _jaxpr_of(tr)
            ctx = sig.describe()
            dts = jw.dtypes_used(jaxpr)
            if "float64" in dts:
                out.append(_f(
                    self.name, "P0", target,
                    "float64 aval in the traced step — the exactness "
                    "contract is defined over f32 decisions (and x64 "
                    "doubles every transfer)", context=ctx))
            # Laundered downcast: a reduced-precision float anywhere in
            # the int8 scoring program breaks bit-identity with f32; in
            # bf16/f32 modes a downcast is legal ONLY on the emission
            # tail (emit_dtype) or with bool-derived provenance.
            if sig.z_mode == "int8" and sig.emit_dtype == "float32":
                for bad in sorted(dts & {"bfloat16", "float16"}):
                    out.append(_f(
                        self.name, "P0", target,
                        f"{bad} aval in the int8-mode scoring program — "
                        "a laundered downcast breaks the int8≡f32 "
                        "bit-identity contract", context=ctx))
            else:
                # The bf16-emission license is bounded, not global: the
                # emission tail is exactly ONE f32→bf16 cast of the
                # outgoing feature matrix, so under emit_dtype=bfloat16
                # the FIRST non-exact narrowing is licensed and every
                # further one still flags (a jaxpr cannot say which
                # convert feeds the output, so one laundered cast can
                # hide behind the emission slot — documented
                # approximation; the runtime bit-identity tests stay
                # the backstop there).
                budget = 1 if sig.emit_dtype == "bfloat16" else 0
                for src, dst, exact in jw.converts_report(jaxpr):
                    if (src in ("float32", "float64")
                            and dst in ("bfloat16", "float16")
                            and not exact):
                        if budget > 0:
                            budget -= 1
                            continue
                        out.append(_f(
                            self.name, "P0", target,
                            f"{src}→{dst} convert of non-integer data in "
                            f"the {sig.z_mode} scoring program (only the "
                            "documented single emission downcast or "
                            "exact 0/1-derived operands may narrow)",
                            context=ctx))
            int_dots = 0
            for d in jw.dot_report(jaxpr):
                floats = {d["lhs_dtype"], d["rhs_dtype"], d["out_dtype"]}
                if not floats & {"float32", "float64", "bfloat16",
                                 "float16"}:
                    int_dots += 1  # integer in, integer out: exact
                    continue
                prec = d["precision"]
                pinned = prec is not None and all(
                    str(p).endswith("HIGHEST") for p in (
                        prec if isinstance(prec, tuple) else (prec,)))
                if pinned and d["lhs_dtype"] == d["rhs_dtype"] == \
                        "float32":
                    continue  # decision/leaf contraction, pinned
                if d["lhs_bool_derived"] or d["rhs_bool_derived"]:
                    # z contraction: the 0/1 decision matrix is one
                    # operand (einsum may place it on either side); the
                    # other is the ±1/0 path table, whose tiny-integer
                    # values to_gemm guarantees by construction — a
                    # VALUE fact the jaxpr cannot carry, so this license
                    # is deliberately one-sided (runtime bit-identity
                    # tests stay the backstop for the table side)
                    continue
                out.append(_f(
                    self.name, "P0", target,
                    f"unpinned contraction {d['lhs_dtype']}×"
                    f"{d['rhs_dtype']}→{d['out_dtype']} "
                    f"(precision={d['precision']}) with non-integer "
                    "operands — decisions can flip under reduced "
                    "precision (the contract pins these to f32-HIGHEST)",
                    context=ctx))
            if sig.z_mode == "int8" and int_dots == 0:
                out.append(_f(
                    self.name, "P0", target,
                    "z_mode=int8 but no integer contraction survives in "
                    "the traced program — the int8 path was silently "
                    "degraded to float arithmetic", context=ctx))
        return out


@register
class DonationSafetyCheck:
    """Donated buffers: only the feature state, never under the
    nan-guard, matching what the jit actually declares, and every
    donated leaf can alias an output."""

    name = "donation-safety"
    doc = ("buffer donation donates exactly the feature state (arg 0), "
           "is OFF under the nan-guard (its rollback re-reads pre-batch "
           "state host-side), matches the traced jit's declaration, and "
           "every donated leaf finds a shape/dtype-matching output to "
           "alias")

    def run(self, target: VerifyTarget, inventory, traced
            ) -> Iterable[Finding]:
        out: List[Finding] = []
        eng = target.engine
        for sig in inventory:
            ctx = sig.describe()
            if eng.cfg.runtime.nan_guard and sig.donate:
                out.append(_f(
                    self.name, "P0", target,
                    "nan_guard is on but the step donates "
                    f"argnums {sig.donate}: the guard's rollback "
                    "re-reads the pre-batch state AFTER dispatch — a "
                    "donated buffer is deleted by then", context=ctx))
            extra = [a for a in sig.donate if a != 0]
            if extra:
                out.append(_f(
                    self.name, "P0", target,
                    f"step donates argnums {tuple(extra)} beyond the "
                    "feature state: params/scaler/batch are re-read "
                    "host-side (checkpoint save, _params_sig, feedback) "
                    "after dispatch", context=ctx))
            tr = traced.get(sig.key)
            if tr is None or isinstance(tr, Exception):
                continue
            # Traced.donate_argnums is FLATTENED (leaf indices); expand
            # the inventory's tree-level claim to the same coordinates.
            import jax

            args = eng.signature_templates(sig)
            offsets, n = [], 0
            for a in args:
                offsets.append(n)
                n += len(jax.tree.leaves(a))
            expect_flat = tuple(sorted(
                i
                for argnum in sig.donate
                for i in range(
                    offsets[argnum],
                    offsets[argnum + 1] if argnum + 1 < len(offsets)
                    else n)))
            declared = tuple(sorted(getattr(tr, "donate_argnums", ())
                                    or ()))
            if declared != expect_flat:
                out.append(_f(
                    self.name, "P0", target,
                    f"inventory claims donate={tuple(sorted(sig.donate))}"
                    f" (flat leaves {expect_flat}) but the traced jit "
                    f"declares {declared} — the inventory has drifted "
                    "from the live step", context=ctx))
            if declared:
                # every donated leaf must find a matching output aval,
                # else XLA silently keeps a copy (donation wasted)
                jaxpr = _jaxpr_of(tr)
                donated = [jaxpr.jaxpr.invars[i].aval for i in declared]
                outs = [v.aval for v in jaxpr.jaxpr.outvars]
                pool = [(getattr(a, "shape", None),
                         str(getattr(a, "dtype", ""))) for a in outs]
                for av in donated:
                    want = (getattr(av, "shape", None),
                            str(getattr(av, "dtype", "")))
                    if want in pool:
                        pool.remove(want)
                    else:
                        out.append(_f(
                            self.name, "P1", target,
                            f"donated feature-state leaf {want} has no "
                            "shape/dtype-matching output to alias — XLA "
                            "keeps a silent copy (donation wasted, "
                            "double HBM for that leaf)", context=ctx))
        return out


@register
class PallasAdmissionCheck:
    """VMEM budget + tile alignment for every signature with the fused
    Pallas path reachable, via the SAME ``admit_block`` predicate the
    engine's trace-time gate runs — plus trace-level agreement (a
    pallas_call is present iff admitted)."""

    name = "pallas-admission"
    doc = ("pallas_block_bytes ≤ VMEM budget and MXU tile alignment "
           "hold statically for every use_pallas signature, and the "
           "traced program agrees with the admission verdict")

    def run(self, target: VerifyTarget, inventory, traced
            ) -> Iterable[Finding]:
        out: List[Finding] = []
        eng = target.engine
        for sig in inventory:
            if not sig.use_pallas or sig.kind not in (
                    "tree", "forest", "gbt"):
                continue
            ctx = sig.describe()
            from real_time_fraud_detection_system_tpu.models.forest \
                import GemmEnsemble
            from real_time_fraud_detection_system_tpu.ops.pallas_forest \
                import admit_block
            from real_time_fraud_detection_system_tpu.runtime.engine \
                import _PALLAS_BLOCK_BUDGET

            params = eng.state.params
            trees = getattr(params, "trees", params)
            if not isinstance(trees, GemmEnsemble):
                out.append(_f(
                    self.name, "P1", target,
                    "use_pallas requested but the live ensemble is in "
                    "descent form (no GEMM tables) — the fused kernel "
                    "can never admit; serving falls back to XLA "
                    "silently", context=ctx))
                continue
            rec = admit_block(trees, sig.z_mode or "f32",
                              _PALLAS_BLOCK_BUDGET)
            # Non-vacuous alignment proof: admit_block re-derives the
            # padded layout with the same _ceil_to math to_pallas uses,
            # so its own tiles_aligned cannot fail unless the two
            # functions drift. Cross-check against the layout the
            # kernel table builder ACTUALLY produces (values are
            # irrelevant; template ensembles are tiny).
            from real_time_fraud_detection_system_tpu.ops.pallas_forest \
                import TREE_BLOCK, to_pallas

            pf = to_pallas(trees, sig.z_mode or "f32")
            tp, fp, ip = (int(d) for d in pf.sel.shape)
            lp = int(pf.path.shape[2])
            built = (tp, fp, ip, lp)
            aligned = (tp % TREE_BLOCK == 0 and fp % 8 == 0
                       and ip % 128 == 0 and lp % 128 == 0)
            if built != tuple(rec.padded):
                out.append(_f(
                    self.name, "P0", target,
                    f"admit_block's padded layout {tuple(rec.padded)} "
                    f"disagrees with the layout to_pallas builds "
                    f"{built} — the admission verdict is judging a "
                    "different kernel than the one that would serve",
                    context=ctx))
            if not (rec.tiles_aligned and aligned):
                out.append(_f(
                    self.name, "P0", target,
                    f"padded kernel layout {built} does not tile the "
                    "MXU/grid sizes — the pallas_call would fail or "
                    "mis-index at dispatch", context=ctx))
            if rec.block_bytes > rec.budget:
                out.append(_f(
                    self.name, "P0", target,
                    f"tree block needs {rec.block_bytes} bytes of VMEM "
                    f"against a {rec.budget}-byte budget — the fused "
                    "kernel cannot admit this ensemble (serving would "
                    "silently fall back to XLA; an unguarded kernel "
                    "would overflow VMEM)", context=ctx))
            tr = traced.get(sig.key)
            if tr is None or isinstance(tr, Exception):
                continue
            has_pallas = jw.has_primitive(_jaxpr_of(tr), "pallas_call")
            if rec.fits and not has_pallas:
                out.append(_f(
                    self.name, "P1", target,
                    "admission passes but no pallas_call appears in the "
                    "traced program — the fused path is gated off "
                    "somewhere else (the operator believes the kernel "
                    "serves; XLA does)", context=ctx))
            elif not rec.fits and has_pallas:
                out.append(_f(
                    self.name, "P0", target,
                    "admission FAILS but a pallas_call is traced anyway "
                    "— the VMEM gate is not protecting this program",
                    context=ctx))
        return out
