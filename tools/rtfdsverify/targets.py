"""Template verification targets: weightless engines, real programs.

A target is a fully-built engine (single-chip or sharded) whose params
are SYNTHETIC but shape-faithful (``models/forest.synthetic_ensemble``/
``models/gbt.synthetic_gbt`` — valid structure, arbitrary values), so
the traced program is EXACTLY the serving program for that
configuration while nothing ever needs data, training, or a device.

The default matrix covers the device-plane contract surface the
runtime can serve: the tree-ensemble kinds across the full z-mode
lattice (f32/bf16/int8 — the exactness contract's domain), selective
emission packing, the fused-Pallas gate, a non-ensemble control
(logreg), and the sharded engine's local+routed variants. Buckets are
kept small (tracing cost scales with program count, not rows — the
contracts are shape-generic), and every engine runs ``scorer='tpu'``
semantics on the CPU backend: same traced program, no hardware.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

ENGINE_ANCHOR = "real_time_fraud_detection_system_tpu/runtime/engine.py"
SHARDED_ANCHOR = (
    "real_time_fraud_detection_system_tpu/runtime/sharded_engine.py")


@dataclass
class VerifyTarget:
    name: str       # stable label ("forest/int8", "sharded/forest/int8"…)
    engine: object  # built ScoringEngine / ShardedScoringEngine
    anchor: str     # repo-relative path findings anchor to
    line: int = 1


def _identity_scaler():
    import numpy as np

    from real_time_fraud_detection_system_tpu.features.spec import (
        N_FEATURES,
    )
    from real_time_fraud_detection_system_tpu.models.scaler import Scaler

    return Scaler(
        mean=np.zeros(N_FEATURES, np.float32),
        scale=np.ones(N_FEATURES, np.float32),
    )


def _base_config(features_kw=None, **runtime_kw):
    import dataclasses as dc

    from real_time_fraud_detection_system_tpu.config import (
        Config,
        FeatureConfig,
        RuntimeConfig,
    )

    return Config(
        features=dc.replace(
            FeatureConfig(customer_capacity=128,
                          terminal_capacity=256,
                          cms_width=1 << 10),
            **(features_kw or {})),
        runtime=dc.replace(
            RuntimeConfig(batch_buckets=(64, 256), max_batch_rows=256),
            **runtime_kw),
    )


def _params_for(kind: str, n_trees: int = 4, depth: int = 3):
    from real_time_fraud_detection_system_tpu.features.spec import (
        N_FEATURES,
    )

    if kind in ("tree", "forest"):
        from real_time_fraud_detection_system_tpu.models.forest import (
            synthetic_ensemble,
        )

        return synthetic_ensemble(n_trees, depth, N_FEATURES)
    if kind == "gbt":
        from real_time_fraud_detection_system_tpu.models.gbt import (
            synthetic_gbt,
        )

        return synthetic_gbt(n_trees, depth, N_FEATURES)
    if kind == "logreg":
        from real_time_fraud_detection_system_tpu.models.logreg import (
            init_logreg,
        )

        return init_logreg(N_FEATURES)
    raise ValueError(f"no synthetic template for kind {kind!r}")


def make_target(kind: str, name: Optional[str] = None,
                sharded: bool = False, n_trees: int = 4, depth: int = 3,
                params=None, features_kw=None, **runtime_kw
                ) -> VerifyTarget:
    """Build one verification target. ``runtime_kw`` land on
    ``RuntimeConfig`` (z_mode, emit_threshold, use_pallas, …) and
    ``features_kw`` on ``FeatureConfig`` (key_mode, compact_every, …);
    ``params`` overrides the synthetic template (the over-budget
    Pallas fixture passes an oversized ensemble)."""
    import jax
    import jax.numpy as jnp

    cfg = _base_config(features_kw=features_kw, **runtime_kw)
    params = params if params is not None else _params_for(
        kind, n_trees, depth)
    if sharded:
        from real_time_fraud_detection_system_tpu.runtime.sharded_engine \
            import ShardedScoringEngine

        eng = ShardedScoringEngine(
            cfg, kind, params, _identity_scaler(),
            n_devices=min(2, jax.device_count()), rows_per_shard=32)
        anchor = SHARDED_ANCHOR
    else:
        from real_time_fraud_detection_system_tpu.runtime.engine import (
            ScoringEngine,
        )

        eng = ScoringEngine(cfg, kind, params, _identity_scaler())
        anchor = ENGINE_ANCHOR
    # Commit scalar leaves to arrays exactly like precompile() does, so
    # the traced dtypes are the runtime-served dtypes.
    eng.state.params = jax.tree.map(jnp.asarray, eng.state.params)
    label = name or (("sharded/" if sharded else "") + kind
                     + (f"/{runtime_kw['z_mode']}"
                        if "z_mode" in runtime_kw else ""))
    return VerifyTarget(name=label, engine=eng, anchor=anchor)


def build_default_targets() -> List[VerifyTarget]:
    """The standard verification matrix (see module docstring)."""
    out: List[VerifyTarget] = []
    for zm in ("f32", "bf16", "int8"):
        out.append(make_target("forest", z_mode=zm))
    out.append(make_target("gbt", z_mode="int8"))
    out.append(make_target("logreg"))
    # selective emission compiles the packed-transfer program
    out.append(make_target("forest", name="forest/int8/selective",
                           z_mode="int8", emit_threshold=0.9))
    # the fused-Pallas gate (trace-time admission on static shapes)
    out.append(make_target("forest", name="forest/int8/pallas",
                           z_mode="int8", use_pallas=True))
    # the tiered feature store: exact key directory + sketch fallback in
    # the scoring program, plus the compaction pass as its own signature
    out.append(make_target(
        "forest", name="forest/int8/exact", z_mode="int8",
        features_kw={"key_mode": "exact", "compact_every": 8}))
    # sharded local + routed variants
    out.append(make_target("forest", sharded=True, z_mode="int8"))
    # the sharded tiered store: per-shard directories + sketch replicas
    # in BOTH step variants plus the shard_map'd compaction signature
    out.append(make_target(
        "forest", name="sharded/forest/int8/exact", sharded=True,
        z_mode="int8",
        features_kw={"key_mode": "exact", "compact_every": 8}))
    return out


#: registry of named target-list builders (CLI --matrix)
MATRICES: dict = {
    "default": build_default_targets,
}
