"""Jaxpr traversal helpers: flatten nested programs, classify dots.

Everything the checks need from a traced program, in one place:

* :func:`iter_eqns` — depth-first over every equation including the
  jaxprs nested inside ``pjit``/``scan``/``while``/``cond``/custom-vjp
  wrappers and ``pallas_call`` kernels (any eqn param that holds a
  Jaxpr/ClosedJaxpr, recursively);
* :func:`all_avals` — every abstract value the program touches
  (invars, outvars, constvars, every eqn's operands/results) — the set
  the no-laundered-downcast lattice check walks;
* :func:`bool_derived_vars` — the transitive closure of values produced
  by boolean comparisons through exactness-preserving ops (convert,
  broadcast, reshape, transpose, select-of-bools) — "provably tiny
  integer" provenance, which is what licenses a reduced-precision or
  unpinned contraction in the z-mode contract.

Pure jax introspection: no device, no compilation, no weights.
"""

from __future__ import annotations

from typing import Iterator, List, Set, Tuple

from jax._src import core as jax_core

#: primitives whose outputs stay exact-small-integer when their inputs
#: are (the provenance closure follows these from a bool compare)
_EXACTNESS_PRESERVING = {
    "convert_element_type", "broadcast_in_dim", "reshape", "transpose",
    "squeeze", "expand_dims", "copy", "slice", "dynamic_slice",
    "concatenate", "rev", "gather", "select_n", "stop_gradient",
}

#: comparison primitives — their boolean outputs root the provenance
_COMPARE_PRIMS = {"lt", "le", "gt", "ge", "eq", "ne"}


def _sub_jaxprs(params: dict) -> Iterator["jax_core.Jaxpr"]:
    """Every Jaxpr nested in an eqn's params (pjit/scan/cond/pallas…)."""
    for val in params.values():
        vals = val if isinstance(val, (tuple, list)) else (val,)
        for v in vals:
            if isinstance(v, jax_core.ClosedJaxpr):
                yield v.jaxpr
            elif isinstance(v, jax_core.Jaxpr):
                yield v


def iter_eqns(jaxpr) -> Iterator["jax_core.JaxprEqn"]:
    """All equations of ``jaxpr`` and every nested sub-jaxpr."""
    if isinstance(jaxpr, jax_core.ClosedJaxpr):
        jaxpr = jaxpr.jaxpr
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in _sub_jaxprs(eqn.params):
            yield from iter_eqns(sub)


def all_avals(jaxpr) -> List:
    """Every aval the program (and nested programs) touches."""
    if isinstance(jaxpr, jax_core.ClosedJaxpr):
        jaxpr = jaxpr.jaxpr
    out = [v.aval for v in jaxpr.invars + jaxpr.constvars + jaxpr.outvars]
    for eqn in iter_eqns(jaxpr):
        for v in eqn.invars:
            if isinstance(v, jax_core.Var):
                out.append(v.aval)
        out.extend(v.aval for v in eqn.outvars)
    return out


def dtypes_used(jaxpr) -> Set[str]:
    """String dtype names of every aval in the program."""
    out: Set[str] = set()
    for av in all_avals(jaxpr):
        dt = getattr(av, "dtype", None)
        if dt is not None:
            out.add(str(dt))
    return out


def _walk_scope(jaxpr, bool_vars: Set[int]) -> None:
    """One scope of the provenance closure (ids are per-Var object ids —
    Vars are unique objects within a jaxpr)."""
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        sub = list(_sub_jaxprs(eqn.params))
        if name in _COMPARE_PRIMS:
            for ov in eqn.outvars:
                bool_vars.add(id(ov))
            continue
        if sub:
            # Map outer boolean operands onto each sub-jaxpr's invars so
            # the provenance survives a pjit/scan boundary, then lift
            # boolean sub-outputs back to the eqn's outvars.
            for s in sub:
                # conservative positional map (trailing args align for
                # pjit/closed_call; scan carries consts first — a miss
                # only makes the check stricter, never unsound)
                scoped = set(bool_vars)
                outer = [v for v in eqn.invars
                         if isinstance(v, jax_core.Var)]
                k = min(len(outer), len(s.invars))
                for ov, iv in zip(outer[-k:], s.invars[-k:]):
                    if id(ov) in bool_vars:
                        scoped.add(id(iv))
                _walk_scope(s, scoped)
                for ov, sv in zip(eqn.outvars, s.outvars):
                    if isinstance(sv, jax_core.Var) \
                            and id(sv) in scoped:
                        bool_vars.add(id(ov))
            continue
        if name in _EXACTNESS_PRESERVING:
            operand_vars = [v for v in eqn.invars
                            if isinstance(v, jax_core.Var)]
            if operand_vars and all(
                    id(v) in bool_vars
                    or str(getattr(v.aval, "dtype", "")) == "bool"
                    for v in operand_vars):
                for ov in eqn.outvars:
                    bool_vars.add(id(ov))


def bool_derived_vars(jaxpr) -> Set[int]:
    """ids of Vars whose values are provably 0/1-derived (from boolean
    comparisons through exactness-preserving ops). Conservative: a miss
    makes the exactness check STRICTER (flags more), never unsound."""
    if isinstance(jaxpr, jax_core.ClosedJaxpr):
        jaxpr = jaxpr.jaxpr
    bool_vars: Set[int] = set()
    # seed: any invar already boolean
    for v in jaxpr.invars + jaxpr.constvars:
        if str(getattr(v.aval, "dtype", "")) == "bool":
            bool_vars.add(id(v))
    _walk_scope(jaxpr, bool_vars)
    return bool_vars


def dot_report(jaxpr) -> List[dict]:
    """One record per ``dot_general`` in the program (nested included):
    operand/output dtypes, precision, preferred_element_type, and
    whether the LHS is bool-derived (exact tiny integers)."""
    out: List[dict] = []

    def _scope(j, bools: Set[int]) -> None:
        for eqn in j.eqns:
            name = eqn.primitive.name
            sub = list(_sub_jaxprs(eqn.params))
            if name == "dot_general":
                lhs, rhs = eqn.invars[0], eqn.invars[1]
                out.append({
                    "lhs_dtype": str(lhs.aval.dtype),
                    "rhs_dtype": str(rhs.aval.dtype),
                    "out_dtype": str(eqn.outvars[0].aval.dtype),
                    "precision": eqn.params.get("precision"),
                    "preferred": str(
                        eqn.params.get("preferred_element_type")),
                    # einsum may put either factor on either side: the
                    # z-contraction license needs "one operand is the
                    # 0/1 decision matrix", wherever it landed
                    "lhs_bool_derived": (
                        isinstance(lhs, jax_core.Var)
                        and id(lhs) in bools),
                    "rhs_bool_derived": (
                        isinstance(rhs, jax_core.Var)
                        and id(rhs) in bools),
                })
            for s in sub:
                inner = set(bools)
                outer = [v for v in eqn.invars
                         if isinstance(v, jax_core.Var)]
                k = min(len(outer), len(s.invars))
                for ov, iv in zip(outer[-k:], s.invars[-k:]):
                    if id(ov) in bools:
                        inner.add(id(iv))
                # recompute provenance inside the sub-scope too
                inner |= bool_derived_vars(s)
                _scope(s, inner)

    if isinstance(jaxpr, jax_core.ClosedJaxpr):
        jaxpr = jaxpr.jaxpr
    _scope(jaxpr, bool_derived_vars(jaxpr))
    return out


def converts_report(jaxpr) -> List[Tuple[str, str, bool]]:
    """(src_dtype, dst_dtype, src_bool_derived) per convert_element_type."""
    out: List[Tuple[str, str, bool]] = []

    def _scope(j, bools: Set[int]) -> None:
        for eqn in j.eqns:
            if eqn.primitive.name == "convert_element_type":
                src = eqn.invars[0]
                out.append((
                    str(src.aval.dtype),
                    str(eqn.outvars[0].aval.dtype),
                    (not isinstance(src, jax_core.Var))
                    or id(src) in bools
                    or str(src.aval.dtype) == "bool",
                ))
            for s in _sub_jaxprs(eqn.params):
                _scope(s, bools | bool_derived_vars(s))

    if isinstance(jaxpr, jax_core.ClosedJaxpr):
        jaxpr = jaxpr.jaxpr
    _scope(jaxpr, bool_derived_vars(jaxpr))
    return out


def has_primitive(jaxpr, name: str) -> bool:
    return any(eqn.primitive.name == name for eqn in iter_eqns(jaxpr))
