"""rtfdsverify — jaxpr-level device-contract verifier for the rtfds
serving loop.

``tools/rtfdslint`` proves source-level invariants with pure ``ast``;
this package goes one level down, to the **traced program**: it builds
weightless template engines (synthetic shape-faithful models, CPU-only
jax, ``JAX_PLATFORMS=cpu``), loads each engine's **dispatch signature
inventory** (:meth:`ScoringEngine.dispatch_inventory` — the single
enumeration ``precompile()`` also compiles, so coverage proof and
warmup can never drift) and abstract-interprets every signature's
jitted step with ``jax.jit(...).trace`` / jaxpr inspection — no device
step ever executes, no weights are needed. Per signature it proves:

* **aot-coverage** — every runtime-reachable dispatch key is in the
  inventory and traces to a lowerable program, so a mid-stream XLA
  recompile is impossible by construction, not just counted at runtime
  (``rtfds_xla_recompiles_total`` stays the backstop);
* **zmode-exactness** — the PR-9 arithmetic-exactness contract as a
  checked theorem: integer z arithmetic survives in the int8 path,
  decision/leaf contractions stay f32 pinned to HIGHEST, and no
  laundered downcast (f32→bf16/f16) enters the scoring program;
* **donation-safety** — the nan-guard's donation-off dance and the
  donate-only-the-feature-state rule, cross-checked against what the
  jit actually declares and whether every donated buffer can alias an
  output;
* **pallas-admission** — ``ops/pallas_forest.admit_block`` (the SAME
  predicate the engine's trace-time gate uses): VMEM block budget and
  MXU tile alignment hold statically for every signature with
  ``use_pallas`` reachable, and the traced program agrees with the
  verdict (a pallas_call is present iff admitted).

Findings report through the rtfdslint chassis (same P0/P1/P2
severities, ``--json`` schema, fingerprint baseline with required
reasons). Semantic findings have no single source line to pragma, so
the baseline (``tools/rtfdsverify/baseline.json``) is the suppression
channel.

Entry points:

* ``rtfds verify-device`` (CLI subcommand) / ``make verify-static``
* ``PYTHONPATH=tools python -m rtfdsverify`` from a checkout
* :func:`run_verify` for in-process use (the tier-1 gate test).
"""

from __future__ import annotations

import os
import sys

# rtfdsverify reuses the rtfdslint chassis (Finding/Baseline/severities);
# both live side by side under tools/, so a bare `import rtfdsverify`
# from a checkout must be able to find its sibling.
_TOOLS_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _TOOLS_DIR not in sys.path:
    sys.path.insert(0, _TOOLS_DIR)
_REPO_ROOT = os.path.dirname(_TOOLS_DIR)
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

from .runner import VerifyResult, run_verify  # noqa: E402,F401
from .checks import all_checks  # noqa: E402,F401

__version__ = "1.0.0"
