"""Argparse front-end: ``python -m rtfdsverify`` / ``rtfds
verify-device``. Forces ``JAX_PLATFORMS=cpu`` before jax initializes —
the proofs are backend-independent shape/jaxpr facts and must never
wait on (or wake) an accelerator."""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional


def _find_root(start: str) -> str:
    cur = os.path.abspath(start)
    while True:
        if os.path.isdir(os.path.join(
                cur, "real_time_fraud_detection_system_tpu")):
            return cur
        nxt = os.path.dirname(cur)
        if nxt == cur:
            return os.path.abspath(start)
        cur = nxt


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="rtfds verify-device",
        description=("jaxpr-level device-contract verifier: AOT "
                     "coverage, z-mode exactness, donation safety, "
                     "Pallas VMEM admission — proven on traced "
                     "programs before a stream starts (CPU-only, no "
                     "weights)"))
    ap.add_argument("--root", default=None,
                    help="repo root (default: discovered from cwd)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default "
                         "tools/rtfdsverify/baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (show everything)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="absorb current P0/P1 findings into the "
                         "baseline")
    ap.add_argument("--reason", default="",
                    help="reason recorded on NEW baseline entries "
                         "(required with --update-baseline)")
    ap.add_argument("--check", action="append", default=None,
                    help="run only this check (repeatable)")
    ap.add_argument("--strict", action="store_true",
                    help="P2 findings also fail the gate")
    ap.add_argument("--verbose", action="store_true",
                    help="also list baselined findings")
    ap.add_argument("--list-checks", action="store_true",
                    help="print the check catalog and exit")
    return ap


def main(argv: Optional[List[str]] = None) -> int:
    # Force CPU BEFORE jax (transitively) initializes: the verifier
    # must run identically on a laptop, in CI, and beside a TPU.
    os.environ["JAX_PLATFORMS"] = "cpu"
    args = build_parser().parse_args(argv)
    from rtfdslint.baseline import BaselineError

    from .checks import all_checks
    from .runner import (
        DEFAULT_BASELINE,
        render_human,
        run_verify,
        update_baseline,
    )

    if args.list_checks:
        for cls in all_checks():
            print(f"{cls.name:24s} {cls.doc}")
        return 0
    root = args.root or _find_root(os.getcwd())
    baseline = None if args.no_baseline \
        else (args.baseline or DEFAULT_BASELINE)
    try:
        result = run_verify(root, baseline_path=baseline,
                            checks=args.check)
    except (BaselineError, ValueError) as e:
        print(f"rtfdsverify: {e}", file=sys.stderr)
        return 2
    if args.update_baseline:
        if args.no_baseline:
            print("rtfdsverify: --update-baseline cannot be combined "
                  "with --no-baseline (prior entries must be loaded to "
                  "be preserved)", file=sys.stderr)
            return 2
        if not args.reason.strip():
            print("rtfdsverify: --update-baseline requires --reason "
                  "'why these findings are accepted'", file=sys.stderr)
            return 2
        n = update_baseline(root, result,
                            args.baseline or DEFAULT_BASELINE,
                            args.reason.strip())
        print(f"rtfdsverify: baseline now holds {n} entr"
              f"{'y' if n == 1 else 'ies'}")
        return 0
    print(json.dumps(result.to_json(strict=args.strict), indent=2)
          if args.json
          else render_human(result, verbose=args.verbose,
                            strict=args.strict))
    return 1 if result.gate_failures(strict=args.strict) else 0
