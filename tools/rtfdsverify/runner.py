"""Verify orchestration: build targets → trace signatures → run checks
→ baseline → verdict. The chassis (Finding, Baseline, severity gate)
is rtfdslint's; only the evidence source differs (traced jaxprs
instead of parsed source)."""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from rtfdslint.baseline import Baseline
from rtfdslint.finding import Finding, RuleStats, severity_rank

DEFAULT_BASELINE = "tools/rtfdsverify/baseline.json"


@dataclass
class VerifyResult:
    """Mirror of ``rtfdslint.runner.LintResult`` over verification
    targets (kept schema-compatible so ``rtfds lint --json`` can carry
    a verifier block unchanged)."""

    findings: List[Finding] = field(default_factory=list)   # active
    baselined: List[Finding] = field(default_factory=list)
    stale_baseline: List[dict] = field(default_factory=list)
    stats: Dict[str, RuleStats] = field(default_factory=dict)
    targets: List[str] = field(default_factory=list)
    signatures_verified: int = 0

    def gate_failures(self, strict: bool = False) -> List[Finding]:
        bad = ("P0", "P1") if not strict else ("P0", "P1", "P2")
        return [f for f in self.findings if f.severity in bad]

    def to_json(self, strict: bool = False) -> dict:
        return {
            "version": 1,
            "targets": self.targets,
            "signatures_verified": self.signatures_verified,
            "strict": strict,
            "findings": [f.to_json() for f in self.findings],
            "baselined": [f.to_json() for f in self.baselined],
            "stale_baseline_entries": self.stale_baseline,
            "checks": {k: v.to_json()
                       for k, v in sorted(self.stats.items())},
            "summary": {
                "active": len(self.findings),
                "gate_failures": len(self.gate_failures(strict=strict)),
                "baselined": len(self.baselined),
            },
        }


def run_verify(root: str,
               targets: Optional[list] = None,
               baseline_path: Optional[str] = DEFAULT_BASELINE,
               checks: Optional[List[str]] = None) -> VerifyResult:
    """Run the device-contract verifier.

    ``targets`` defaults to :func:`~.targets.build_default_targets`
    (pass a list of :class:`~.targets.VerifyTarget` to verify specific
    engines — the sensitivity fixtures do). ``baseline_path`` is
    repo-root-relative; None verifies without a baseline. ``checks``
    filters by check name (unknown names are a hard error, never a
    vacuous pass — same contract as rtfdslint's ``--rule``).
    """
    # Pin CPU at the CONFIG level, whoever the caller is (the rtfdslint
    # --verify-device integration path reaches here without the CLI's
    # env pin): a TPU-proxy sitecustomize may have force-set
    # jax_platforms at interpreter start, and the first traced op would
    # otherwise wake — or hang on — an accelerator the proofs never
    # need. Env alone is not enough once jax has read its config.
    import os as _os

    import jax

    _os.environ.setdefault("JAX_PLATFORMS", "cpu")
    jax.config.update("jax_platforms", "cpu")

    from .checks import all_checks, known_check_names
    from .targets import build_default_targets

    selected = all_checks()
    if checks:
        unknown = set(checks) - known_check_names()
        if unknown:
            raise ValueError(
                f"unknown check name(s) {sorted(unknown)} — see "
                "--list-checks for the catalog")
        selected = [c for c in selected if c.name in set(checks)]
    if targets is None:
        targets = build_default_targets()

    raw: List[Finding] = []
    n_sigs = 0
    for t in targets:
        inventory = t.engine.dispatch_inventory()
        traced: dict = {}
        for sig in inventory:
            n_sigs += 1
            try:
                traced[sig.key] = t.engine.signature_step(sig).trace(
                    *t.engine.signature_templates(sig))
            # a trace failure is exactly what aot-coverage must report,
            # whatever its type — never abort the other signatures
            except Exception as e:  # noqa: BLE001
                traced[sig.key] = e
        for check_cls in selected:
            raw.extend(check_cls().run(t, inventory, traced))

    baseline = Baseline(path="")
    if baseline_path:
        bp = baseline_path if os.path.isabs(baseline_path) \
            else os.path.join(root, baseline_path)
        baseline = Baseline.load(bp)

    result = VerifyResult(targets=[t.name for t in targets],
                          signatures_verified=n_sigs)
    raw.sort(key=lambda f: (f.path, f.context, f.rule, f.message))
    for f in raw:
        stats = result.stats.setdefault(f.rule, RuleStats())
        if baseline.absorb(f):
            f.suppressed = "baseline"
            result.baselined.append(f)
            stats.baselined += 1
        else:
            result.findings.append(f)
            stats.active += 1
    if targets and baseline_path:
        result.stale_baseline = baseline.stale_entries()
    return result


def render_human(result: VerifyResult, verbose: bool = False,
                 strict: bool = False) -> str:
    out: List[str] = []
    for f in sorted(result.findings,
                    key=lambda f: (severity_rank(f.severity), f.path,
                                   f.context)):
        out.append(f.render())
    if verbose and result.baselined:
        out.append("")
        out.append(f"-- baselined ({len(result.baselined)}):")
        out.extend("   " + f.render() for f in result.baselined)
    if result.stale_baseline:
        out.append("")
        out.append("-- stale baseline entries (matched nothing; delete "
                   "or re-run --update-baseline):")
        for ent in result.stale_baseline:
            out.append(f"   {ent.get('rule')} {ent.get('context', '')}: "
                       f"{ent.get('message', '')[:80]}")
    counts = {"P0": 0, "P1": 0, "P2": 0}
    for f in result.findings:
        counts[f.severity] += 1
    gate = result.gate_failures(strict=strict)
    bar = "P0/P1/P2" if strict else "P0/P1"
    out.append("")
    out.append(
        f"rtfdsverify: {len(result.targets)} target(s), "
        f"{result.signatures_verified} signature(s), "
        f"{len(result.findings)} active finding(s) "
        f"[P0={counts['P0']} P1={counts['P1']} P2={counts['P2']}], "
        f"{len(result.baselined)} baselined")
    out.append("gate: " + (f"FAIL — unbaselined {bar} present"
                           if gate else f"clean (no unbaselined {bar})"))
    return "\n".join(out)


def update_baseline(root: str, result: VerifyResult,
                    baseline_path: str, reason: str) -> int:
    """``--update-baseline``: absorb current gate failures, carrying
    prior reasons forward (rtfdslint semantics)."""
    bp = baseline_path if os.path.isabs(baseline_path) \
        else os.path.join(root, baseline_path)
    prior = Baseline.load(bp)
    keep = result.gate_failures() + result.baselined
    return Baseline.write(bp, keep, prior, reason)
