"""Generate the vendored XGBoost golden fixture for tests/test_gbt.py.

Run ONCE in any environment with xgboost installed (the reference's
dependency set — ``model_training.ipynb · cell 50`` fits XGBClassifier):

    python tools/make_xgb_golden.py

writes ``tests/data/xgb_golden.npz`` containing the fitted model's tree
dumps, base score, held-out predictions and AUC on the same seeded
dataset the test suite regenerates. With the fixture committed, the two
xgboost parity tests assert on every run — no xgboost needed at test
time; without it they fall back to live xgboost, else skip (this
sandbox has neither xgboost nor network egress, so the fixture must be
produced out-of-band).
"""

from __future__ import annotations

import json
import os

import numpy as np


def dataset():
    """The exact ``xy`` fixture from tests/test_gbt.py (seeded rng(0))."""
    rng = np.random.default_rng(0)
    n, f = 8000, 15
    x = rng.normal(0, 1, (n, f))
    logits = np.sin(x[:, 0] * 2) + x[:, 1] * x[:, 2] + 0.5 * x[:, 3] - 1
    y = (rng.random(n) < 1 / (1 + np.exp(-logits))).astype(np.float64)
    return x[:6000], y[:6000], x[6000:], y[6000:]


def main() -> None:
    import xgboost
    from sklearn.metrics import roc_auc_score

    xtr, ytr, xte, yte = dataset()
    out = {}
    # Matched-hyperparameter model (test_gbt_matches_xgboost_parity).
    xgb = xgboost.XGBClassifier(
        n_estimators=60, max_depth=5, learning_rate=0.1,
        tree_method="hist", max_bin=64, reg_lambda=1.0,
        min_child_weight=1.0, eval_metric="logloss",
    ).fit(xtr, ytr)
    out["auc_matched"] = roc_auc_score(yte, xgb.predict_proba(xte)[:, 1])

    # Import-parity model (test_xgboost_model_import_parity).
    xgb2 = xgboost.XGBClassifier(
        n_estimators=30, max_depth=4, learning_rate=0.2,
        tree_method="hist", eval_metric="logloss",
    ).fit(xtr, ytr)
    booster = xgb2.get_booster()
    cfg = json.loads(booster.save_config())
    p0 = float(cfg["learner"]["learner_model_param"]["base_score"])
    out["import_dumps"] = np.asarray(
        booster.get_dump(dump_format="json"), dtype=object)
    out["import_base_score"] = float(np.log(p0 / (1.0 - p0)))
    out["import_probs"] = xgb2.predict_proba(xte)[:, 1].astype(np.float64)
    out["xgboost_version"] = str(xgboost.__version__)

    dest = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tests", "data", "xgb_golden.npz")
    os.makedirs(os.path.dirname(dest), exist_ok=True)
    np.savez_compressed(dest, **out)  # load with allow_pickle=True
    print(f"wrote {dest}: matched AUC {out['auc_matched']:.4f}, "
          f"{len(out['import_dumps'])} import trees, "
          f"xgboost {out['xgboost_version']}")


if __name__ == "__main__":
    main()
