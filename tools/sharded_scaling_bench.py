"""Virtual-mesh scaling curve for the sharded serving engine.

Measures the sharded engine at mesh widths {1, 2, 4, 8} on the virtual
CPU mesh (``--xla_force_host_platform_device_count=8``), plus the
single-chip engine on the same stream as the reference row. All widths
execute on the SAME host cores, so wall-clock speedup is not the claim
— the claim this curve substantiates is that the shard_map machinery
(host partition/spill, packed per-chunk H2D, owner all_to_all,
re-assembly) does NOT compound with width: rows/s at a fixed total batch
should stay ≈flat from 1 → 8 devices, and width 1 should sit within a
few percent of the single-chip engine (the round-4 verdict's 29%
single-device tax, since removed via the identity owner-exchange and the
packed chunk transfer).

Prints ONE JSON line:

    {"total_rows": ..., "batches": ..., "model": ...,
     "single_chip_rows_per_s": ...,
     "by_devices": {"1": ..., "2": ..., "4": ..., "8": ...}}

Run standalone (``python tools/sharded_scaling_bench.py [--quick]``) or
let ``bench.py`` spawn it (recorded under ``detail.sharded_scaling``).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _rand_batches(n_batches: int, rows: int, seed: int = 2) -> list:
    rng = np.random.default_rng(seed)
    out = []
    for b in range(n_batches):
        out.append({
            "tx_id": np.arange(b * rows, (b + 1) * rows, dtype=np.int64),
            "tx_datetime_us": (
                (20200 * 86400 + rng.integers(0, 86400, rows)).astype(
                    np.int64) * 1_000_000),
            "customer_id": rng.integers(0, 5000, rows).astype(np.int64),
            "terminal_id": rng.integers(0, 10000, rows).astype(np.int64),
            "tx_amount_cents": rng.integers(100, 50000, rows).astype(
                np.int64),
            "kafka_ts_ms": np.full(rows, b, dtype=np.int64),
        })
    return out


class _Replay:
    def __init__(self, batches):
        self._b = list(batches)
        self._i = 0
        self.offsets = [0]

    def poll_batch(self):
        if self._i >= len(self._b):
            return None
        b = self._b[self._i]
        self._i += 1
        self.offsets = [self._i]
        return b


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--rows", type=int, default=8192)
    ap.add_argument("--batches", type=int, default=6)
    args = ap.parse_args()

    import jax.numpy as jnp

    from real_time_fraud_detection_system_tpu.config import (
        Config,
        FeatureConfig,
        RuntimeConfig,
    )
    from real_time_fraud_detection_system_tpu.models.logreg import (
        init_logreg,
    )
    from real_time_fraud_detection_system_tpu.models.scaler import Scaler
    from real_time_fraud_detection_system_tpu.runtime import (
        ScoringEngine,
        ShardedScoringEngine,
    )

    rows = 2048 if args.quick else args.rows
    n_meas = 3 if args.quick else args.batches
    cfg = Config(
        features=FeatureConfig(customer_capacity=8192,
                               terminal_capacity=16384),
        runtime=RuntimeConfig(batch_buckets=(rows,), max_batch_rows=rows,
                              trigger_seconds=0.0, pipeline_depth=2),
    )
    params = init_logreg(15)
    scaler = Scaler(mean=jnp.zeros(15), scale=jnp.ones(15))

    def _measure(make_engine) -> float:
        e = make_engine()
        e.run(_Replay(_rand_batches(1, rows, seed=3)), trigger_seconds=0.0)
        s = e.run(_Replay(_rand_batches(n_meas, rows)),
                  trigger_seconds=0.0)
        return round(s["rows_per_s"], 1)

    result = {
        "total_rows": rows,
        "batches": n_meas,
        "model": "logreg",
        "host_cores": os.cpu_count(),
        "note": ("virtual 8-device CPU mesh on shared host cores: the "
                 "claim is flat rows/s across widths >= 2 (the "
                 "capacity-bounded owner exchange keeps TOTAL buffer "
                 "work ~2x batch regardless of width, so per-device "
                 "work shrinks as 1/width), not wall-clock speedup; "
                 "the 1 -> 2 step is the structural cost of turning "
                 "the routed exchange on"),
        "single_chip_rows_per_s": _measure(
            lambda: ScoringEngine(cfg, kind="logreg", params=params,
                                  scaler=scaler)),
        "by_devices": {},
    }
    for n_dev in (1, 2, 4, 8):
        # uniform 25% padding headroom at every width (pad = 1.25×rows),
        # so ordinary customer%n imbalance stays in one chunk and the
        # per-width numbers compare like for like
        rps = (rows * 5 // 4) // n_dev
        result["by_devices"][str(n_dev)] = _measure(
            lambda: ShardedScoringEngine(
                cfg, kind="logreg", params=params, scaler=scaler,
                n_devices=n_dev, rows_per_shard=rps))
        print(f"# devices={n_dev} -> "
              f"{result['by_devices'][str(n_dev)]} rows/s",
              file=sys.stderr, flush=True)
    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
