"""Multi-host scaling matrix: real OS processes × one shared stream.

ROADMAP item 1's proof shape (``bench.py`` records it as
``detail.multihost_scaling``): launch 1, 2 and 4 REAL serving processes
(``tools/multihost_launcher.py`` → ``rtfds score`` workers with
``jax.distributed`` coordination where the backend allows it) over one
co-partitioned synthetic stream, under ``--precompile``, and show the
classic distributed-ML failure mode — coordination cost eating the
speedup — does not happen:

- **per-process rate flat within 15%** as the fleet grows 1→2→4. On a
  CI box with fewer cores than processes, wall-clock rows/s measures
  the box (N processes time-slice one core), so the gate is rows per
  process-CPU-second (``stats.cpu_s`` — serving loop only, precompile
  excluded; the same load-immunity trick as
  test_instrumentation_overhead_bounded). Wall rates are reported too.
- **zero mid-stream recompiles in every arm** — from each worker's own
  registry dump (``--metrics-dump``), not prints;
- **no lost or duplicated rows**: fleet total == stream rows in every
  arm (partition-affine ingest covers the residue space exactly).

Bit-identity multi ≡ single-process is pinned in
``tests/test_multihost_smoke.py``; this matrix measures scaling.

Prints ONE JSON line. Run standalone
(``python tools/multihost_scaling_bench.py [--quick]``) or let
``bench.py`` spawn it.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _make_dataset(path: str, n_rows: int, n_total_shards: int,
                  seed: int = 11) -> None:
    """Co-partitioned stream: terminal residues track customer residues
    (mod the widest arm's shard count), so every arm's partition-affine
    slices keep each key's history wholly inside one process — the same
    property a broker keyed on both ids gives a production fleet."""
    import numpy as np

    from real_time_fraud_detection_system_tpu.data.generator import (
        Transactions,
    )
    from real_time_fraud_detection_system_tpu.io.artifacts import (
        save_transactions,
    )

    rng = np.random.default_rng(seed)
    cust = rng.integers(0, 2048, n_rows).astype(np.int64)
    term = (rng.integers(0, 512, n_rows) * n_total_shards
            + (cust % n_total_shards)).astype(np.int64)
    t_s = np.sort(rng.integers(0, 30 * 86400, n_rows)).astype(np.int64)
    txs = Transactions(
        tx_id=np.arange(n_rows, dtype=np.int64),
        tx_time_seconds=t_s,
        tx_time_days=(t_s // 86400).astype(np.int32),
        customer_id=cust,
        terminal_id=term,
        amount_cents=(rng.integers(1, 500, n_rows) * 100).astype(np.int64),
        tx_fraud=np.zeros(n_rows, np.int8),
        tx_fraud_scenario=np.zeros(n_rows, np.int8),
    )
    save_transactions(path, txs)


def _make_model(path: str) -> None:
    import numpy as np

    from real_time_fraud_detection_system_tpu.io.artifacts import (
        save_model,
    )
    from real_time_fraud_detection_system_tpu.models.logreg import (
        init_logreg,
    )
    from real_time_fraud_detection_system_tpu.models.scaler import Scaler
    from real_time_fraud_detection_system_tpu.models.train import (
        TrainedModel,
    )

    save_model(path, TrainedModel(
        kind="logreg",
        scaler=Scaler(mean=np.zeros(15, np.float32),
                      scale=np.ones(15, np.float32)),
        params=init_logreg(15)))


def _run_arm(n_proc: int, work: str, data: str, model: str,
             batch_rows: int, timeout_s: float,
             serialize: bool = False) -> dict:
    """One fleet arm through the real launcher + CLI; returns per-worker
    stats + registry-sourced recompile counts.

    ``serialize=False``: the real concurrent fleet behind one
    jax.distributed barrier — the correctness arm (recompiles,
    coverage, coordination actually happening). ``serialize=True``:
    same fleet, workers run one at a time uncoordinated — the RATE arm:
    on a shared-core CI box, N concurrent jax processes time-slice one
    core and even CPU-time inflates with cache eviction, so concurrent
    rates measure the box; serialized, each process gets the host to
    itself, which is exactly what a pod deployment gives it."""
    arm_dir = os.path.join(
        work, f"procs-{n_proc}{'-ser' if serialize else ''}")
    dumps = os.path.join(arm_dir, "dumps")
    os.makedirs(dumps, exist_ok=True)
    launcher = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "multihost_launcher.py")
    cmd = [
        sys.executable, launcher,
        "--processes", str(n_proc),
        "--workdir", os.path.join(arm_dir, "wd"),
        "--timeout", str(timeout_s),
    ] + (["--no-coordinator", "--serialize"] if serialize else []) + [
        "--",
        "score",
        "--source", "replay",
        "--data", data,
        "--model-file", model,
        "--scorer", "tpu",
        "--precompile",
        "--devices", "1",
        # The replay emulation polls the SHARED stream (every process's
        # residues) and filters to its own — so the inner poll must be
        # P× for each process's device batches to stay at batch_rows,
        # which is what a broker-partitioned fleet polls natively
        # (each consumer reads only its partitions at full batch size).
        # Without this, every worker pays 1-proc's step count for 1/P
        # of the rows and the matrix measures padding, not coordination.
        "--batch-rows", str(batch_rows * n_proc),
        "--coalesce-rows", str(batch_rows),
        "--max-batch-rows", str(2 * batch_rows),
        "--out", os.path.join(arm_dir, "out"),
        "--metrics-dump", os.path.join(dumps, "{proc}.json"),
    ]
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # 1 local device per worker process
    p = subprocess.run(cmd, env=env, stdout=subprocess.PIPE,
                       stderr=subprocess.PIPE, text=True,
                       timeout=timeout_s + 120)
    lines = [ln for ln in p.stdout.splitlines() if ln.startswith("{")]
    if p.returncode != 0 or not lines:
        raise RuntimeError(
            f"arm procs={n_proc} rc={p.returncode}: "
            f"{p.stderr.strip()[-300:]}")
    fleet = json.loads(lines[-1])
    recompiles = []
    for pid in range(n_proc):
        dump = os.path.join(dumps, f"{pid:02d}.json")
        with open(dump, "r", encoding="utf-8") as f:
            snap = json.load(f)
        series = snap.get("rtfds_xla_recompiles_total",
                          {}).get("series", [])
        recompiles.append(sum(float(r.get("value", 0.0))
                              for r in series))
    return {"fleet": fleet, "recompiles": recompiles}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--rows", type=int, default=32768)
    ap.add_argument("--batch-rows", type=int, default=512)
    ap.add_argument("--process-counts", type=int, nargs="*",
                    default=[1, 2, 4])
    ap.add_argument("--timeout", type=float, default=420.0)
    args = ap.parse_args()

    n_rows = 16384 if args.quick else args.rows
    counts = args.process_counts
    work = tempfile.mkdtemp(prefix="rtfds-multihost-")
    result = {
        "rows_per_process": n_rows,
        "batch_rows": args.batch_rows,
        "host_cores": os.cpu_count(),
        "note": ("WEAK scaling (stream grows with the fleet; "
                 "per-process load constant) with two runs per arm: a "
                 "CONCURRENT coordinated fleet proves correctness "
                 "(zero recompiles, exact stream coverage, a real "
                 "jax.distributed barrier) and a SERIALIZED "
                 "uncoordinated fleet measures per-process rows/s "
                 "with each worker given the host to itself — the pod "
                 "deployment's shape (one host per process). On a "
                 "shared-core CI box, concurrent rates (reported "
                 "alongside) measure core time-slicing and cache "
                 "eviction, not this repo's coordination cost."),
        "by_processes": {},
    }
    try:
        model = os.path.join(work, "model.npz")
        _make_model(model)
        base_rate = None
        for n_proc in counts:
            # WEAK scaling — the paper's deployment claim ("add
            # executors behind the topic to absorb more traffic"): the
            # stream grows with the fleet, per-process load stays
            # n_rows. Strong scaling on a fixed stream would compare
            # arms at different batch counts and measure per-run warmup
            # amortization, not coordination.
            data = os.path.join(work, f"txs-{n_proc}.npz")
            _make_dataset(data, n_rows * n_proc, max(counts),
                          seed=11)
            # correctness arm: the real concurrent coordinated fleet
            arm = _run_arm(n_proc, work, data, model, args.batch_rows,
                           args.timeout)
            # rate arm: same fleet serialized — per-process rates as a
            # one-host-per-process pod delivers them
            rate_arm = _run_arm(n_proc, work, data, model,
                                args.batch_rows, args.timeout,
                                serialize=True)
            fleet = arm["fleet"]
            rate_by_proc = {w["process"]: w
                            for w in rate_arm["fleet"]["workers"]}
            per_proc = []
            for wrow in fleet["workers"]:
                cpu = float(wrow.get("cpu_s", 0.0) or 0.0)
                rw = rate_by_proc.get(wrow["process"], {})
                per_proc.append({
                    "process": wrow["process"],
                    "rows": wrow["rows"],
                    "rows_per_s": rw.get("rows_per_s"),
                    "rows_per_s_concurrent_wall": wrow["rows_per_s"],
                    "rows_per_cpu_s_concurrent": (
                        round(wrow["rows"] / cpu, 1) if cpu > 0
                        else None),
                })
            rates = sorted(r["rows_per_s"] for r in per_proc
                           if r["rows_per_s"])
            med = rates[len(rates) // 2] if rates else None
            if base_rate is None:
                base_rate = med
            cell = {
                "rows_total": fleet["rows_total"],
                "rows_lost_or_duplicated": (n_rows * n_proc
                                            - fleet["rows_total"]),
                "per_process": per_proc,
                "median_rows_per_s": med,
                "vs_1proc": (round(med / base_rate, 3)
                             if med and base_rate else None),
                "mid_stream_recompiles": arm["recompiles"],
                "coordinated": fleet["coordinated"],
            }
            result["by_processes"][str(n_proc)] = cell
            print(f"# procs={n_proc}: median {med} rows/s per process "
                  f"(vs 1-proc {cell['vs_1proc']}), recompiles "
                  f"{arm['recompiles']}", file=sys.stderr, flush=True)
        cells = [c for c in result["by_processes"].values()
                 if isinstance(c, dict)]
        result["flat_within_15pct"] = all(
            c["vs_1proc"] is None or c["vs_1proc"] >= 0.85
            for c in cells)
        result["zero_recompiles_all_arms"] = all(
            all(v == 0 for v in c["mid_stream_recompiles"])
            for c in cells)
        result["no_rows_lost"] = all(
            c["rows_lost_or_duplicated"] == 0 for c in cells)
    finally:
        shutil.rmtree(work, ignore_errors=True)
    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
