"""CpuScorer — the reference's exact serving pipeline kept as truth
(``fraud_detection.py:183-195``: scaler.transform → predict_proba[:,1])."""

import numpy as np

from real_time_fraud_detection_system_tpu.models.cpu_oracle import (
    CpuScorer,
    fit_cpu_scorer,
)


def test_fit_and_predict_matches_manual_pipeline():
    rng = np.random.default_rng(0)
    x = rng.normal(0, 3, (600, 15))
    y = (x[:, 0] - 0.5 * x[:, 3] > 1.0).astype(np.int32)
    scorer = fit_cpu_scorer(x, y, kind="forest", n_trees=20, max_depth=5)
    p = scorer.predict_proba(x)
    assert p.shape == (600,)
    assert ((p >= 0) & (p <= 1)).all()
    # exactly scaler → predict_proba[:, 1], nothing else
    manual = scorer.model.predict_proba(scorer.scaler.transform(x))[:, 1]
    np.testing.assert_array_equal(p, manual)
    # the pipeline learns this separable rule
    from real_time_fraud_detection_system_tpu.models.metrics import roc_auc

    assert roc_auc(y, p) > 0.95


def test_kinds():
    rng = np.random.default_rng(1)
    x = rng.normal(0, 1, (200, 15))
    y = (x[:, 0] > 0).astype(np.int32)
    for kind in ("logreg", "tree", "forest"):
        p = fit_cpu_scorer(x, y, kind=kind).predict_proba(x)
        assert p.shape == (200,)


def test_wraps_any_sklearn_pair():
    from sklearn.linear_model import LogisticRegression
    from sklearn.preprocessing import StandardScaler

    rng = np.random.default_rng(2)
    x = rng.normal(0, 1, (100, 4))
    y = (x[:, 0] > 0).astype(np.int32)
    scaler = StandardScaler().fit(x)
    model = LogisticRegression().fit(scaler.transform(x), y)
    p = CpuScorer(scaler, model).predict_proba(x)
    np.testing.assert_allclose(
        p, model.predict_proba(scaler.transform(x))[:, 1])


def test_logging_namespacing():
    import io
    import logging

    from real_time_fraud_detection_system_tpu.utils.logging import get_logger

    log = get_logger("oracle")
    assert log.name == "rtfds.oracle"
    assert get_logger("rtfds.engine").name == "rtfds.engine"
    assert get_logger().name == "rtfds"
    # the configured handler binds the real stderr at first call, so
    # assert via our own handler rather than capsys
    buf = io.StringIO()
    h = logging.StreamHandler(buf)
    root = logging.getLogger("rtfds")
    root.addHandler(h)
    try:
        log.info("hello %d", 7)
    finally:
        root.removeHandler(h)
    assert "hello 7" in buf.getvalue()
