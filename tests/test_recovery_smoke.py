"""`make recovery-smoke` — the tier-1 dirty-recovery chaos matrix.

Every cell scripts one durable-state failure mode — kill-during-save,
corrupt-latest (byte-flip and truncation), a flaky store, a torn PUT, a
broken delta chain — against BOTH checkpoint planes (local directory and
object store) and asserts the recovery contract END TO END from the
metrics registry and the sink's ``batch_index`` lineage (never prints):

- the stream COMPLETES: restore quarantines the corrupt entry, falls back
  down the lineage to the newest valid checkpoint, and the supervisor
  replays from the older fence instead of dying;
- exact ``rtfds_checkpoint_corrupt_total{reason=…}`` and
  ``rtfds_checkpoint_fallbacks_total`` deltas;
- flaky-store ops retry (``rtfds_retry_attempts_total``) instead of
  killing the stream, with zero false corruption;
- gap/dup-free ``batch_index`` part lineage and the complete row set in
  the Parquet sink after recovery (replays overwrite, never duplicate).
"""

import os
from types import SimpleNamespace

import numpy as np
import pyarrow.parquet as pq
import pytest

from real_time_fraud_detection_system_tpu.config import (
    Config,
    FeatureConfig,
    RuntimeConfig,
)
from real_time_fraud_detection_system_tpu.io.checkpoint import (
    Checkpointer,
    StoreCheckpointer,
)
from real_time_fraud_detection_system_tpu.io.sink import ParquetSink
from real_time_fraud_detection_system_tpu.io.store import LocalStore
from real_time_fraud_detection_system_tpu.models.logreg import init_logreg
from real_time_fraud_detection_system_tpu.models.scaler import Scaler
from real_time_fraud_detection_system_tpu.runtime.engine import ScoringEngine
from real_time_fraud_detection_system_tpu.runtime.faults import (
    FlakySource,
    FlakyStore,
    TornStore,
    run_with_recovery,
)
from real_time_fraud_detection_system_tpu.runtime.sources import ReplaySource
from real_time_fraud_detection_system_tpu.utils.metrics import get_registry

EPOCH0 = 1_743_465_600
REASONS = ("checksum", "truncated", "incompatible")


def _counters():
    reg = get_registry()
    vals = {r: reg.counter("rtfds_checkpoint_corrupt_total",
                           reason=r).value for r in REASONS}
    vals["fallbacks"] = reg.counter(
        "rtfds_checkpoint_fallbacks_total").value
    vals["retried"] = reg.counter("rtfds_retry_attempts_total",
                                  outcome="retried").value
    return vals


def _mk(small_dataset, rows: int):
    dcfg, _, _, txs = small_dataset
    part = txs.slice(slice(0, rows))
    cfg = Config(
        data=dcfg,
        features=FeatureConfig(customer_capacity=256, terminal_capacity=512,
                               cms_width=1 << 10),
        runtime=RuntimeConfig(checkpoint_every_batches=2,
                              batch_buckets=(256,), max_batch_rows=256),
    )
    params = init_logreg(15)

    def make_engine():
        import jax.numpy as jnp

        return ScoringEngine(
            cfg, kind="logreg", params=params,
            scaler=Scaler(mean=jnp.zeros(15), scale=jnp.ones(15)),
        )

    return part, make_engine


@pytest.fixture(params=["local", "store"])
def plane(request, tmp_path):
    """One durable-state plane per run: a local checkpoint directory or
    an object store (LocalStore-backed, so cells can reach under the
    API to corrupt the stored bytes — exactly what a bit-flipping disk
    or a torn multipart PUT does)."""
    kind = request.param
    if kind == "local":
        d = str(tmp_path / "ck")

        def make(**kw):
            return Checkpointer(d, **kw)

        def file_of(path):
            return path
    else:
        root = str(tmp_path / "obj")

        def make(**kw):
            return StoreCheckpointer(LocalStore(root), **kw)

        def file_of(key):
            return os.path.join(root, key)

    return SimpleNamespace(kind=kind, make=make, file_of=file_of,
                           tmp_path=tmp_path)


def _phase1(make_engine, part, ckpt, sink_dir, max_batches):
    """Run the first stretch of the stream, checkpointing — the state a
    crash/corruption then lands on."""
    eng = make_engine()
    src = ReplaySource(part, EPOCH0, batch_rows=256)
    eng.run(src, sink=ParquetSink(sink_dir), checkpointer=ckpt,
            max_batches=max_batches)
    return eng


def _phase2(make_engine, part, ckpt, sink_dir, max_restarts=3):
    """Resume the stream supervised (a restarted deployment): restore —
    verified, with fallback — then complete."""
    src = ReplaySource(part, EPOCH0, batch_rows=256)
    return run_with_recovery(
        make_engine, src, ckpt, sink=ParquetSink(sink_dir),
        max_restarts=max_restarts)


def _assert_lineage(sink_dir, part, n_parts):
    """Gap/dup-free batch_index lineage + the complete row set."""
    parts = sorted((p for p in os.listdir(sink_dir)
                    if p.startswith("part-")),)
    idxs = [int(p[len("part-"):-len(".parquet")]) for p in parts]
    assert idxs == list(range(1, n_parts + 1))
    total = sum(pq.read_table(os.path.join(sink_dir, f)).num_rows
                for f in parts)
    assert total == part.n
    back = ParquetSink(sink_dir).read_all()
    assert sorted(np.unique(back["tx_id"]).tolist()) == sorted(
        part.tx_id.tolist())


def test_corrupt_latest_byte_flip(plane, tmp_path, small_dataset):
    """A bit-flip in the newest checkpoint: restore detects it
    (reason=checksum), quarantines the file, falls back one fence and
    replays to a complete, gap-free stream."""
    part, make_engine = _mk(small_dataset, 1536)
    sink_dir = str(tmp_path / "analyzed")
    ckpt = plane.make()
    _phase1(make_engine, part, ckpt, sink_dir, max_batches=4)
    latest = ckpt.latest()
    f = plane.file_of(latest)
    data = open(f, "rb").read()
    with open(f, "r+b") as fh:
        fh.seek(len(data) // 2)
        fh.write(bytes([data[len(data) // 2] ^ 0xFF]))

    base = _counters()
    stats = _phase2(make_engine, part, plane.make(), sink_dir)
    after = _counters()

    assert stats["batches"] == 6 and stats["rows"] >= 1536
    assert after["checksum"] - base["checksum"] == 1
    assert after["truncated"] == base["truncated"]
    assert after["incompatible"] == base["incompatible"]
    assert after["fallbacks"] - base["fallbacks"] == 1
    # the corrupt bytes are quarantined (stashed, not deleted) for
    # forensics; the replay re-created the fence under the same name,
    # and the post-recovery lineage re-verifies clean end to end
    fresh = plane.make()
    assert sum(1 for n in fresh._backend.list_names()
               if n.startswith("stale-")) == 1
    assert all(e["valid"] for e in fresh.verify_all())
    _assert_lineage(sink_dir, part, 6)


def test_corrupt_latest_truncation(plane, tmp_path, small_dataset):
    """A torn write leaves the newest checkpoint half-length: restore
    classifies it truncated and replays from the previous fence."""
    part, make_engine = _mk(small_dataset, 1536)
    sink_dir = str(tmp_path / "analyzed")
    ckpt = plane.make()
    _phase1(make_engine, part, ckpt, sink_dir, max_batches=4)
    f = plane.file_of(ckpt.latest())
    data = open(f, "rb").read()
    with open(f, "wb") as fh:
        fh.write(data[: len(data) // 3])

    base = _counters()
    stats = _phase2(make_engine, part, plane.make(), sink_dir)
    after = _counters()

    assert stats["batches"] == 6
    assert after["truncated"] - base["truncated"] == 1
    assert after["checksum"] == base["checksum"]
    assert after["fallbacks"] - base["fallbacks"] == 1
    _assert_lineage(sink_dir, part, 6)


def test_kill_during_save_local(tmp_path, small_dataset):
    """Local plane killed between the tmp write and os.replace: the
    committed lineage is intact (atomic rename), the orphan ``.tmp`` is
    swept at construction, and recovery replays with ZERO corruption
    counted — a clean kill must not look like corruption."""
    part, make_engine = _mk(small_dataset, 1536)
    d = str(tmp_path / "ck")
    sink_dir = str(tmp_path / "analyzed")
    ckpt = Checkpointer(d)
    _phase1(make_engine, part, ckpt, sink_dir, max_batches=4)
    # the save at batch 4 "died mid-write": its file never committed,
    # its tmp remains
    latest = ckpt.latest()
    os.remove(latest)
    orphan = latest + ".tmp"
    with open(orphan, "wb") as fh:
        fh.write(b"half a checkpoint, interrupted")

    base = _counters()
    stats = _phase2(make_engine, part, Checkpointer(d), sink_dir)
    after = _counters()

    assert stats["batches"] == 6
    assert not os.path.exists(orphan)  # swept at construction
    assert after == base  # no corruption, no fallback, no retries
    _assert_lineage(sink_dir, part, 6)


def test_kill_during_save_store_torn_put(tmp_path, small_dataset):
    """Store plane killed mid-PUT (torn multipart upload that still
    'succeeded'): only restore-time verification catches the truncated
    object; recovery falls back one fence and completes."""
    part, make_engine = _mk(small_dataset, 1536)
    root = str(tmp_path / "obj")
    sink_dir = str(tmp_path / "analyzed")
    torn = TornStore(LocalStore(root), tear_at=1, keep_bytes=256)
    _phase1(make_engine, part, StoreCheckpointer(torn), sink_dir,
            max_batches=4)  # save @2 lands, save @4 lands TORN

    base = _counters()
    stats = _phase2(make_engine, part,
                    StoreCheckpointer(LocalStore(root)), sink_dir)
    after = _counters()

    assert stats["batches"] == 6
    assert after["truncated"] - base["truncated"] == 1
    assert after["fallbacks"] - base["fallbacks"] == 1
    _assert_lineage(sink_dir, part, 6)
    assert get_registry().counter(
        "rtfds_faults_injected_total", kind="torn_store_put").value >= 1


def test_flaky_store_hardening(tmp_path, small_dataset):
    """A flaky store (scripted PUT and GET failures) plus a mid-stream
    crash: every checkpoint op retries with original-typed errors, the
    post-crash restore succeeds through the flake, and NOTHING is
    counted corrupt — flakiness is not corruption."""
    part, make_engine = _mk(small_dataset, 1536)
    root = str(tmp_path / "obj")
    sink_dir = str(tmp_path / "analyzed")
    flaky = FlakyStore(LocalStore(root), fail_puts=(0,), fail_gets=(0,))
    ckpt = StoreCheckpointer(flaky, op_attempts=3)
    src = FlakySource(ReplaySource(part, EPOCH0, batch_rows=256),
                      fail_at=(3,))

    base = _counters()
    stats = run_with_recovery(
        make_engine, src, ckpt, sink=ParquetSink(sink_dir),
        max_restarts=3)
    after = _counters()

    assert stats["batches"] == 6
    assert stats["restarts"] == 1  # the scripted poll crash, recovered
    assert after["retried"] - base["retried"] >= 2  # PUT + GET retried
    for r in REASONS:
        assert after[r] == base[r]  # zero false corruption
    assert after["fallbacks"] == base["fallbacks"]
    _assert_lineage(sink_dir, part, 6)


def test_delta_chain_break(plane, tmp_path, small_dataset):
    """Delta lineage with a corrupted mid-chain entry: the tip's chain
    no longer resolves, both dead entries are quarantined, and restore
    falls back to the last valid FULL checkpoint — then the supervisor
    replays the gap and the stream completes."""
    part, make_engine = _mk(small_dataset, 2048)
    sink_dir = str(tmp_path / "analyzed")
    ckpt = plane.make(full_every=10)  # one full, then deltas
    _phase1(make_engine, part, ckpt, sink_dir, max_batches=6)
    names = [os.path.basename(p) for p in ckpt.list_checkpoints()]
    assert names == ["ckpt-0000000002.npz",
                     "ckpt-0000000004-delta.npz",
                     "ckpt-0000000006-delta.npz"]
    mid = ckpt.list_checkpoints()[1]
    with open(plane.file_of(mid), "wb") as fh:
        fh.write(b"garbage where a delta used to be")

    base = _counters()
    stats = _phase2(make_engine, part, plane.make(full_every=10),
                    sink_dir)
    after = _counters()

    assert stats["batches"] == 8
    # the tip (whose chain reads the garbage) AND the garbage entry
    # itself both count + quarantine; the full at batch 2 serves
    assert after["truncated"] - base["truncated"] == 2
    assert after["fallbacks"] - base["fallbacks"] == 1
    # both dead entries sit in the quarantine stash, and the lineage the
    # replay rebuilt (fresh full + chain) re-verifies clean end to end
    fresh = plane.make()
    assert sum(1 for n in fresh._backend.list_names()
               if n.startswith("stale-")) == 2
    report = fresh.verify_all()
    assert report and all(e["valid"] for e in report)
    _assert_lineage(sink_dir, part, 8)


def test_recovery_events_in_flight_record(tmp_path, small_dataset):
    """The flight record tells the fallback story: one
    ``checkpoint_fallback`` event per quarantined entry plus the final
    restored-fence event — the trail the ops dashboard renders."""
    from real_time_fraud_detection_system_tpu.utils.metrics import (
        FlightRecorder,
        set_active_recorder,
    )

    part, make_engine = _mk(small_dataset, 1536)
    d = str(tmp_path / "ck")
    sink_dir = str(tmp_path / "analyzed")
    ckpt = Checkpointer(d)
    _phase1(make_engine, part, ckpt, sink_dir, max_batches=4)
    latest = ckpt.latest()
    with open(latest, "wb") as fh:
        fh.write(b"garbage")

    rec = FlightRecorder(str(tmp_path / "flight.jsonl"))
    set_active_recorder(rec)
    try:
        _phase2(make_engine, part, Checkpointer(d), sink_dir)
    finally:
        set_active_recorder(None)
        rec.close()
    _, records = FlightRecorder.read(str(tmp_path / "flight.jsonl"))
    evs = [r for r in records if r.get("kind") == "event"
           and r.get("event") == "checkpoint_fallback"]
    assert any(e.get("path") == os.path.basename(latest)
               and e.get("reason") == "truncated" for e in evs)
    assert any(e.get("restored") and e.get("skipped") == 1 for e in evs)


# -- cold tier (features.cold_store) ----------------------------------------


def _mk_cold(small_dataset, rows: int, cold_dir: str):
    """A cold-tier variant of :func:`_mk`: hot tier oversubscribed
    (64 slots, 120 customers) so compaction demotes under pressure and
    recurring customers force promotion traffic every batch."""
    dcfg, _, _, txs = small_dataset
    part = txs.slice(slice(0, rows))
    cfg = Config(
        data=dcfg,
        features=FeatureConfig(
            key_mode="exact", customer_capacity=64, terminal_capacity=128,
            cms_width=1 << 10, compact_every=1, cold_store=cold_dir,
            cold_demote_slots=16, cold_highwater=0.5,
            cold_promote_queue=64),
        runtime=RuntimeConfig(checkpoint_every_batches=2,
                              batch_buckets=(256,), max_batch_rows=256),
    )
    params = init_logreg(15)

    def make_engine():
        import jax.numpy as jnp

        return ScoringEngine(
            cfg, kind="logreg", params=params,
            scaler=Scaler(mean=jnp.zeros(15), scale=jnp.ones(15)),
        )

    return part, make_engine


def test_cold_crash_mid_promotion_resume_exactly_once(
        tmp_path, small_dataset):
    """SIGKILL mid-promotion, emulated the way the kill-during-save
    cells do: the dying incarnation leaves (a) a POST-checkpoint cold
    segment (demotions flushed after the last fence) and (b) an
    enqueued promotion that never lands. Resume must prune the
    post-checkpoint segment from the cold store (replay regenerates
    those demotions — exactly-once across the tier boundary), fence the
    promoter, survive a second scripted crash mid-replay, and complete
    with a gap/dup-free sink lineage and ZERO corruption counted."""
    cold_dir = str(tmp_path / "cold")
    part, make_engine = _mk_cold(small_dataset, 1536, cold_dir)
    d = str(tmp_path / "ck")
    sink_dir = str(tmp_path / "analyzed")
    eng = _phase1(make_engine, part, Checkpointer(d), sink_dir,
                  max_batches=4)
    assert eng._cold.keys_count > 0, "phase 1 must demote"
    man = Checkpointer(d).manifest(Checkpointer(d).latest())
    lineage = man["meta"]["cold_lineage"]
    assert lineage["segments"], "checkpoint must record cold lineage"

    # the crash artifacts: a post-checkpoint segment + an in-flight
    # promotion request on the promoter the "kill" abandons
    nb = eng.cfg.features.n_day_buckets
    eng._cold.append(
        "customer", np.array([999_999], np.uint32),
        np.full((1, nb), 20_000, np.int32),
        np.ones((1, nb), np.float32), np.ones((1, nb), np.float32),
        np.zeros((1, nb), np.float32))
    orphan_seq = eng._cold.flush()
    assert orphan_seq is not None
    assert eng._promoter.request("customer", 999_999)  # never lands

    base = _counters()
    src = FlakySource(ReplaySource(part, EPOCH0, batch_rows=256),
                      fail_at=(1,))  # a SECOND crash, mid-replay
    stats = run_with_recovery(
        make_engine, src, Checkpointer(d), sink=ParquetSink(sink_dir),
        max_restarts=3)
    after = _counters()

    assert stats["batches"] == 6 and stats["restarts"] == 1
    for r in REASONS:
        assert after[r] == base[r]  # cold replay is not corruption
    assert after["fallbacks"] == base["fallbacks"]
    _assert_lineage(sink_dir, part, 6)
    # the post-checkpoint segment was pruned at restore (its seq number
    # may be legitimately reused by post-restore demotions): the crash
    # incarnation's key appears in NO manifest and no index
    import json

    for n in os.listdir(cold_dir):
        if n.startswith("seg-") and n.endswith(".json"):
            man_keys = json.loads(
                open(os.path.join(cold_dir, n)).read())["keys"]
            assert 999_999 not in man_keys.get("customer", [])
    from real_time_fraud_detection_system_tpu.io.coldstore import ColdStore

    assert not ColdStore(cold_dir).contains("customer", 999_999)


def test_cold_torn_manifest_degrades_honestly(tmp_path, small_dataset):
    """A torn cold-segment manifest (half-written JSON): re-open
    quarantines it, restore warns that the checkpoint's lineage lists a
    now-missing segment, its keys serve from CMS honestly, and the
    resumed stream still completes gap/dup-free — cold-tier damage
    never becomes checkpoint corruption or a dead stream."""
    cold_dir = str(tmp_path / "cold")
    part, make_engine = _mk_cold(small_dataset, 1536, cold_dir)
    d = str(tmp_path / "ck")
    sink_dir = str(tmp_path / "analyzed")
    _phase1(make_engine, part, Checkpointer(d), sink_dir, max_batches=4)
    lineage = Checkpointer(d).manifest(
        Checkpointer(d).latest())["meta"]["cold_lineage"]
    assert lineage["segments"]
    seq = int(lineage["segments"][0]["seq"])
    man_file = os.path.join(cold_dir, f"seg-{seq:08d}.json")
    data = open(man_file, "rb").read()
    with open(man_file, "wb") as fh:
        fh.write(data[: len(data) // 2])

    base = _counters()
    stats = _phase2(make_engine, part, Checkpointer(d), sink_dir)
    after = _counters()

    assert stats["batches"] == 6
    for r in REASONS:
        assert after[r] == base[r]
    _assert_lineage(sink_dir, part, 6)
    names = os.listdir(cold_dir)
    assert f"quarantine-seg-{seq:08d}.json" in names
    assert f"seg-{seq:08d}.npz" not in names  # uncommitted blob swept


def test_cold_byte_flip_poisons_segment_not_stream(
        tmp_path, small_dataset):
    """Bit-flipped cold-segment blobs: CRC verification catches them at
    promotion-read time, the segments quarantine, the affected keys
    degrade to CMS (rows=None poison isolation — the promoter never
    wedges, the exact tier never ingests garbage) and the resumed
    stream completes gap/dup-free."""
    cold_dir = str(tmp_path / "cold")
    part, make_engine = _mk_cold(small_dataset, 1536, cold_dir)
    d = str(tmp_path / "ck")
    sink_dir = str(tmp_path / "analyzed")
    _phase1(make_engine, part, Checkpointer(d), sink_dir, max_batches=4)
    blobs = [n for n in os.listdir(cold_dir) if n.endswith(".npz")]
    assert blobs
    for n in blobs:
        f = os.path.join(cold_dir, n)
        data = open(f, "rb").read()
        with open(f, "r+b") as fh:
            fh.seek(len(data) // 2)
            fh.write(bytes([data[len(data) // 2] ^ 0xFF]))

    base = _counters()
    stats = _phase2(make_engine, part, Checkpointer(d), sink_dir)
    after = _counters()

    assert stats["batches"] == 6
    for r in REASONS:
        assert after[r] == base[r]  # cold damage ≠ checkpoint corruption
    _assert_lineage(sink_dir, part, 6)
    # at least one poisoned read fired during replay and quarantined
    assert any(n.startswith("quarantine-seg-")
               for n in os.listdir(cold_dir))
