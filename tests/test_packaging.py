"""Packaging surface: pyproject + Makefile (the reference's installable-
system role, ``pyproject.toml:1-30`` + ``Makefile:1-58``)."""

import os
import tomllib

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_pyproject_parses_and_script_resolves():
    with open(os.path.join(REPO, "pyproject.toml"), "rb") as f:
        meta = tomllib.load(f)
    proj = meta["project"]
    assert proj["name"] == "real-time-fraud-detection-system-tpu"
    target = proj["scripts"]["rtfds"]
    mod_name, attr = target.split(":")
    import importlib

    mod = importlib.import_module(mod_name)
    assert callable(getattr(mod, attr))


def test_makefile_mirrors_reference_targets():
    with open(os.path.join(REPO, "Makefile")) as f:
        mk = f.read()
    for target in ("demo:", "datagen:", "train:", "score:", "run-all:",
                   "bench:", "test:", "install:"):
        assert target in mk, target
