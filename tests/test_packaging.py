"""Packaging surface: pyproject + Makefile (the reference's installable-
system role, ``pyproject.toml:1-30`` + ``Makefile:1-58``)."""

import importlib.util
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Capability skip, not a collection error: tomllib is stdlib only from
# py3.11 — on 3.10 the pyproject test SKIPS with a precise reason
# instead of erroring the whole file's collection under
# --continue-on-collection-errors (the Makefile/bench tests below don't
# need tomllib and keep running).
_HAS_TOMLLIB = importlib.util.find_spec("tomllib") is not None


@pytest.mark.skipif(
    not _HAS_TOMLLIB,
    reason="tomllib is stdlib from py3.11; pyproject parsing needs it")
def test_pyproject_parses_and_script_resolves():
    import tomllib

    with open(os.path.join(REPO, "pyproject.toml"), "rb") as f:
        meta = tomllib.load(f)
    proj = meta["project"]
    assert proj["name"] == "real-time-fraud-detection-system-tpu"
    target = proj["scripts"]["rtfds"]
    mod_name, attr = target.split(":")
    import importlib

    mod = importlib.import_module(mod_name)
    assert callable(getattr(mod, attr))


def test_makefile_mirrors_reference_targets():
    with open(os.path.join(REPO, "Makefile")) as f:
        mk = f.read()
    for target in ("demo:", "datagen:", "train:", "score:", "run-all:",
                   "bench:", "test:", "install:"):
        assert target in mk, target


def test_bench_emit_final_compact_line_last(capsys):
    """The driver records only a tail window of bench stdout, so the LAST
    line must be a complete, parseable result JSON on its own (round-4
    `BENCH_r04.json` had ``parsed: null`` because the full detail line
    outgrew the window)."""
    import json
    import sys

    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.remove(REPO)

    result = {
        "metric": "score_txns_per_sec", "value": 123.4, "unit": "txns/s",
        "vs_baseline": 2.0,
        "detail": {"backend": "tpu", "device_kind": "TPU v5 lite",
                   "tpu_attempts": 1, "huge": "x" * 20000},
    }
    bench._emit_final(result)
    lines = [ln for ln in capsys.readouterr().out.splitlines() if ln]
    assert len(lines) == 2
    full = json.loads(lines[0])
    assert full["detail"]["huge"]  # full detail preserved first
    compact = json.loads(lines[-1])
    assert compact["metric"] == "score_txns_per_sec"
    assert compact["value"] == 123.4
    assert compact["vs_baseline"] == 2.0
    assert compact["detail"]["backend"] == "tpu"
    assert len(lines[-1]) < 400  # fits any sane tail window
