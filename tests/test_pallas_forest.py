"""Fused forest kernel parity vs the XLA GEMM composition (interpret mode).

``pallas_leaf_sum`` must agree with ``gemm_leaf_sum`` to f32 accumulation
order (both are decision-exact vs sklearn); the fuzz cases hit the padding
paths (non-×128 node counts, non-×TREE_BLOCK tree counts, non-×block_rows
batches) and the threshold-equality decision edge.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from real_time_fraud_detection_system_tpu.models.forest import (
    ensemble_from_sklearn,
    gemm_leaf_sum,
    gemm_predict_proba,
    to_gemm,
)
from real_time_fraud_detection_system_tpu.ops.pallas_forest import (
    TREE_BLOCK,
    pallas_leaf_sum,
    pallas_predict_proba,
    pallas_table_bytes,
    to_pallas,
)

N_FEAT = 15


def _fit(rng, n_trees=7, max_depth=5, n=600):
    from sklearn.ensemble import RandomForestClassifier

    x = rng.normal(size=(n, N_FEAT)).astype(np.float32)
    y = (x[:, 0] + 0.5 * x[:, 3] + rng.normal(scale=0.3, size=n) > 0.4)
    clf = RandomForestClassifier(
        n_estimators=n_trees, max_depth=max_depth, random_state=0, n_jobs=1
    )
    clf.fit(x, y.astype(np.int32))
    ens = ensemble_from_sklearn(clf, N_FEAT)
    return clf, ens, x


@pytest.mark.parametrize("n_trees,max_depth", [(7, 5), (TREE_BLOCK, 3), (13, 6)])
def test_pallas_matches_gemm(n_trees, max_depth):
    rng = np.random.default_rng(3)
    clf, ens, x = _fit(rng, n_trees=n_trees, max_depth=max_depth)
    g = to_gemm(ens, N_FEAT)
    pf = to_pallas(g)

    xq = rng.normal(size=(300, N_FEAT)).astype(np.float32)  # non-×block rows
    want = np.asarray(gemm_leaf_sum(g, jnp.asarray(xq), z_mode="f32"))
    got = np.asarray(pallas_leaf_sum(pf, jnp.asarray(xq), block_rows=128))
    np.testing.assert_allclose(got, want, atol=1e-5)

    # and the bagged probability agrees with sklearn exactly in decisions
    p_skl = clf.predict_proba(xq)[:, 1]
    p_pal = np.asarray(pallas_predict_proba(pf, jnp.asarray(xq),
                                            block_rows=128))
    np.testing.assert_allclose(p_pal, p_skl, atol=1e-6)


def test_threshold_edge_inputs():
    """Inputs placed EXACTLY on thresholds: decisions must not flip."""
    rng = np.random.default_rng(5)
    clf, ens, _ = _fit(rng, n_trees=5, max_depth=4)
    g = to_gemm(ens, N_FEAT)
    pf = to_pallas(g)

    th = np.asarray(ens.thresh).ravel()
    th = th[np.isfinite(th) & (th != 0)]
    k = min(len(th), 64)
    xq = np.tile(th[:k, None], (1, N_FEAT)).astype(np.float32)
    p_skl = clf.predict_proba(xq)[:, 1]
    p_pal = np.asarray(pallas_predict_proba(pf, jnp.asarray(xq),
                                            block_rows=64))
    np.testing.assert_allclose(p_pal, p_skl, atol=1e-6)


def test_gbt_leaf_sum_path():
    """The kernel's leaf SUM also serves boosting (base logit added on top)."""
    rng = np.random.default_rng(11)
    _, ens, x = _fit(rng, n_trees=6, max_depth=4)
    g = to_gemm(ens, N_FEAT)
    pf = to_pallas(g)
    want = np.asarray(gemm_leaf_sum(g, jnp.asarray(x[:200]), z_mode="f32"))
    got = np.asarray(pallas_leaf_sum(pf, jnp.asarray(x[:200])))
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_padding_is_inert():
    """Padded trees/nodes/rows contribute exactly zero."""
    rng = np.random.default_rng(7)
    _, ens, _ = _fit(rng, n_trees=3, max_depth=3)  # tiny: heavy padding
    g = to_gemm(ens, N_FEAT)
    pf = to_pallas(g)
    assert pf.sel.shape[0] == TREE_BLOCK  # 3 → padded to one tree block
    assert int(pf.n_trees) == 3
    xq = rng.normal(size=(9, N_FEAT)).astype(np.float32)  # 9 → padded rows
    want = np.asarray(gemm_predict_proba(g, jnp.asarray(xq), z_mode="f32"))
    got = np.asarray(pallas_predict_proba(pf, jnp.asarray(xq)))
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_engine_forest_pallas_path_matches(small_dataset):
    """ScoringEngine with use_pallas=True swaps the forest predict for the
    fused kernel; served probabilities must match the XLA GEMM engine."""
    import dataclasses

    from real_time_fraud_detection_system_tpu.config import small_config
    from real_time_fraud_detection_system_tpu.models.forest import fit_forest
    from real_time_fraud_detection_system_tpu.models.scaler import Scaler
    from real_time_fraud_detection_system_tpu.runtime import (
        ReplaySource,
        ScoringEngine,
    )

    rng = np.random.default_rng(2)
    x = rng.normal(size=(500, N_FEAT)).astype(np.float32)
    y = (x[:, 0] > 0.3).astype(np.int32)
    ens = fit_forest(x, y, n_trees=5, max_depth=4)
    scaler = Scaler(mean=jnp.zeros(N_FEAT), scale=jnp.ones(N_FEAT))

    _, _, _, txs = small_dataset
    cfg = small_config()
    cfg_p = dataclasses.replace(
        cfg, runtime=dataclasses.replace(cfg.runtime, use_pallas=True)
    )
    outs = []
    for c in (cfg, cfg_p):
        eng = ScoringEngine(c, kind="forest", params=ens, scaler=scaler)
        src = ReplaySource(txs.slice(slice(0, 300)), 1_743_465_600,
                           batch_rows=128)
        probs = []
        while True:
            cols = src.poll_batch()
            if cols is None:
                break
            probs.append(eng.process_batch(cols).probs)
        outs.append(np.concatenate(probs))
    np.testing.assert_allclose(outs[0], outs[1], rtol=1e-5, atol=1e-6)


def test_engine_gbt_pallas_path_matches(small_dataset):
    """kind='gbt' with use_pallas=True: sigmoid(base + fused leaf sum) must
    match the XLA gbt engine (pins the base_score handling and the
    GBTModel gate actually matching)."""
    import dataclasses

    from real_time_fraud_detection_system_tpu.config import small_config
    from real_time_fraud_detection_system_tpu.models.gbt import train_gbt
    from real_time_fraud_detection_system_tpu.models.scaler import Scaler
    from real_time_fraud_detection_system_tpu.runtime import (
        ReplaySource,
        ScoringEngine,
    )

    rng = np.random.default_rng(6)
    x = rng.normal(size=(500, N_FEAT)).astype(np.float32)
    y = (x[:, 2] > 0.1).astype(np.int32)
    model = train_gbt(x, y, n_trees=6, max_depth=3)
    scaler = Scaler(mean=jnp.zeros(N_FEAT), scale=jnp.ones(N_FEAT))

    _, _, _, txs = small_dataset
    cfg = small_config()
    cfg_p = dataclasses.replace(
        cfg, runtime=dataclasses.replace(cfg.runtime, use_pallas=True))
    outs = []
    for c in (cfg, cfg_p):
        eng = ScoringEngine(c, kind="gbt", params=model, scaler=scaler)
        src = ReplaySource(txs.slice(slice(0, 300)), 1_743_465_600,
                           batch_rows=128)
        probs = []
        while True:
            cols = src.poll_batch()
            if cols is None:
                break
            probs.append(eng.process_batch(cols).probs)
        outs.append(np.concatenate(probs))
    assert outs[0].std() > 0  # non-degenerate scores
    np.testing.assert_allclose(outs[0], outs[1], rtol=1e-5, atol=1e-6)


def test_pallas_path_serves_restored_params(small_dataset):
    """The kernel tables are derived from LIVE params inside the step: after
    a checkpoint restore overwrites ``state.params`` in place (the
    ``io/checkpoint.py`` contract), served scores must come from the
    restored trees, not a stale build-time copy."""
    import dataclasses

    from real_time_fraud_detection_system_tpu.config import small_config
    from real_time_fraud_detection_system_tpu.models.forest import (
        fit_forest, for_device,
    )
    from real_time_fraud_detection_system_tpu.models.scaler import Scaler
    from real_time_fraud_detection_system_tpu.runtime import (
        ReplaySource,
        ScoringEngine,
    )

    rng = np.random.default_rng(4)
    x = rng.normal(size=(400, N_FEAT)).astype(np.float32)
    y = (x[:, 1] > 0.0).astype(np.int32)
    ens = fit_forest(x, y, n_trees=4, max_depth=3)
    g1 = for_device(ens, N_FEAT)
    # same structure, different leaf values — a shape-compatible "refit"
    g2 = g1._replace(leaf_val=jnp.asarray(
        np.asarray(g1.leaf_val)[:, ::-1].copy()))
    scaler = Scaler(mean=jnp.zeros(N_FEAT), scale=jnp.ones(N_FEAT))

    _, _, _, txs = small_dataset
    cfg = small_config()
    cfg = dataclasses.replace(
        cfg, runtime=dataclasses.replace(cfg.runtime, use_pallas=True))

    def run(engine):
        src = ReplaySource(txs.slice(slice(0, 200)), 1_743_465_600,
                           batch_rows=128)
        out = []
        while True:
            cols = src.poll_batch()
            if cols is None:
                break
            out.append(engine.process_batch(cols).probs)
        return np.concatenate(out)

    fresh_g2 = run(ScoringEngine(cfg, "forest", params=g2, scaler=scaler))
    eng = ScoringEngine(cfg, "forest", params=g1, scaler=scaler)
    eng.state.params = g2  # what Checkpointer.restore does, in place
    np.testing.assert_allclose(run(eng), fresh_g2, rtol=1e-5, atol=1e-6)


def test_sharded_engine_serves_pallas_kernel(small_dataset):
    """use_pallas=True must reach the mesh engine's per-shard step (the
    sharded build consumes the base class's swapped predict), matching the
    single-chip pallas engine exactly."""
    import dataclasses

    from real_time_fraud_detection_system_tpu.config import small_config
    from real_time_fraud_detection_system_tpu.models.forest import fit_forest
    from real_time_fraud_detection_system_tpu.models.scaler import Scaler
    from real_time_fraud_detection_system_tpu.runtime import (
        ReplaySource,
        ScoringEngine,
    )
    from real_time_fraud_detection_system_tpu.runtime.sharded_engine import (
        ShardedScoringEngine,
    )

    rng = np.random.default_rng(8)
    x = rng.normal(size=(400, N_FEAT)).astype(np.float32)
    y = (x[:, 0] > 0.2).astype(np.int32)
    ens = fit_forest(x, y, n_trees=4, max_depth=3)
    scaler = Scaler(mean=jnp.zeros(N_FEAT), scale=jnp.ones(N_FEAT))

    _, _, _, txs = small_dataset
    cfg = small_config()
    cfg = dataclasses.replace(
        cfg, runtime=dataclasses.replace(cfg.runtime, use_pallas=True))

    def run(engine):
        src = ReplaySource(txs.slice(slice(0, 256)), 1_743_465_600,
                           batch_rows=128)
        out = []
        while True:
            cols = src.poll_batch()
            if cols is None:
                break
            out.append(engine.process_batch(cols).probs)
        return np.concatenate(out)

    single = run(ScoringEngine(cfg, "forest", params=ens, scaler=scaler))
    sharded = run(ShardedScoringEngine(cfg, kind="forest", params=ens,
                                       scaler=scaler, n_devices=2))
    np.testing.assert_allclose(sharded, single, rtol=1e-5, atol=1e-6)


def test_table_bytes_gate():
    rng = np.random.default_rng(9)
    _, ens, _ = _fit(rng, n_trees=4, max_depth=4)
    g = to_gemm(ens, N_FEAT)
    for zm in ("bf16", "int8", "f32"):
        nbytes = pallas_table_bytes(g, zm)
        assert nbytes > 0
        pf = to_pallas(g, zm)
        # one padded tree block of depth-4 trees: sel + path dominate
        got = sum(int(np.asarray(a).nbytes) for a in
                  (pf.sel, pf.path, pf.thresh, pf.target, pf.leaf_val))
        assert nbytes == got, zm


@pytest.mark.parametrize("z_mode", ["f32", "int8", "bf16"])
def test_classify_kernel_z_modes_match(z_mode):
    """The traversal core follows the table z dtype (to_pallas z_mode):
    every mode must agree with the f32 gemm composition."""
    rng = np.random.default_rng(13)
    clf, ens, _ = _fit(rng, n_trees=7, max_depth=5)
    g = to_gemm(ens, N_FEAT)
    pf = to_pallas(g, z_mode)
    xq = rng.normal(size=(200, N_FEAT)).astype(np.float32)
    want = np.asarray(gemm_leaf_sum(g, jnp.asarray(xq), z_mode="f32"))
    got = np.asarray(pallas_leaf_sum(pf, jnp.asarray(xq), block_rows=64))
    np.testing.assert_allclose(got, want, atol=1e-5)
    # and decisions vs sklearn stay exact through the int8 path too
    p_skl = clf.predict_proba(xq)[:, 1]
    p_pal = np.asarray(pallas_predict_proba(pf, jnp.asarray(xq),
                                            block_rows=64))
    assert np.array_equal(p_pal >= 0.5, p_skl >= 0.5)


# -- fused featurize→score step (round 9) -----------------------------------


def _batch_cols(rng, n):
    return {
        "customer_id": rng.integers(0, 100, n).astype(np.int64),
        "terminal_id": rng.integers(0, 200, n).astype(np.int64),
        "tx_datetime_us": (
            (20200 * 86400 + rng.integers(0, 86400, n)).astype(np.int64)
            * 1_000_000),
        "amount_cents": rng.integers(100, 50000, n).astype(np.int64),
    }


@pytest.mark.parametrize("z_mode", ["f32", "int8", "bf16"])
@pytest.mark.parametrize("rows", [64, 256, 300])  # 300: non-×8 row pad path
def test_fused_step_matches_unfused_composition(z_mode, rows):
    """Interpret-mode parity for the fused featurize→score kernel vs the
    unfused jit composition (update_and_featurize → transform →
    gemm_leaf_sum) — same rows, every bucket size, every z mode — so
    tier-1 validates the exact code path the TPU compiles. Features must
    be BIT-identical (same age-mask math); the leaf sum agrees to f32
    accumulation order and decisions exactly."""
    import jax

    from real_time_fraud_detection_system_tpu.config import FeatureConfig
    from real_time_fraud_detection_system_tpu.core.batch import make_batch
    from real_time_fraud_detection_system_tpu.features.online import (
        init_feature_state,
        update_and_featurize,
        update_and_score_pallas_forest,
    )
    from real_time_fraud_detection_system_tpu.models.scaler import (
        Scaler,
        transform,
    )

    rng = np.random.default_rng(17)
    _, ens, _ = _fit(rng, n_trees=7, max_depth=5)
    g = to_gemm(ens, N_FEAT)
    fcfg = FeatureConfig(customer_capacity=128, terminal_capacity=256)
    scaler = Scaler(
        mean=jnp.asarray(rng.normal(size=N_FEAT).astype(np.float32)),
        scale=jnp.asarray((1.0 + rng.random(N_FEAT)).astype(np.float32)))
    batch = jax.tree.map(jnp.asarray,
                         make_batch(**_batch_cols(rng, rows)))

    def unfused(fstate, batch):
        fstate, feats = update_and_featurize(fstate, batch, fcfg)
        leaf = gemm_leaf_sum(g, transform(scaler, feats), z_mode=z_mode)
        return fstate, leaf, feats

    def fused(fstate, batch):
        pf = to_pallas(g, z_mode)
        return update_and_score_pallas_forest(
            fstate, batch, fcfg, scaler.mean, scaler.scale, pf)

    outs = {}
    for name, fn in (("unfused", unfused), ("fused", fused)):
        jfn = jax.jit(fn, donate_argnums=(0,))
        fs = init_feature_state(fcfg)
        # two chained batches: the second reads state the first scattered
        for _ in range(2):
            fs, leaf, feats = jfn(fs, batch)
        outs[name] = (np.asarray(leaf), np.asarray(feats))

    np.testing.assert_array_equal(outs["fused"][1], outs["unfused"][1])
    np.testing.assert_allclose(outs["fused"][0], outs["unfused"][0],
                               atol=1e-5)
    n_trees = g.sel.shape[0]
    assert np.array_equal(outs["fused"][0] / n_trees >= 0.5,
                          outs["unfused"][0] / n_trees >= 0.5)


def test_fused_engine_int8_matches_f32_unfused_engine(small_dataset):
    """The full serving gate: use_pallas + z_mode=int8 (the round-9
    device plane, both stages on) must stay decision-identical to the
    plain f32 XLA engine over a replayed stream."""
    import dataclasses

    from real_time_fraud_detection_system_tpu.config import small_config
    from real_time_fraud_detection_system_tpu.models.forest import fit_forest
    from real_time_fraud_detection_system_tpu.models.scaler import Scaler
    from real_time_fraud_detection_system_tpu.runtime import (
        ReplaySource,
        ScoringEngine,
    )

    rng = np.random.default_rng(23)
    x = rng.normal(size=(500, N_FEAT)).astype(np.float32)
    y = (x[:, 0] > 0.3).astype(np.int32)
    ens = fit_forest(x, y, n_trees=5, max_depth=4)
    scaler = Scaler(mean=jnp.zeros(N_FEAT), scale=jnp.ones(N_FEAT))

    _, _, _, txs = small_dataset
    base = small_config()
    fused = dataclasses.replace(base, runtime=dataclasses.replace(
        base.runtime, use_pallas=True, z_mode="int8"))
    outs = []
    for c in (base, fused):
        eng = ScoringEngine(c, kind="forest", params=ens, scaler=scaler)
        src = ReplaySource(txs.slice(slice(0, 300)), 1_743_465_600,
                           batch_rows=128)
        probs = []
        while True:
            cols = src.poll_batch()
            if cols is None:
                break
            probs.append(eng.process_batch(cols).probs)
        outs.append(np.concatenate(probs))
    np.testing.assert_allclose(outs[1], outs[0], rtol=1e-5, atol=1e-6)
    assert np.array_equal(outs[1] >= 0.5, outs[0] >= 0.5)
