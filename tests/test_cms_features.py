"""CMS-backed customer velocity features (BASELINE.json config 3):
``customer_source='cms'`` serves count/avg-amount windows from the
day-ringed count-min sketch instead of the dense table."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from real_time_fraud_detection_system_tpu.config import (
    Config,
    FeatureConfig,
)
from real_time_fraud_detection_system_tpu.core.batch import make_batch
from real_time_fraud_detection_system_tpu.features.online import (
    init_feature_state,
    update_and_featurize,
)


def _batch(rng, n=256, n_cust=40, day0=20200):
    return make_batch(
        customer_id=rng.integers(0, n_cust, n).astype(np.int64),
        terminal_id=rng.integers(0, 80, n).astype(np.int64),
        tx_datetime_us=(
            (day0 + rng.integers(0, 3, n)) * 86400
            + rng.integers(0, 86400, n)
        ).astype(np.int64) * 1_000_000,
        amount_cents=rng.integers(100, 50000, n).astype(np.int64),
    )


def _cfgs():
    table = FeatureConfig(customer_capacity=256, terminal_capacity=512,
                          cms_width=1 << 12)
    cms = dataclasses.replace(table, customer_source="cms")
    return table, cms


def test_cms_features_match_exact_when_collision_free(rng):
    """With width >> keys the sketch is collision-free, so its windowed
    count/amount estimates equal the exact table's."""
    table_cfg, cms_cfg = _cfgs()
    b = jax.tree.map(jnp.asarray, _batch(rng))

    st_t = init_feature_state(table_cfg)
    st_c = init_feature_state(cms_cfg)
    assert st_c.cms is not None and st_t.cms is None

    st_t, f_t = update_and_featurize(st_t, b, table_cfg)
    st_c, f_c = update_and_featurize(st_c, b, cms_cfg)
    np.testing.assert_allclose(np.asarray(f_c), np.asarray(f_t),
                               rtol=1e-5, atol=1e-5)


def test_cms_features_overestimate_only_under_collisions(rng):
    """A tiny sketch collides; CMS guarantees estimates >= truth."""
    table_cfg, _ = _cfgs()
    cms_cfg = dataclasses.replace(table_cfg, customer_source="cms",
                                  cms_width=8, cms_depth=2)
    b = jax.tree.map(jnp.asarray, _batch(rng))
    st_t = init_feature_state(table_cfg)
    st_c = init_feature_state(cms_cfg)
    _, f_t = update_and_featurize(st_t, b, table_cfg)
    _, f_c = update_and_featurize(st_c, b, cms_cfg)
    # customer count columns are indices 3,5,7 (spec order)
    for col in (3, 5, 7):
        assert (np.asarray(f_c)[:, col] >= np.asarray(f_t)[:, col] - 1e-5).all()


def test_cms_mode_requires_sketch(rng):
    _, cms_cfg = _cfgs()
    st = init_feature_state(cms_cfg, with_cms=False)
    b = jax.tree.map(jnp.asarray, _batch(rng))
    with pytest.raises(ValueError, match="cms"):
        update_and_featurize(st, b, cms_cfg)


def test_engine_runs_cms_mode(small_dataset):
    from real_time_fraud_detection_system_tpu.models.logreg import init_logreg
    from real_time_fraud_detection_system_tpu.models.scaler import Scaler
    from real_time_fraud_detection_system_tpu.runtime import (
        ReplaySource,
        ScoringEngine,
    )

    _, _, _, txs = small_dataset
    cfg = Config(
        features=FeatureConfig(customer_capacity=256, terminal_capacity=512,
                               cms_width=1 << 12, customer_source="cms"),
    )
    eng = ScoringEngine(cfg, kind="logreg", params=init_logreg(15),
                        scaler=Scaler(jnp.zeros(15), jnp.ones(15)))
    stats = eng.run(ReplaySource(txs.slice(slice(0, 1024)), 1_743_465_600,
                                 batch_rows=512))
    assert stats["rows"] == 1024


# ---------------------------------------------------------------------------
# fraud-column back-compat (the tiered store's sketch-tier extension)
# ---------------------------------------------------------------------------

def test_cms_fraud_column_backcompat_bit_identical(rng):
    """count/amount behavior of a fraud-tracking sketch is BIT-identical
    to the historical 2-column sketch for the same stream, and
    cms_query is untouched for existing configs."""
    from real_time_fraud_detection_system_tpu.ops.cms import (
        cms_init,
        cms_query,
        cms_query_fraud,
        cms_update,
    )

    b = _batch(rng)
    key = jnp.asarray(b.customer_key if hasattr(b, "customer_key")
                      else b.customer_id)
    day = jnp.asarray(b.day)
    amt = jnp.asarray(b.amount)
    valid = jnp.ones(day.shape, bool)
    fraud = jnp.asarray((np.asarray(day) % 3 == 0).astype(np.float32))

    old = cms_init(4, 1 << 10, 40)                      # 2-column
    new = cms_init(4, 1 << 10, 40, track_fraud=True)    # 3-column
    assert old.fraud is None and new.fraud is not None
    old = cms_update(old, key, amt, day, valid)
    new = cms_update(new, key, amt, day, valid, fraud=fraud)
    np.testing.assert_array_equal(np.asarray(old.count),
                                  np.asarray(new.count))
    np.testing.assert_array_equal(np.asarray(old.amount),
                                  np.asarray(new.amount))
    c_o, a_o = cms_query(old, key, day, (1, 7, 30))
    c_n, a_n, f_n = cms_query_fraud(new, key, day, (1, 7, 30))
    np.testing.assert_array_equal(np.asarray(c_o), np.asarray(c_n))
    np.testing.assert_array_equal(np.asarray(a_o), np.asarray(a_n))
    # fraud estimates obey the overestimate-only contract per key/day
    assert (np.asarray(f_n) >= -1e-6).all()
    # querying fraud off a 2-column sketch refuses loudly
    with pytest.raises(ValueError, match="track_fraud"):
        cms_query_fraud(old, key, day, (1, 7, 30))


def test_cms_delay_zero_query_bit_identical(rng):
    """cms_query grew a delay param for the terminal sketch tier;
    delay=0 (every existing call site) must stay bit-identical."""
    from real_time_fraud_detection_system_tpu.ops.cms import (
        cms_init,
        cms_query,
        cms_update,
    )

    b = _batch(rng)
    key, day = jnp.asarray(b.customer_key), jnp.asarray(b.day)
    sk = cms_update(cms_init(4, 1 << 10, 40), key,
                    jnp.asarray(b.amount), day, jnp.ones(day.shape, bool))
    c0, a0 = cms_query(sk, key, day, (1, 7, 30))
    c1, a1 = cms_query(sk, key, day, (1, 7, 30), delay=0)
    np.testing.assert_array_equal(np.asarray(c0), np.asarray(c1))
    np.testing.assert_array_equal(np.asarray(a0), np.asarray(a1))
    # a positive delay shifts the window exactly like the dense tier:
    # querying at day+d with delay=d sees the same buckets as delay=0
    d = 7
    c2, a2 = cms_query(sk, key, day + d, (1, 7, 30), delay=d)
    np.testing.assert_array_equal(np.asarray(c0), np.asarray(c2))
    np.testing.assert_array_equal(np.asarray(a0), np.asarray(a2))


def test_v1_checkpoint_with_two_column_sketch_still_restores(
        rng, tmp_path):
    """A checkpoint written from a pre-tiering config (2-column sketch,
    no directories) must restore into today's template for the SAME
    config — the Optional fields contribute no pytree leaves."""
    import jax as _jax

    from real_time_fraud_detection_system_tpu.io.checkpoint import (
        Checkpointer,
    )
    from real_time_fraud_detection_system_tpu.models.logreg import (
        init_logreg,
    )
    from real_time_fraud_detection_system_tpu.models.scaler import Scaler
    from real_time_fraud_detection_system_tpu.runtime.engine import (
        EngineState,
    )

    _, cms_cfg = _cfgs()
    b = jax.tree.map(jnp.asarray, _batch(rng))
    st = init_feature_state(cms_cfg)
    # pin the pre-tiering leaf structure: 4+4 window leaves + 3 sketch
    # leaves, exactly what a v1 checkpoint holds for this config
    assert len(_jax.tree.leaves(st)) == 11
    st, _ = update_and_featurize(st, b, cms_cfg)
    state = EngineState(feature_state=st, params=init_logreg(15),
                        scaler=Scaler(jnp.zeros(15), jnp.ones(15)),
                        offsets=[3], batches_done=1, rows_done=256)
    ck = Checkpointer(str(tmp_path))
    ck.save(state)
    tmpl = EngineState(feature_state=init_feature_state(cms_cfg),
                       params=init_logreg(15),
                       scaler=Scaler(jnp.zeros(15), jnp.ones(15)))
    restored = ck.restore(tmpl)
    rs = restored.feature_state
    assert rs.customer_dir is None and rs.terminal_cms is None
    np.testing.assert_array_equal(np.asarray(rs.cms.count),
                                  np.asarray(st.cms.count))
    assert restored.batches_done == 1
