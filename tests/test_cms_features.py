"""CMS-backed customer velocity features (BASELINE.json config 3):
``customer_source='cms'`` serves count/avg-amount windows from the
day-ringed count-min sketch instead of the dense table."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from real_time_fraud_detection_system_tpu.config import (
    Config,
    FeatureConfig,
)
from real_time_fraud_detection_system_tpu.core.batch import make_batch
from real_time_fraud_detection_system_tpu.features.online import (
    init_feature_state,
    update_and_featurize,
)


def _batch(rng, n=256, n_cust=40, day0=20200):
    return make_batch(
        customer_id=rng.integers(0, n_cust, n).astype(np.int64),
        terminal_id=rng.integers(0, 80, n).astype(np.int64),
        tx_datetime_us=(
            (day0 + rng.integers(0, 3, n)) * 86400
            + rng.integers(0, 86400, n)
        ).astype(np.int64) * 1_000_000,
        amount_cents=rng.integers(100, 50000, n).astype(np.int64),
    )


def _cfgs():
    table = FeatureConfig(customer_capacity=256, terminal_capacity=512,
                          cms_width=1 << 12)
    cms = dataclasses.replace(table, customer_source="cms")
    return table, cms


def test_cms_features_match_exact_when_collision_free(rng):
    """With width >> keys the sketch is collision-free, so its windowed
    count/amount estimates equal the exact table's."""
    table_cfg, cms_cfg = _cfgs()
    b = jax.tree.map(jnp.asarray, _batch(rng))

    st_t = init_feature_state(table_cfg)
    st_c = init_feature_state(cms_cfg)
    assert st_c.cms is not None and st_t.cms is None

    st_t, f_t = update_and_featurize(st_t, b, table_cfg)
    st_c, f_c = update_and_featurize(st_c, b, cms_cfg)
    np.testing.assert_allclose(np.asarray(f_c), np.asarray(f_t),
                               rtol=1e-5, atol=1e-5)


def test_cms_features_overestimate_only_under_collisions(rng):
    """A tiny sketch collides; CMS guarantees estimates >= truth."""
    table_cfg, _ = _cfgs()
    cms_cfg = dataclasses.replace(table_cfg, customer_source="cms",
                                  cms_width=8, cms_depth=2)
    b = jax.tree.map(jnp.asarray, _batch(rng))
    st_t = init_feature_state(table_cfg)
    st_c = init_feature_state(cms_cfg)
    _, f_t = update_and_featurize(st_t, b, table_cfg)
    _, f_c = update_and_featurize(st_c, b, cms_cfg)
    # customer count columns are indices 3,5,7 (spec order)
    for col in (3, 5, 7):
        assert (np.asarray(f_c)[:, col] >= np.asarray(f_t)[:, col] - 1e-5).all()


def test_cms_mode_requires_sketch(rng):
    _, cms_cfg = _cfgs()
    st = init_feature_state(cms_cfg, with_cms=False)
    b = jax.tree.map(jnp.asarray, _batch(rng))
    with pytest.raises(ValueError, match="cms"):
        update_and_featurize(st, b, cms_cfg)


def test_engine_runs_cms_mode(small_dataset):
    from real_time_fraud_detection_system_tpu.models.logreg import init_logreg
    from real_time_fraud_detection_system_tpu.models.scaler import Scaler
    from real_time_fraud_detection_system_tpu.runtime import (
        ReplaySource,
        ScoringEngine,
    )

    _, _, _, txs = small_dataset
    cfg = Config(
        features=FeatureConfig(customer_capacity=256, terminal_capacity=512,
                               cms_width=1 << 12, customer_source="cms"),
    )
    eng = ScoringEngine(cfg, kind="logreg", params=init_logreg(15),
                        scaler=Scaler(jnp.zeros(15), jnp.ones(15)))
    stats = eng.run(ReplaySource(txs.slice(slice(0, 1024)), 1_743_465_600,
                                 batch_rows=512))
    assert stats["rows"] == 1024
