"""REAL multi-process distributed runtime: 2 OS processes, TCP
coordinator, Gloo collectives on CPU — `initialize_distributed` and the
tensor-parallel step running across process boundaries, not just a
single-process virtual mesh.

This is the closest a single host gets to the multi-host DCN story
(SURVEY §5.8): the same `jax.distributed.initialize` + mesh + shard_map
code path that runs on a TPU pod, with the coordinator/Gloo transport
standing in for DCN.
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

_WORKER = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("XLA_FLAGS", None)  # 1 local device per process
    import jax
    jax.config.update("jax_platforms", "cpu")
    pid, port = int(sys.argv[1]), sys.argv[2]

    from real_time_fraud_detection_system_tpu.parallel.distributed import (
        initialize_distributed,
    )

    assert initialize_distributed(f"127.0.0.1:{port}", 2, pid)
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 2 and jax.local_device_count() == 1

    import numpy as np
    import jax.numpy as jnp
    import optax
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    from real_time_fraud_detection_system_tpu.models.mlp import (
        init_mlp, mlp_logits,
    )
    from real_time_fraud_detection_system_tpu.parallel.tensor_parallel import (
        make_tp_step,
    )

    try:
        mesh = Mesh(mesh_utils.create_device_mesh((2,)), ("data",))
        params = init_mlp(15, hidden=(32, 16), seed=7)
        rng = np.random.default_rng(5)
        x = jnp.asarray(rng.normal(0, 1, (64, 15)), jnp.float32)
        y = jnp.asarray((rng.random(64) < 0.3).astype(np.int32))

        sharded, step = make_tp_step(mesh, params, lr=1.0)
        new, loss = step(sharded, x, y)
    except Exception as e:
        # jaxlib builds without cross-process CPU collectives (no Gloo/
        # MPI) refuse ANY multi-process computation with exactly this
        # capability error. That is an environment limit, not a
        # regression in this repo's TP code — report it as a skip
        # sentinel so the test can skip with a precise reason, while
        # every other failure still propagates as a real failure.
        if "Multiprocess computations aren't implemented" in str(e):
            print("MPSKIP this jaxlib's CPU backend has no cross-process "
                  "collectives (Gloo/MPI not built in): "
                  + str(e).splitlines()[-1][:160], flush=True)
            sys.exit(0)
        raise

    def ref_loss(p):
        per = optax.sigmoid_binary_cross_entropy(
            mlp_logits(p, x), y.astype(jnp.float32))
        return per.mean()

    ref = float(ref_loss(params))
    got = float(jax.device_get(loss))  # replicated output: readable
    # psum reorders the f32 layer-2 reduction: relative parity
    assert abs(got - ref) < 1e-4 * max(abs(ref), 1.0), (got, ref)
    print(f"MPOK {pid} {got:.6f}", flush=True)
""")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture()
def mp_env():
    """Probe the pieces a 2-process run needs BEFORE paying for worker
    launches, and skip with a precise reason where the environment
    genuinely cannot run it (the capability probe for cross-process
    collectives happens inside the worker — it is only discoverable by
    running one)."""
    try:
        port = _free_port()
    except OSError as e:
        pytest.skip(f"cannot bind a loopback port for the coordinator: {e}")
    try:
        p = subprocess.run([sys.executable, "-c", "print('spawn-ok')"],
                           capture_output=True, text=True, timeout=60)
        assert "spawn-ok" in p.stdout
    except Exception as e:  # noqa: BLE001 — any spawn failure is a skip
        pytest.skip(f"cannot spawn worker subprocesses: {e}")
    return port


_BOOT_WORKER = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("XLA_FLAGS", None)  # 1 local device per process
    import jax
    jax.config.update("jax_platforms", "cpu")
    pid, port = int(sys.argv[1]), sys.argv[2]

    from real_time_fraud_detection_system_tpu.config import (
        DistributedConfig,
    )
    from real_time_fraud_detection_system_tpu.runtime.distributed import (
        bootstrap_distributed,
    )

    topo = bootstrap_distributed(
        DistributedConfig(coordinator=f"127.0.0.1:{port}",
                          num_processes=2, process_id=pid),
        local_devices=1)
    assert topo is not None and topo.coordinated, topo
    assert jax.process_count() == 2, jax.process_count()
    assert topo.n_shards_total == 2
    assert list(topo.owned_shards) == [pid]

    # Local-mesh serving computation under the REAL distributed runtime:
    # this is what the partitioned multi-host deployment executes, and
    # it must work on EVERY backend (no capability involved) — a hard
    # assertion, never a skip.
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from real_time_fraud_detection_system_tpu.parallel.mesh import (
        compat_shard_map,
        make_local_mesh,
    )

    mesh = make_local_mesh(1)
    assert int(mesh.devices.size) == 1
    f = jax.jit(compat_shard_map(
        lambda x: x * 2 + pid, mesh, P("data"), P("data")))
    y = f(jnp.arange(8.0))
    assert float(y.sum()) == 2 * 28 + 8 * pid, y
    print(f"BOOTOK {pid}", flush=True)

    # The process-SPANNING mesh: cross-process collectives — the one
    # leg that is a backend capability. Probe first; refusal prints the
    # precise MPSKIP sentinel, support runs a REAL global computation.
    from real_time_fraud_detection_system_tpu.parallel.mesh import (
        cross_process_collectives_supported,
        make_process_mesh,
    )

    pmesh = make_process_mesh()
    assert int(pmesh.devices.size) == 2
    assert [d.process_index for d in pmesh.devices.flat] == [0, 1]
    err = cross_process_collectives_supported(pmesh)
    if err is not None:
        print("MPSKIP " + err[:200], flush=True)
        sys.exit(0)
    from jax.sharding import NamedSharding
    out = jax.jit(
        lambda: jnp.ones((2,), jnp.float32) * (pid + 1),
        out_shardings=NamedSharding(pmesh, P("data")))()
    total = float(jnp.sum(out))  # cross-process reduction
    print(f"SPANOK {pid} {total}", flush=True)
""")


@pytest.fixture(scope="module")
def boot_run(tmp_path_factory):
    """ONE 2-process distributed-bootstrap run shared by the promoted
    tests below (worker launches cost seconds; the two halves assert
    different contracts over the same run). Probes its own port/spawn
    capability (module-scoped; ``mp_env`` stays function-scoped for the
    TP test)."""
    try:
        port = str(_free_port())
    except OSError as e:
        pytest.skip(f"cannot bind a loopback port for the coordinator: {e}")
    try:
        p = subprocess.run([sys.executable, "-c", "print('spawn-ok')"],
                           capture_output=True, text=True, timeout=60)
        assert "spawn-ok" in p.stdout
    except Exception as e:  # noqa: BLE001 — any spawn failure is a skip
        pytest.skip(f"cannot spawn worker subprocesses: {e}")
    worker = tmp_path_factory.mktemp("mp") / "boot_worker.py"
    worker.write_text(_BOOT_WORKER)
    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(pid), port],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, cwd=repo, env=env,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-process worker timed out")
        outs.append(out)
    return procs, outs


def test_two_process_distributed_bootstrap_and_local_serving(boot_run):
    """The promoted half that runs — and must PASS — on EVERY backend:
    2 real processes, a real jax.distributed coordination barrier, the
    ProcessTopology contract, and a local-mesh shard_map serving
    computation under the distributed runtime. No capability skip
    exists on this path: the partitioned multi-host deployment needs
    nothing more, so a failure here is a real regression."""
    procs, outs = boot_run
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} rc={p.returncode}:\n{out}"
        assert f"BOOTOK {pid}" in out, out


def test_two_process_spanning_mesh_collective(boot_run):
    """The collective leg: a REAL cross-process reduction over the
    process-spanning mesh where jaxlib's CPU collectives support it;
    the precise MPSKIP sentinel otherwise (bootstrap/local-serving
    failures still fail in the test above — never a vacuous pass)."""
    _, outs = boot_run
    skips = [ln for out in outs for ln in out.splitlines()
             if ln.startswith("MPSKIP")]
    if skips:
        pytest.skip(skips[0][len("MPSKIP "):])
    for pid, out in enumerate(outs):
        assert f"SPANOK {pid}" in out, out


def test_two_process_tp_step(tmp_path, mp_env):
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)
    port = str(mp_env)
    # the worker strips XLA_FLAGS itself (single env owner)
    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(pid), port],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, cwd=repo, env=env,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-process worker timed out")
        outs.append(out)
    skips = [ln for out in outs for ln in out.splitlines()
             if ln.startswith("MPSKIP")]
    if skips:
        # fix-or-pin: the jaxlib build genuinely cannot run multiprocess
        # CPU computations — skip with the worker's precise reason so a
        # capable box still runs (and can regress) the real test
        pytest.skip(skips[0][len("MPSKIP "):])
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} rc={p.returncode}:\n{out}"
        assert f"MPOK {pid}" in out, out
    # both processes agree on the replicated loss value
    v0 = [ln for ln in outs[0].splitlines() if ln.startswith("MPOK")][0]
    v1 = [ln for ln in outs[1].splitlines() if ln.startswith("MPOK")][0]
    assert v0.split()[2] == v1.split()[2]
