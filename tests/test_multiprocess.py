"""REAL multi-process distributed runtime: 2 OS processes, TCP
coordinator, Gloo collectives on CPU — `initialize_distributed` and the
tensor-parallel step running across process boundaries, not just a
single-process virtual mesh.

This is the closest a single host gets to the multi-host DCN story
(SURVEY §5.8): the same `jax.distributed.initialize` + mesh + shard_map
code path that runs on a TPU pod, with the coordinator/Gloo transport
standing in for DCN.
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

_WORKER = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("XLA_FLAGS", None)  # 1 local device per process
    import jax
    jax.config.update("jax_platforms", "cpu")
    pid, port = int(sys.argv[1]), sys.argv[2]

    from real_time_fraud_detection_system_tpu.parallel.distributed import (
        initialize_distributed,
    )

    assert initialize_distributed(f"127.0.0.1:{port}", 2, pid)
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 2 and jax.local_device_count() == 1

    import numpy as np
    import jax.numpy as jnp
    import optax
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    from real_time_fraud_detection_system_tpu.models.mlp import (
        init_mlp, mlp_logits,
    )
    from real_time_fraud_detection_system_tpu.parallel.tensor_parallel import (
        make_tp_step,
    )

    mesh = Mesh(mesh_utils.create_device_mesh((2,)), ("data",))
    params = init_mlp(15, hidden=(32, 16), seed=7)
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.normal(0, 1, (64, 15)), jnp.float32)
    y = jnp.asarray((rng.random(64) < 0.3).astype(np.int32))

    sharded, step = make_tp_step(mesh, params, lr=1.0)
    new, loss = step(sharded, x, y)

    def ref_loss(p):
        per = optax.sigmoid_binary_cross_entropy(
            mlp_logits(p, x), y.astype(jnp.float32))
        return per.mean()

    ref = float(ref_loss(params))
    got = float(jax.device_get(loss))  # replicated output: readable
    # psum reorders the f32 layer-2 reduction: relative parity
    assert abs(got - ref) < 1e-4 * max(abs(ref), 1.0), (got, ref)
    print(f"MPOK {pid} {got:.6f}", flush=True)
""")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_tp_step(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)
    port = str(_free_port())
    # the worker strips XLA_FLAGS itself (single env owner)
    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(pid), port],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, cwd=repo, env=env,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-process worker timed out")
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} rc={p.returncode}:\n{out}"
        assert f"MPOK {pid}" in out, out
    # both processes agree on the replicated loss value
    v0 = [ln for ln in outs[0].splitlines() if ln.startswith("MPOK")][0]
    v1 = [ln for ln in outs[1].splitlines() if ln.startswith("MPOK")][0]
    assert v0.split()[2] == v1.split()[2]
